"""yblint CLI: `python -m tools.analysis [targets...]`.

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = usage error / refused baseline update. See README "Static analysis"
for the workflow.
"""

from __future__ import annotations

import argparse
import subprocess
import sys

from tools.analysis.core import (DEFAULT_BASELINE, DEFAULT_TARGETS,
                                 REPO_ROOT, Baseline, format_human,
                                 format_json, run_analysis)


def _changed_files() -> list:
    """Repo-relative .py paths touched vs HEAD (staged, unstaged and
    untracked) — the pre-commit file set."""
    out = set()
    for args in (["git", "diff", "--name-only", "HEAD", "--"],
                 ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(args, cwd=REPO_ROOT, capture_output=True,
                                  text=True, check=True)
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"--changed: git failed: {e}", file=sys.stderr)
            return []
        out.update(ln.strip() for ln in proc.stdout.splitlines()
                   if ln.strip().endswith(".py"))
    return sorted(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="yblint: project-specific whole-program AST analysis "
                    "(jit trace-safety, lock discipline, reactor "
                    "blocking, swallowed errors, metric names, donation "
                    "safety, error propagation, resource lifetime, "
                    "wire drift, kernel contracts)")
    ap.add_argument("targets", nargs="*", default=list(DEFAULT_TARGETS),
                    help="files or directories relative to the repo root "
                         f"(default: {' '.join(DEFAULT_TARGETS)})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/analysis/"
                         "baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--changed", action="store_true",
                    help="report only findings in files changed vs HEAD "
                         "(incl. staged/untracked); the whole-program "
                         "index is still built over the full targets, so "
                         "cross-file passes stay sound — this is the "
                         "seconds-fast pre-commit mode")
    ap.add_argument("--update-baseline", action="store_true",
                    help="regenerate the baseline (sectioned per pass) "
                         "from the current findings; REFUSES entries "
                         "lacking a `  # justification` — append one to "
                         "each listed fingerprint in the baseline file, "
                         "then rerun")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept the current findings into the baseline "
                         "unconditionally and exit 0 (bootstrap only; "
                         "prefer --update-baseline)")
    ap.add_argument("--passes", default=None,
                    help="comma-separated subset of passes to run")
    ap.add_argument("--jobs", type=int, default=None,
                    help="parallel file workers (default: cpu count, "
                         "capped at 8)")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also list baselined findings")
    args = ap.parse_args(argv)

    passes = None
    if args.passes:
        from tools.analysis.passes import passes_by_name
        try:
            passes = passes_by_name(
                [p.strip() for p in args.passes.split(",") if p.strip()])
        except KeyError as e:
            print(e.args[0], file=sys.stderr)
            return 2

    report_only = None
    if args.changed:
        report_only = _changed_files()
        if not report_only:
            print("yblint: no changed python files")
            return 0

    baseline_path = None if args.no_baseline else args.baseline
    result = run_analysis(root=REPO_ROOT, targets=args.targets,
                          passes=passes, baseline_path=baseline_path,
                          jobs=args.jobs, report_only=report_only)
    if args.update_baseline:
        bl = Baseline.load(args.baseline)
        unjustified = bl.update(args.baseline, result.findings)
        if unjustified:
            print("refusing to baseline entries without a justification "
                  "— append `  # <why this is acceptable>` to each in "
                  f"{args.baseline}:", file=sys.stderr)
            for fp in unjustified:
                print(f"  {fp}", file=sys.stderr)
            return 2
        print(f"wrote {len(result.findings)} justified fingerprint(s) "
              f"to {args.baseline}")
        return 0
    if args.write_baseline:
        bl = Baseline.load(args.baseline)
        bl.save(args.baseline, result.findings)
        print(f"wrote {len(result.findings)} fingerprint(s) to "
              f"{args.baseline}")
        return 0
    print(format_json(result) if args.json
          else format_human(result, verbose=args.verbose))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
