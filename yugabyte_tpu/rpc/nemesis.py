"""Network nemesis: programmable per-(src, dst) fault rules.

The Jepsen-style fault fabric for the RPC and consensus layers: a
`NemesisRules` table holds link rules keyed by (src, dst) endpoint
names — symmetric and ONE-WAY partitions, probabilistic drops,
latency/reorder injection, duplicate delivery — and the two transports
consult it at their send points:

  - `Messenger.call` (rpc/messenger.py): every outbound RPC — client
    writes/reads, master heartbeats, raft AppendEntries/RequestVote over
    `RpcTransport` — checks the link (messenger name -> destination
    endpoint name) before the wire send.
  - `LocalTransport._check_link` (consensus/transport.py): the in-process
    raft fabric applies the same rule semantics, so RaftHarness tests and
    MiniCluster clusters express faults identically.

Faults fire at the CALLER, which covers both directions of a link with
one hook: a one-way partition src->dst blocks requests in that direction
only (the reverse link consults its own (dst, src) rule), and response
loss is modeled by `drop_response` — the request IS delivered and
executed, then the caller sees a timeout, exactly the ambiguity a real
lost response produces (the retryable-request dedup layer is what makes
that survivable).

Zero overhead when idle: the process-global table is None until a test
or a NemesisController installs one, and every hook starts with that
None check.

Time semantics: a dropped request surfaces as an immediate RpcTimeout
rather than sleeping out the caller's full timeout — the caller-visible
outcome (timeout, op fate unknown) is identical and chaos cycles stay
fast enough to run in CI.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Set, Tuple


class LinkBlocked(Exception):
    """Raised by check_link when the (src, dst) link is partitioned or the
    destination is down. Transports translate it to their own unreachable
    error (ServiceUnavailable / PeerUnreachable)."""


class LinkDropped(Exception):
    """Raised when a rule drops this request. The caller translates it to
    its timeout error (op fate unknown, like a real lost datagram)."""


@dataclass
class LinkRule:
    """Faults applied to messages src->dst. Endpoint names match exactly,
    by server prefix ("ts0" matches "ts0/tablet1"), or as the wildcard
    "*". All probabilities are independent per message."""
    src: str
    dst: str
    block: bool = False            # partition: nothing gets through
    drop_prob: float = 0.0         # request lost -> caller timeout
    drop_response_prob: float = 0.0  # delivered+executed, response lost
    latency_s: float = 0.0         # fixed delay before the send
    jitter_s: float = 0.0          # + uniform(0, jitter): reorders
    duplicate_prob: float = 0.0    # deliver the request twice


def _match(pattern: str, name: str) -> bool:
    if pattern == "*" or pattern == name:
        return True
    # server-level pattern matches every channel of that server
    # ("ts0" matches "ts0/t1"), mirroring LocalTransport's semantics
    return name.startswith(pattern + "/")


@dataclass
class LinkVerdict:
    """What check_link decided for one message (after raising for
    block/drop): the caller applies these on its send path."""
    duplicate: bool = False
    drop_response: bool = False


class NemesisRules:
    """Thread-safe fault-rule table. One per process while a chaos test
    runs (installed via `install()`); transports consult the singleton
    through `active()`."""

    def __init__(self, seed: int = 0):
        from yugabyte_tpu.utils import lock_rank
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "nemesis.rules_lock")
        self._rules: list = []                  # guarded-by: _lock
        self._down: Set[str] = set()            # guarded-by: _lock
        self._names: Dict[str, str] = {}        # guarded-by: _lock
        self._rng = random.Random(seed)         # guarded-by: _lock
        self._injected: Dict[str, int] = {}     # guarded-by: _lock

    # ------------------------------------------------------------- naming
    def register_endpoint(self, addr: str, name: str) -> None:
        """Bind a wire address ('host:port') to a nemesis endpoint name
        ('ts0', 'm0') so messenger-level rules can be written in terms of
        server ids."""
        with self._lock:
            self._names[addr] = name

    def name_of(self, addr_or_name: str) -> str:
        with self._lock:
            return self._names.get(addr_or_name, addr_or_name)

    # -------------------------------------------------------------- rules
    def add_rule(self, rule: LinkRule) -> LinkRule:
        with self._lock:
            self._rules.append(rule)
        return rule

    def partition(self, a: str, b: str, one_way: bool = False) -> None:
        """Cut the a->b link; symmetric (both directions) unless one_way."""
        self.add_rule(LinkRule(a, b, block=True))
        if not one_way:
            self.add_rule(LinkRule(b, a, block=True))

    def isolate(self, name: str) -> None:
        """Cut `name` off from everyone (crash-failure emulation)."""
        with self._lock:
            self._down.add(name)

    def drop(self, src: str, dst: str, prob: float,
             response: bool = False) -> None:
        self.add_rule(LinkRule(src, dst,
                               drop_response_prob=prob if response else 0.0,
                               drop_prob=0.0 if response else prob))

    def latency(self, src: str, dst: str, delay_s: float,
                jitter_s: float = 0.0) -> None:
        self.add_rule(LinkRule(src, dst, latency_s=delay_s,
                               jitter_s=jitter_s))

    def duplicate(self, src: str, dst: str, prob: float) -> None:
        self.add_rule(LinkRule(src, dst, duplicate_prob=prob))

    def heal(self) -> None:
        """Remove every rule and isolation (the end of a fault window)."""
        with self._lock:
            self._rules.clear()
            self._down.clear()

    def remove_rule(self, rule: LinkRule) -> None:
        with self._lock:
            try:
                self._rules.remove(rule)
            except ValueError:  # yblint: contained(rule already removed by heal() — removal is idempotent)
                pass

    # ---------------------------------------------------------- inspection
    def injected_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._injected)

    def _count(self, kind: str) -> None:  # guarded-by: _lock
        self._injected[kind] = self._injected.get(kind, 0) + 1
        _nemesis_counter(kind).increment()

    # ------------------------------------------------------------ the hook
    def check_link(self, src: str, dst: str) -> LinkVerdict:
        """Consulted by a transport immediately before sending src->dst.

        Raises LinkBlocked (partition / peer down) or LinkDropped
        (probabilistic request loss); may SLEEP for latency/reorder
        rules; returns a verdict carrying the duplicate / drop-response
        decisions the caller must apply around its send."""
        delay = 0.0
        verdict = LinkVerdict()
        with self._lock:
            src = self._names.get(src, src)
            dst = self._names.get(dst, dst)
            src_srv = src.split("/", 1)[0]
            dst_srv = dst.split("/", 1)[0]
            if src_srv in self._down or dst_srv in self._down \
                    or src in self._down or dst in self._down:
                self._count("blocked")
                raise LinkBlocked(f"{src}->{dst}: peer down (nemesis)")
            for r in self._rules:
                if not (_match(r.src, src) or _match(r.src, src_srv)):
                    continue
                if not (_match(r.dst, dst) or _match(r.dst, dst_srv)):
                    continue
                if r.block:
                    self._count("blocked")
                    raise LinkBlocked(f"{src}->{dst}: partitioned (nemesis)")
                if r.drop_prob and self._rng.random() < r.drop_prob:
                    self._count("dropped")
                    raise LinkDropped(f"{src}->{dst}: dropped (nemesis)")
                if r.drop_response_prob and \
                        self._rng.random() < r.drop_response_prob:
                    self._count("response_dropped")
                    verdict.drop_response = True
                if r.duplicate_prob and \
                        self._rng.random() < r.duplicate_prob:
                    self._count("duplicated")
                    verdict.duplicate = True
                if r.latency_s or r.jitter_s:
                    self._count("delayed")
                    delay += r.latency_s + (self._rng.random() * r.jitter_s
                                            if r.jitter_s else 0.0)
        if delay:
            time.sleep(delay)  # outside the lock: never stall other links
        return verdict


def _nemesis_counter(kind: str):
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    return ROOT_REGISTRY.entity("server", "nemesis").counter(
        f"nemesis_faults_{kind}_total",
        f"nemesis-injected {kind} network faults")


# Process-global installation (one chaos run at a time; tests install in
# a fixture and uninstall in teardown).
_active: Optional[NemesisRules] = None  # guarded-by: _active_lock
_active_lock = threading.Lock()


def install(rules: Optional[NemesisRules] = None,
            seed: int = 0) -> NemesisRules:
    """Install (and return) the process-global rule table. Idempotent:
    installing over an existing table replaces it."""
    global _active
    rules = rules if rules is not None else NemesisRules(seed=seed)
    with _active_lock:
        _active = rules
    return rules


def uninstall() -> None:
    global _active
    with _active_lock:
        _active = None


def active() -> Optional[NemesisRules]:
    # benign racy read: installation happens before the chaos window
    # opens and the reference is either None or a complete table
    return _active  # yblint: disable=lock-discipline
