"""End-to-end data integrity (PR: robustness): shadow-verified device
kernels + the scrub-and-repair loop.

Covers the full loop at every layer:

  - at-rest corruption injection (``FaultInjectionEnv.corrupt_range`` /
    ``corrupt_file_range``) and the ``verify_sst`` deep check behind
    ``sst_dump --verify`` / ``ldb verify``;
  - read-path containment: a corrupt block routes to the background-
    error slot (sticky Corruption, in-place retry refused) and surfaces
    RETRYABLY to the client, never as a raw Corruption;
  - ``DB.scrub`` quarantining corrupt SSTs (``*.corrupt``) + the
    ``ScrubTabletsOp`` interval scheduling;
  - online shadow verification: an injected bit flip in a device-
    produced survivor chunk is caught BEFORE install, the job completes
    natively byte-identical and the shape bucket is quarantined — and
    without shadow verification the same flip lands silently (the
    surface the feature closes);
  - the cluster loop: corrupt-at-rest SST detected within one scrub
    cycle -> tablet FAILED (heartbeat-reported) -> master rebuilds the
    replica in place from a healthy peer with zero acked-write loss;
    leader-driven digest divergence detection likewise ends in a
    rebuild.
"""

import glob
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_device_fault_containment import (  # noqa: E402
    CUTOFF, _mk_run, _native_reference, _run_device_native, _sst_bytes,
    _write_runs)

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime  # noqa: E402
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema  # noqa: E402
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey  # noqa: E402
from yugabyte_tpu.docdb.value import Value  # noqa: E402
from yugabyte_tpu.ops import device_faults, run_merge  # noqa: E402
from yugabyte_tpu.storage import compaction as compaction_mod  # noqa: E402
from yugabyte_tpu.storage import integrity, native_engine, offload_policy  # noqa: E402
from yugabyte_tpu.storage.db import DB, DBOptions  # noqa: E402
from yugabyte_tpu.tserver.maintenance_manager import (  # noqa: E402
    MaintenanceOpStats, ScrubTabletsOp)
from yugabyte_tpu.utils import env as env_mod  # noqa: E402
from yugabyte_tpu.utils import flags  # noqa: E402
from yugabyte_tpu.utils.env import corrupt_file_range  # noqa: E402
from yugabyte_tpu.utils.status import Code, StatusError  # noqa: E402

pytestmark = pytest.mark.skipif(not native_engine.available(),
                                reason="native engine unavailable")


@pytest.fixture(autouse=True)
def _clean_state():
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()
    yield
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()


@pytest.fixture()
def shadow_all():
    """Verify EVERY device job (tests must not depend on sampling luck)."""
    old = flags.get_flag("shadow_verify_sample")
    flags.set_flag("shadow_verify_sample", 1.0)
    yield
    flags.set_flag("shadow_verify_sample", old)


def wait_for(pred, timeout=60.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        assert time.monotonic() < deadline, f"timeout: {msg}"
        time.sleep(0.05)


def _key(i):
    return SubDocKey(DocKey(range_components=(f"r{i:04d}",)),
                     (("col", 0),)).encode(include_ht=False)


def _items(lo, hi):
    return [(_key(i), DocHybridTime(HybridTime((i + 1) << 12), 0),
             Value(primitive=f"v{i}").encode()) for i in range(lo, hi)]


def _fill_db(tmp_path, n=80):
    db = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
    db.write_batch(_items(0, n))
    db.flush()
    return db


def _data_files(db_dir):
    return sorted(glob.glob(os.path.join(db_dir, "*.sblock.0")))


# ------------------------------------------------------ at-rest corruption
class TestCorruptRange:
    def test_flips_exactly_requested_bits(self, tmp_path):
        p = str(tmp_path / "f")
        payload = bytes(range(256)) * 4
        with open(p, "wb") as f:
            f.write(payload)
        flipped = corrupt_file_range(p, offset=100, length=64, nbits=3)
        assert len(flipped) == 3
        with open(p, "rb") as f:
            got = f.read()
        assert got != payload
        diff = [i for i in range(len(payload)) if got[i] != payload[i]]
        assert diff == flipped
        for i in diff:
            assert 100 <= i < 164
            # exactly one bit differs per corrupted byte
            assert bin(got[i] ^ payload[i]).count("1") == 1

    def test_env_wrapper_counts(self, tmp_path):
        fi = env_mod.FaultInjectionEnv()
        p = str(tmp_path / "f")
        fi.write_file(p, b"x" * 100)
        fi.corrupt_range(p)
        assert fi.corruptions_injected == 1

    def test_empty_file_rejected(self, tmp_path):
        p = str(tmp_path / "f")
        open(p, "wb").close()
        with pytest.raises(ValueError):
            corrupt_file_range(p)


# ----------------------------------------------------------- verify_sst
class TestVerifySST:
    def test_clean_sst_verifies(self, tmp_path):
        db = _fill_db(tmp_path)
        try:
            base = next(iter(db._readers.values())).base_path
            rep = integrity.verify_sst(base)
            assert rep.ok, rep.errors
            assert rep.n_blocks >= 1
            assert rep.n_entries == 80
            assert rep.bytes_verified > 0
        finally:
            db.close()

    def test_data_block_bitflip_detected(self, tmp_path):
        db = _fill_db(tmp_path)
        try:
            base = next(iter(db._readers.values())).base_path
            corrupt_file_range(_data_files(db.db_dir)[0], length=16,
                               nbits=2)
            rep = integrity.verify_sst(base)
            assert not rep.ok
            assert any("block" in e for e in rep.errors), rep.errors
        finally:
            db.close()

    def test_base_file_bitflip_detected(self, tmp_path):
        db = _fill_db(tmp_path)
        try:
            base = next(iter(db._readers.values())).base_path
            # hit the index/bloom/props region (front of the base file)
            corrupt_file_range(base, offset=4, length=8, nbits=1)
            rep = integrity.verify_sst(base)
            assert not rep.ok
            assert any("base" in e for e in rep.errors), rep.errors
        finally:
            db.close()

    def test_sst_dump_verify_exit_codes(self, tmp_path, capsys):
        from yugabyte_tpu.tools import sst_dump
        db = _fill_db(tmp_path)
        try:
            base = next(iter(db._readers.values())).base_path
            assert sst_dump.main([base, "--verify"]) == 0
            corrupt_file_range(_data_files(db.db_dir)[0], nbits=1)
            assert sst_dump.main([base, "--verify"]) == 1
            out = capsys.readouterr().out
            assert "CORRUPT" in out
        finally:
            db.close()

    def test_ldb_verify_exit_codes(self, tmp_path, capsys):
        from yugabyte_tpu.tools import ldb
        db = _fill_db(tmp_path)
        db_dir = db.db_dir
        try:
            assert ldb.main(["verify", "--db", db_dir]) == 0
            corrupt_file_range(_data_files(db_dir)[0], nbits=1)
            assert ldb.main(["verify", "--db", db_dir]) == 1
            assert "CORRUPT" in capsys.readouterr().out
        finally:
            db.close()


# ------------------------------------------------- read-path containment
class TestReadPathContainment:
    def test_get_routes_corruption_retryably(self, tmp_path):
        old = flags.get_flag("read_native")
        flags.set_flag("read_native", False)  # exercise the Python path
        db = _fill_db(tmp_path)
        try:
            corrupt_file_range(_data_files(db.db_dir)[0], length=32,
                               nbits=2)
            with pytest.raises(StatusError) as ei:
                db.get(_key(10))
            # retryable to the client (walks replicas), NOT a raw
            # Corruption exception
            assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
            assert db.background_error is not None
            assert db.background_error.code == Code.CORRUPTION
            # sticky: in-place retry cannot restore lost bytes
            assert db.retry_background_work() is False
            assert db.background_error is not None
        finally:
            db.close()
            flags.set_flag("read_native", old)


# ---------------------------------------------------------------- scrub
class TestDBScrub:
    def test_clean_scrub_reports_totals(self, tmp_path):
        db = _fill_db(tmp_path)
        try:
            rep = db.scrub()
            assert rep["files"] == 1 and not rep["corrupt"]
            assert rep["entries"] == 80 and rep["bytes"] > 0
            assert db.background_error is None
        finally:
            db.close()

    def test_scrub_detects_quarantines_and_parks_sticky(self, tmp_path):
        db = _fill_db(tmp_path)
        try:
            base = next(iter(db._readers.values())).base_path
            data = _data_files(db.db_dir)[0]
            corrupt_file_range(data, length=16, nbits=2)
            rep = db.scrub()
            assert rep["corrupt"] and rep["corrupt"][0]["path"] == base
            # quarantined: both halves renamed *.corrupt
            assert os.path.exists(base + ".corrupt")
            assert os.path.exists(data + ".corrupt")
            assert not os.path.exists(base) and not os.path.exists(data)
            assert any(q["path"] == base
                       for q in integrity.quarantined_files())
            # parked with the STICKY corruption error
            assert db.background_error.code == Code.CORRUPTION
            assert db.retry_background_work() is False
        finally:
            db.close()

    def test_scrub_throttles_through_limiter(self, tmp_path):
        from yugabyte_tpu.utils.rate_limiter import RateLimiter
        db = _fill_db(tmp_path)
        try:
            limiter = RateLimiter(1 << 30)
            db.scrub(limiter=limiter)
            assert limiter.total_through > 0
        finally:
            db.close()


class _StubTablet:
    def __init__(self):
        self.scrubbed = 0

    def scrub(self, limiter=None, cancel=None):
        self.scrubbed += 1
        return {"files": 1, "blocks": 2, "entries": 10, "bytes": 100,
                "corrupt": []}


class _StubRaft:
    def is_leader(self):
        return False


class _StubPeer:
    def __init__(self, tid):
        self.tablet_id = tid
        self.state = "RUNNING"
        self.tablet = _StubTablet()
        self.raft = _StubRaft()
        self.scrub_state = {}


class TestScrubOp:
    def test_interval_gating_and_rotation(self):
        old = flags.get_flag("scrub_interval_s")
        flags.set_flag("scrub_interval_s", 0.05)
        try:
            peers = [_StubPeer("t1"), _StubPeer("t2")]
            op = ScrubTabletsOp(peers_fn=lambda: peers)
            stats = MaintenanceOpStats()
            op.update_stats(stats)
            assert not stats.runnable, "nothing due right after start"
            time.sleep(0.08)
            op.update_stats(stats)
            assert stats.runnable
            op.perform()
            op.perform()
            assert peers[0].tablet.scrubbed == 1
            assert peers[1].tablet.scrubbed == 1
            assert peers[0].scrub_state["files"] == 1
            assert peers[0].scrub_state["last_scrub_ts"] > 0
            op.update_stats(stats)
            assert not stats.runnable, "both tablets freshly scrubbed"
            # FAILED tablets are skipped
            time.sleep(0.08)
            peers[0].state = peers[1].state = "FAILED"
            op.update_stats(stats)
            assert not stats.runnable
            # flag 0 disables outright
            peers[0].state = "RUNNING"
            flags.set_flag("scrub_interval_s", 0.0)
            op.update_stats(stats)
            assert not stats.runnable
        finally:
            flags.set_flag("scrub_interval_s", old)


# ------------------------------------------------ shadow verification
class TestShadowVerify:
    def test_bitflip_caught_pre_install_and_native_completion(
            self, tmp_path, shadow_all):
        """Acceptance: an injected bit flip in a device-produced survivor
        chunk is detected by shadow verification before SST install, the
        job completes natively byte-identical, and the bucket is
        quarantined."""
        rng = np.random.default_rng(21)
        runs = [_mk_run(rng, 1200, 5000) for _ in range(4)]
        readers = _write_runs(str(tmp_path), runs)
        try:
            res_native = _native_reference(readers, str(tmp_path / "nat"))
            mm0 = integrity.shadow_mismatch_counter().value()
            fb0 = compaction_mod._storage_fallback_counter().value()
            device_faults.arm("bitflip", site="survivor", count=1)
            res_dev = _run_device_native(readers, str(tmp_path / "dev"))
            assert device_faults.armed_count() == 0, \
                "the bit flip must have fired"
            assert integrity.shadow_mismatch_counter().value() == mm0 + 1
            assert compaction_mod._storage_fallback_counter().value() \
                == fb0 + 1
            # byte-identical native completion
            assert res_dev.rows_out == res_native.rows_out
            assert _sst_bytes(res_dev.outputs) \
                == _sst_bytes(res_native.outputs)
            # the shape bucket is quarantined
            qkey = offload_policy.bucket_key(run_merge.packed_run_ns(
                [r.props.n_entries for r in readers]))
            snap = offload_policy.bucket_quarantine().snapshot()
            assert [e for e in snap if tuple(e["bucket"]) == qkey], snap
        finally:
            for r in readers:
                r.close()

    def test_clean_job_verifies_byte_identical(self, tmp_path,
                                               shadow_all):
        rng = np.random.default_rng(23)
        runs = [_mk_run(rng, 1000, 4000) for _ in range(4)]
        readers = _write_runs(str(tmp_path), runs)
        try:
            res_native = _native_reference(readers, str(tmp_path / "nat"))
            jobs0 = integrity.integrity_metrics().counter(
                "shadow_verify_jobs_total", "").value()
            mm0 = integrity.shadow_mismatch_counter().value()
            res_dev = _run_device_native(readers, str(tmp_path / "dev"))
            assert _sst_bytes(res_dev.outputs) \
                == _sst_bytes(res_native.outputs)
            assert integrity.integrity_metrics().counter(
                "shadow_verify_jobs_total", "").value() == jobs0 + 1
            assert integrity.shadow_mismatch_counter().value() == mm0
            assert not offload_policy.bucket_quarantine().snapshot()
        finally:
            for r in readers:
                r.close()

    def test_unverified_bitflip_lands_silently(self, tmp_path):
        """The surface shadow verification closes: with sampling off, the
        same injected flip produces a DIFFERENT (silently corrupt) SST
        and no alarm fires."""
        old = flags.get_flag("shadow_verify_sample")
        flags.set_flag("shadow_verify_sample", 0.0)
        rng = np.random.default_rng(29)
        runs = [_mk_run(rng, 1200, 5000) for _ in range(4)]
        readers = _write_runs(str(tmp_path), runs)
        try:
            res_native = _native_reference(readers, str(tmp_path / "nat"))
            mm0 = integrity.shadow_mismatch_counter().value()
            fb0 = compaction_mod._storage_fallback_counter().value()
            device_faults.arm("bitflip", site="survivor", count=1)
            res_dev = _run_device_native(readers, str(tmp_path / "dev"))
            assert device_faults.armed_count() == 0
            assert _sst_bytes(res_dev.outputs) \
                != _sst_bytes(res_native.outputs), \
                "flip should corrupt the output when unverified"
            assert integrity.shadow_mismatch_counter().value() == mm0
            assert compaction_mod._storage_fallback_counter().value() \
                == fb0
        finally:
            flags.set_flag("shadow_verify_sample", old)
            for r in readers:
                r.close()


# ------------------------------------------------------ the cluster loop
SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


@pytest.fixture
def cluster(tmp_path):
    from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                       MiniClusterOptions)
    flags.set_flag("replication_factor", 3)
    flags.set_flag("load_balancer_dead_grace_ms", 400)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path / "cluster"))).start()
    yield c
    flags.reset_flag("load_balancer_dead_grace_ms")
    c.shutdown()


def _tablet_peers(cluster, tablet_id):
    """(leader_ts, leader_peer, follower_ts, follower_peer)."""
    leader = follower = None
    for ts in cluster.tservers:
        peer = ts.tablet_manager.get_tablet(tablet_id)
        if peer.raft.is_leader():
            leader = (ts, peer)
        elif follower is None:
            follower = (ts, peer)
    assert leader and follower
    return (*leader, *follower)


def _checksums(cluster, client, tablet_id):
    read_ht = None
    for ts in cluster.tservers:   # pin one read time at the leader
        try:
            read_ht = client._messenger.call(
                ts.address, "tserver", "scan", tablet_id=tablet_id,
                limit=1)["read_ht"]
            break
        except StatusError:
            continue
    assert read_ht is not None, "no leader answered the read-time pin"
    sums = []
    for ts in cluster.tservers:
        resp = client._messenger.call(
            ts.address, "tserver", "checksum_tablet", timeout_s=30.0,
            tablet_id=tablet_id, read_ht=read_ht)
        sums.append(resp["checksum"])
    return sums


class TestClusterScrubRepairLoop:
    def test_corrupt_sst_detected_failed_and_rebuilt(self, cluster):
        """The acceptance loop: at-rest corruption on a follower is
        detected within one scrub cycle, the tablet goes FAILED
        (heartbeat-reported, corrupt), and the master rebuilds the
        replica in place from a healthy peer with zero acked-write
        loss."""
        client = cluster.new_client()
        client.create_namespace("db")
        from yugabyte_tpu.docdb.doc_operations import (QLWriteOp,
                                                       WriteOpKind)
        table = client.create_table("db", "t", SCHEMA, num_tablets=1)
        cluster.wait_all_replicas_running(table.table_id)
        cluster.wait_for_table_leaders("db", "t")
        acked = {}
        for i in range(120):
            client.write(table, [QLWriteOp(WriteOpKind.INSERT,
                                           dk(f"k{i:04d}"),
                                           {"v": f"v{i}"})])
            acked[f"k{i:04d}"] = f"v{i}"
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        _lts, _lp, fts, fpeer = _tablet_peers(cluster, tablet_id)
        fpeer.tablet.flush()
        data_files = _data_files(fpeer.tablet.regular_db.db_dir)
        assert data_files, "follower flush produced no SST"
        corrupt_file_range(data_files[0], length=64, nbits=3)

        # one scrub cycle detects it
        old_interval = flags.get_flag("scrub_interval_s")
        flags.set_flag("scrub_interval_s", 0.01)
        try:
            time.sleep(0.02)
            for _ in range(4):   # rotate through hosted tablets
                fts.scrub_op.perform()
                if fpeer.state == "FAILED":
                    break
        finally:
            flags.set_flag("scrub_interval_s", old_interval)
        assert fpeer.state == "FAILED" and fpeer.failed_corrupt
        assert fpeer.tablet.regular_db.background_error.code \
            == Code.CORRUPTION
        # in-place retry refuses (sticky)
        assert not fts.tablet_manager.recover_failed_tablet(tablet_id)

        # heartbeat-reported -> master rebuilds the replica IN PLACE
        def rebuilt():
            try:
                p = fts.tablet_manager.get_tablet(tablet_id)
            except StatusError:
                return False  # mid-rebuild: torn down, not yet reopened
            return p is not fpeer and p.state == "RUNNING"
        wait_for(rebuilt, timeout=90,
                 msg="master rebuilds the corrupt replica")
        cluster.wait_all_replicas_running(table.table_id)

        # zero acked-write loss + replicas converge byte-for-byte
        for k, want in sorted(acked.items())[::10]:
            row = client.read_row(table, dk(k))
            assert row is not None
            assert row.columns[SCHEMA.column_id("v")] == want
        wait_for(lambda: len(set(_checksums(cluster, client,
                                            tablet_id))) == 1,
                 timeout=60, msg="replica digests converge after rebuild")
        # ysck-visible state: the rebuilt replica reports clean
        st = client._messenger.call(
            fts.address, "tserver", "scrub_status", tablet_id=tablet_id)
        assert st["state"] == "RUNNING" and not st["failed_corrupt"]

    def test_digest_divergence_fails_follower_for_rebuild(self, cluster):
        """Cross-replica digest exchange: a follower whose resolved rows
        diverge from the leader's is failed (corrupt) after the strike
        threshold and rebuilt from the leader."""
        client = cluster.new_client()
        client.create_namespace("db")
        from yugabyte_tpu.docdb.doc_key import split_key_and_ht
        from yugabyte_tpu.docdb.doc_operations import (QLWriteOp,
                                                       WriteOpKind)
        table = client.create_table("db", "d", SCHEMA, num_tablets=1)
        cluster.wait_all_replicas_running(table.table_id)
        cluster.wait_for_table_leaders("db", "d")
        for i in range(40):
            client.write(table, [QLWriteOp(WriteOpKind.INSERT,
                                           dk(f"k{i:04d}"),
                                           {"v": f"v{i}"})])
        tablet_id = client.meta_cache.tablets(table.table_id)[0].tablet_id
        lts, lpeer, _fts, fpeer = _tablet_peers(cluster, tablet_id)

        # no divergence: digest exchange is quiet
        assert lts._scrub_digest_check(lpeer) == 0

        # diverge the follower: rewrite an existing row's newest version
        # at a later hybrid time DIRECTLY into its DB (bypassing raft)
        ikey, value = next(fpeer.tablet.regular_db.iter_from(b""))
        prefix, dht = split_key_and_ht(ikey)
        newer = DocHybridTime(HybridTime(dht.ht.value + (1000 << 12)), 0)
        fpeer.tablet.regular_db.write_batch([(prefix, newer, value)])

        mm0 = integrity.replica_mismatch_counter().value()
        assert lts._scrub_digest_check(lpeer) >= 1   # strike 1
        assert fpeer.state == "RUNNING", "one strike must not fail it"
        assert lts._scrub_digest_check(lpeer) >= 1   # strike 2 -> FAILED
        assert integrity.replica_mismatch_counter().value() >= mm0 + 2
        wait_for(lambda: fpeer.state == "FAILED", timeout=10,
                 msg="diverged follower failed after strike threshold")
        assert fpeer.failed_corrupt

        # the master rebuilds it from the leader; digests converge
        def rebuilt():
            try:
                p = _fts.tablet_manager.get_tablet(tablet_id)
            except StatusError:
                return False
            return p is not fpeer and p.state == "RUNNING"
        wait_for(rebuilt, timeout=90, msg="diverged replica rebuilt")
        cluster.wait_all_replicas_running(table.table_id)
        wait_for(lambda: lts._scrub_digest_check(
            lts.tablet_manager.get_tablet(tablet_id)) == 0,
            timeout=60, msg="digests agree after rebuild")
