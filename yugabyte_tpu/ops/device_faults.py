"""Device-fault injection + classification for the kernel offload path.

The storage-layer twin of utils/env.FaultInjectionEnv (PR 1): where that
injects disk faults under the byte stack, this injects ACCELERATOR
faults under the stage-B kernel path of the compaction pipeline —
XLA compile errors, RESOURCE_EXHAUSTED (HBM OOM), and runtime dispatch
faults — so tests can prove a mid-job device failure is contained
(per-chunk retry, then a byte-identical native fallback + shape-bucket
quarantine) instead of corrupting the writer.

Sites:
  - "dispatch": fired inside ops/run_merge.launch_merge_gc before the
    fused program runs (where a real XLA compile error surfaces);
  - "result":   fired when decisions are downloaded/decoded
    (MergeGCHandle.result / the chunked handle's download paths) —
    where an async runtime fault or OOM actually materializes, because
    JAX dispatch is asynchronous and errors ride the value.

  - "survivor": the SILENT kind — `maybe_flip_survivors` corrupts one
    downloaded survivor decision in place (kind "bitflip", no
    exception), modeling an HBM bit flip / donation bug / miscompile
    that loud-fault containment cannot see. Shadow verification
    (storage/integrity.py) is the defense it tests.

A fourth kind, "slow", raises nothing at all: it sleeps `delay_s` at
the injection site, modeling a degraded-but-alive accelerator (thermal
throttle, contended PCIe tunnel, a straggling mesh shard). Nothing in
the loud-fault containment sees it — the bucket-health board's rate
race (storage/bucket_health.py) is the defense it tests, and it can be
pinned to one shape bucket via arm(..., bucket=...) so a nemesis can
slow a single (k_pad, m) while its neighbours stay fast.

Arming is programmatic (`arm()`) or via the environment for child
processes: YBTPU_INJECT_DEVICE_FAULT="<kind>:<site>:<count>[:delay_s]",
e.g. "oom:result:1" or "slow:dispatch:4:0.05". Counts decrement per
fire; count <= 0 disarms.

`is_device_fault()` classifies BOTH injected and real device failures
(jaxlib XlaRuntimeError, RESOURCE_EXHAUSTED messages) so the
containment code in storage/compaction.py treats them uniformly.
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional

__all__ = ["InjectedDeviceFault", "InjectedCompileError",
           "InjectedResourceExhausted", "InjectedDispatchFault",
           "arm", "disarm_all", "maybe_fault", "maybe_flip_survivors",
           "is_device_fault", "armed_count"]


class InjectedDeviceFault(Exception):
    """Base for injected accelerator faults."""


class InjectedCompileError(InjectedDeviceFault):
    """Mimics an XLA lowering/compile failure of the fused program."""


class InjectedResourceExhausted(InjectedDeviceFault):
    """Mimics RESOURCE_EXHAUSTED: HBM allocation failure at dispatch."""


class InjectedDispatchFault(InjectedDeviceFault):
    """Mimics an asynchronous runtime fault surfacing on the value."""


_KINDS = {
    "compile": (InjectedCompileError,
                "injected XLA compile failure (nemesis)"),
    "oom": (InjectedResourceExhausted,
            "RESOURCE_EXHAUSTED: injected HBM OOM (nemesis)"),
    "runtime": (InjectedDispatchFault,
                "injected device dispatch fault (nemesis)"),
}

# Silent-corruption model (no exception — the HBM-bit-flip class that
# shadow verification exists to catch): armed like the loud kinds but
# consumed by maybe_flip_survivors, which MUTATES a downloaded survivor
# decision instead of raising.
_BITFLIP = "bitflip"
# Silent-slowness model (no exception — the degraded-accelerator class
# the bucket-health rate race exists to catch): maybe_fault sleeps
# delay_s instead of raising, optionally only for one shape bucket.
_SLOW = "slow"
_SITES = ("dispatch", "result", "survivor")

_lock = threading.Lock()
_armed: List[dict] = []   # guarded-by: _lock
_env_loaded = False       # guarded-by: _lock


def arm(kind: str, site: str = "dispatch", count: int = 1,
        delay_s: float = 0.05, bucket=None) -> None:
    """Arm `count` faults of `kind`
    ('compile'|'oom'|'runtime'|'bitflip'|'slow') at `site`
    ('dispatch'|'result'|'survivor'). Several armings stack; 'bitflip'
    only fires at the 'survivor' site (silent corruption of a downloaded
    decision buffer, no exception); 'slow' sleeps `delay_s` at the site
    without raising, and when `bucket` is given it fires only at
    bucket-aware sites dispatching that exact shape bucket."""
    assert kind in _KINDS or kind in (_BITFLIP, _SLOW), kind
    assert site in _SITES, site
    with _lock:
        _armed.append({"kind": kind, "site": site, "count": count,
                       "delay_s": float(delay_s),
                       "bucket": tuple(bucket) if bucket is not None
                       else None})


def disarm_all() -> None:
    with _lock:
        _armed.clear()


def armed_count() -> int:
    with _lock:
        return sum(max(0, a["count"]) for a in _armed)


def _load_env_locked() -> None:  # guarded-by: _lock
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get("YBTPU_INJECT_DEVICE_FAULT", "")
    if not spec:
        return
    for part in spec.split(","):
        bits = part.strip().split(":")
        if len(bits) >= 1 and (bits[0] in _KINDS or bits[0] == _BITFLIP):
            site = bits[1] if len(bits) > 1 else (
                "survivor" if bits[0] == _BITFLIP else "dispatch")
            try:
                count = int(bits[2]) if len(bits) > 2 else 1
            except ValueError:  # yblint: contained(malformed env count defaults to 1 — arming still happens)
                count = 1
            try:
                delay_s = float(bits[3]) if len(bits) > 3 else 0.05
            except ValueError:  # yblint: contained(malformed env delay defaults to 50ms — arming still happens)
                delay_s = 0.05
            if site in _SITES:
                _armed.append({"kind": bits[0], "site": site,
                               "count": count, "delay_s": delay_s,
                               "bucket": None})


def maybe_fault(site: str, bucket=None) -> None:
    """Fire the next armed fault for `site`, if any (decrements its
    count). 'slow' entries SLEEP (outside the lock) instead of raising
    and consume independently of the loud kinds; a loud entry still
    raises on the same call after the sleep, so a slow-AND-faulty
    device is expressible. `bucket` is the dispatching shape bucket at
    bucket-aware sites; bucket-pinned slow entries fire only when it
    matches. A single locked list check when nothing is armed."""
    delay = 0.0
    hit = None
    with _lock:
        _load_env_locked()
        if not _armed:
            return
        for a in list(_armed):
            if a["site"] != site or a["count"] <= 0:
                continue
            if a["kind"] == _SLOW:
                want = a.get("bucket")
                if want is not None and (bucket is None
                                         or tuple(bucket) != want):
                    continue
                a["count"] -= 1
                if a["count"] <= 0:
                    _armed.remove(a)
                delay = max(delay, float(a.get("delay_s", 0.05)))
            elif hit is None and a["kind"] != _BITFLIP:
                a["count"] -= 1
                if a["count"] <= 0:
                    _armed.remove(a)
                hit = a
    if delay > 0.0:
        _fault_counter(_SLOW).increment()
        time.sleep(delay)
    if hit is not None:
        exc_type, msg = _KINDS[hit["kind"]]
        _fault_counter(hit["kind"]).increment()
        raise exc_type(msg)


def maybe_flip_survivors(surv, make_tomb) -> bool:
    """Consume one armed 'bitflip' fault by SILENTLY corrupting a
    downloaded survivor decision in place — the HBM-bit-flip /
    miscompile model the shadow verifier exists to catch. Flips the low
    bit of an odd survivor index (stays in range: the write path would
    gather a duplicate row, not crash), falling back to a tombstone-flag
    flip when every index is even. Returns True when a flip fired."""
    with _lock:
        _load_env_locked()
        hit = None
        for a in _armed:
            if a["kind"] == _BITFLIP and a["count"] > 0:
                a["count"] -= 1
                if a["count"] <= 0:
                    _armed.remove(a)
                hit = a
                break
        if hit is None:
            return False
    flipped = False
    if len(surv):
        odd = [i for i in range(len(surv)) if int(surv[i]) & 1]
        if odd:
            i = odd[len(odd) // 2]
            surv[i] = int(surv[i]) ^ 1
            flipped = True
    if not flipped and len(make_tomb):
        i = len(make_tomb) // 2
        make_tomb[i] = not bool(make_tomb[i])
        flipped = True
    if flipped:
        _fault_counter(_BITFLIP).increment()
    return flipped


def _fault_counter(kind: str):
    from yugabyte_tpu.utils.metrics import kernel_metrics
    return kernel_metrics().counter(
        f"kernel_injected_fault_{kind}_total",
        f"injected device faults of kind {kind}")


def is_device_fault(exc: BaseException) -> bool:
    """True for failures of the DEVICE path — injected or real — that the
    compaction containment may survive via the native fallback. Cancel-
    lation and ordinary host-side errors (OSError from the byte shell)
    are NOT device faults: those take their own paths."""
    if isinstance(exc, InjectedDeviceFault):
        return True
    from yugabyte_tpu.utils.cancellation import OperationCancelled
    if isinstance(exc, OperationCancelled):
        return False
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "JaxRuntimeError"):
        return True
    msg = str(exc)
    return ("RESOURCE_EXHAUSTED" in msg or "Mosaic" in msg
            or "xla" in name.lower())
