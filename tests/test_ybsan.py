"""ybsan self-tests: seeded positive fixtures MUST be flagged, ordered
negative fixtures MUST stay clean, the baseline round-trips, and the
armed overhead stays bounded.

This module arms/disarms the sanitizer per test, so it is EXCLUDED from
the env-armed lanes (`YBSAN=1` runs, tools/check.sh --sanitize): its
deliberate races would poison the session gate. The skipif below makes
that exclusion self-enforcing.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

import tools.sanitizer as san
from tools.sanitizer import report as san_report
from tools.sanitizer.detector import (CODE_GUARD_NOT_HELD,
                                      CODE_READ_WRITE,
                                      CODE_SINGLE_WRITER,
                                      CODE_WRITE_WRITE)
from yugabyte_tpu.utils import lock_rank

pytestmark = pytest.mark.skipif(
    os.environ.get("YBSAN", "") not in ("", "0", "false", "off"),
    reason="positive fixtures would poison the armed session gate")


@pytest.fixture
def det():
    """A fresh detector per test: arm, hand it out, disarm."""
    d = san.arm()
    yield d
    san.disarm()


def _codes(d):
    return {r.code for r in d.reports()}


def _spin(fn, name):
    t = threading.Thread(target=fn, name=name)
    t.start()
    return t


class _Guarded:
    """Fixture with a declared guard, instrumented manually."""

    def __init__(self):
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "ybsan.test.guarded")
        self.v = 0


class _Bare:
    """Fixture for stated lock-free disciplines."""

    def __init__(self):
        self.x = 0


# --------------------------------------------------------------- positives
# Completion signalling in positives uses an UNTRACKED Event: a patched
# Thread.join would hand the main thread a happens-before edge and
# legitimately hide the race.

def test_positive_write_write(det):
    san.patch_class(_Guarded, guards={"v": "_lock"})
    obj = _Guarded()
    done = [threading.Event(), threading.Event()]

    def w(i):
        obj.v = i          # no lock, no ordering between the writers
        done[i].set()

    _spin(lambda: w(0), "ybsan-w0")
    _spin(lambda: w(1), "ybsan-w1")
    for e in done:
        assert e.wait(5.0)
    time.sleep(0.05)
    assert CODE_WRITE_WRITE in _codes(det)


def test_positive_read_write(det):
    san.patch_class(_Guarded, guards={"v": "_lock"})
    obj = _Guarded()
    done = threading.Event()

    def w():
        obj.v = 7
        done.set()

    _spin(w, "ybsan-w")
    assert done.wait(5.0)   # untracked: no HB edge back to this thread
    _ = obj.v
    assert CODE_READ_WRITE in _codes(det)


def test_positive_guarded_attr_without_lock(det):
    san.patch_class(_Guarded, guards={"v": "_lock"})
    obj = _Guarded()

    def w():
        with obj._lock:
            obj.v += 1

    ts = [_spin(w, f"ybsan-g{i}") for i in range(2)]
    for t in ts:
        t.join()            # HB edge: the bare read below cannot race
    _ = obj.v               # ...but it drops the declared guard
    assert CODE_GUARD_NOT_HELD in _codes(det)
    assert CODE_READ_WRITE not in _codes(det)


def test_positive_shadow_single_writer(det):
    san.patch_class(_Bare, shadow_spec={"x": san.SINGLE_WRITER})
    obj = _Bare()
    done = [threading.Event(), threading.Event()]

    def w(i):
        obj.x = i
        done[i].set()

    _spin(lambda: w(0), "ybsan-s0")
    _spin(lambda: w(1), "ybsan-s1")
    for e in done:
        assert e.wait(5.0)
    time.sleep(0.05)
    assert CODE_SINGLE_WRITER in _codes(det)


# --------------------------------------------------------------- negatives

def test_negative_hb_via_start_join(det):
    san.patch_class(_Bare, shadow_spec={"x": san.SINGLE_WRITER})
    obj = _Bare()
    obj.x = 1

    def w():
        obj.x = 2

    t = _spin(w, "ybsan-join")
    t.join()
    obj.x = 3               # ordered: start -> child -> join
    assert not det.reports()


def test_negative_hb_via_queue(det):
    import queue
    san.patch_class(_Bare, shadow_spec={"x": san.SINGLE_WRITER})
    obj = _Bare()
    q = queue.Queue()

    def producer():
        obj.x = 10
        q.put("token")

    def consumer():
        q.get()
        obj.x = 11          # ordered through the channel

    t1 = _spin(producer, "ybsan-prod")
    t2 = _spin(consumer, "ybsan-cons")
    t1.join()
    t2.join()
    assert not det.reports()


def test_negative_hb_via_tracked_lock(det):
    san.patch_class(_Guarded, guards={"v": "_lock"})
    obj = _Guarded()

    def w():
        for _ in range(20):
            with obj._lock:
                obj.v += 1

    ts = [_spin(w, f"ybsan-l{i}") for i in range(3)]
    for t in ts:
        t.join()
    with obj._lock:
        assert obj.v == 60
    assert not det.reports()


def test_negative_hb_via_condition(det):
    """Condition HB flows through its tracked inner lock."""
    san.patch_class(_Bare, shadow_spec={"x": san.SINGLE_WRITER})
    obj = _Bare()
    cond = threading.Condition(
        lock_rank.tracked(threading.Lock(), "ybsan.test.cond"))
    ready = [False]

    def producer():
        with cond:
            obj.x = 1
            ready[0] = True
            cond.notify()

    def consumer():
        with cond:
            while not ready[0]:
                cond.wait(5.0)
            obj.x = 2       # ordered: notify released, wait re-acquired

    t2 = _spin(consumer, "ybsan-cwait")
    t1 = _spin(producer, "ybsan-cnotify")
    t1.join()
    t2.join()
    assert not det.reports()


# ------------------------------------------------------ baseline round-trip

def test_baseline_round_trip(det, tmp_path):
    """A justified fingerprint moves a report from `new` to `known`."""
    san.patch_class(_Bare, shadow_spec={"x": san.SINGLE_WRITER})
    obj = _Bare()
    done = [threading.Event(), threading.Event()]

    def w(i):
        obj.x = i
        done[i].set()

    _spin(lambda: w(0), "ybsan-b0")
    _spin(lambda: w(1), "ybsan-b1")
    for e in done:
        assert e.wait(5.0)
    time.sleep(0.05)
    reps = det.reports()
    assert reps
    new, known = san_report.split_reports(reps, None)
    assert new and not known

    bl = tmp_path / "baseline.txt"
    bl.write_text("# --- pass: ybsan ---\n" + "\n".join(
        san_report.to_finding(r).fingerprint
        + "  # test fixture: deliberately racy"
        for r in reps) + "\n")
    new, known = san_report.split_reports(reps, str(bl))
    assert not new and len(known) == len(reps)


def test_race_reports_merge_into_lock_rank(det):
    """Latched races surface through the merged lock_rank violation
    report alongside lock-order cycles."""
    before = len(lock_rank.race_violations())
    san.patch_class(_Bare, shadow_spec={"x": san.SINGLE_WRITER})
    obj = _Bare()
    done = [threading.Event(), threading.Event()]

    def w(i):
        obj.x = i
        done[i].set()

    _spin(lambda: w(0), "ybsan-m0")
    _spin(lambda: w(1), "ybsan-m1")
    for e in done:
        assert e.wait(5.0)
    time.sleep(0.05)
    assert det.reports()
    races = lock_rank.race_violations()
    assert len(races) > before
    assert any("[ybsan/" in r for r in races[before:])
    assert races[-1] in lock_rank.violations()


# ------------------------------------------------------------ overhead bound

@pytest.mark.slow
def test_armed_overhead_bound(tmp_path):
    """Arming must cost <= 2.5x wall on a concurrency-heavy subset."""
    suites = ["tests/test_txn_coordinator.py", "tests/test_backoff.py"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("YBSAN", None)

    def run(extra_env):
        t0 = time.monotonic()
        r = subprocess.run(
            [sys.executable, "-m", "pytest", *suites, "-q",
             "-p", "no:cacheprovider", "-p", "no:randomly"],
            env=dict(env, **extra_env), capture_output=True, text=True,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert r.returncode == 0, r.stdout + r.stderr
        return time.monotonic() - t0

    cold = run({})          # warm caches so the armed run isn't penalized
    base = run({})
    armed = run({"YBSAN": "1"})
    del cold
    assert armed <= 2.5 * base, (
        f"armed {armed:.2f}s vs unarmed {base:.2f}s exceeds 2.5x")
