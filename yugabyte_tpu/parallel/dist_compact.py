"""Distributed compaction: range-repartition + per-shard merge/GC over a mesh.

The multi-chip form of the north-star kernel, in two shapes:

1. `distributed_compact` — ONE large job, key-range-sharded: each key range
   is one DEVICE of a `jax.sharding.Mesh`, and the data movement that the
   reference does with per-thread file iterators (ref:
   rocksdb/db/compaction_job.cc:330 GenSubcompactionBoundaries, :456-468)
   happens as XLA collectives over ICI:

     1. each shard samples its local route keys
     2. all_gather the samples -> identical global splitters on every shard
     3. bucket rows by destination shard; all_to_all exchanges the buckets
        (fixed per-destination capacity with all-0xFF padding rows, which
        sort to the tail and are dropped by the GC keep-mask like padding)
     4. per-shard fused radix merge + MVCC GC (ops/merge_gc.sort_and_gc)

   The input cols upload ONCE as a device-resident sharded buffer
   (explicit `NamedSharding` over the shard axis); the overflow retry
   (splitter skew blew a bucket past capacity) re-launches at doubled
   capacity FROM that resident buffer — no host re-pack, no re-upload.
   Attempts that provably cannot retry (capacity already covers every
   row, or the 64x ceiling) donate the buffer so XLA reuses its HBM for
   the exchange scratch.

2. `pooled_merge_gc` — MANY small jobs, one job per device: the
   compaction-pool wave kernel (tserver/compaction_pool.py). Concurrent
   tablets' merge+GC jobs of one shape bucket stack along the mesh axis
   and run as ONE shard_map dispatch; each slot runs the same fused
   program as the single-device path (ops/run_merge._merge_gc_runs_impl),
   so per-slot decisions are bit-identical to a sequential job — the
   multi-tablet aggregate-throughput service is a scheduling win, never a
   semantics change. Per-slot merge products stay device-resident for the
   write-through survivor-span gather, so the resident L0->L1->L2 chain
   survives sharding (the slot's device IS the tablet's cache partition).

Routing is by the first `_W_ROUTE` 32-bit words of the DOC KEY portion of
each key (words masked to doc_key_len, zero beyond it), compared
lexicographically. Every entry of one document has identical doc-key bytes
and doc_key_len, hence an identical route key — so a document's root + column
entries and all versions of a key always land on one shard and the GC segment
logic never straddles shards. Because routing is an order-preserving prefix
of the key, shards remain globally range-partitioned: shard s's keys all
sort <= shard s+1's.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # pre-0.4.35 jax exports it under experimental only
    from jax.experimental.shard_map import shard_map

from yugabyte_tpu.ops import merge_gc
from yugabyte_tpu.ops.merge_gc import (
    _ROW_DKL, _ROW_FLAGS, _ROW_KEY_LEN, _ROW_WORDS, GCParams, PAD_SENTINEL,
    StagedCols, bucket_size, build_sort_schedule, column_stats, pack_cols,
    pad_template, sort_and_gc)

# Route on up to this many leading doc-key words (16 bytes). Documents whose
# doc keys share all 16 bytes route to the same bucket; the overflow retry
# absorbs the resulting skew, so this is a perf knob, not correctness.
_W_ROUTE = 4

_SAMPLES_PER_SHARD = 64

# Capacity lattice floor + retry ceiling: capacity quantizes to powers of
# two >= _CAPACITY_MIN (the manifest's declared compile-key lattice), and
# the overflow retry doubles capacity_factor up to _MAX_CAPACITY_FACTOR
# before declaring the splitters hopeless.
_CAPACITY_MIN = 64
_MAX_CAPACITY_FACTOR = 64


def _overflow_retry_counter():
    from yugabyte_tpu.utils.metrics import kernel_metrics
    return kernel_metrics().counter(
        "dist_compact_overflow_retry_total",
        "distributed-compaction attempts re-launched at doubled "
        "per-destination capacity after a bucket overflow (splitter "
        "skew); retries re-shard from the device-resident cols")


@functools.lru_cache(maxsize=64)
def dist_compact_fn(mesh: Mesh, capacity: int, is_major: bool,
                    retain_deletes: bool = False, axis: str = "shard",
                    donate: bool = False):
    """Build (and cache) the jitted distributed compaction step for a mesh.

    Cached per (mesh, capacity, is_major, retain_deletes, axis, donate):
    rebuilding the shard_map closure per call would defeat the jit trace
    cache and re-lower the whole multi-collective program every compaction.

    Input cols: [R, n_total] sharded along dim 1; n_total = n_shards * n_local.
    Output: (cols_out [R, n_shards*capacity] sharded, keep, make_tombstone,
             overflow flag per shard, source-row index per merged position).

    donate: the caller promises the cols buffer is dead after this launch
    (an attempt that cannot be retried) — XLA then reuses its HBM for the
    exchange scratch instead of holding input + working set live together.
    """
    n_shards = mesh.devices.size

    def per_shard(cols_local, cutoff_hi, cutoff_lo, cph, cpl):
        r, n_local = cols_local.shape
        w_route = min(_W_ROUTE, r - _ROW_WORDS)
        u32max = jnp.uint32(0xFFFFFFFF)
        is_pad_in = cols_local[_ROW_KEY_LEN] == jnp.uint32(PAD_SENTINEL)
        # -- route key: doc-key words masked to doc_key_len ----------------
        # (identical across every entry/version of one document; padding
        # rows get all-0xFF route words so they route to the last shard)
        dkl = cols_local[_ROW_DKL].astype(jnp.int32)      # pad rows: -1
        words = cols_local[_ROW_WORDS:_ROW_WORDS + w_route]
        mask = merge_gc.route_word_mask(dkl, w_route)     # shared defn
        route = jnp.where(is_pad_in[None, :], u32max, words & mask)
        # -- 1/2: sample + all_gather + splitters --------------------------
        step = max(1, n_local // _SAMPLES_PER_SHARD)
        samples = route[:, ::step][:, :_SAMPLES_PER_SHARD]  # [w_route, s_loc]
        samp_pad = is_pad_in[::step][:_SAMPLES_PER_SHARD]
        g_samp = jax.lax.all_gather(samples, axis)          # [shards, w, s_loc]
        g_samp = jnp.moveaxis(g_samp, 1, 0).reshape(w_route, -1)
        g_pad = jax.lax.all_gather(samp_pad, axis).reshape(-1)
        # lex sort on the route words with the pad flag as final tiebreak,
        # so padding samples sort strictly after real ones even on 0xFF ties
        sorted_ops = jax.lax.sort(
            [g_samp[i] for i in range(w_route)] + [g_pad.astype(jnp.uint32)],
            num_keys=w_route + 1)
        # exact real-sample count (no row-count arithmetic -> no overflow)
        n_real_samples = jnp.maximum(
            g_pad.shape[0] - jnp.sum(g_pad.astype(jnp.int32)), 1)
        qs = (jnp.arange(1, n_shards) * n_real_samples) // n_shards
        splitters = [sorted_ops[i][qs] for i in range(w_route)]  # each [S-1]
        # -- 3: bucket + exchange ------------------------------------------
        # dest = number of splitters lexicographically <= route key
        lt = jnp.zeros((n_local, n_shards - 1), bool)
        eq = jnp.ones((n_local, n_shards - 1), bool)
        for i in range(w_route):
            rw, sw = route[i][:, None], splitters[i][None, :]
            lt = lt | (eq & (rw < sw))
            eq = eq & (rw == sw)
        dest = jnp.sum(~lt, axis=1)                          # [n_local]
        order = jnp.argsort(dest)                            # stable
        # input padding rows route to the LAST shard but are excluded from
        # counts so they can't trigger a spurious overflow
        real_dest = jnp.where(is_pad_in, n_shards, dest)     # bin n_shards: pad
        counts = jnp.bincount(real_dest, length=n_shards + 1)[:n_shards]
        all_counts = jnp.bincount(dest, length=n_shards)
        offsets = jnp.concatenate(
            [jnp.zeros(1, all_counts.dtype), jnp.cumsum(all_counts)[:-1]])
        overflow = jnp.any(counts > capacity)
        pos_in_group = jnp.arange(n_local) - offsets[dest[order]]
        valid = pos_in_group < capacity
        # rows past capacity go to a dump column that is sliced off before
        # the exchange — they can never clobber a real slot
        slot = jnp.where(valid, dest[order] * capacity + pos_in_group,
                         n_shards * capacity)
        # the global input index rides the exchange as one extra u32 row so
        # the host can map every surviving (shuffled, merged) row back to
        # its source slab row — output VALUES are gathered host-side from
        # exactly these indices (values never cross the mesh)
        idx_local = (jax.lax.axis_index(axis).astype(jnp.uint32)
                     * jnp.uint32(n_local)
                     + jnp.arange(n_local, dtype=jnp.uint32))
        ship = jnp.concatenate([cols_local, idx_local[None, :]], axis=0)
        pad_col = jnp.concatenate(
            [jnp.asarray(pad_template(r)), jnp.full(1, 0xFFFFFFFF,
                                                    jnp.uint32)])
        send = jnp.tile(pad_col[:, None], (1, n_shards * capacity + 1))
        send = send.at[:, slot].set(ship[:, order])
        send3 = send[:, :-1].reshape(r + 1, n_shards, capacity)
        recv = jax.lax.all_to_all(send3, axis, split_axis=1, concat_axis=1,
                                  tiled=False)
        recv = recv.reshape(r + 1, n_shards * capacity)
        cols_shard, idx_shard = recv[:r], recv[r]
        # -- 4: local fused merge + GC -------------------------------------
        perm, keep, mk = sort_and_gc(cols_shard, cutoff_hi, cutoff_lo, cph, cpl,
                                     w=r - _ROW_WORDS, is_major=is_major,
                                     retain_deletes=retain_deletes)
        out = cols_shard[:, perm]
        # padding rows are identified explicitly by the key_len sentinel
        is_pad = out[_ROW_KEY_LEN] == jnp.uint32(PAD_SENTINEL)
        keep = keep & ~is_pad
        return out, keep, mk, overflow[None], idx_shard[perm]

    spec = P(None, axis)
    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(spec, P(), P(), P(), P()),
        out_specs=(spec, P(axis), P(axis), P(axis), P(axis)))
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def _quantized_capacity(n_local: int, n_shards: int, factor: float) -> int:
    """Per-destination exchange capacity on the power-of-two lattice.

    Raw rows-per-destination varies per job and would mint a fresh
    shard_map executable per size; quantized, a tablet's whole compaction
    lifetime (including doubling retries) stays on a handful of compile
    keys — the manifest's declared dist_compact lattice."""
    cap_raw = max(_CAPACITY_MIN, int(n_local / n_shards * factor))
    return 1 << (cap_raw - 1).bit_length()


@dataclass
class DistOutputs:
    """Device-resident products of one distributed compaction step: the
    merged output cols (still sharded over the mesh) plus keep/tombstone
    masks, for zero-reupload survivor-span staging (the write-through
    path of the dist-native job — storage/compaction.py installs each
    output file's span into the HBM slab cache from HERE, never from a
    host round trip)."""
    cols_dev: object           # [r, S*capacity] sharded, merged order
    keep_dev: object           # [S*capacity] sharded
    mk_dev: object             # [S*capacity] sharded
    w: int                     # key words (r - _ROW_WORDS)
    capacity: int
    n_shards: int
    _pos_all: object = field(default=None, repr=False)

    def bucket_key(self) -> Tuple[int, int]:
        """Quarantine vocabulary for the dist family: (n_shards,
        capacity) — the dominant compile-key pair of dist_compact_fn."""
        return (self.n_shards, self.capacity)

    def gather_span(self, start: int, end: int) -> StagedCols:
        """Stage ONE output file's [start, end) survivor span directly
        from the sharded device outputs — the dist twin of
        ops/run_merge.gather_staged_output_span. The gather crosses shard
        boundaries as XLA collectives; the result is committed to the
        first mesh device so later merges see a single-device input."""
        from yugabyte_tpu.ops.run_merge import _survivor_positions
        if self._pos_all is None:
            self._pos_all = _survivor_positions(self.keep_dev)
        n_out = end - start
        n_out_pad = bucket_size(n_out)
        out = _dist_gather_span(self.cols_dev, self._pos_all, self.mk_dev,
                                jnp.int32(start), jnp.int32(end),
                                n_out_pad)
        r = _ROW_WORDS + self.w
        sort_rows, n_sort = build_sort_schedule(self.w,
                                               np.zeros(r, dtype=bool))
        return StagedCols(out, sort_rows, n_sort, n_out, n_out_pad,
                          self.w, None, None)


@functools.partial(jax.jit, static_argnames=("n_out_pad",))
def _dist_gather_span(cols, pos_all, mk, start, end, n_out_pad: int):
    """Gather survivors [start, end) of the sharded merged order into a
    padded StagedCols matrix (single logical result; the cross-shard
    gather lowers to collectives). Mirrors _gather_staged_output's
    tombstone-flag rewrite so the staged entry matches the SST bytes the
    shell writes for the same span."""
    from yugabyte_tpu.ops.slabs import FLAG_TOMBSTONE
    n_pad = cols.shape[1]
    idx = start + jnp.arange(n_out_pad, dtype=jnp.int32)
    valid = idx < end
    pos = pos_all[jnp.clip(idx, 0, n_pad - 1)]
    sub = cols[:, pos]
    fl = sub[_ROW_FLAGS] | jnp.where(mk[pos] & valid,
                                     jnp.uint32(FLAG_TOMBSTONE),
                                     jnp.uint32(0))
    sub = sub.at[_ROW_FLAGS].set(fl)
    pad_col = jnp.asarray(pad_template(cols.shape[0]))
    return jnp.where(valid[None, :], sub, pad_col[:, None])


def stage_sharded_cols(slab, mesh: Mesh, axis: str = "shard"):
    """Pack a slab's key columns ONCE and upload them ONCE as a
    device-resident buffer sharded over the mesh. Returns (cols_dev,
    n_local). Overflow retries re-shard from this buffer instead of
    re-packing and re-uploading the whole slab from host."""
    n_shards = mesh.devices.size
    cols = pack_cols(slab)[0]
    # pad the column count to a multiple of shards (pack_cols gives powers
    # of two; mesh sizes are powers of two on TPU pods)
    if cols.shape[1] % n_shards:
        extra = n_shards - (cols.shape[1] % n_shards)
        pad_block = np.tile(pad_template(cols.shape[0])[:, None], (1, extra))
        cols = np.concatenate([cols, pad_block], axis=1)
    cols_dev = jax.device_put(cols, NamedSharding(mesh, P(None, axis)))
    return cols_dev, cols.shape[1] // n_shards


def distributed_compact(slab, params: GCParams, mesh: Mesh, axis: str = "shard",
                        capacity_factor: float = 2.0):
    """Host wrapper: pack a slab, shard it over the mesh, run the step.

    Returns (cols_out, keep, make_tombstone, src_idx) as host arrays;
    cols_out rows follow ops/merge_gc layout, in globally range-partitioned
    sorted order (shard s holds keys <= shard s+1's); src_idx[i] is the
    input slab row that produced merged position i (valid where keep/mk
    apply — padding positions carry sentinel indices and keep=False)."""
    (out, keep, mk, src_idx), _outputs = _distributed_compact_impl(
        slab, params, mesh, axis, capacity_factor, want_outputs=False)
    return np.asarray(out), keep, mk, src_idx


def distributed_compact_with_outputs(slab, params: GCParams, mesh: Mesh,
                                     axis: str = "shard",
                                     capacity_factor: float = 2.0
                                     ) -> Tuple[np.ndarray, np.ndarray,
                                                np.ndarray, DistOutputs]:
    """The dist-native form: decisions as host arrays (keep, mk, src_idx)
    plus the DEVICE-RESIDENT merged outputs for write-through span
    staging — the full output cols never cross back to the host."""
    (_out, keep, mk, src_idx), outputs = _distributed_compact_impl(
        slab, params, mesh, axis, capacity_factor, want_outputs=True)
    return keep, mk, src_idx, outputs


def _distributed_compact_impl(slab, params: GCParams, mesh: Mesh,
                              axis: str, capacity_factor: float,
                              want_outputs: bool):
    import time as _time
    from yugabyte_tpu.ops import device_faults
    from yugabyte_tpu.ops.run_merge import _donation_supported
    from yugabyte_tpu.utils.metrics import (record_kernel_dispatch,
                                            record_pipeline_stage)
    t0 = _time.monotonic()
    n_shards = mesh.devices.size
    cols_dev, n_local = stage_sharded_cols(slab, mesh, axis)
    cutoff = params.history_cutoff_ht
    cutoff_phys = cutoff >> 12
    cut_args = (jnp.uint32(cutoff >> 32), jnp.uint32(cutoff & 0xFFFFFFFF),
                jnp.uint32(cutoff_phys >> 20),
                jnp.uint32(cutoff_phys & 0xFFFFF))
    # ONE host stage per job: pack + upload happen once, regardless of
    # how many capacity-doubling retries follow (the old recursive form
    # re-packed per attempt and double-counted this stage)
    record_pipeline_stage("host", (_time.monotonic() - t0) * 1e3)
    factor = capacity_factor
    while True:
        capacity = _quantized_capacity(n_local, n_shards, factor)
        # an attempt that provably cannot overflow (capacity covers every
        # real row) or that has exhausted the retry ladder will never
        # need the input again: donate it so XLA reuses its HBM for the
        # exchange scratch (no-op on backends that ignore donation)
        no_retry = (capacity >= slab.n or factor >= _MAX_CAPACITY_FACTOR)
        donate = no_retry and _donation_supported()
        fn = dist_compact_fn(mesh, capacity, params.is_major_compaction,
                             params.retain_deletes, axis, donate)
        t_dev = _time.monotonic()
        # fault-injection site: a real XLA compile/dispatch failure of the
        # sharded program surfaces here (containment in storage/compaction)
        device_faults.maybe_fault("dispatch")
        out, keep, mk, overflow, src_idx = fn(cols_dev, *cut_args)
        if donate:
            cols_dev = None   # consumed by the launch
        # kick every shard output's D2H in one async wave (the overflow
        # word decides retry first, so the big buffers ride the link
        # while the host inspects the small one)
        for a in ((keep, mk, src_idx) if want_outputs
                  else (out, keep, mk, src_idx)):
            try:
                a.copy_to_host_async()
            except (AttributeError, NotImplementedError):  # yblint: contained(backend lacks async D2H; the sync download below covers it)
                pass
        device_faults.maybe_fault("result")
        ovf = bool(np.any(np.asarray(overflow)))
        # the device stage is recorded per ATTEMPT — a failed (overflowed)
        # attempt burns real device wall and must show in the profile
        record_pipeline_stage("device", (_time.monotonic() - t_dev) * 1e3)
        if not ovf:
            break
        if factor >= _MAX_CAPACITY_FACTOR:
            raise RuntimeError(
                f"distributed compaction bucket overflow at "
                f"{_MAX_CAPACITY_FACTOR}x")
        _overflow_retry_counter().increment()
        factor *= 2
    t_host = _time.monotonic()
    keep_h = np.asarray(keep)
    mk_h = np.asarray(mk)
    src_h = np.asarray(src_idx).astype(np.int64)
    outputs = None
    if want_outputs:
        outputs = DistOutputs(out, keep, mk,
                              w=int(out.shape[0]) - _ROW_WORDS,
                              capacity=capacity, n_shards=n_shards)
    record_pipeline_stage("host", (_time.monotonic() - t_host) * 1e3)
    record_kernel_dispatch("kernel_dist_compact", slab.n,
                           n_shards * n_local,
                           (_time.monotonic() - t0) * 1e3)
    return (out, keep_h, mk_h, src_h), outputs


# ---------------------------------------------------------------------------
# Pooled multi-job waves: one tablet job per mesh device.
#
# The compaction pool (tserver/compaction_pool.py) packs queued jobs of one
# shape bucket into the slots of a single shard_map dispatch: slot i's
# device runs job i's complete fused merge+GC (the SAME program as the
# single-device path, so decisions are bit-identical), and only the packed
# decision words come back. On a real mesh this is J-way device
# parallelism; on any backend it amortizes the per-job dispatch + transfer
# overhead across the wave.

@functools.lru_cache(maxsize=64)
def pool_wave_fn(mesh: Mesh, k_pad: int, m: int, w: int, n_cmp: int,
                 is_major: bool, retain_deletes: bool, lexsort: bool,
                 axis: str = "shard"):
    """One compaction-pool wave: mesh-size independent merge+GC jobs of
    one (k_pad, m, w, n_cmp) bucket, one job per device.

    Inputs (global shapes; leading axis = slot): cols [S, r, n],
    cmp_rows [S, n_cmp], pos [n] (replicated), cut [S, 4] (the per-job
    cutoff words). Output: packed decisions [S, n//32, 2+b] plus the
    per-slot device-resident merge products (perm/keep/mk) for
    write-through survivor staging."""
    from yugabyte_tpu.ops import run_merge

    def per_slot(cols, cmp_rows, pos, cut):
        packed, perm, keep, mk = run_merge._merge_gc_runs_impl(
            cols[0], cmp_rows[0], pos, cut[0, 0], cut[0, 1], cut[0, 2],
            cut[0, 3], k_pad=k_pad, m=m, w=w, n_cmp=n_cmp,
            is_major=is_major, retain_deletes=retain_deletes,
            snapshot=False, lexsort=lexsort)
        return packed[None], perm[None], keep[None], mk[None]

    spec3 = P(axis, None, None)
    spec2 = P(axis, None)
    fn = shard_map(per_slot, mesh=mesh,
                   in_specs=(spec3, spec2, P(), spec2),
                   out_specs=(spec3, spec2, spec2, spec2))
    return jax.jit(fn)


def pool_slot_bucket(slabs: Sequence) -> Tuple[int, int, int]:
    """(k_pad, m, w) shape bucket a job's runs stage into — computed the
    same way stage_pool_slot lays the matrix out (greedy run packing
    included) WITHOUT packing anything, so the pool's wave grouping and
    the actual staging agree on the bucket."""
    from yugabyte_tpu.ops.run_merge import (packed_run_ns, quantize_width,
                                            run_bucket)
    live = [s for s in slabs if s.n]
    ns = packed_run_ns([s.n for s in live])
    k = len(ns)
    k_pad = 1 << max(0, (k - 1).bit_length()) if k > 1 else 1
    m = max(run_bucket(n) for n in ns)
    w = quantize_width(max(int(s.width_words) for s in live))
    return (k_pad, m, w)


def stage_pool_slot(slabs: Sequence, k_pad: int, m: int, w: int):
    """Pack one job's runs into a HOST [r, k_pad*m] run-major matrix (the
    wave stacks these and uploads once). Returns a StagedRuns whose
    cols_dev is the host ndarray — pooled_merge_gc moves it to the slot's
    device; everything else (run_ns/run_maps/cmp schedule) is exactly
    what stage_runs_from_slabs would record for the same job."""
    from yugabyte_tpu.ops.run_merge import (StagedRuns, _cmp_schedule,
                                            _merge_const_stats,
                                            pack_runs_greedy)
    live, run_maps = pack_runs_greedy([s for s in slabs if s.n])
    r = _ROW_WORDS + w
    cols = np.empty((r, k_pad * m), dtype=np.uint32)
    cols[:] = pad_template(r)[:, None]
    stats = []
    for i, s in enumerate(live):
        sub, n_s, _, _ = pack_cols(s, n_pad_override=s.n, w_pad_override=w)
        cols[:, i * m: i * m + n_s] = sub
        stats.append(column_stats(sub, n_s))
    cmp_rows, n_cmp = _cmp_schedule(w, _merge_const_stats(stats, r))
    return StagedRuns(cols, m, k_pad, w, [s.n for s in live],
                      cmp_rows, n_cmp, run_maps=run_maps)


class PoolWaveHandle:
    """Result of one pooled wave: per-job host decisions plus per-slot
    device-resident merge products for write-through survivor staging."""

    def __init__(self, decisions, metas, cols_dev, perm_dev, keep_dev,
                 mk_dev, w: int, n_pad: int):
        self.decisions = decisions     # [(perm, keep, mk)] per job
        self._metas = metas
        self._cols_dev = cols_dev
        self._perm_dev = perm_dev
        self._keep_dev = keep_dev
        self._mk_dev = mk_dev
        self._w = w
        self._n_pad = n_pad
        self._pos_all: dict = {}

    def _slot_piece(self, arr, slot: int):
        """The [1, ...] per-device piece of a wave output for one slot
        (looked up by shard index, not list position — addressable-shard
        order is a backend detail)."""
        for sh in arr.addressable_shards:
            idx = sh.index[0]
            if idx.start == slot:
                return sh.data
        raise KeyError(f"slot {slot} not addressable")

    def gather_span(self, slot: int, start: int, end: int) -> StagedCols:
        """Stage job `slot`'s [start, end) survivor span directly from
        that slot's device — the pooled twin of
        ops/run_merge.gather_staged_output_span: the tablet's output
        cache entry is gathered on ITS shard of the mesh, so the
        resident chain survives sharding."""
        from yugabyte_tpu.ops.run_merge import (_gather_staged_output,
                                                _survivor_positions)
        cols = self._slot_piece(self._cols_dev, slot)[0]
        perm = self._slot_piece(self._perm_dev, slot)[0]
        mk = self._slot_piece(self._mk_dev, slot)[0]
        pos_all = self._pos_all.get(slot)
        if pos_all is None:
            keep = self._slot_piece(self._keep_dev, slot)[0]
            pos_all = self._pos_all[slot] = _survivor_positions(keep)
        n_out = end - start
        n_out_pad = bucket_size(n_out)
        out = _gather_staged_output(cols, perm, pos_all, mk,
                                    jnp.int32(start), jnp.int32(end),
                                    n_out_pad)
        r = _ROW_WORDS + self._w
        sort_rows, n_sort = build_sort_schedule(self._w,
                                               np.zeros(r, dtype=bool))
        return StagedCols(out, sort_rows, n_sort, n_out, n_out_pad,
                          self._w, None, None)


def pooled_merge_gc(mesh: Mesh, jobs: Sequence[Tuple[object, GCParams]],
                    axis: str = "shard") -> PoolWaveHandle:
    """Run up to mesh-size merge+GC jobs as ONE wave dispatch.

    jobs: [(staged, params)] where staged is a StagedRuns from
    stage_pool_slot (host cols) or stage_runs_from_staged (device cols on
    the slot's cache partition — the resident hit path). All jobs must
    share one (k_pad, m, w) bucket and one (is_major, retain_deletes)
    pair — the pool's wave builder groups by exactly this key. Unfilled
    slots carry all-pad matrices (they sort trivially and keep nothing).

    Decisions per job are bit-identical to a single-device
    launch_merge_gc of the same staged runs: each slot runs the same
    fused program with the same comparator, schedule quantization and
    packed-decision encoding."""
    import time as _time
    from yugabyte_tpu.ops import device_faults, run_merge
    from yugabyte_tpu.utils.metrics import record_kernel_dispatch

    t0 = _time.monotonic()
    n_slots = mesh.devices.size
    assert 0 < len(jobs) <= n_slots, (len(jobs), n_slots)
    k_pad, m, w = (jobs[0][0].k_pad, jobs[0][0].m, jobs[0][0].w)
    p0 = jobs[0][1]
    for st, p in jobs:
        assert (st.k_pad, st.m, st.w) == (k_pad, m, w), \
            "wave jobs must share one shape bucket"
        assert (p.is_major_compaction, p.retain_deletes) == \
            (p0.is_major_compaction, p0.retain_deletes), \
            "wave jobs must share GC statics"
    r = _ROW_WORDS + w
    n = k_pad * m
    # one wave-wide n_cmp (the max of the jobs' lattice points): padding a
    # job's schedule by repeating its last row is a comparator no-op, so
    # only the shared static changes
    n_cmp = max(st.n_cmp for st, _p in jobs)
    cmp_all = np.empty((n_slots, n_cmp), dtype=np.int32)
    cut_all = np.zeros((n_slots, 4), dtype=np.uint32)
    devices = list(mesh.devices.flat)
    pieces: List[object] = []
    any_device_staged = any(not isinstance(st.cols_dev, np.ndarray)
                            for st, _p in jobs)
    host_stack = (None if any_device_staged
                  else np.empty((n_slots, r, n), dtype=np.uint32))
    pad_mat = None
    for i in range(n_slots):
        if i < len(jobs):
            st, p = jobs[i]
            rows = np.asarray(st.cmp_rows, dtype=np.int32)
            if len(rows) < n_cmp:
                rows = np.concatenate(
                    [rows, np.full(n_cmp - len(rows), rows[-1], np.int32)])
            cmp_all[i] = rows[:n_cmp]
            cutoff = int(p.history_cutoff_ht)
            cph = cutoff >> 12
            cut_all[i] = ((cutoff >> 32) & 0xFFFFFFFF,
                          cutoff & 0xFFFFFFFF,
                          (cph >> 20) & 0xFFFFFFFF, cph & 0xFFFFF)
            if host_stack is not None:
                host_stack[i] = st.cols_dev
            else:
                cd = st.cols_dev
                if isinstance(cd, np.ndarray):
                    pieces.append(jax.device_put(cd[None], devices[i]))
                else:
                    # resident hit: the job restaged from its shard's
                    # cache partition; move only if it sits elsewhere
                    # (a device-to-device copy, never through the host)
                    piece = jnp.expand_dims(cd, 0)
                    pieces.append(jax.device_put(piece, devices[i]))
        else:
            cmp_all[i] = np.int32(_ROW_KEY_LEN)
            if host_stack is not None:
                if pad_mat is None:
                    pad_mat = np.broadcast_to(pad_template(r)[:, None],
                                              (r, n))
                host_stack[i] = pad_mat
            else:
                if pad_mat is None:
                    pad_mat = np.broadcast_to(pad_template(r)[:, None],
                                              (r, n)).copy()
                pieces.append(jax.device_put(pad_mat[None], devices[i]))
    sharding3 = NamedSharding(mesh, P(axis, None, None))
    if host_stack is not None:
        cols_dev = jax.device_put(host_stack, sharding3)
    else:
        cols_dev = jax.make_array_from_single_device_arrays(
            (n_slots, r, n), sharding3, pieces)
    sharding2 = NamedSharding(mesh, P(axis, None))
    cmp_dev = jax.device_put(cmp_all, sharding2)
    cut_dev = jax.device_put(cut_all, sharding2)
    pos = np.arange(n, dtype=np.int32)
    lexsort = run_merge._use_lexsort()
    fn = pool_wave_fn(mesh, k_pad, m, w, n_cmp, p0.is_major_compaction,
                      p0.retain_deletes, lexsort, axis)
    run_merge._record_bucket(("pool_wave", n_slots, k_pad, m, w, n_cmp,
                              p0.is_major_compaction, p0.retain_deletes,
                              lexsort))
    # fault-injection sites: the wave's containment (the pool demotes the
    # bucket on the health board and completes every wave job natively)
    # hooks here; the bucket lets a "slow" nemesis throttle one (k, m)
    device_faults.maybe_fault("dispatch", bucket=(k_pad, m))
    packed, perm, keep, mk = fn(cols_dev, cmp_dev, pos, cut_dev)
    try:
        packed.copy_to_host_async()
    except (AttributeError, NotImplementedError):  # yblint: contained(backend lacks async D2H; the sync download below covers it)
        pass
    device_faults.maybe_fault("result")
    packed_h = np.asarray(packed)
    decisions = [run_merge._decode_packed(packed_h[i], st)
                 for i, (st, _p) in enumerate(jobs)]
    record_kernel_dispatch("kernel_pool_wave",
                           sum(st.n for st, _p in jobs), n_slots * n,
                           (_time.monotonic() - t0) * 1e3)
    return PoolWaveHandle(decisions, [st for st, _p in jobs], cols_dev,
                          perm, keep, mk, w, n)


# ---------------------------------------------------------------------------
# Prewarm: the dist/pool families land inside the PR-7 manifest/budget/
# prewarm discipline like every other kernel family.

# The declared compile-key lattice (mirrored by the kernel manifest's
# dist_compact entries): per-destination capacities universal compaction
# actually produces for flush-sized through once-compacted runs, times
# both is_major variants, on whatever mesh the server resolved.
_PREWARM_CAPACITIES = (1 << 13, 1 << 14)
_PREWARM_POOL_SHAPES = ((2, 1 << 16, 4, 8), (4, 1 << 16, 4, 8))


def prewarm_dist_compact(mesh: Mesh,
                         capacities: Optional[Sequence[int]] = None,
                         pool_shapes: Optional[Sequence[Tuple[int, int,
                                                              int, int]]]
                         = None) -> int:
    """Ahead-of-traffic compile of the mesh families: the key-range
    sharded dist_compact step per (capacity, is_major) and the pool wave
    program per (bucket, is_major). Run by PrewarmKernelsOp when the
    server resolved a >1-device mesh; returns executables compiled."""
    from yugabyte_tpu.ops import run_merge
    caps = tuple(capacities) if capacities is not None \
        else _PREWARM_CAPACITIES
    shapes = tuple(pool_shapes) if pool_shapes is not None \
        else _PREWARM_POOL_SHAPES
    n_shards = mesh.devices.size
    lexsort = run_merge._use_lexsort()
    compiled = 0

    def _warm(what: str, lower_fn) -> int:
        try:
            lower_fn()
            return 1
        except Exception as e:  # noqa: BLE001 — prewarm must never block
            import sys as _sys                       # server startup
            print(f"[dist_compact] prewarm of {what} failed: {e!r}",
                  file=_sys.stderr, flush=True)
            return 0

    u32 = jax.ShapeDtypeStruct((), jnp.uint32)
    for capacity in caps:
        r = _ROW_WORDS + 4
        n_total = n_shards * max(capacity, _CAPACITY_MIN)
        cols = jax.ShapeDtypeStruct((r, n_total), jnp.uint32)
        for is_major in (True, False):
            compiled += _warm(
                f"dist_compact (n_shards={n_shards} capacity={capacity} "
                f"is_major={is_major})",
                lambda: dist_compact_fn(mesh, capacity, is_major)
                .lower(cols, u32, u32, u32, u32).compile())
    for (k_pad, m, w, n_cmp) in shapes:
        r = _ROW_WORDS + w
        n = k_pad * m
        args = (jax.ShapeDtypeStruct((n_shards, r, n), jnp.uint32),
                jax.ShapeDtypeStruct((n_shards, n_cmp), jnp.int32),
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((n_shards, 4), jnp.uint32))
        for is_major in (True, False):
            got = _warm(
                f"pool_wave (slots={n_shards} k_pad={k_pad} m={m} w={w} "
                f"is_major={is_major})",
                lambda: pool_wave_fn(mesh, k_pad, m, w, n_cmp, is_major,
                                     False, lexsort)
                .lower(*args).compile())
            if got:
                run_merge._record_bucket(
                    ("pool_wave", n_shards, k_pad, m, w, n_cmp, is_major,
                     False, lexsort))
            compiled += got
    return compiled
