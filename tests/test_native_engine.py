"""Native compaction shell (native/compaction_engine.cc) equivalence tests.

The C++ byte path must produce BYTE-IDENTICAL output SSTs to the Python
shell + JAX kernel route — same data files, same base files (index, bloom,
props) — across compression, TTL-rewrite and multi-output splits.
"""

import os
import tempfile

import numpy as np
import pytest

from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.ops.slabs import FLAG_HAS_TTL, KVSlab, ValueArray
from yugabyte_tpu.storage import compaction as compaction_mod
from yugabyte_tpu.storage import native_engine
from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter
from yugabyte_tpu.utils import flags

pytestmark = pytest.mark.skipif(not native_engine.available(),
                                reason="native engine unavailable")


def _write_runs(workdir, runs):
    paths = []
    for i, slab in enumerate(runs):
        p = os.path.join(workdir, f"in{i:03d}.sst")
        SSTWriter(p).write(slab, Frontier())
        paths.append(p)
    return [SSTReader(p) for p in paths]


def _mk_run(rng, n, key_space, value_bytes=32, ttl_frac=0.0):
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from test_run_merge import _make_run
    slab = _make_run(rng, n, key_space, ttl_frac=ttl_frac)
    data = rng.integers(0, 256, size=n * value_bytes, dtype=np.uint8)
    offs = np.arange(n + 1, dtype=np.int64) * value_bytes
    slab.values = ValueArray(data, offs)
    return slab


def _run_both(readers, cutoff, is_major, tmp, block_entries=512):
    ids_n = iter(range(1, 500))
    ids_p = iter(range(1, 500))
    nat_dir = os.path.join(tmp, "nat")
    py_dir = os.path.join(tmp, "py")
    os.makedirs(nat_dir)
    os.makedirs(py_dir)
    rn = compaction_mod._run_native_job(
        readers, nat_dir, lambda: next(ids_n), cutoff, is_major, False,
        block_entries)
    rp = compaction_mod.run_compaction_job(
        readers, py_dir, lambda: next(ids_p), cutoff, is_major,
        block_entries=block_entries, device=None)
    assert rn.rows_in == rp.rows_in
    assert rn.rows_out == rp.rows_out
    assert len(rn.outputs) == len(rp.outputs)
    for (_, b1, p1), (_, b2, p2) in zip(rn.outputs, rp.outputs):
        with open(b1 + ".sblock.0", "rb") as f1, \
                open(b2 + ".sblock.0", "rb") as f2:
            assert f1.read() == f2.read(), "data file mismatch"
        with open(b1, "rb") as f1, open(b2, "rb") as f2:
            assert f1.read() == f2.read(), "base file mismatch"
    return rn


def test_byte_identical_basic(tmp_path):
    rng = np.random.default_rng(5)
    runs = [_mk_run(rng, int(rng.integers(200, 800)), 120)
            for _ in range(4)]
    readers = _write_runs(str(tmp_path), runs)
    _run_both(readers, (1 << 21) << 12, True, str(tmp_path))
    for r in readers:
        r.close()


def test_byte_identical_ttl_rewrite(tmp_path):
    """Minor compaction TTL expiry rewrites values as tombstones in both."""
    rng = np.random.default_rng(6)
    runs = [_mk_run(rng, 400, 60, ttl_frac=0.5) for _ in range(3)]
    readers = _write_runs(str(tmp_path), runs)
    rn = _run_both(readers, (1 << 22) << 12, False, str(tmp_path))
    assert rn.rows_out > 0
    for r in readers:
        r.close()


def test_multi_output_split(tmp_path):
    rng = np.random.default_rng(7)
    runs = [_mk_run(rng, 600, 4000) for _ in range(3)]
    readers = _write_runs(str(tmp_path), runs)
    old = flags.get_flag("compaction_max_output_entries_per_sst")
    flags.set_flag("compaction_max_output_entries_per_sst", 500)
    try:
        rn = _run_both(readers, (1 << 21) << 12, True, str(tmp_path))
        assert len(rn.outputs) >= 2
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old)
    for r in readers:
        r.close()


def test_multi_output_split_with_ttl_rewrite(tmp_path):
    """Regression: surv_mk is survivor-absolute — output files after the
    first must read tombstone-rewrite flags from absolute positions, not
    file-relative ones (caught in round-3 review; silent corruption)."""
    rng = np.random.default_rng(9)
    runs = [_mk_run(rng, 500, 3000, ttl_frac=0.5) for _ in range(3)]
    readers = _write_runs(str(tmp_path), runs)
    old = flags.get_flag("compaction_max_output_entries_per_sst")
    flags.set_flag("compaction_max_output_entries_per_sst", 400)
    try:
        rn = _run_both(readers, (1 << 22) << 12, False, str(tmp_path))
        assert len(rn.outputs) >= 2
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old)
    for r in readers:
        r.close()


def test_outputs_reopen_and_read(tmp_path):
    """Native outputs must be readable by the Python SSTReader path."""
    rng = np.random.default_rng(8)
    runs = [_mk_run(rng, 300, 50) for _ in range(3)]
    readers = _write_runs(str(tmp_path), runs)
    ids = iter(range(1, 50))
    out_dir = os.path.join(str(tmp_path), "out")
    os.makedirs(out_dir)
    rn = compaction_mod._run_native_job(
        readers, out_dir, lambda: next(ids), (1 << 21) << 12, True, False,
        256)
    total = 0
    for _, base, props in rn.outputs:
        rd = SSTReader(base)
        slab = rd.read_all()
        assert slab.n == props.n_entries
        # bloom must answer positively for every doc key it holds
        for i in range(0, slab.n, 37):
            dk = slab.key_bytes(i)[: int(slab.doc_key_len[i])]
            assert rd.may_contain_doc(dk)
        total += slab.n
        rd.close()
    assert total == rn.rows_out
    for r in readers:
        r.close()
