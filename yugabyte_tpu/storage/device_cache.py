"""Device-resident slab cache: SST key columns pinned in TPU HBM.

The TPU-native analog of the reference's block cache (ref:
rocksdb/util/lru_cache.cc) — but where the reference caches decoded blocks in
host RAM to avoid disk reads, this caches *staged key-column matrices* in
device HBM to avoid host->device transfers, which dominate compaction cost on
a transfer-limited interconnect. Flush and compaction write-through: every
new SST's key columns are staged once, so steady-state compaction finds all
inputs already resident and only ships back the (bit-packed) keep masks.

Residency is a real multi-level set, not a flat LRU: entries carry the LSM
level of the file they stage (flush outputs are level 0; a compaction output
is one above its deepest input), and capacity eviction prefers the SHALLOW
levels — an L0 slab is small, short-lived (the next pick consumes and drops
it) and cheap to re-stage, while an L2 base run is the expensive thing the
chained L0->L1->L2 path exists to keep in HBM. Entries referenced by an
in-flight compaction are PINNED so eviction can never race a running merge.

Values stay host-side: merge+GC only permutes and drops entries, so value
bytes never need to cross to the device at all (the original sidecar
insight, SURVEY.md section 2.7).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from yugabyte_tpu.ops.merge_gc import (
    _ROW_WORDS, StagedCols, bucket_size, build_sort_schedule,
    pad_template, stage_slab)
from yugabyte_tpu.ops.slabs import KVSlab
from yugabyte_tpu.utils import flags

flags.define_flag("device_cache_capacity_bytes", 4 << 30,
                  "HBM budget for the device-resident slab cache "
                  "(staged SST key columns); eviction prefers shallow "
                  "levels and never touches pinned entries")

CacheKey = Tuple[str, int]  # (namespace, file_id) — file ids are per-DB


@dataclass
class _Resident:
    """One cache entry: the staged columns plus residency metadata."""
    staged: StagedCols
    level: int = 0      # LSM level of the staged file (0 = flush output)
    pins: int = 0       # in-flight compactions reading this entry
    bytes: int = 0      # nbytes RECORDED in _used (vals staging grows
    #                     an entry in place; eviction must subtract what
    #                     was added, not what is there now)


class DeviceSlabCache:
    """Server-wide cache; keys are namespaced per DB because VersionSet file
    ids are only unique within one DB (like the reference's per-DB file
    numbers under a shared block cache)."""

    def __init__(self, device=None, capacity_bytes: Optional[int] = None):
        from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
        from yugabyte_tpu.utils import lock_rank
        self.device = device
        self.capacity = (capacity_bytes if capacity_bytes is not None
                         else flags.get_flag("device_cache_capacity_bytes"))
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "device_cache.slab_lock")
        self._map: "OrderedDict[CacheKey, _Resident]" = \
            OrderedDict()                  # guarded-by: _lock
        self._used = 0                     # guarded-by: _lock
        # per-instance ints (tests diff fresh caches) + process-wide
        # registry counters so the hit ratio is scrapeable
        self.hits = 0                      # guarded-by: _lock
        self.misses = 0                    # guarded-by: _lock
        self.evictions = 0                 # guarded-by: _lock
        e = ROOT_REGISTRY.entity("server", "device_cache")
        self._c_hits = e.counter("device_cache_hits_total",
                                 "HBM slab cache hits")
        self._c_misses = e.counter("device_cache_misses_total",
                                   "HBM slab cache misses")
        self._c_evict = e.counter("device_cache_evictions_total",
                                  "entries evicted under HBM pressure")
        self._c_read_stage = e.counter(
            "device_cache_read_stage_total",
            "entries staged by the SERVE path (batched point reads / "
            "scans) on a residency miss — write-through from flush and "
            "compaction should keep this near zero in steady state")
        self._g_used = e.gauge("device_cache_used_bytes",
                               "HBM bytes resident in the slab cache")
        self._g_pinned = e.gauge("device_cache_pinned_count",
                                 "entries pinned by in-flight compactions")

    def get(self, key: CacheKey) -> Optional[StagedCols]:
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                self.misses += 1
                self._c_misses.increment()
                return None
            self._map.move_to_end(key)
            self.hits += 1
            self._c_hits.increment()
            return ent.staged

    def contains(self, key: CacheKey) -> bool:
        """Metrics-neutral probe (offload policy peeks without counting)."""
        with self._lock:
            return key in self._map

    def level_of(self, key: CacheKey) -> Optional[int]:
        """Resident entry's LSM level, or None when absent (metrics-neutral:
        compaction derives its output level from the input levels)."""
        with self._lock:
            ent = self._map.get(key)
            return None if ent is None else ent.level

    # ------------------------------------------------------------- pinning
    def pin(self, key: CacheKey) -> bool:
        """Pin an entry for an in-flight job: capacity eviction skips it.
        Returns False when the key is not resident (nothing to pin)."""
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                return False
            ent.pins += 1
            self._g_pinned.set(self._pinned_unlocked())
            return True

    def unpin(self, key: CacheKey) -> None:
        with self._lock:
            ent = self._map.get(key)
            if ent is not None and ent.pins > 0:
                ent.pins -= 1
            self._g_pinned.set(self._pinned_unlocked())

    def pinned_count(self) -> int:
        """Entries with at least one pin — the chaos/fault tests assert
        this drains to zero after every job, including faulted ones."""
        with self._lock:
            return self._pinned_unlocked()

    def _pinned_unlocked(self) -> int:
        return sum(1 for e in self._map.values() if e.pins > 0)

    # ----------------------------------------------------------- mutation
    def put(self, key: CacheKey, staged: StagedCols, level: int = 0) -> None:
        with self._lock:
            prior = self._map.pop(key, None)
            pins = 0
            if prior is not None:
                # replace, not refuse: a stale entry under a reused id must
                # never shadow fresh data (correctness, not just freshness)
                self._used -= prior.bytes
                pins = prior.pins
            self._map[key] = _Resident(staged, level=level, pins=pins,
                                       bytes=staged.nbytes)
            self._used += staged.nbytes
            self._evict_unlocked(protect=key)
            self._g_used.set(self._used)

    def attach_vals(self, key: CacheKey, vals_dev) -> None:
        """Attach staged value words to a resident entry (pushdown-scan
        write-through): the entry grows in place and the growth is
        accounted so eviction stays balanced."""
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                return
            ent.staged.vals_dev = vals_dev
            delta = ent.staged.nbytes - ent.bytes
            ent.bytes += delta
            self._used += delta
            self._evict_unlocked(protect=key)
            self._g_used.set(self._used)

    def _evict_unlocked(self, protect: Optional[CacheKey] = None) -> None:
        """Capacity eviction, shallow levels first (L0 slabs are cheap to
        re-stage and about to be consumed anyway), LRU within a level.
        Pinned entries — inputs of a running merge — are never touched;
        if only pinned entries remain over budget, residency temporarily
        exceeds capacity rather than racing the job."""
        while self._used > self.capacity:
            victim = None
            best = None
            for age, (k, ent) in enumerate(self._map.items()):
                if ent.pins > 0 or k == protect:
                    continue
                rank = (ent.level, age)
                if best is None or rank < best:
                    best = rank
                    victim = k
            if victim is None:
                break
            self._used -= self._map.pop(victim).bytes
            self.evictions += 1
            self._c_evict.increment()

    def drop(self, key: CacheKey) -> None:
        with self._lock:
            ent = self._map.pop(key, None)
            if ent is not None:
                self._used -= ent.bytes
                self._g_used.set(self._used)
                self._g_pinned.set(self._pinned_unlocked())

    def drop_namespace(self, namespace: str) -> None:
        """Evict everything a closed DB staged, freeing its HBM residency."""
        with self._lock:
            dead = [k for k in self._map if k[0] == namespace]
            for k in dead:
                self._used -= self._map.pop(k).bytes
            if dead:
                self._g_used.set(self._used)
                self._g_pinned.set(self._pinned_unlocked())

    def stage_from_raw(self, key: CacheKey, rfb,
                       level: int = 0) -> StagedCols:
        """Raw-block staging (the device codec's cache miss path): decode
        one parsed file's raw block regions ON DEVICE
        (ops/block_codec.decode_file_to_staged) and install the resulting
        cols — no host decode_block runs, so sst_block_decode_total stays
        flat even when the chain starts cold."""
        from yugabyte_tpu.ops.block_codec import decode_file_to_staged
        staged = decode_file_to_staged(rfb, self.device)
        self.put(key, staged, level=level)
        return staged

    def stage(self, key: CacheKey, slab: KVSlab,
              level: int = 0, for_read: bool = False,
              include_vals: bool = False, device=None) -> StagedCols:
        staged = stage_slab(slab, device if device is not None
                            else self.device)
        if include_vals:
            # pushdown-scan write-through: the value words ride along so
            # the NEXT filtered/aggregating scan is fully resident
            import jax
            import jax.numpy as jnp
            from yugabyte_tpu.ops.scan import pack_vals, pushdown_metrics
            packed = pack_vals(slab, staged.n_pad)
            staged.vals_dev = (jax.device_put(packed, self.device)
                               if self.device is not None
                               else jnp.asarray(packed))
            pushdown_metrics()["vals_staged"].increment()
        self.put(key, staged, level=level)
        if for_read:
            # a read had to decode+upload what write-through was
            # supposed to have left resident — the residency-health
            # signal for the batched point-read path
            self._c_read_stage.increment()
        return staged

    def snapshot(self) -> dict:
        """Residency block for /compactionz: totals plus the per-level
        breakdown the multi-level eviction policy acts on."""
        with self._lock:
            levels: Dict[int, dict] = {}
            for ent in self._map.values():
                lv = levels.setdefault(ent.level,
                                       {"entries": 0, "bytes": 0,
                                        "pinned": 0})
                lv["entries"] += 1
                lv["bytes"] += ent.staged.nbytes
                if ent.pins > 0:
                    lv["pinned"] += 1
            shards: Dict[str, dict] = {}
            for key, ent in self._map.items():
                # direct-keyed caches (tests) use bare ids, not
                # (namespace, file_id) tuples — they have no shard view
                ns = key[0] if isinstance(key, tuple) and key else None
                if not isinstance(ns, str) or "/shard" not in ns:
                    continue
                sh = shards.setdefault(
                    "shard" + ns.rsplit("/shard", 1)[1],
                    {"entries": 0, "bytes": 0, "pinned": 0})
                sh["entries"] += 1
                sh["bytes"] += ent.staged.nbytes
                if ent.pins > 0:
                    sh["pinned"] += 1
            out = {
                "capacity_bytes": self.capacity,
                "used_bytes": self._used,
                "entries": len(self._map),
                "pinned": self._pinned_unlocked(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "levels": {f"L{k}": v for k, v in sorted(levels.items())},
            }
            if shards:
                # per-mesh-shard residency (the compaction pool's
                # partitioned namespaces — storage survives sharding)
                out["shards"] = dict(sorted(shards.items()))
            return out


class NamespacedSlabCache:
    """Per-DB view over a shared DeviceSlabCache: callers use bare file ids."""

    def __init__(self, shared: DeviceSlabCache, namespace: str):
        self._shared = shared
        self.namespace = namespace

    @property
    def device(self):
        return self._shared.device

    @property
    def hits(self):
        return self._shared.hits

    @property
    def misses(self):
        return self._shared.misses

    def get(self, file_id: int):
        return self._shared.get((self.namespace, file_id))

    def contains(self, file_id: int) -> bool:
        return self._shared.contains((self.namespace, file_id))

    def level_of(self, file_id: int) -> Optional[int]:
        return self._shared.level_of((self.namespace, file_id))

    def pin(self, file_id: int) -> bool:
        return self._shared.pin((self.namespace, file_id))

    def unpin(self, file_id: int) -> None:
        self._shared.unpin((self.namespace, file_id))

    def pinned_count(self) -> int:
        return self._shared.pinned_count()

    def put(self, file_id: int, staged: StagedCols, level: int = 0) -> None:
        self._shared.put((self.namespace, file_id), staged, level=level)

    def attach_vals(self, file_id: int, vals_dev) -> None:
        self._shared.attach_vals((self.namespace, file_id), vals_dev)

    def drop(self, file_id: int) -> None:
        self._shared.drop((self.namespace, file_id))

    def drop_all(self) -> None:
        self._shared.drop_namespace(self.namespace)

    def stage(self, file_id: int, slab: KVSlab,
              level: int = 0, for_read: bool = False,
              include_vals: bool = False) -> StagedCols:
        return self._shared.stage((self.namespace, file_id), slab,
                                  level=level, for_read=for_read,
                                  include_vals=include_vals)

    def stage_from_raw(self, file_id: int, rfb, level: int = 0
                       ) -> StagedCols:
        return self._shared.stage_from_raw((self.namespace, file_id), rfb,
                                           level=level)


class ShardPartition(NamespacedSlabCache):
    """Per-mesh-shard partition of the shared cache: keys carry the shard
    in the namespace (``<ns>/shard<i>``) and staging commits to that
    shard's DEVICE — so a pooled tablet's resident L0->L1->L2 chain lives
    in the HBM of the mesh slot that compacts it (the compaction pool
    gives each tablet a sticky home shard for exactly this affinity).
    Pins, eviction, levels and metrics are the shared cache's; only key
    spelling and device placement change."""

    def __init__(self, shared: DeviceSlabCache, namespace: str,
                 shard: int, device=None):
        super().__init__(shared, f"{namespace}/shard{shard}")
        self.shard = shard
        self._device = device

    @property
    def device(self):
        return self._device if self._device is not None \
            else self._shared.device

    def stage(self, file_id: int, slab: KVSlab,
              level: int = 0, for_read: bool = False,
              include_vals: bool = False) -> StagedCols:
        return self._shared.stage((self.namespace, file_id), slab,
                                  level=level, for_read=for_read,
                                  include_vals=include_vals,
                                  device=self._device)


class HostStagingPool:
    """Reusable host-side staging arrays for stage A of the compaction
    pipeline (ops/run_merge.stage_runs_from_slabs packs column matrices
    into these before the H2D upload).

    Shape buckets make reuse effective: every chunk of a pipelined job
    (and most jobs of a tablet's lifetime) stages the same [r, k_pad*m]
    matrix shape, so after warmup the host never allocates — the pinned
    pages stay hot and the allocator never fragments under a double-
    buffered producer that holds two staging arrays in flight.

    Callers must only release() an array once the upload has COPIED it
    (true on tpu/gpu backends; the CPU backend may alias host memory, so
    its callers skip release and the array is simply garbage-collected).
    """

    def __init__(self, max_per_shape: int = 2, max_bytes: int = 1 << 30):
        from yugabyte_tpu.utils import lock_rank
        self._free: dict = {}              # guarded-by: _lock
        self._bytes = 0                    # guarded-by: _lock
        # ids of arrays acquired and not yet released/forgotten — the
        # chaos harness's leak detector: after every job (including a
        # cancelled or device-faulted one) this must drain back to 0
        self._leases: set = set()          # guarded-by: _lock
        self._max_per_shape = max_per_shape
        self._max_bytes = max_bytes
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "device_cache.staging_pool_lock")
        from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
        e = ROOT_REGISTRY.entity("server", "device_cache")
        self._c_reuse = e.counter(
            "staging_pool_reuse_total",
            "stage-A packings served from a pooled host array")
        self._c_alloc = e.counter(
            "staging_pool_alloc_total",
            "stage-A packings that allocated a fresh host array")
        self._g_leases = e.gauge(
            "staging_pool_outstanding_lease_count",
            "staging arrays acquired and not yet released")

    def acquire(self, shape: Tuple[int, int], dtype=np.uint32) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            bucket = self._free.get(key)
            if bucket:
                arr = bucket.pop()
                self._bytes -= arr.nbytes
                self._leases.add(id(arr))
                self._g_leases.set(len(self._leases))
                self._c_reuse.increment()
                return arr
        arr = np.empty(shape, dtype=dtype)
        with self._lock:
            self._leases.add(id(arr))
            self._g_leases.set(len(self._leases))
        self._c_alloc.increment()
        return arr

    def release(self, arr: np.ndarray) -> None:
        key = (arr.shape, arr.dtype.str)
        with self._lock:
            self._leases.discard(id(arr))
            self._g_leases.set(len(self._leases))
            bucket = self._free.setdefault(key, [])
            if (len(bucket) < self._max_per_shape
                    and self._bytes + arr.nbytes <= self._max_bytes):
                bucket.append(arr)
                self._bytes += arr.nbytes

    def forget(self, arr: np.ndarray) -> None:
        """End a lease WITHOUT recycling the pages: the CPU backend may
        alias the array's memory into the device buffer, so the caller
        hands the array off for garbage collection instead of release().
        Not a leak — the lease is accounted done."""
        with self._lock:
            self._leases.discard(id(arr))
            self._g_leases.set(len(self._leases))

    def outstanding(self) -> int:
        """Leases neither released nor forgotten — the chaos soak asserts
        this returns to zero after fault windows heal."""
        with self._lock:
            return len(self._leases)


_staging_pool: Optional[HostStagingPool] = None  # guarded-by: _staging_pool_lock
_staging_pool_lock = threading.Lock()


def host_staging_pool() -> HostStagingPool:
    """Process-wide staging pool (one per process, like the slab cache)."""
    global _staging_pool
    with _staging_pool_lock:
        if _staging_pool is None:
            _staging_pool = HostStagingPool()
        return _staging_pool


def merged_column_stats(staged_list: Sequence[StagedCols], w: int
                        ) -> np.ndarray:
    """Cross-input is_const vector over staged inputs, vectorized: a row
    prunes from the sort/compare schedule only when it is constant WITH
    THE SAME VALUE across every input (constant-per-input with differing
    values still orders the merge). Inputs narrower than w expose their
    extra word rows as constant zero; inputs without column stats (device
    write-through gathers skip the host fetch) poison every row they
    cover as non-constant."""
    r_total = _ROW_WORDS + w
    k = len(staged_list)
    consts = np.zeros((k, r_total), dtype=bool)
    firsts = np.zeros((k, r_total), dtype=np.uint32)
    for i, s in enumerate(staged_list):
        rs = min(_ROW_WORDS + s.w, r_total)
        consts[i, rs:] = True              # implicit zero-pad word rows
        if s.col_const is not None:
            consts[i, :rs] = s.col_const[:rs]
            firsts[i, :rs] = s.col_first[:rs]
    return consts.all(axis=0) & (firsts == firsts[0:1]).all(axis=0)


def concat_staged(staged_list: Sequence[StagedCols]) -> StagedCols:
    """Concatenate staged inputs ON DEVICE into one padded cols matrix.

    All transfers avoided: ONE cached jitted program (_concat_staged_fused,
    ops/run_merge.py — part of the restage_concat kernel family in the
    compile-surface manifest) pads each input's width to the max, lays the
    real rows out contiguously and pads the tail to the bucket size, all
    in HBM. The merged sort schedule prunes rows via the vectorized
    cross-input column stats (merged_column_stats).
    """
    import jax.numpy as jnp
    from yugabyte_tpu.ops.run_merge import _concat_staged_fused

    w = max(s.w for s in staged_list)
    n = sum(s.n for s in staged_list)
    n_pad = bucket_size(n)
    parts = tuple(s.cols_dev for s in staged_list)
    ns = jnp.asarray([s.n for s in staged_list], dtype=jnp.int32)
    cat = _concat_staged_fused(parts, ns, w=w, n_pad=n_pad)
    is_const = merged_column_stats(staged_list, w)
    sort_rows, n_sort = build_sort_schedule(w, is_const)
    return StagedCols(cat, sort_rows, n_sort, n, n_pad, w)
