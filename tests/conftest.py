"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; sharding tests run on a virtual
8-device CPU mesh exactly as the driver's dryrun does.

NOTE: the axon sitecustomize (PYTHONPATH=/root/.axon_site) force-registers
the tunnel TPU at interpreter start and overrides JAX_PLATFORMS from the
environment — but `jax.config.update` after import still wins, so the
platform is pinned here, before any backend initialization.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Older jax builds (< 0.4.34) spell the device-count knob as an XLA flag
# rather than jax_num_cpu_devices; set it before the backend initializes
# so either path yields the 8-device mesh.
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # pre-0.4.34 jax: the XLA_FLAGS fallback above applies


import pytest


def pytest_configure(config):
    """Arm the race sanitizer when the environment asks (`YBSAN=1
    pytest ...`): the vector-clock detector patches the sync vocabulary
    and every guarded-by / @ybsan.shadow class before any test runs."""
    from yugabyte_tpu.utils import ybsan as _shim
    if _shim.enabled():
        import tools.sanitizer
        tools.sanitizer.arm()


def pytest_sessionfinish(session, exitstatus):
    """The armed gate: any race report whose fingerprint is not
    justified in tools/analysis/baseline.txt fails the whole session
    (wrap_session returns session.exitstatus after this hook)."""
    from yugabyte_tpu.utils import ybsan as _shim
    if not _shim.armed():
        return
    import tools.sanitizer
    failures = tools.sanitizer.session_gate()
    if failures:
        print("\n=== ybsan: unbaselined race reports ===", file=sys.stderr)
        for f in failures:
            print(f, file=sys.stderr)
        session.exitstatus = 1


@pytest.fixture(autouse=True)
def _fresh_bucket_health_board():
    """The bucket-health board is process-global by design (one routing
    memory per server). Between TESTS that memory is leakage: a cluster
    test that organically demotes a merge bucket (CPU device paths
    measure slower than native) would silently park the next test's
    device dispatches. Every test starts with a cold board."""
    from yugabyte_tpu.storage.bucket_health import health_board
    health_board().reset()
    yield
    health_board().reset()


@pytest.fixture(autouse=True)
def _fresh_timeseries_store():
    """The telemetry timebase is process-global too: a sampler thread
    left running by one test would scrape (and pin sources of) servers
    the next test already tore down. Every test starts storeless; the
    teardown stop also joins any sampler the test leaked."""
    from yugabyte_tpu.utils.timeseries import reset_timeseries_store
    reset_timeseries_store()
    yield
    reset_timeseries_store()


def pytest_collection_modifyitems(config, items):
    """Run the sync-point interleaving schedules FIRST: they pin exact
    thread timings, and by the end of a full-suite run hundreds of
    daemon threads from earlier cluster tests are still contending for
    the GIL on CI's single core — the dominant source of their flakes."""
    early = [i for i in items if "test_sync_interleavings" in i.nodeid]
    rest = [i for i in items if "test_sync_interleavings" not in i.nodeid]
    items[:] = early + rest
