"""Retry pacing: capped exponential backoff with decorrelated jitter.

Capability parity with the reference's retry waiters (ref:
src/yb/util/backoff_waiter.h BackoffWaiter; rpc/rpc.cc
RpcRetrier::DelayMillis adds jitter the same way): every retry loop in the
stack — client master lookup, tablet-call replica walks, the heartbeater's
master hunt, and the maintenance manager's background-error recovery —
draws its sleeps from here instead of hard-coding a fixed interval.

Two shapes:

- `Backoff`: an iterator of delays for one bounded retry *attempt*
  (deadline-aware; decorrelated jitter so a thundering herd of retriers
  de-synchronizes: delay_n = uniform(base, prev * 3), clamped to cap).
  A server-provided `retry_after_ms` hint (the overload-shedding
  response extra) floors the next delay — the server measured its own
  queue drain, so the client must not return before that.
- `RetrySchedule`: open-ended pacing for a long-lived background retrier
  (the maintenance manager's flush-recovery op): `ready()` gates the next
  attempt, `record_failure()` doubles the spacing up to a cap,
  `reset()` re-arms after success.
- `RetryBudget`: a per-client token bucket every retry loop draws from
  (ref: rpc/rpc.cc RpcRetrier + the reference's server-side call budget):
  first attempts are free, each RETRY spends one token, tokens refill at
  a bounded rate — so a saturated cluster's rejections can never make
  the client multiply its own offered load unboundedly (the retry-storm
  amplifier the overload-protection design exists to break).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import Code, Status, StatusError

flags.define_flag("client_retry_budget_tokens", 120,
                  "burst capacity of the per-client retry token bucket; "
                  "every retry (never a first attempt) spends one token")
flags.define_flag("client_retry_budget_refill_per_s", 30.0,
                  "sustained retry rate the per-client budget allows; "
                  "caps retry amplification under overload at roughly "
                  "this many extra attempts per second per client")

__all__ = ["Backoff", "RetrySchedule", "RetryBudget",
           "RetryBudgetExhausted"]


class Backoff:
    """Decorrelated-jitter delay source for one retry loop.

    next_delay() never exceeds cap_s nor the remaining deadline;
    sleep() performs the wait and returns False once the deadline is
    exhausted (callers break their loop and surface the last error).
    """

    def __init__(self, base_s: float = 0.05, cap_s: float = 2.0,
                 deadline_s: Optional[float] = None, rng=None):
        self.base_s = base_s
        self.cap_s = cap_s
        self._prev = base_s
        self._deadline = (None if deadline_s is None
                          else time.monotonic() + deadline_s)
        self._rng = rng if rng is not None else random
        self.attempts = 0
        self._hint_s = 0.0  # pending server retry_after floor

    @property
    def expired(self) -> bool:
        return (self._deadline is not None
                and time.monotonic() >= self._deadline)

    def note_server_hint(self, retry_after_ms) -> None:
        """Record a server-sent `retry_after_ms` overload hint: the NEXT
        delay will be at least this long (the server measured its own
        queue drain; coming back sooner is a wasted, load-amplifying
        attempt). Consumed by one next_delay(); the hint may exceed
        cap_s — the server's measurement wins — but never the
        deadline."""
        if retry_after_ms:
            self._hint_s = max(self._hint_s, float(retry_after_ms) / 1e3)

    def remaining_s(self) -> Optional[float]:
        """Seconds left until the deadline; None when unbounded. Callers
        clamp per-attempt RPC timeouts to this so one slow attempt
        cannot blow the whole op budget."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def next_delay(self) -> float:
        """Draw the next delay (decorrelated jitter), floored by any
        pending server retry_after hint, deadline-clamped."""
        self.attempts += 1
        d = min(self.cap_s, self._rng.uniform(self.base_s, self._prev * 3))
        self._prev = d
        if self._hint_s:
            d = max(d, self._hint_s)
            self._hint_s = 0.0
        if self._deadline is not None:
            d = min(d, max(0.0, self._deadline - time.monotonic()))
        return d

    def sleep(self) -> bool:
        """Sleep for the next delay; False when the deadline is spent
        (no sleep happens in that case)."""
        if self.expired:
            return False
        time.sleep(self.next_delay())
        return not self.expired


class RetrySchedule:
    """Open-ended capped-exponential pacing for a background retrier.

    Unlike Backoff (one bounded loop), this survives across scheduler
    polls: the maintenance manager asks ready() each round, performs the
    recovery attempt when it fires, and records the outcome.

    deadline_s bounds the WHOLE schedule to an overall per-op budget:
    record_failure clamps each delay to the remaining budget (never
    scheduling an attempt past the deadline), and once the budget is
    spent `expired` turns True / ready() turns False — the owner must
    surface DeadlineExceeded instead of retrying forever."""

    def __init__(self, initial_s: float = 0.5, max_s: float = 30.0,
                 deadline_s: Optional[float] = None, rng=None):
        self.initial_s = initial_s
        self.max_s = max_s
        self._deadline = (None if deadline_s is None
                          else time.monotonic() + deadline_s)
        self._rng = rng if rng is not None else random
        self.failures = 0
        self._next_attempt = 0.0  # monotonic time; 0 = immediately ready

    @property
    def expired(self) -> bool:
        return (self._deadline is not None
                and time.monotonic() >= self._deadline)

    def remaining_s(self) -> Optional[float]:
        """Seconds left in the overall budget; None when unbounded."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def ready(self) -> bool:
        if self.expired:
            return False  # budget spent: surface, don't retry
        return time.monotonic() >= self._next_attempt

    def record_failure(self) -> float:
        """Push the next attempt out by initial * 2^n (capped), with a
        +-25% jitter so many parked tablets don't retry in lockstep;
        clamped to the remaining per-op budget so the schedule never
        waits past its deadline. Returns the chosen delay."""
        delay = min(self.max_s, self.initial_s * (2 ** self.failures))
        delay *= self._rng.uniform(0.75, 1.25)
        rem = self.remaining_s()
        if rem is not None:
            delay = min(delay, rem)
        self.failures += 1
        self._next_attempt = time.monotonic() + delay
        return delay

    def reset(self) -> None:
        self.failures = 0
        self._next_attempt = 0.0


class RetryBudgetExhausted(StatusError):
    """The per-client retry budget ran dry: surfacing (typed, with the
    last underlying error in the message) instead of retrying is what
    keeps a saturated cluster's retries from amplifying its own
    collapse. Carries the same `overloaded` extra shape as server-side
    shedding so callers classify both identically."""

    def __init__(self, msg: str):
        super().__init__(Status(Code.BUSY, msg))
        self.extra = {"overloaded": True, "retry_budget_exhausted": True}


class RetryBudget:
    """Token bucket bounding a client's RETRY rate (first attempts are
    free). Thread-safe: one instance is shared by every retry loop of a
    client, so concurrent sessions draw from one budget.

    spend() refills by elapsed-time * refill rate (capped at the burst
    capacity), then takes one token; an empty bucket means the caller
    must surface its last error instead of retrying."""

    def __init__(self, capacity: Optional[int] = None,
                 refill_per_s: Optional[float] = None):
        self.capacity = float(capacity if capacity is not None
                              else flags.get_flag(
                                  "client_retry_budget_tokens"))
        self.refill_per_s = float(
            refill_per_s if refill_per_s is not None
            else flags.get_flag("client_retry_budget_refill_per_s"))
        self._tokens = self.capacity
        self._last_refill = time.monotonic()
        self._lock = threading.Lock()
        self.exhausted_total = 0  # budget denials (observability)
        self.spent_total = 0      # retries the budget admitted

    def _refill_locked(self, now: float) -> None:
        self._tokens = min(self.capacity, self._tokens
                           + (now - self._last_refill) * self.refill_per_s)
        self._last_refill = now

    def try_spend(self) -> bool:
        with self._lock:
            self._refill_locked(time.monotonic())
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent_total += 1
                return True
            self.exhausted_total += 1
            return False

    def tokens(self) -> float:
        with self._lock:
            self._refill_locked(time.monotonic())
            return self._tokens

    def spend_or_raise(self, what: str, last_err=None) -> None:
        """Charge one retry; raises the typed RetryBudgetExhausted —
        carrying the last underlying error — when the bucket is dry."""
        if not self.try_spend():
            raise RetryBudgetExhausted(
                f"{what}: client retry budget exhausted "
                f"({self.capacity:.0f} tokens, "
                f"{self.refill_per_s}/s refill); last error: {last_err}")
