"""yugabyted: single-command node launcher.

Capability parity with the reference (ref: bin/yugabyted — starts a master
+ tserver pair with sensible defaults, prints connection endpoints, joins
an existing cluster via --join). One process runs both server objects,
exactly like `yugabyted start` does for a single node.

Usage:
  python -m yugabyte_tpu.tools.yugabyted start --base-dir DIR
      [--master-port N] [--tserver-port N] [--join HOST:PORT]
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import time
from typing import List, Optional

from yugabyte_tpu.master.master import Master, MasterOptions
from yugabyte_tpu.tserver.tablet_server import (
    TabletServer, TabletServerOptions)


class YugabytedNode:
    def __init__(self, base_dir: str, master_port: int = 0,
                 tserver_port: int = 0, join: Optional[str] = None,
                 server_id: Optional[str] = None,
                 replication_factor: Optional[int] = None,
                 pg_port: int = 0, cql_port: int = 0):
        os.makedirs(base_dir, exist_ok=True)
        if join is None:
            # Single-node bringup defaults to RF1 (ref yugabyted defaults);
            # joining nodes inherit the existing master's setting.
            from yugabyte_tpu.utils import flags
            flags.set_flag("replication_factor", replication_factor or 1)
        self.master: Optional[Master] = None
        if join is None:
            self.master = Master(MasterOptions(
                master_id="m0",
                fs_root=os.path.join(base_dir, "master"),
                port=master_port)).start()
            master_addrs = [self.master.address]
        else:
            master_addrs = [join]
        sid = server_id or f"ts-{os.path.basename(base_dir)}"
        self.tserver = TabletServer(TabletServerOptions(
            server_id=sid,
            fs_root=os.path.join(base_dir, "tserver"),
            master_addrs=master_addrs,
            port=tserver_port)).start()
        self.master_addrs = master_addrs
        # Readiness: wait until THIS tserver has registered with the
        # master (ref: yugabyted's post-start wait) — DDL issued right
        # after bringup must not race the first heartbeat and fail with
        # "need N live tservers". On timeout, stop what we started — a
        # failed __init__ returns no handle to shut anything down with.
        try:
            self._wait_registered(sid)
        except BaseException:
            self.tserver.shutdown()
            if self.master is not None:
                self.master.shutdown()
            raise
        # Query-layer frontends (the reference tserver hosts the postgres
        # child + CQL/redis servers the same way; ref pg_wrapper.cc)
        from yugabyte_tpu.client.client import YBClient
        from yugabyte_tpu.yql.pgsql import PgServer
        self._pg_client = YBClient(master_addrs)
        self.pg_server = PgServer(self._pg_client, port=pg_port)
        from yugabyte_tpu.yql.cql.binary_server import CQLBinaryServer
        self._cql_client = YBClient(master_addrs)
        self.cql_server = CQLBinaryServer(self._cql_client, port=cql_port)

    def _wait_registered(self, server_id: str, timeout_s: float = 20.0
                         ) -> None:
        import time
        from yugabyte_tpu.client.client import YBClient
        c = YBClient(self.master_addrs)
        try:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                try:
                    live = c.list_tservers()
                    if any(t.get("server_id") == server_id
                           and t.get("alive", True) for t in live):
                        return
                except Exception:  # noqa: BLE001 — master still warming
                    pass
                time.sleep(0.1)
            raise TimeoutError(
                f"tserver {server_id} never registered with master(s) "
                f"{self.master_addrs} within {timeout_s:.0f}s")
        finally:
            c.close()

    def endpoints(self) -> dict:
        out = {"tserver_rpc": self.tserver.address,
               "ysql": self.pg_server.address,
               "ycql": f"{self.cql_server.host}:{self.cql_server.port}",
               "masters": self.master_addrs}
        if self.tserver.webserver:
            out["tserver_web"] = self.tserver.webserver.address
        if self.master is not None:
            out["master_rpc"] = self.master.address
            if self.master.webserver:
                out["master_web"] = self.master.webserver.address
        return out

    def shutdown(self) -> None:
        self.cql_server.shutdown()
        self._cql_client.close()
        self.pg_server.shutdown()
        self._pg_client.close()
        self.tserver.shutdown()
        if self.master is not None:
            self.master.shutdown()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="yugabyted")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("start")
    p.add_argument("--base-dir", required=True)
    p.add_argument("--master-port", type=int, default=7100)
    p.add_argument("--tserver-port", type=int, default=9100)
    p.add_argument("--join", default=None,
                   help="master address of an existing cluster to join")
    p.add_argument("--server-id", default=None)
    p.add_argument("--rf", type=int, default=None,
                   help="replication factor for new tables (default 1)")
    p.add_argument("--ysql-port", type=int, default=0,
                   help="YSQL (PG wire) port; 0 = ephemeral (printed at "
                   "startup), pass 5433 for the PG convention")
    args = ap.parse_args(argv)
    node = YugabytedNode(args.base_dir, args.master_port,
                         args.tserver_port, args.join, args.server_id,
                         replication_factor=args.rf,
                         pg_port=args.ysql_port)
    for k, v in node.endpoints().items():
        print(f"{k}: {v}", flush=True)
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    print("node running; Ctrl-C to stop", flush=True)
    while not stop:
        time.sleep(0.2)
    node.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
