"""Device-resident write-through: compaction outputs staged from HBM.

run_compaction_job_device_native's write-through must stage the output
files by gathering the surviving columns ON DEVICE (ops/run_merge.py
_gather_staged_output) — the staged entries must be indistinguishable from
host restaging (stage_slab over SSTReader.read_all()) for everything a
later merge reads, and a chained second compaction consuming the cache
entries must keep exactly what a from-disk compaction keeps.
"""

import os

import numpy as np
import pytest

from yugabyte_tpu.ops.merge_gc import _ROW_WORDS, stage_slab
from yugabyte_tpu.ops.slabs import ValueArray
from yugabyte_tpu.storage import compaction as compaction_mod
from yugabyte_tpu.storage import native_engine
from yugabyte_tpu.storage.device_cache import DeviceSlabCache
from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter
from yugabyte_tpu.utils import flags

pytestmark = pytest.mark.skipif(not native_engine.available(),
                                reason="native engine unavailable")


def _mk_run(rng, n, key_space, value_bytes=16, ttl_frac=0.0):
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from test_run_merge import _make_run
    slab = _make_run(rng, n, key_space, ttl_frac=ttl_frac)
    data = rng.integers(0, 256, size=n * value_bytes, dtype=np.uint8)
    offs = np.arange(n + 1, dtype=np.int64) * value_bytes
    slab.values = ValueArray(data, offs)
    return slab


def _write_runs(workdir, runs):
    readers = []
    for i, slab in enumerate(runs):
        p = os.path.join(workdir, f"in{i:03d}.sst")
        SSTWriter(p).write(slab, Frontier())
        readers.append(SSTReader(p))
    return readers


def _device():
    import jax
    return jax.devices()[0]


def _run_device_native(readers, out_dir, cutoff, cache, input_ids,
                       first_id=100):
    os.makedirs(out_dir, exist_ok=True)
    ids = iter(range(first_id, first_id + 500))
    return compaction_mod.run_compaction_job_device_native(
        readers, out_dir, lambda: next(ids), cutoff, True,
        device=_device(), device_cache=cache, input_ids=input_ids)


CUTOFF = (10_000_000 << 12)


def test_staged_output_matches_host_restage(tmp_path):
    rng = np.random.default_rng(11)
    runs = [_mk_run(rng, 800, 500) for _ in range(3)]
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    ids = list(range(len(readers)))
    for fid, r in zip(ids, readers):
        cache.stage(fid, r.read_all())
    res = _run_device_native(readers, str(tmp_path / "out"), CUTOFF,
                            cache, ids)
    assert res.outputs, "compaction produced no outputs"
    for fid, base_path, _props in res.outputs:
        dev_staged = cache.get(fid)
        assert dev_staged is not None, "write-through missed the cache"
        rdr = SSTReader(base_path)
        host_staged = stage_slab(rdr.read_all())
        rdr.close()
        assert dev_staged.n == host_staged.n
        dev_cols = np.asarray(dev_staged.cols_dev)
        host_cols = np.asarray(host_staged.cols_dev)
        n = host_staged.n
        r_common = min(dev_cols.shape[0], host_cols.shape[0])
        np.testing.assert_array_equal(
            dev_cols[:r_common, :n], host_cols[:r_common, :n],
            err_msg="device-staged columns differ from host restage")
        # any extra device rows are key-word padding and must be zero
        if dev_cols.shape[0] > r_common:
            assert (dev_cols[r_common:, :n] == 0).all()
        # padding columns must carry the pad template (sort to tail)
        from yugabyte_tpu.ops.merge_gc import pad_template
        if dev_staged.n_pad > n:
            pt = pad_template(dev_cols.shape[0])
            np.testing.assert_array_equal(
                dev_cols[:, n:], np.tile(pt[:, None], (1, dev_staged.n_pad - n)))


def test_ttl_rewrite_flag_mirrored(tmp_path):
    """TTL-expired survivors written as tombstones must carry the
    tombstone flag in the device-staged entry too (non-major keeps them)."""
    rng = np.random.default_rng(12)
    runs = [_mk_run(rng, 600, 400, ttl_frac=0.5) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    ids = list(range(len(readers)))
    for fid, r in zip(ids, readers):
        cache.stage(fid, r.read_all())
    os.makedirs(str(tmp_path / "out"), exist_ok=True)
    idgen = iter(range(10, 500))
    res = compaction_mod.run_compaction_job_device_native(
        readers, str(tmp_path / "out"), lambda: next(idgen), CUTOFF,
        False,  # non-major: TTL expiry rewrites values as tombstones
        device=_device(), device_cache=cache, input_ids=ids)
    for fid, base_path, _props in res.outputs:
        dev_cols = np.asarray(cache.get(fid).cols_dev)
        rdr = SSTReader(base_path)
        host_staged = stage_slab(rdr.read_all())
        rdr.close()
        host_cols = np.asarray(host_staged.cols_dev)
        r_common = min(dev_cols.shape[0], host_cols.shape[0])
        np.testing.assert_array_equal(dev_cols[:r_common, :host_staged.n],
                                      host_cols[:r_common, :host_staged.n])


def test_chained_compaction_from_cache(tmp_path):
    """Second compaction consuming device-staged outputs == from-disk."""
    rng = np.random.default_rng(13)
    runs_a = [_mk_run(rng, 700, 450) for _ in range(2)]
    runs_b = [_mk_run(rng, 700, 450) for _ in range(2)]
    cache = DeviceSlabCache(device=_device())

    os.makedirs(str(tmp_path / "a"))
    os.makedirs(str(tmp_path / "b"))
    readers_a = _write_runs(str(tmp_path / "a"), runs_a)
    readers_b = _write_runs(str(tmp_path / "b"), runs_b)
    for fid, r in zip((0, 1), readers_a):
        cache.stage(fid, r.read_all())
    for fid, r in zip((2, 3), readers_b):
        cache.stage(fid, r.read_all())

    res_a = _run_device_native(readers_a, str(tmp_path / "oa"), CUTOFF,
                               cache, [0, 1], first_id=100)
    res_b = _run_device_native(readers_b, str(tmp_path / "ob"), CUTOFF,
                               cache, [2, 3], first_id=200)

    # L1: compact the two outputs together, inputs from the cache
    l1_readers = [SSTReader(p) for _, p, _ in res_a.outputs + res_b.outputs]
    l1_ids = [fid for fid, _, _ in res_a.outputs + res_b.outputs]
    res_l1 = _run_device_native(l1_readers, str(tmp_path / "l1"), CUTOFF,
                                cache, l1_ids, first_id=300)

    # reference: same L1 compaction fully from disk, no cache
    os.makedirs(str(tmp_path / "l1ref"))
    ids = iter(range(400, 500))
    ref = compaction_mod.run_compaction_job(
        l1_readers, str(tmp_path / "l1ref"), lambda: next(ids), CUTOFF,
        True, device="native")
    assert res_l1.rows_out == ref.rows_out
    # outputs must be byte-identical
    for (_, b1, _), (_, b2, _) in zip(res_l1.outputs, ref.outputs):
        with open(b1 + ".sblock.0", "rb") as f1, \
                open(b2 + ".sblock.0", "rb") as f2:
            assert f1.read() == f2.read()
    for r in l1_readers + readers_a + readers_b:
        r.close()


def test_multi_file_split_ranges(tmp_path):
    """File splits: each cache entry covers exactly its file's rows."""
    rng = np.random.default_rng(14)
    runs = [_mk_run(rng, 900, 4000) for _ in range(2)]  # few dups: big out
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    ids = [0, 1]
    for fid, r in zip(ids, readers):
        cache.stage(fid, r.read_all())
    old = flags.get_flag("compaction_max_output_entries_per_sst")
    flags.set_flag("compaction_max_output_entries_per_sst", 500)
    try:
        res = _run_device_native(readers, str(tmp_path / "out"), CUTOFF,
                                 cache, ids)
        assert len(res.outputs) >= 2, "expected a multi-file split"
        for fid, base_path, props in res.outputs:
            dev_staged = cache.get(fid)
            rdr = SSTReader(base_path)
            host_staged = stage_slab(rdr.read_all())
            rdr.close()
            assert dev_staged.n == host_staged.n == props.n_entries
            dev_cols = np.asarray(dev_staged.cols_dev)
            host_cols = np.asarray(host_staged.cols_dev)
            r_common = min(dev_cols.shape[0], host_cols.shape[0])
            np.testing.assert_array_equal(
                dev_cols[:r_common, :host_staged.n],
                host_cols[:r_common, :host_staged.n])
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old)


def test_production_db_routes_to_combined_path(tmp_path, monkeypatch):
    """DB background compaction on a JAX device takes the flagship
    device-decisions + native-shell path (the configuration the bench
    measures), and deep-document inputs do NOT."""
    import jax
    from yugabyte_tpu.storage import compaction as comp
    from yugabyte_tpu.storage.db import DB, DBOptions
    from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
    from yugabyte_tpu.docdb.value import Value

    calls = []
    orig = comp.run_compaction_job_device_native

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)
    monkeypatch.setattr(comp, "run_compaction_job_device_native", spy)

    from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey
    db = DB(str(tmp_path / "db"),
            DBOptions(auto_compact=False, device=jax.devices()[0]))
    for batch in range(4):
        kvs = []
        for i in range(50):
            dk = DocKey(range_components=(f"r{i:04d}",))
            key = SubDocKey(dk, (("col", 0),)).encode(include_ht=False)
            kvs.append((key, DocHybridTime(
                HybridTime.from_micros(1000 + batch * 100 + i), 0),
                Value(primitive=batch).encode()))
        db.write_batch(kvs)
        db.flush()
    assert db.n_live_files == 4
    db.compact_all()
    assert calls, "combined device+native path was not taken"
    assert db.n_live_files == 1
    db.close()

    # deep inputs: props.has_deep gates the combined path off
    calls.clear()
    from yugabyte_tpu.docdb.subdocument import subdocument_writes
    db2 = DB(str(tmp_path / "db2"),
             DBOptions(auto_compact=False, device=jax.devices()[0]))
    for batch in range(4):
        kvs = [(k, DocHybridTime(HybridTime.from_micros(1000 + batch), i), v)
               for i, (k, v) in enumerate(subdocument_writes(
                   DocKey(range_components=(f"d{batch}",)), (),
                   {"a": {"b": {"c": batch}}}))]
        db2.write_batch(kvs)
        db2.flush()
    db2.compact_all()
    assert not calls, "deep inputs must not take the depth-2 device path"
    db2.close()


def test_chunked_write_through_matches_host(tmp_path, monkeypatch):
    """Chunked subcompactions must still stage outputs into the HBM cache
    (to_parent_products rebuilds the parent-domain arrays): entries match
    a host restage of the written files byte-for-byte."""
    from yugabyte_tpu.ops import run_merge

    rng = np.random.default_rng(15)
    runs = [_mk_run(rng, 2000, 8000) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    ids = [0, 1]
    for fid, r in zip(ids, readers):
        cache.stage(fid, r.read_all())

    chunked_calls = {"n": 0}
    real = run_merge._launch_chunked

    def spy(*a, **k):
        h = real(*a, **k)
        if h is not None:
            chunked_calls["n"] += 1
        return h

    monkeypatch.setattr(run_merge, "_launch_chunked", spy)
    monkeypatch.setenv("YBTPU_MERGE_CHUNK_ROWS", "2048")
    res = _run_device_native(readers, str(tmp_path / "out"), CUTOFF,
                             cache, ids)
    assert chunked_calls["n"] == 1, "chunked path did not engage"
    assert res.outputs, "no outputs written"
    for fid, base_path, props in res.outputs:
        dev_staged = cache.get(fid)
        assert dev_staged is not None, "write-through skipped"
        rdr = SSTReader(base_path)
        host_staged = stage_slab(rdr.read_all())
        rdr.close()
        assert dev_staged.n == host_staged.n == props.n_entries
        dev_cols = np.asarray(dev_staged.cols_dev)
        host_cols = np.asarray(host_staged.cols_dev)
        r_common = min(dev_cols.shape[0], host_cols.shape[0])
        np.testing.assert_array_equal(
            dev_cols[:r_common, :host_staged.n],
            host_cols[:r_common, :host_staged.n])
    for r in readers:
        r.close()
