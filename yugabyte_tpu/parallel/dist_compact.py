"""Distributed compaction: range-repartition + per-shard merge/GC over a mesh.

The multi-chip form of the north-star kernel. The reference parallelizes a
big compaction into key-range subcompactions, one THREAD each
(ref: rocksdb/db/compaction_job.cc:330 GenSubcompactionBoundaries, :456-468);
here each key range is one DEVICE of a `jax.sharding.Mesh`, and the data
movement that the reference does with per-thread file iterators happens as
XLA collectives over ICI:

  1. each shard samples its local route keys (first key word)
  2. all_gather the samples -> identical global splitters on every shard
  3. bucket rows by destination shard; all_to_all exchanges the buckets
     (fixed per-destination capacity with all-0xFF padding rows, which sort
     to the tail and are dropped by the GC keep-mask like all padding)
  4. per-shard fused radix merge + MVCC GC (ops/merge_gc.sort_and_gc)

Routing is by the first 32-bit key word, which keeps every version of a key
AND every subkey of a document on one shard (a document's entries share
their first 4 key bytes), so GC segment logic never straddles shards.

Returns per-shard sorted cols + keep/make-tombstone masks + an overflow flag
(a bucket exceeding capacity means splitters were too skewed: the caller
retries with higher capacity — compaction correctness is never silently
sacrificed).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from yugabyte_tpu.ops import merge_gc
from yugabyte_tpu.ops.merge_gc import (
    _ROW_KEY_LEN, _ROW_WORDS, GCParams, PAD_SENTINEL, pack_cols, pad_template,
    sort_and_gc)


def dist_compact_fn(mesh: Mesh, w: int, capacity: int, is_major: bool,
                    retain_deletes: bool = False, axis: str = "shard"):
    """Build the jitted distributed compaction step for a mesh.

    Input cols: [R, n_total] sharded along dim 1; n_total = n_shards * n_local.
    Output: (cols_out [R, n_shards*capacity] sharded, keep, make_tombstone,
             overflow flag per shard).
    """
    n_shards = mesh.devices.size

    def per_shard(cols_local, n_real_total, cutoff_hi, cutoff_lo, cph, cpl):
        r, n_local = cols_local.shape
        route = cols_local[_ROW_WORDS]                      # first key word
        is_pad_in = cols_local[_ROW_KEY_LEN] == jnp.uint32(PAD_SENTINEL)
        # -- 1/2: sample + all_gather + splitters --------------------------
        # padding samples carry 0xFFFFFFFF route words and sort to the tail;
        # quantiles are taken over the expected REAL sample count so padding
        # never skews splitters toward empty high shards.
        step = max(1, n_local // 64)
        samples = route[::step][:64] if n_local >= 64 else route
        n_samp = samples.shape[0]
        all_samples = jax.lax.all_gather(samples, axis).reshape(-1)
        (sorted_samples,) = jax.lax.sort([all_samples], num_keys=1)
        total_rows = n_shards * n_local
        n_real_samples = (all_samples.shape[0] * n_real_total) // total_rows
        n_real_samples = jnp.maximum(n_real_samples, 1)
        qs = (jnp.arange(1, n_shards) * n_real_samples) // n_shards
        splitters = sorted_samples[qs]                      # [n_shards-1]
        # -- 3: bucket + exchange ------------------------------------------
        # input padding rows route to the LAST shard (route word 0xFF..) but
        # are excluded from counts so they can't trigger a spurious overflow
        dest = jnp.sum(route[:, None] >= splitters[None, :], axis=1)  # [n_local]
        order = jnp.argsort(dest)                           # stable
        real_dest = jnp.where(is_pad_in, n_shards, dest)    # bin n_shards: pad
        counts = jnp.bincount(real_dest, length=n_shards + 1)[:n_shards]
        all_counts = jnp.bincount(dest, length=n_shards)
        offsets = jnp.concatenate(
            [jnp.zeros(1, all_counts.dtype), jnp.cumsum(all_counts)[:-1]])
        overflow = jnp.any(counts > capacity)
        pos_in_group = jnp.arange(n_local) - offsets[dest[order]]
        valid = pos_in_group < capacity
        # rows past capacity go to a dump column that is sliced off before
        # the exchange — they can never clobber a real slot
        slot = jnp.where(valid, dest[order] * capacity + pos_in_group,
                         n_shards * capacity)
        pad_col = jnp.asarray(pad_template(r))
        send = jnp.tile(pad_col[:, None], (1, n_shards * capacity + 1))
        send = send.at[:, slot].set(cols_local[:, order])
        send3 = send[:, :-1].reshape(r, n_shards, capacity)
        recv = jax.lax.all_to_all(send3, axis, split_axis=1, concat_axis=1,
                                  tiled=False)
        cols_shard = recv.reshape(r, n_shards * capacity)
        # -- 4: local fused merge + GC -------------------------------------
        perm, keep, mk = sort_and_gc(cols_shard, cutoff_hi, cutoff_lo, cph, cpl,
                                     w=r - _ROW_WORDS, is_major=is_major,
                                     retain_deletes=retain_deletes)
        out = cols_shard[:, perm]
        # padding rows are identified explicitly by the key_len sentinel
        is_pad = out[_ROW_KEY_LEN] == jnp.uint32(PAD_SENTINEL)
        keep = keep & ~is_pad
        return out, keep, mk, overflow[None]

    spec = P(None, axis)
    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(spec, P(), P(), P(), P(), P()),
        out_specs=(spec, P(axis), P(axis), P(axis)))
    return jax.jit(fn)


def distributed_compact(slab, params: GCParams, mesh: Mesh, axis: str = "shard",
                        capacity_factor: float = 2.0):
    """Host wrapper: pack a slab, shard it over the mesh, run the step.

    Returns (cols_out, keep, make_tombstone) as host arrays; cols_out rows
    follow ops/merge_gc layout, in globally range-partitioned sorted order
    (shard s holds keys <= shard s+1's)."""
    n_shards = mesh.devices.size
    cols, n, n_pad, w = pack_cols(slab)
    # pad n_pad to a multiple of shards (pack_cols gives powers of two; mesh
    # sizes are powers of two on TPU pods)
    if n_pad % n_shards:
        extra = n_shards - (n_pad % n_shards)
        pad_block = np.tile(pad_template(cols.shape[0])[:, None], (1, extra))
        cols = np.concatenate([cols, pad_block], axis=1)
    n_local = cols.shape[1] // n_shards
    # each source sends ~n_local/n_shards rows to each destination; the
    # factor absorbs skew, with the overflow retry as the hard guard
    capacity = max(64, int(n_local / n_shards * capacity_factor))
    cutoff = params.history_cutoff_ht
    cutoff_phys = cutoff >> 12
    fn = dist_compact_fn(mesh, w, capacity, params.is_major_compaction,
                         params.retain_deletes, axis)
    out, keep, mk, overflow = fn(
        cols, jnp.int32(n), jnp.uint32(cutoff >> 32),
        jnp.uint32(cutoff & 0xFFFFFFFF),
        jnp.uint32(cutoff_phys >> 20), jnp.uint32(cutoff_phys & 0xFFFFF))
    if bool(np.any(np.asarray(overflow))):
        if capacity_factor >= 64:
            raise RuntimeError("distributed compaction bucket overflow at 64x")
        return distributed_compact(slab, params, mesh, axis, capacity_factor * 2)
    return np.asarray(out), np.asarray(keep), np.asarray(mk)
