"""yblint whole-program index: the one-shot substrate the v2 passes share.

Built EXACTLY ONCE per run from the same `FileContext`s the per-file
passes walk (one parse per file stays the invariant — the index adds one
extra linear walk per module, no re-parse). It provides:

- a module/symbol table: per module, its import-alias map (including
  relative imports), module-level literal constants, top-level functions
  and classes;
- class-attribute types, inferred from annotations (`self.x: Foo`,
  class-body `x: Foo`) and `__init__`-style assignments
  (`self.x = Foo(...)`, `self.x = param` with an annotated param);
- a call graph over fully-qualified function keys
  (`pkg.mod.func` / `pkg.mod.Class.method`), with bare-name, import-alias,
  `self.method`, `self.attr.method` (through attr types), annotated-param
  and local-constructor receiver resolution — plus weak "reference" edges
  for functions passed as callbacks (`Thread(target=f)`), so reachability
  analyses see work handed to helper threads;
- `reachable(seeds)` BFS and `key_of(node)` so a per-file pass can map
  its AST nodes back into the global graph.

Resolution is conservative: an unresolvable name simply contributes no
edge/type (missed edges, never invented ones), matching the rest of
yblint's no-false-positive bias.

Passes opt in with `needs_index = True`; their `run(ctx, index)` then
receives the shared index (or a single-file index when run standalone,
e.g. from unit-test fixtures).
"""

from __future__ import annotations

import ast
import threading
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple


def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains; '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def modname_of(relpath: str) -> str:
    """'yugabyte_tpu/storage/db.py' -> 'yugabyte_tpu.storage.db';
    a package __init__.py maps to the package name itself."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class ClassInfo:
    __slots__ = ("name", "fq", "modname", "node", "base_exprs", "bases",
                 "methods", "attr_types")

    def __init__(self, name: str, fq: str, modname: str, node: ast.ClassDef):
        self.name = name
        self.fq = fq
        self.modname = modname
        self.node = node
        self.base_exprs: List[ast.AST] = list(node.bases)
        self.bases: List[str] = []           # resolved fq class names
        self.methods: Dict[str, "FuncInfo"] = {}
        self.attr_types: Dict[str, str] = {}  # attr -> fq class name


class FuncInfo:
    __slots__ = ("key", "modname", "qualname", "node", "cls")

    def __init__(self, key: str, modname: str, qualname: str,
                 node: ast.AST, cls: Optional[ClassInfo]):
        self.key = key
        self.modname = modname
        self.qualname = qualname
        self.node = node
        self.cls = cls

    @property
    def name(self) -> str:
        return self.node.name

    def param_names(self) -> List[str]:
        a = self.node.args
        return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
                + [p.arg for p in a.kwonlyargs])


class ModuleInfo:
    __slots__ = ("relpath", "modname", "ctx", "imports", "constants",
                 "functions", "classes", "assigned")

    def __init__(self, ctx) -> None:
        self.relpath = ctx.relpath
        self.modname = modname_of(ctx.relpath)
        self.ctx = ctx
        self.imports: Dict[str, str] = {}     # local alias -> fq target
        self.constants: Dict[str, object] = {}
        self.functions: Dict[str, FuncInfo] = {}   # top-level only
        self.classes: Dict[str, ClassInfo] = {}
        self.assigned: set = set()   # every top-level assigned name


class ProjectIndex:
    """See module docstring. Constructed from the run's FileContexts."""

    def __init__(self, ctxs: Sequence) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_relpath: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}   # every def, incl nested
        self.call_graph: Dict[str, Set[str]] = {}
        self._key_of_node: Dict[int, str] = {}
        self._memo: Dict[str, object] = {}
        self._memo_lock = threading.Lock()
        for ctx in ctxs:
            self._collect_module(ctx)
        for ci in self.classes.values():
            self._resolve_bases(ci)
        for ci in self.classes.values():
            self._infer_attr_types(ci)
        for fi in list(self.functions.values()):
            self.call_graph[fi.key] = self._edges(fi)

    # ------------------------------------------------------------ memoizing
    def memo(self, key: str, builder: Callable[[], object]) -> object:
        """Compute-once cache for whole-program facts a pass derives from
        the index (thread-safe: pass workers share one index)."""
        with self._memo_lock:
            if key not in self._memo:
                self._memo[key] = builder()
            return self._memo[key]

    # ----------------------------------------------------------- collection
    def _collect_module(self, ctx) -> None:
        mi = ModuleInfo(ctx)
        self.modules[mi.modname] = mi
        self.by_relpath[mi.relpath] = mi
        pkg_parts = mi.modname.split(".")
        for node in ctx.nodes_of(ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".")[0]
                mi.imports[local] = (alias.name if alias.asname
                                     else alias.name.split(".")[0])
        for node in ctx.nodes_of(ast.ImportFrom):
            if node.level:
                # relative: level 1 = this module's package
                base_parts = pkg_parts[: len(pkg_parts) - node.level]
                base = ".".join(base_parts)
            else:
                base = ""
            src = node.module or ""
            prefix = ".".join(p for p in (base, src) if p)
            for alias in node.names:
                if alias.name == "*":
                    continue
                local = alias.asname or alias.name
                mi.imports[local] = (prefix + "." + alias.name
                                     if prefix else alias.name)
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                mi.assigned.add(stmt.targets[0].id)
                val = _literal_inner(stmt.value)
                if val is not _NOT_LITERAL:
                    mi.constants[stmt.targets[0].id] = val
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                mi.assigned.add(stmt.target.id)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mi, ctx, stmt, cls=None)
            elif isinstance(stmt, ast.ClassDef):
                self._add_class(mi, ctx, stmt)
        # nested defs (inside functions) still get keys + graph nodes so
        # reachability sees closures handed to threads/callbacks
        for node in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            if id(node) not in self._key_of_node:
                key = mi.modname + "." + ctx.qualname(node)
                owner = self._owning_class_info(mi, ctx, node)
                fi = FuncInfo(key, mi.modname, ctx.qualname(node), node,
                              owner)
                self.functions.setdefault(key, fi)
                self._key_of_node[id(node)] = key

    def _owning_class_info(self, mi: ModuleInfo, ctx,
                           node: ast.AST) -> Optional[ClassInfo]:
        for a in ctx.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return self.classes.get(mi.modname + "." + a.name)
        return None

    def _add_function(self, mi: ModuleInfo, ctx, node: ast.AST,
                      cls: Optional[ClassInfo]) -> None:
        qual = ctx.qualname(node)
        key = mi.modname + "." + qual
        fi = FuncInfo(key, mi.modname, qual, node, cls)
        self.functions[key] = fi
        self._key_of_node[id(node)] = key
        if cls is None:
            mi.functions[node.name] = fi
        else:
            cls.methods[node.name] = fi

    def _add_class(self, mi: ModuleInfo, ctx, node: ast.ClassDef) -> None:
        fq = mi.modname + "." + node.name
        ci = ClassInfo(node.name, fq, mi.modname, node)
        mi.classes[node.name] = ci
        self.classes[fq] = ci
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mi, ctx, stmt, cls=ci)
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name):
                t = self._resolve_type_expr(mi, stmt.annotation)
                if t:
                    ci.attr_types.setdefault(stmt.target.id, t)

    # ----------------------------------------------------------- resolution
    def resolve(self, mi: ModuleInfo, dotted: str) -> Optional[str]:
        """Local dotted name -> fully-qualified name, through the module's
        import aliases or its own top-level symbols. None if unknown."""
        if not dotted:
            return None
        parts = dotted.split(".")
        head = parts[0]
        if head in mi.imports:
            return ".".join([mi.imports[head]] + parts[1:])
        if head in mi.functions or head in mi.classes \
                or head in mi.constants or head in mi.assigned:
            return mi.modname + "." + dotted
        return None

    def lookup_function(self, fq: Optional[str]) -> Optional[FuncInfo]:
        return self.functions.get(fq) if fq else None

    def lookup_class(self, fq: Optional[str]) -> Optional[ClassInfo]:
        return self.classes.get(fq) if fq else None

    def resolve_str_const(self, mi: ModuleInfo,
                          expr: ast.AST) -> Optional[str]:
        """String literal, or a Name/Attribute resolving to a module-level
        string constant (cross-module through import aliases)."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        fq = self.resolve(mi, dotted_name(expr))
        if fq is None:
            return None
        mod, _, name = fq.rpartition(".")
        owner = self.modules.get(mod)
        if owner is not None:
            v = owner.constants.get(name)
            if isinstance(v, str):
                return v
        return None

    def find_method(self, ci: Optional[ClassInfo],
                    name: str) -> Optional[FuncInfo]:
        """Method resolution through the (index-visible) base chain."""
        seen: Set[str] = set()
        stack = [ci] if ci else []
        while stack:
            c = stack.pop(0)
            if c is None or c.fq in seen:
                continue
            seen.add(c.fq)
            if name in c.methods:
                return c.methods[name]
            stack.extend(self.classes.get(b) for b in c.bases)
        return None

    def key_of(self, node: ast.AST) -> Optional[str]:
        """Graph key of a def node from one of the indexed contexts."""
        return self._key_of_node.get(id(node))

    def reachable(self, seeds: Sequence[str]) -> Set[str]:
        out = set(k for k in seeds if k in self.call_graph)
        frontier = list(out)
        while frontier:
            cur = frontier.pop()
            for nxt in self.call_graph.get(cur, ()):
                if nxt not in out:
                    out.add(nxt)
                    frontier.append(nxt)
        return out

    # ------------------------------------------------------- type inference
    def _resolve_bases(self, ci: ClassInfo) -> None:
        mi = self.modules[ci.modname]
        for b in ci.base_exprs:
            fq = self.resolve(mi, dotted_name(b))
            if fq in self.classes:
                ci.bases.append(fq)

    def _resolve_type_expr(self, mi: ModuleInfo,
                           ann: ast.AST) -> Optional[str]:
        """Annotation -> fq class name ('Foo', 'mod.Foo', Optional[Foo],
        'Foo' as a string literal). None when not an index-known class."""
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):
            base = dotted_name(ann.value)
            if base.rpartition(".")[2] == "Optional":
                return self._resolve_type_expr(mi, ann.slice)
            return None
        fq = self.resolve(mi, dotted_name(ann))
        return fq if fq in self.classes else None

    def _infer_attr_types(self, ci: ClassInfo) -> None:
        mi = self.modules[ci.modname]
        ordered = sorted(ci.methods.values(),
                         key=lambda f: f.name != "__init__")
        for fi in ordered:
            ann_of: Dict[str, Optional[str]] = {}
            args = fi.node.args
            for p in args.posonlyargs + args.args + args.kwonlyargs:
                if p.annotation is not None:
                    ann_of[p.arg] = self._resolve_type_expr(
                        mi, p.annotation)
            for node in ast.walk(fi.node):
                target = None
                value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target = node.target
                    t = self._resolve_type_expr(mi, node.annotation)
                    if t and isinstance(target, ast.Attribute) \
                            and isinstance(target.value, ast.Name) \
                            and target.value.id == "self":
                        ci.attr_types.setdefault(target.attr, t)
                    continue
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                t = self._value_type(mi, value, ann_of)
                if t:
                    ci.attr_types.setdefault(target.attr, t)

    def _value_type(self, mi: ModuleInfo, value: Optional[ast.AST],
                    ann_of: Dict[str, Optional[str]]) -> Optional[str]:
        """Type of an assigned value: Ctor(...) of a known class, an
        annotated parameter, or a call to a function whose return
        annotation is a known class."""
        if value is None:
            return None
        if isinstance(value, ast.Name):
            return ann_of.get(value.id)
        if isinstance(value, ast.Call):
            fq = self.resolve(mi, dotted_name(value.func))
            if fq in self.classes:
                return fq
            fi = self.lookup_function(fq)
            if fi is not None and fi.node.returns is not None:
                owner = self.modules[fi.modname]
                return self._resolve_type_expr(owner, fi.node.returns)
        return None

    # ------------------------------------------------------------ call graph
    def local_types(self, fi: FuncInfo) -> Dict[str, str]:
        """name -> fq class for a function's params (annotations) and
        simple locals (constructor / annotated-return-call assignments)."""
        mi = self.modules[fi.modname]
        ann_of: Dict[str, Optional[str]] = {}
        args = fi.node.args
        for p in args.posonlyargs + args.args + args.kwonlyargs:
            if p.annotation is not None:
                ann_of[p.arg] = self._resolve_type_expr(mi, p.annotation)
        env: Dict[str, str] = {k: v for k, v in ann_of.items() if v}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._value_type(mi, node.value, ann_of)
                if t:
                    env.setdefault(node.targets[0].id, t)
        return env

    def _nested_defs(self, fi: FuncInfo) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for node in ast.walk(fi.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fi.node:
                key = self._key_of_node.get(id(node))
                if key:
                    out[node.name] = key
        return out

    def _edges(self, fi: FuncInfo) -> Set[str]:
        mi = self.modules[fi.modname]
        env = self.local_types(fi)
        nested = self._nested_defs(fi)
        edges: Set[str] = set()

        def add_callable(fq: Optional[str]) -> None:
            if fq is None:
                return
            if fq in self.functions:
                edges.add(fq)
            elif fq in self.classes:
                init = self.find_method(self.classes[fq], "__init__")
                if init is not None:
                    edges.add(init.key)

        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name):
                    if f.id in nested:
                        edges.add(nested[f.id])
                    else:
                        add_callable(self.resolve(mi, f.id))
                elif isinstance(f, ast.Attribute):
                    recv = f.value
                    target: Optional[FuncInfo] = None
                    if isinstance(recv, ast.Name) and recv.id in ("self",
                                                                  "cls"):
                        target = self.find_method(fi.cls, f.attr)
                    elif isinstance(recv, ast.Name) and recv.id in env:
                        target = self.find_method(
                            self.classes.get(env[recv.id]), f.attr)
                    elif (isinstance(recv, ast.Attribute)
                          and isinstance(recv.value, ast.Name)
                          and recv.value.id == "self" and fi.cls is not None
                          and recv.attr in fi.cls.attr_types):
                        target = self.find_method(
                            self.classes.get(fi.cls.attr_types[recv.attr]),
                            f.attr)
                    else:
                        add_callable(self.resolve(mi, dotted_name(f)))
                    if target is not None:
                        edges.add(target.key)
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                # weak callback-reference edge (Thread(target=f), map(f, ..))
                if node.id in nested:
                    edges.add(nested[node.id])
                elif node.id in mi.functions:
                    edges.add(mi.functions[node.id].key)
        edges.discard(fi.key)
        return edges


class _NotLiteral:
    pass


_NOT_LITERAL = _NotLiteral()


def _literal_inner(node: ast.AST):
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [_literal_inner(e) for e in node.elts]
        if any(v is _NOT_LITERAL for v in vals):
            return _NOT_LITERAL
        return tuple(vals)
    return _NOT_LITERAL
