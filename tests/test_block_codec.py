"""Device SST block codec (ops/block_codec.py): differential byte-identity
vs the host codec (block_format.decode_block/encode_block via the native
shell), typed corruption handling, and device-fault containment.

The contract under test:
  - device decode of raw block bytes produces the EXACT StagedCols matrix
    stage_slab(read_all()) builds — bit for bit, including the column
    stats — across block sizes, key widths, TTL mixes, compression,
    empty/single-entry blocks and max-width keys;
  - a codec-driven compaction writes files byte-identical (data AND base)
    to the shell-driven device-native job;
  - corrupt blocks surface typed Status.Corruption before anything
    uploads — never wrong bytes;
  - device faults at the dispatch/result sites quarantine the shape
    bucket and complete byte-identically via the native merge with zero
    leaked pins and zero outstanding staging leases; a transient result
    fault retries once and stays on device.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_run_merge import _make_run  # noqa: E402

from yugabyte_tpu.ops import block_codec, device_faults  # noqa: E402
from yugabyte_tpu.ops.merge_gc import stage_slab  # noqa: E402
from yugabyte_tpu.ops.slabs import ValueArray  # noqa: E402
from yugabyte_tpu.storage import block_format  # noqa: E402
from yugabyte_tpu.storage import compaction as compaction_mod  # noqa: E402
from yugabyte_tpu.storage import integrity  # noqa: E402,F401 (flag defs)
from yugabyte_tpu.storage import native_engine  # noqa: E402
from yugabyte_tpu.storage import offload_policy  # noqa: E402
from yugabyte_tpu.storage.device_cache import (DeviceSlabCache,  # noqa: E402
                                               host_staging_pool)
from yugabyte_tpu.storage.sst import (Frontier, SSTReader,  # noqa: E402
                                      SSTWriter, _block_decode_counter)
from yugabyte_tpu.utils import flags  # noqa: E402
from yugabyte_tpu.utils.status import Code, StatusError  # noqa: E402

CUTOFF = (10_000_000 << 12)


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.setenv("YBTPU_DEVICE_CODEC", "1")
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()
    yield
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()


def _device():
    import jax
    return jax.devices()[0]


def _mk_run(rng, n, key_space, value_bytes=16, ttl_frac=0.0, w=3):
    slab = _make_run(rng, n, key_space, ttl_frac=ttl_frac, w=w)
    data = rng.integers(0, 256, size=n * value_bytes, dtype=np.uint8)
    offs = np.arange(n + 1, dtype=np.int64) * value_bytes
    slab.values = ValueArray(data, offs)
    return slab


def _write_runs(workdir, runs, block_entries=None):
    readers = []
    for i, slab in enumerate(runs):
        p = os.path.join(workdir, f"in{i:03d}.sst")
        SSTWriter(p, block_entries=block_entries).write(slab, Frontier())
        readers.append(SSTReader(p))
    return readers


def _run_job(readers, out_dir, cache=None, input_ids=None, first_id=100,
             is_major=True, prestage=False, cancel_token=None):
    os.makedirs(out_dir, exist_ok=True)
    if cache is None:
        cache = DeviceSlabCache(device=_device())
    if input_ids is None:
        input_ids = list(range(len(readers)))
    if prestage:
        for fid, r in zip(input_ids, readers):
            cache.stage(fid, r.read_all())
    ids = iter(range(first_id, first_id + 500))
    return compaction_mod.run_compaction_job_device_native(
        readers, out_dir, lambda: next(ids), CUTOFF, is_major,
        device=_device(), device_cache=cache, input_ids=input_ids,
        cancel=cancel_token)


def _file_bytes(outputs):
    out = []
    for _fid, base_path, _props in outputs:
        with open(base_path + ".sblock.0", "rb") as f:
            data = f.read()
        with open(base_path, "rb") as f:
            base = f.read()
        out.append((data, base))
    return out


# ---------------------------------------------------------------- decode


@pytest.mark.parametrize("n,block_entries,ttl_frac,w", [
    (700, 128, 0.0, 3),       # multi-block
    (700, 4096, 0.3, 3),      # single block + TTL entries
    (1, 64, 0.0, 3),          # single-entry file
    (129, 1, 0.0, 3),         # one entry per block (restart-interval floor)
    (350, 100, 0.0, 7),       # wide keys
])
def test_decode_matches_host_staging(tmp_path, n, block_entries,
                                     ttl_frac, w):
    """Device decode of raw block bytes == stage_slab over the host
    decode path, bit for bit (cols, stats, shape bucket)."""
    rng = np.random.default_rng(31)
    slab = _mk_run(rng, n, max(2, n // 2), ttl_frac=ttl_frac, w=w)
    [r] = _write_runs(str(tmp_path), [slab], block_entries=block_entries)
    ref = stage_slab(r.read_all())
    blocks0 = _block_decode_counter().value()
    rfb = block_codec.parse_raw_file(r.read_raw(), r.block_handles)
    st = block_codec.decode_file_to_staged(rfb, _device())
    assert _block_decode_counter().value() == blocks0, \
        "device decode touched the host decode path"
    assert (st.n, st.n_pad, st.w) == (ref.n, ref.n_pad, ref.w)
    assert np.array_equal(np.asarray(st.cols_dev), np.asarray(ref.cols_dev))
    assert np.array_equal(st.col_const, ref.col_const)
    assert np.array_equal(st.col_first, ref.col_first)
    assert np.array_equal(st.sort_rows, ref.sort_rows)
    assert st.n_sort == ref.n_sort
    # zero-copy values match the decoded rows
    want = r.read_all()
    got = rfb.values
    assert len(got) == want.n
    assert all(got[i] == want.values[int(want.value_idx[i])]
               for i in range(want.n))
    r.close()


def test_decode_max_width_keys(tmp_path):
    """Keys that exactly fill the stride (no zero pad in the final
    word) decode identically."""
    rng = np.random.default_rng(32)
    slab = _mk_run(rng, 200, 80, w=3)
    slab.key_len[:] = 12            # every key exactly w*4 bytes
    slab.doc_key_len[:] = 12
    [r] = _write_runs(str(tmp_path), [slab], block_entries=64)
    ref = stage_slab(r.read_all())
    rfb = block_codec.parse_raw_file(r.read_raw(), r.block_handles)
    st = block_codec.decode_file_to_staged(rfb, _device())
    assert np.array_equal(np.asarray(st.cols_dev), np.asarray(ref.cols_dev))
    r.close()


def test_decode_compressed_blocks(tmp_path):
    """zlib-compressed blocks: host decompress (C speed) + device
    decode, still bit-identical."""
    rng = np.random.default_rng(33)
    slab = _mk_run(rng, 500, 200)
    old = flags.get_flag("sst_compression")
    flags.set_flag("sst_compression", "zlib")
    try:
        [r] = _write_runs(str(tmp_path), [slab], block_entries=128)
    finally:
        flags.set_flag("sst_compression", old)
    ref = stage_slab(r.read_all())
    rfb = block_codec.parse_raw_file(r.read_raw(), r.block_handles)
    st = block_codec.decode_file_to_staged(rfb, _device())
    assert np.array_equal(np.asarray(st.cols_dev), np.asarray(ref.cols_dev))
    r.close()


def test_decode_empty_file_unsupported(tmp_path):
    rfb = block_codec.RawFileBlocks(
        n=0, w=1, counts=np.zeros(0, dtype=np.int64),
        strides_w=np.zeros(0, dtype=np.int64), bodies=[],
        value_parts=[])
    with pytest.raises(block_codec.BlockCodecUnsupported):
        block_codec.decode_file_to_staged(rfb, _device())


def test_corrupt_crc_raises_typed_corruption(tmp_path):
    """A flipped byte in a block surfaces Status.Corruption from the raw
    parse — BEFORE anything uploads; never wrong bytes."""
    rng = np.random.default_rng(34)
    slab = _mk_run(rng, 300, 120)
    [r] = _write_runs(str(tmp_path), [slab], block_entries=64)
    with open(r.data_path, "rb") as f:
        raw = bytearray(f.read())
    off, size, _cnt = r.block_handles[1]
    raw[off + block_format.HEADER_BYTES + 5] ^= 0x40   # body byte flip
    with pytest.raises(StatusError) as ei:
        block_codec.parse_raw_file(bytes(raw), r.block_handles)
    assert ei.value.status.code == Code.CORRUPTION
    # magic corruption too
    raw2 = bytearray(raw)
    raw2[off + block_format.HEADER_BYTES + 5] ^= 0x40  # restore body
    raw2[off] ^= 0xFF                                  # break the magic
    with pytest.raises(StatusError) as ei2:
        block_codec.parse_raw_file(bytes(raw2), r.block_handles)
    assert ei2.value.status.code == Code.CORRUPTION
    r.close()


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_corrupt_input_fails_job_without_fallback(tmp_path):
    """Corruption is NOT a device fault: the codec job surfaces it typed
    instead of silently completing via the native merge."""
    rng = np.random.default_rng(35)
    runs = [_mk_run(rng, 300, 150) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs, block_entries=64)
    with open(readers[0].data_path, "r+b") as f:
        off, size, _ = readers[0].block_handles[0]
        f.seek(off + block_format.HEADER_BYTES + 3)
        b = f.read(1)
        f.seek(off + block_format.HEADER_BYTES + 3)
        f.write(bytes([b[0] ^ 0x10]))
    with pytest.raises(StatusError) as ei:
        _run_job(readers, str(tmp_path / "out"))
    assert ei.value.status.code == Code.CORRUPTION
    for r in readers:
        r.close()


# ---------------------------------------------------------------- encode


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
@pytest.mark.parametrize("compress", [False, True])
def test_codec_job_byte_identical_to_shell(tmp_path, compress):
    """The codec-driven compaction == the shell-driven device-native job
    over the same inputs: data files AND base files (incl. the learned
    index and bloom/index blocks), across a multi-file split."""
    rng = np.random.default_rng(36)
    runs = [_mk_run(rng, 900, 3000, ttl_frac=0.2) for _ in range(3)]
    old_comp = flags.get_flag("sst_compression")
    old_split = flags.get_flag("compaction_max_output_entries_per_sst")
    old_shadow = flags.get_flag("shadow_verify_sample")
    flags.set_flag("sst_compression", "zlib" if compress else "none")
    flags.set_flag("compaction_max_output_entries_per_sst", 700)
    flags.set_flag("shadow_verify_sample", 0.0)
    try:
        readers = _write_runs(str(tmp_path), runs)
        res = _run_job(readers, str(tmp_path / "codec"), is_major=False)
        os.environ["YBTPU_DEVICE_CODEC"] = "0"
        ref = _run_job(readers, str(tmp_path / "shell"), is_major=False,
                       prestage=True)
    finally:
        os.environ["YBTPU_DEVICE_CODEC"] = "1"
        flags.set_flag("sst_compression", old_comp)
        flags.set_flag("compaction_max_output_entries_per_sst", old_split)
        flags.set_flag("shadow_verify_sample", old_shadow)
    assert len(res.outputs) >= 2, "expected a multi-file split"
    assert res.rows_out == ref.rows_out
    assert res.rows_in == ref.rows_in
    assert res.tombstones_written == ref.tombstones_written
    assert _file_bytes(res.outputs) == _file_bytes(ref.outputs)
    for r in readers:
        r.close()


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_codec_counters_and_flat_host_decode(tmp_path):
    """A codec job moves ONLY the device codec counters: host block
    decode and shell ingest stay flat; device decode/encode counters
    increment; a shell job increments the encode fallback counter."""
    rng = np.random.default_rng(37)
    runs = [_mk_run(rng, 400, 200) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs, block_entries=100)
    old_shadow = flags.get_flag("shadow_verify_sample")
    old_digest = flags.get_flag("resident_digest_sample")
    flags.set_flag("shadow_verify_sample", 0.0)
    flags.set_flag("resident_digest_sample", 0.0)
    cm = block_codec.codec_metrics()
    try:
        b0 = _block_decode_counter().value()
        i0 = compaction_mod._ingest_decode_counter().value()
        d0 = cm["decode_blocks"].value()
        e0 = cm["encode_blocks"].value()
        f0 = cm["encode_fallbacks"].value()
        _run_job(readers, str(tmp_path / "codec"))
        assert _block_decode_counter().value() == b0
        assert compaction_mod._ingest_decode_counter().value() == i0
        assert cm["decode_blocks"].value() == d0 + 8  # 2 files x 4 blocks
        assert cm["encode_blocks"].value() > e0
        assert cm["encode_fallbacks"].value() == f0
        os.environ["YBTPU_DEVICE_CODEC"] = "0"
        _run_job(readers, str(tmp_path / "shell"), prestage=True,
                 first_id=700)
        assert cm["encode_fallbacks"].value() == f0 + 1
    finally:
        os.environ["YBTPU_DEVICE_CODEC"] = "1"
        flags.set_flag("shadow_verify_sample", old_shadow)
        flags.set_flag("resident_digest_sample", old_digest)
    for r in readers:
        r.close()


# ------------------------------------------------- device-fault containment


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
@pytest.mark.parametrize("site", ["dispatch", "result"])
def test_persistent_fault_falls_back_byte_identical(tmp_path, site):
    """A persistent device fault in the codec path quarantines the shape
    bucket, completes via the native merge byte-identically, does not
    re-fault the next job (pre-dispatch native routing), and leaks zero
    pins and zero staging leases."""
    rng = np.random.default_rng(38)
    runs = [_mk_run(rng, 500, 250) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    fb0 = compaction_mod._storage_fallback_counter().value()

    device_faults.arm("runtime", site=site, count=100)  # persistent
    try:
        res = _run_job(readers, str(tmp_path / "out"), cache=cache)
    finally:
        device_faults.disarm_all()
    assert res.outputs, "fallback produced no outputs"
    assert compaction_mod._storage_fallback_counter().value() == fb0 + 1
    assert cache.pinned_count() == 0, "leaked pins after fault fallback"
    assert host_staging_pool().outstanding() == 0
    for fid, _p, _props in res.outputs:
        assert not cache.contains(fid), \
            "cache entry survived for a deleted partial output"
    # quarantined: the NEXT job routes native pre-dispatch, no re-fault
    assert offload_policy.bucket_quarantine().snapshot()
    device_faults.arm("runtime", site=site, count=100)
    try:
        res2 = _run_job(readers, str(tmp_path / "out2"), cache=cache,
                        first_id=300)
    finally:
        device_faults.disarm_all()
    assert compaction_mod._storage_fallback_counter().value() == fb0 + 1, \
        "quarantined bucket re-entered the device path"
    # byte-identity with the pure-native job (data files: the native
    # reference carries no learned index, so base files legitimately
    # differ by the advisory model)
    os.makedirs(str(tmp_path / "ref"))
    ids = iter(range(500, 600))
    ref = compaction_mod.run_compaction_job(
        readers, str(tmp_path / "ref"), lambda: next(ids), CUTOFF, True,
        device="native")
    assert [d for d, _b in _file_bytes(res.outputs)] == \
        [d for d, _b in _file_bytes(ref.outputs)]
    assert [d for d, _b in _file_bytes(res2.outputs)] == \
        [d for d, _b in _file_bytes(ref.outputs)]
    for r in readers:
        r.close()


def test_transient_decode_fault_retries_and_stays_on_device(tmp_path):
    """count=1 result fault fires at the decode download: the codec
    retries the launch once and the job completes WITHOUT the native
    fallback."""
    if not native_engine.available():
        pytest.skip("native engine unavailable")
    rng = np.random.default_rng(39)
    runs = [_mk_run(rng, 400, 200) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    from yugabyte_tpu.ops.run_merge import _chunk_retry_counter
    r0 = _chunk_retry_counter().value()
    fb0 = compaction_mod._storage_fallback_counter().value()
    device_faults.arm("runtime", site="result", count=1)
    res = _run_job(readers, str(tmp_path / "out"))
    assert device_faults.armed_count() == 0, "fault must have fired"
    assert _chunk_retry_counter().value() == r0 + 1
    assert compaction_mod._storage_fallback_counter().value() == fb0, \
        "retry succeeded: no native fallback"
    assert not offload_policy.bucket_quarantine().snapshot()
    assert res.outputs
    for r in readers:
        r.close()


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_cancel_mid_codec_stage_c_sweeps_partials(tmp_path, monkeypatch):
    """Cancellation between codec span writes sweeps the already-written
    files and leaks nothing."""
    from yugabyte_tpu.utils.cancellation import (CancellationToken,
                                                 OperationCancelled)
    rng = np.random.default_rng(40)
    runs = [_mk_run(rng, 900, 4000) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    old = flags.get_flag("compaction_max_output_entries_per_sst")
    flags.set_flag("compaction_max_output_entries_per_sst", 500)
    token = CancellationToken("test-job")
    orig = compaction_mod._DeviceCodecWriter._write_span

    def tripping(self, surv, mk, start, end, more_coming):
        orig(self, surv, mk, start, end, more_coming)
        token.cancel("mid-job shutdown")

    monkeypatch.setattr(compaction_mod._DeviceCodecWriter, "_write_span",
                        tripping)
    out_dir = str(tmp_path / "out")
    try:
        with pytest.raises(OperationCancelled):
            _run_job(readers, out_dir, cancel_token=token)
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old)
    leftovers = os.listdir(out_dir) if os.path.isdir(out_dir) else []
    assert not leftovers, f"partial outputs leaked: {leftovers}"
    assert host_staging_pool().outstanding() == 0
    for r in readers:
        r.close()
