// Shared native merge + MVCC-GC core.
//
// One implementation of the internal-key comparator, the k-way heap merge
// (ref: src/yb/rocksdb/table/merger.cc:51 MergingIterator) and the
// sequential overwrite-stack GC filter
// (ref: src/yb/docdb/docdb_compaction_filter.cc:74-320), used by
//   - compaction_baseline.cc  (the vs_baseline denominator + differential
//     test oracle, operating on Python-packed arrays), and
//   - compaction_engine.cc    (the production native shell: SST block
//     decode -> merge+GC -> block encode, operating on decoded columns).
// Keeping the GC semantics in exactly one place is what lets three
// implementations (TPU kernel, Python model, native) stay byte-identical.

#ifndef YBTPU_MERGE_GC_CORE_H_
#define YBTPU_MERGE_GC_CORE_H_

#include <cstdint>
#include <cstring>
#include <vector>

namespace ybtpu {

struct Ctx {
  const uint8_t* keys;     // row i at keys + i*stride (raw memcmp bytes)
  const int32_t* key_len;
  int32_t stride;
  const uint64_t* ht;
  const uint32_t* wid;
};

// internal-key comparator: key memcmp asc, then ht desc, then wid desc
inline int cmp_entries(const Ctx& c, int64_t a, int64_t b) {
  const uint8_t* ka = c.keys + a * c.stride;
  const uint8_t* kb = c.keys + b * c.stride;
  int32_t la = c.key_len[a], lb = c.key_len[b];
  int32_t m = la < lb ? la : lb;
  int r = memcmp(ka, kb, m);
  if (r) return r;
  if (la != lb) return la < lb ? -1 : 1;
  if (c.ht[a] != c.ht[b]) return c.ht[a] > c.ht[b] ? -1 : 1;  // desc
  if (c.wid[a] != c.wid[b]) return c.wid[a] > c.wid[b] ? -1 : 1;
  return 0;
}

// Skip one encoded key component starting at *pos (tag + payload).
// Tags per docdb/value_type.py; zero-encoded strings per doc_kv_util.h:95.
inline bool skip_key_component(const uint8_t* k, int32_t len, int32_t* pos) {
  if (*pos >= len) return false;
  uint8_t tag = k[(*pos)++];
  switch (tag) {
    case '$': case 'F': case 'T': return true;     // null / false / true
    case 'H': *pos += 4; break;                    // int32
    case 'I': case 'D': *pos += 8; break;          // int64 / double
    case 'J': case 'K': *pos += 2; break;          // system / column id
    case 'S': case 'Y':                            // zero-encoded bytes
      for (;;) {
        if (*pos + 1 > len) return false;
        if (k[*pos] != 0) { ++*pos; continue; }
        if (*pos + 2 > len) return false;
        if (k[*pos + 1] == 0) { *pos += 2; return true; }
        if (k[*pos + 1] == 1) { *pos += 2; continue; }
        return false;
      }
    default:
      return false;
  }
  return *pos <= len;
}

// Byte length of the DocKey portion of key_prefix (through the range-group
// kGroupEnd '!'), or len when the prefix is not a doc key — system keys
// count as one whole-key "document" (docdb/doc_key.py _doc_key_len).
inline int32_t doc_key_len(const uint8_t* k, int32_t len) {
  int32_t pos = 0;
  if (pos < len && k[pos] == 'G') {  // kUInt16Hash + 2-byte hash
    pos += 3;
    while (pos < len && k[pos] != '!') {
      if (!skip_key_component(k, len, &pos)) return len;
    }
    if (pos >= len) return len;
    ++pos;  // hashed kGroupEnd
  }
  while (pos < len && k[pos] != '!') {
    if (!skip_key_component(k, len, &pos)) return len;
  }
  if (pos >= len) return len;
  return pos + 1;  // range kGroupEnd
}

// Number of subkey components below the DocKey (slabs.py subkey_depth);
// undecodable tails count as deep (conservative).
inline int32_t subkey_depth(const uint8_t* k, int32_t len, int32_t d) {
  int32_t pos = d, depth = 0;
  while (pos < len) {
    if (!skip_key_component(k, len, &pos)) return depth + 1;
    ++depth;
  }
  return depth;
}

// Component end offsets of a SubDocKey: [dkl, end_of_subkey_1, ...] — the
// reference's sub_key_ends_ (ref: SubDocKey::DecodeDocKeyAndSubKeyEnds).
// Tag bytes per docdb/doc_key.py PrimitiveValue: fixed-width payloads or
// zero-encoded strings terminated by 00 00 (00 01 escapes interior zeros).
// Returns false when the subkey tail is undecodable (system keys).
inline bool sub_key_ends(const uint8_t* k, int32_t len, int32_t d,
                         std::vector<int32_t>* ends) {
  ends->clear();
  ends->push_back(d);
  int32_t pos = d;
  while (pos < len) {
    uint8_t tag = k[pos++];
    switch (tag) {
      case '$': case 'F': case 'T': break;           // null / false / true
      case 'H': pos += 4; break;                     // int32
      case 'I': case 'D': pos += 8; break;           // int64 / double
      case 'J': case 'K': pos += 2; break;           // system / column id
      case 'S': case 'Y':                            // zero-encoded bytes
        for (;;) {
          if (pos + 1 > len) return false;
          if (k[pos] != 0) { ++pos; continue; }
          if (pos + 2 > len) return false;
          if (k[pos + 1] == 0) { pos += 2; break; }
          if (k[pos + 1] == 1) { pos += 2; continue; }
          return false;
        }
        break;
      default:
        return false;
    }
    if (pos > len) return false;
    ends->push_back(pos);
  }
  return true;
}

// DocHybridTime as an ordered pair; {0,0} doubles as the kMin sentinel
// (real hybrid times are > 0, so nothing is strictly below it).
struct Ov {
  uint64_t ht;
  uint32_t wid;
};
inline bool ov_less(uint64_t ht, uint32_t wid, const Ov& o) {
  return ht < o.ht || (ht == o.ht && wid < o.wid);
}

// The full merge + filter loop. Writes the merged order into order_out and
// per-merged-position keep/make-tombstone into keep_out/mk_out (all length
// n). Returns the number of kept entries.
inline int64_t merge_and_filter(
    const Ctx& c, int32_t n_runs, const int64_t* run_offsets,
    const int32_t* dkl, const uint8_t* flags, const int64_t* ttl_ms,
    uint64_t cutoff_ht, int32_t is_major, int32_t retain_deletes,
    uint8_t* keep_out, uint8_t* mk_out, int64_t* order_out) {
  // ---- binary min-heap of run heads (MergingIterator) --------------------
  std::vector<int64_t> heap;      // entry index
  std::vector<int32_t> heap_run;  // owning run
  std::vector<int64_t> pos(n_runs);
  heap.reserve(n_runs);
  auto heap_less = [&](size_t i, size_t j) {
    return cmp_entries(c, heap[i], heap[j]) < 0;
  };
  auto sift_up = [&](size_t i) {
    while (i > 0) {
      size_t p = (i - 1) / 2;
      if (heap_less(i, p)) {
        std::swap(heap[i], heap[p]);
        std::swap(heap_run[i], heap_run[p]);
        i = p;
      } else break;
    }
  };
  auto sift_down = [&](size_t i) {
    size_t sz = heap.size();
    for (;;) {
      size_t l = 2 * i + 1, r = l + 1, s = i;
      if (l < sz && heap_less(l, s)) s = l;
      if (r < sz && heap_less(r, s)) s = r;
      if (s == i) break;
      std::swap(heap[i], heap[s]);
      std::swap(heap_run[i], heap_run[s]);
      i = s;
    }
  };
  for (int32_t r = 0; r < n_runs; ++r) {
    pos[r] = run_offsets[r];
    if (pos[r] < run_offsets[r + 1]) {
      heap.push_back(pos[r]);
      heap_run.push_back(r);
      sift_up(heap.size() - 1);
    }
  }

  // ---- sequential GC filter state ---------------------------------------
  // Full overwrite-STACK semantics, mirroring the reference filter (ref:
  // docdb/docdb_compaction_filter.cc:104-198): one overwrite hybrid time
  // per key component; a kept at-or-below-cutoff entry pushes
  // max(parent_ov, own dht) for its subtree; the obsolete check is strict.
  const uint64_t cutoff_phys = cutoff_ht >> 12;
  std::vector<int32_t> ends;        // current key component ends
  std::vector<int32_t> prev_ends;   // sub_key_ends_ (updated every entry)
  std::vector<Ov> overwrite;        // overwrite_ stack
  std::vector<uint8_t> prev_key;    // prev_subdoc_key_ (kept entries only)
  int32_t prev_len = 0;

  int64_t out = 0, kept = 0;
  while (!heap.empty()) {
    int64_t e = heap[0];
    int32_t run = heap_run[0];
    // advance the winning run (pop + push next = replace top + sift)
    if (++pos[run] < run_offsets[run + 1]) {
      heap[0] = pos[run];
      sift_down(0);
    } else {
      heap[0] = heap.back();
      heap_run[0] = heap_run.back();
      heap.pop_back();
      heap_run.pop_back();  // keep the entry<->run pairing aligned
      if (!heap.empty()) sift_down(0);
    }

    const uint8_t* k = c.keys + e * c.stride;
    int32_t len = c.key_len[e], d = dkl[e];
    // bytes shared with prev_subdoc_key_, then truncate the stacks to the
    // components fully inside the shared prefix
    int32_t m = len < prev_len ? len : prev_len;
    int32_t same = 0;
    while (same < m && k[same] == prev_key[same]) ++same;
    size_t ns = prev_ends.size();
    while (ns > 0 && prev_ends[ns - 1] > same) --ns;
    if (!sub_key_ends(k, len, d, &ends)) {
      // undecodable subkey tail (system keys): one trailing component
      ends.clear();
      ends.push_back(d < len ? d : len);
      if (d < len) ends.push_back(len);
    }
    size_t new_size = ends.size();
    if (overwrite.size() > ns) overwrite.resize(ns);
    Ov prev_ov = overwrite.empty() ? Ov{0, 0} : overwrite.back();

    if (ov_less(c.ht[e], c.wid[e], prev_ov)) {
      // fully overwritten at/before the cutoff by an ancestor or a newer
      // version of the same key (strict <, ref :166)
      prev_ends = ends;
      order_out[out] = e; keep_out[out] = 0; mk_out[out] = 0; ++out;
      continue;
    }
    if (overwrite.size() + 1 < new_size)
      overwrite.resize(new_size - 1, prev_ov);
    if (overwrite.size() == new_size) overwrite.pop_back();

    bool below = c.ht[e] <= cutoff_ht;
    prev_ends = ends;
    prev_key.assign(k, k + len);
    prev_len = len;
    if (!below) {
      overwrite.push_back(prev_ov);  // retained history above the cutoff
      order_out[out] = e; keep_out[out] = 1; mk_out[out] = 0; ++out; ++kept;
      continue;
    }
    Ov own{c.ht[e], c.wid[e]};
    overwrite.push_back(ov_less(own.ht, own.wid, prev_ov) ? prev_ov : own);

    bool has_ttl = flags[e] & 4;
    bool expired = has_ttl &&
        ((c.ht[e] >> 12) + (uint64_t)ttl_ms[e] * 1000 <= cutoff_phys);
    bool already_tomb = flags[e] & 1;
    bool tomb = already_tomb || expired;
    if (tomb && is_major && !retain_deletes) {
      order_out[out] = e; keep_out[out] = 0; mk_out[out] = 0; ++out;
      continue;  // visible tombstone at bottommost level (ref :316-319)
    }
    order_out[out] = e;
    keep_out[out] = 1;
    mk_out[out] = (expired && !already_tomb && !is_major) ? 1 : 0;
    ++out;
    ++kept;
  }
  return kept;
}

}  // namespace ybtpu

#endif  // YBTPU_MERGE_GC_CORE_H_
