"""SharedLockManager / LockBatch: in-memory row+prefix intent locks.

Capability parity with the reference (ref: src/yb/docdb/shared_lock_manager.h,
src/yb/docdb/lock_batch.h, intent types in src/yb/docdb/intent.h). Four
intent lock modes: weak/strong x read/write. A write to a document path takes
a STRONG lock on the full path and WEAK locks on every prefix, so that
operations on disjoint subpaths of one document don't serialize, while a
whole-document operation conflicts with any write below it.

Conflict rule (ref shared_lock_manager.cc conflict matrix): two intent types
conflict iff at least one of them is STRONG and at least one of them is WRITE.
(read/read never conflicts; weak/weak never conflicts.)
"""

from __future__ import annotations

import enum
import threading
from collections import defaultdict
from typing import Dict, Iterable, List, Sequence, Tuple


class IntentType(enum.IntEnum):
    kWeakRead = 0
    kWeakWrite = 1
    kStrongRead = 2
    kStrongWrite = 3

    @property
    def is_strong(self) -> bool:
        return self >= IntentType.kStrongRead

    @property
    def is_write(self) -> bool:
        return self in (IntentType.kWeakWrite, IntentType.kStrongWrite)


def intents_conflict(a: IntentType, b: IntentType) -> bool:
    return (a.is_strong or b.is_strong) and (a.is_write or b.is_write)


# For each held-type bitmask, which intent types may NOT newly enter.
_CONFLICTS: Dict[IntentType, Tuple[IntentType, ...]] = {
    t: tuple(u for u in IntentType if intents_conflict(t, u)) for t in IntentType
}


class LockBatch:
    """A set of (key, intent_type) entries acquired and released atomically
    (ref lock_batch.h:61). Duplicate (key, intent_type) pairs collapse to one
    entry; distinct intent types on one key are all kept."""

    def __init__(self, entries: Iterable[Tuple[bytes, IntentType]] = ()):
        self.entries: List[Tuple[bytes, IntentType]] = sorted(set(entries))
        self._manager = None

    def __len__(self) -> int:
        return len(self.entries)

    def release(self) -> None:
        if self._manager is not None:
            self._manager._release(self)
            self._manager = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class SharedLockManager:
    """Grants LockBatches; blocks while any entry conflicts with held locks."""

    def __init__(self):
        self._cv = threading.Condition()
        # key -> [ref counts per IntentType]
        self._held: Dict[bytes, List[int]] = defaultdict(lambda: [0, 0, 0, 0])

    def lock(self, batch: LockBatch, timeout_s: float = 10.0) -> LockBatch:
        with self._cv:
            ok = self._cv.wait_for(lambda: self._grantable(batch), timeout=timeout_s)
            if not ok:
                raise TimeoutError("lock batch acquisition timed out "
                                   f"({len(batch)} entries)")
            for key, it in batch.entries:
                self._held[key][it] += 1
        batch._manager = self
        return batch

    def try_lock(self, batch: LockBatch) -> bool:
        with self._cv:
            if not self._grantable(batch):
                return False
            for key, it in batch.entries:
                self._held[key][it] += 1
        batch._manager = self
        return True

    def _grantable(self, batch: LockBatch) -> bool:
        for key, it in batch.entries:
            counts = self._held.get(key)
            if not counts:
                continue
            for other in _CONFLICTS[it]:
                if counts[other]:
                    return False
        return True

    def _release(self, batch: LockBatch) -> None:
        with self._cv:
            for key, it in batch.entries:
                counts = self._held[key]
                counts[it] -= 1
                if not any(counts):
                    del self._held[key]
            self._cv.notify_all()

    def held_count(self) -> int:
        with self._cv:
            return len(self._held)


def doc_path_lock_entries(full_key: bytes, prefixes: Sequence[bytes],
                          is_write: bool) -> List[Tuple[bytes, IntentType]]:
    """Strong lock on the full doc path, weak locks on every prefix
    (ref: docdb/docdb.cc DetermineKeysToLock)."""
    strong = IntentType.kStrongWrite if is_write else IntentType.kStrongRead
    weak = IntentType.kWeakWrite if is_write else IntentType.kWeakRead
    entries = [(p, weak) for p in prefixes if p != full_key]
    entries.append((full_key, strong))
    return entries
