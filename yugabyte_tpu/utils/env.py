"""Env: the storage-file abstraction, with transparent encryption at rest
and a fault-injection wrapper for crash/disk-error testing.

Capability parity with the reference's Env + encrypted file layer (ref:
src/yb/util/env.h; src/yb/encryption/encrypted_file.cc — every data file
gets a random DATA KEY, wrapped by the cluster-wide UNIVERSE KEY and
stored in a file header; AES-CTR keyed per file allows random-access
reads). The storage engine's byte paths (SST data/base files, WAL
segments, MANIFEST edits) go through the process Env; the plaintext Env is
a thin passthru and the encrypted Env wraps the same operations.

FaultInjectionEnv (ref: rocksdb/db/fault_injection_test.cc
FaultInjectionTestEnv) stacks over either and injects pread errors,
failed/short (torn) appends, ENOSPC, and silently-dropped fsyncs whose
unsynced bytes are lost on simulate_crash() — the substrate every
background-error-containment test drives.

Header layout of an encrypted file:
    b"YBENCv1\\0" | u16 key_id_len | key_id | 16B nonce | 32B wrapped key
Body bytes at logical offset L live at physical offset header_len + L,
encrypted with AES-CTR(data_key, nonce) at counter position L — so pread
at any offset decrypts exactly the requested range.
"""

from __future__ import annotations

import os
import secrets
import struct
import threading
from typing import Dict, List, Optional, Tuple

_MAGIC = b"YBENCv1\x00"


def _ctr_cipher(key: bytes, nonce: bytes, byte_offset: int = 0):
    from cryptography.hazmat.primitives.ciphers import (
        Cipher, algorithms, modes)
    # advance the 128-bit counter to the block containing byte_offset
    blocks = byte_offset // 16
    ctr = (int.from_bytes(nonce, "big") + blocks) % (1 << 128)
    c = Cipher(algorithms.AES(key),
               modes.CTR(ctr.to_bytes(16, "big"))).encryptor()
    skip = byte_offset % 16
    if skip:
        c.update(b"\x00" * skip)  # discard partial leading block
    return c


class Env:
    """Plaintext passthru (the default)."""

    encrypted = False

    # ---------------------------------------------------------- whole file
    def read_file(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_file(self, path: str, data: bytes) -> None:
        with open(path, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())

    # ------------------------------------------------------- random access
    def open_random(self, path: str) -> "RandomAccessFile":
        return RandomAccessFile(path)

    # -------------------------------------------------------------- append
    def open_append(self, path: str) -> "AppendFile":
        return AppendFile(path)


class RandomAccessFile:
    def __init__(self, path: str):
        self._fd = os.open(path, os.O_RDONLY)

    def pread(self, size: int, offset: int) -> bytes:
        return os.pread(self._fd, size, offset)

    def size(self) -> int:
        return os.fstat(self._fd).st_size

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


class AppendFile:
    def __init__(self, path: str):
        self._f = open(path, "ab")

    @property
    def offset(self) -> int:
        return self._f.tell()

    def append(self, data: bytes) -> None:
        self._f.write(data)

    def flush(self, fsync: bool = True) -> None:
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()


# ---------------------------------------------------------------- encrypted
class UniverseKeys:
    """In-process registry of universe keys (master-distributed;
    ref ent/src/yb/master/universe_key_registry_service.cc)."""

    def __init__(self):
        self._keys: Dict[str, bytes] = {}
        self._latest: Optional[str] = None
        self._lock = threading.Lock()

    def add(self, key_id: str, key: bytes, make_latest: bool = True) -> None:
        assert len(key) == 32, "universe keys are AES-256"
        with self._lock:
            self._keys[key_id] = key
            if make_latest or self._latest is None:
                self._latest = key_id

    def get(self, key_id: str) -> bytes:
        with self._lock:
            key = self._keys.get(key_id)
        if key is None:
            raise KeyError(f"universe key {key_id!r} not available")
        return key

    def latest(self) -> Tuple[str, bytes]:
        with self._lock:
            if self._latest is None:
                raise KeyError("no universe key configured")
            return self._latest, self._keys[self._latest]


class EncryptedEnv(Env):
    encrypted = True

    def __init__(self, keys: UniverseKeys):
        self.keys = keys

    # ------------------------------------------------------------- header
    def _new_header(self) -> Tuple[bytes, bytes]:
        key_id, ukey = self.keys.latest()
        nonce = secrets.token_bytes(16)
        data_key = secrets.token_bytes(32)
        wrapped = _ctr_cipher(ukey, nonce).update(data_key)
        kid = key_id.encode()
        header = (_MAGIC + struct.pack("<H", len(kid)) + kid + nonce
                  + wrapped)
        return header, (data_key, nonce)

    def _read_header(self, blob: bytes) -> Tuple[int, bytes, bytes]:
        """-> (header_len, data_key, nonce). A short blob (torn/truncated
        header after a crash mid-create) fails loudly here instead of
        keying the cipher with garbage bytes."""
        if blob[: len(_MAGIC)] != _MAGIC:
            raise ValueError("not an encrypted file")
        if len(blob) < len(_MAGIC) + 2:
            raise ValueError("truncated encrypted-file header "
                             f"({len(blob)} bytes)")
        (kid_len,) = struct.unpack_from("<H", blob, len(_MAGIC))
        p = len(_MAGIC) + 2
        if len(blob) < p + kid_len + 48:
            raise ValueError("truncated encrypted-file header "
                             f"({len(blob)} bytes, need {p + kid_len + 48})")
        key_id = blob[p: p + kid_len].decode()
        p += kid_len
        nonce = blob[p: p + 16]
        wrapped = blob[p + 16: p + 48]
        ukey = self.keys.get(key_id)
        data_key = _ctr_cipher(ukey, nonce).update(wrapped)
        return p + 48, data_key, nonce

    # ---------------------------------------------------------- whole file
    def read_file(self, path: str) -> bytes:
        blob = super().read_file(path)
        if blob[: len(_MAGIC)] != _MAGIC:
            return blob  # legacy plaintext file (pre-encryption enable)
        hlen, data_key, nonce = self._read_header(blob)
        return _ctr_cipher(data_key, nonce).update(blob[hlen:])

    def write_file(self, path: str, data: bytes) -> None:
        header, (data_key, nonce) = self._new_header()
        super().write_file(
            path, header + _ctr_cipher(data_key, nonce).update(data))

    # ------------------------------------------------------- random access
    def open_random(self, path: str):
        raw = RandomAccessFile(path)
        head = raw.pread(len(_MAGIC), 0)
        if head != _MAGIC:
            return raw  # legacy plaintext file
        raw.close()
        return EncryptedRandomAccessFile(self, path)

    def open_append(self, path: str):
        if os.path.exists(path) and os.path.getsize(path) > 0:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    return AppendFile(path)  # continue a legacy file
        return EncryptedAppendFile(self, path)


class EncryptedRandomAccessFile:
    def __init__(self, env: EncryptedEnv, path: str):
        self._raw = RandomAccessFile(path)
        try:
            head = self._raw.pread(4096, 0)
            self._hlen, self._key, self._nonce = env._read_header(head)
        except BaseException:
            self._raw.close()  # no fd leak on a torn header
            raise

    def pread(self, size: int, offset: int) -> bytes:
        enc = self._raw.pread(size, self._hlen + offset)
        return _ctr_cipher(self._key, self._nonce, offset).update(enc)

    def size(self) -> int:
        return self._raw.size() - self._hlen

    def close(self) -> None:
        self._raw.close()


class EncryptedAppendFile:
    def __init__(self, env: EncryptedEnv, path: str):
        exists = os.path.exists(path) and os.path.getsize(path) > 0
        if exists:
            with open(path, "rb") as f:
                head = f.read(4096)
            self._hlen, key, nonce = env._read_header(head)
            self._f = open(path, "ab")
            start = self._f.tell() - self._hlen
        else:
            header, (key, nonce) = env._new_header()
            self._hlen = len(header)
            self._f = open(path, "wb")
            self._f.write(header)
            start = 0
        self._key, self._nonce = key, nonce
        self._cipher = _ctr_cipher(key, nonce, start)

    @property
    def offset(self) -> int:
        return self._f.tell() - self._hlen

    def append(self, data: bytes) -> None:
        self._f.write(self._cipher.update(data))

    def flush(self, fsync: bool = True) -> None:
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()


def looks_encrypted(path: str) -> bool:
    """True if the file carries the encrypted-file header."""
    try:
        with open(path, "rb") as f:
            return f.read(len(_MAGIC)) == _MAGIC
    except OSError:
        return False


# ----------------------------------------------------------- fault injection
class FaultError(OSError):
    """An injected disk fault. Subclasses OSError so every layer treats it
    exactly like a real I/O error; tests can still single it out."""


class FaultInjectionEnv(Env):
    """Env wrapper that injects disk faults (ref:
    rocksdb/db/fault_injection_test.cc FaultInjectionTestEnv). Stacks over
    any base Env — including EncryptedEnv, so faults hit the ciphertext
    byte stream exactly like a failing disk would.

    Fault kinds (armed via set_fault(kind, path_filter, count)):
      - "read":         pread / read_file raises FaultError
      - "append":       append raises before writing anything
      - "append_short": append writes a PREFIX then raises (a torn write)
      - "enospc":       append / write_file raise OSError(ENOSPC)
    path_filter is a substring match on the path ("" = every file); count
    bounds how many times the fault fires (None = until cleared).

    At-rest corruption (corrupt_range): bit-flips in ALREADY-WRITTEN
    bytes of an SST/WAL file — the silent bit-rot model the background
    scrubber and read-path CRC containment are tested against. Applied
    to the PHYSICAL bytes (below any encryption layer), exactly like a
    decaying disk; nothing raises at flip time — detection is the
    storage layer's job.

    Dropped fsyncs (set_drop_fsyncs): flush(fsync=True) silently succeeds
    without durability — the lying-disk model. simulate_crash() then
    applies the loss: append files are truncated to their last truly
    synced size (removed if never synced), whole-file writes revert to
    their last synced content. Files touched only before this env was
    installed are untouched. Rename-based flows (os.replace of a .tmp)
    happen outside the Env and re-track on next open.
    """

    def __init__(self, base: Optional[Env] = None):
        self.base = base if base is not None else Env()
        self._lock = threading.Lock()
        self._faults: Dict[str, dict] = {}   # kind -> {filter, remaining}
        self._drop_fsyncs = False
        self._fsync_filter = ""
        # append files: path -> [synced_size, existed_at_first_open]
        self._synced: Dict[str, list] = {}
        # whole-file writes under dropped fsyncs: path -> prior raw bytes
        # (None = file did not exist)
        self._whole: Dict[str, Optional[bytes]] = {}
        self.faults_injected = 0
        self.corruptions_injected = 0

    # -------------------------------------------------- at-rest corruption
    def corrupt_range(self, path: str, offset: Optional[int] = None,
                      length: int = 1, nbits: int = 1) -> List[int]:
        """Flip ``nbits`` bits spread over ``[offset, offset+length)`` of
        the file's PHYSICAL bytes in place (read-modify-write below any
        Env layering) — silent at-rest bit rot. offset=None targets the
        middle of the file. Returns the byte offsets flipped."""
        self.corruptions_injected += 1
        return corrupt_file_range(path, offset, length, nbits)

    @property
    def encrypted(self) -> bool:  # type: ignore[override]
        return self.base.encrypted

    # ------------------------------------------------------------- arming
    def set_fault(self, kind: str, path_filter: str = "",
                  count: Optional[int] = None) -> None:
        assert kind in ("read", "append", "append_short", "enospc"), kind
        with self._lock:
            self._faults[kind] = {"filter": path_filter, "remaining": count}

    def clear_fault(self, kind: str) -> None:
        with self._lock:
            self._faults.pop(kind, None)

    def clear_faults(self) -> None:
        with self._lock:
            self._faults.clear()
            self._drop_fsyncs = False

    def set_drop_fsyncs(self, on: bool, path_filter: str = "") -> None:
        with self._lock:
            self._drop_fsyncs = on
            self._fsync_filter = path_filter

    def _should_fire(self, kind: str, path: str) -> bool:
        with self._lock:
            f = self._faults.get(kind)
            if f is None or f["filter"] not in path:
                return False
            if f["remaining"] is not None:
                if f["remaining"] <= 0:
                    return False
                f["remaining"] -= 1
            self.faults_injected += 1
            return True

    def _fsync_dropped(self, path: str) -> bool:
        with self._lock:
            return self._drop_fsyncs and self._fsync_filter in path

    # ----------------------------------------------------- sync tracking
    def _note_open_append(self, path: str) -> None:
        with self._lock:
            if path not in self._synced:
                exists = os.path.exists(path)
                self._synced[path] = [
                    os.path.getsize(path) if exists else 0, exists]

    def _mark_synced(self, path: str) -> None:
        with self._lock:
            rec = self._synced.setdefault(path, [0, True])
            try:
                rec[0] = os.path.getsize(path)
            except OSError:
                pass

    def simulate_crash(self) -> List[str]:
        """Apply unsynced-data loss as a crash would, and reset tracking
        (the 'restarted process' opens files fresh). Returns the paths
        whose bytes were rolled back."""
        with self._lock:
            synced = self._synced
            whole = self._whole
            self._synced = {}
            self._whole = {}
            self._drop_fsyncs = False
        affected = []
        for path, (size, existed) in synced.items():
            if not os.path.exists(path):
                continue
            if os.path.getsize(path) > size:
                affected.append(path)
                if size == 0 and not existed:
                    os.remove(path)
                else:
                    with open(path, "r+b") as f:
                        f.truncate(size)
        for path, prior in whole.items():
            affected.append(path)
            if prior is None:
                if os.path.exists(path):
                    os.remove(path)
            else:
                with open(path, "wb") as f:
                    f.write(prior)
        return affected

    # ------------------------------------------------------------- file ops
    def read_file(self, path: str) -> bytes:
        if self._should_fire("read", path):
            raise FaultError(f"injected read error: {path}")
        return self.base.read_file(path)

    def write_file(self, path: str, data: bytes) -> None:
        import errno
        if self._should_fire("enospc", path):
            raise FaultError(errno.ENOSPC,
                             f"injected ENOSPC writing {path}")
        if self._fsync_dropped(path):
            with self._lock:
                if path not in self._whole:
                    prior = None
                    if os.path.exists(path):
                        with open(path, "rb") as f:
                            prior = f.read()
                    self._whole[path] = prior
        else:
            with self._lock:
                self._whole.pop(path, None)
        self.base.write_file(path, data)
        if not self._fsync_dropped(path):
            self._mark_synced(path)

    def open_random(self, path: str):
        return _FaultRandomAccessFile(self, path, self.base.open_random(path))

    def open_append(self, path: str):
        self._note_open_append(path)
        return _FaultAppendFile(self, path, self.base.open_append(path))


class _FaultRandomAccessFile:
    def __init__(self, env: FaultInjectionEnv, path: str, raw):
        self._env = env
        self._path = path
        self._raw = raw

    def pread(self, size: int, offset: int) -> bytes:
        if self._env._should_fire("read", self._path):
            raise FaultError(f"injected pread error: {self._path}"
                             f" @{offset}+{size}")
        return self._raw.pread(size, offset)

    def size(self) -> int:
        return self._raw.size()

    def close(self) -> None:
        self._raw.close()


class _FaultAppendFile:
    def __init__(self, env: FaultInjectionEnv, path: str, raw):
        self._env = env
        self._path = path
        self._raw = raw

    @property
    def offset(self) -> int:
        return self._raw.offset

    def append(self, data: bytes) -> None:
        import errno
        env, path = self._env, self._path
        if env._should_fire("enospc", path):
            raise FaultError(errno.ENOSPC, f"injected ENOSPC: {path}")
        if env._should_fire("append", path):
            raise FaultError(f"injected append error: {path}")
        if env._should_fire("append_short", path):
            self._raw.append(data[: max(1, len(data) // 2)])
            raise FaultError(f"injected short (torn) append: {path}")
        self._raw.append(data)

    def flush(self, fsync: bool = True) -> None:
        if fsync and self._env._fsync_dropped(self._path):
            # lying disk: bytes reach the OS (still readable) but the
            # durability claim is false — simulate_crash() collects them
            self._raw.flush(fsync=False)
            return
        self._raw.flush(fsync=fsync)
        if fsync:
            self._env._mark_synced(self._path)

    def close(self) -> None:
        self._raw.close()


def corrupt_file_range(path: str, offset: Optional[int] = None,
                       length: int = 1, nbits: int = 1) -> List[int]:
    """Flip ``nbits`` bits over ``[offset, offset+length)`` of ``path``'s
    physical bytes (see FaultInjectionEnv.corrupt_range, which delegates
    here so tests without a fault env can corrupt too). Deterministic:
    flipped offsets are evenly spread over the range, one bit (cycling
    bit position by index) per byte."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path}")
    if offset is None:
        offset = size // 2
    offset = max(0, min(offset, size - 1))
    length = max(1, min(length, size - offset))
    nbits = max(1, nbits)
    step = max(1, length // nbits)
    flipped: List[int] = []
    with open(path, "r+b") as f:
        for i in range(nbits):
            off = offset + min(i * step, length - 1)
            if off >= size:
                break
            f.seek(off)
            (b,) = f.read(1)
            f.seek(off)
            f.write(bytes([b ^ (1 << (i % 8))]))
            flipped.append(off)
        f.flush()
        os.fsync(f.fileno())
    return flipped


# ------------------------------------------------------------ process env
_env: Env = Env()


def get_env() -> Env:
    return _env


def set_env(env: Env) -> None:
    global _env
    _env = env


def enable_encryption(keys: UniverseKeys) -> None:
    set_env(EncryptedEnv(keys))


def disable_encryption() -> None:
    set_env(Env())


def enable_fault_injection(base: Optional[Env] = None) -> FaultInjectionEnv:
    """Stack a FaultInjectionEnv over `base` (default: the current process
    env, so it composes with encryption) and install it. Returns the
    wrapper for arming."""
    fi = FaultInjectionEnv(base if base is not None else _env)
    set_env(fi)
    return fi


def disable_fault_injection() -> None:
    if isinstance(_env, FaultInjectionEnv):
        set_env(_env.base)
