"""Host-RAM packed-run cache: compaction inputs retained decoded.

The host-side counterpart of the HBM key-column cache
(storage/device_cache.py). Every flush and compaction output is exported
ONCE from the native shell as decoded SoA columns (ce_runcache_export,
native/compaction_engine.cc) and retained keyed by SST file id; the next
compaction over all-cached inputs skips file read, block decode and the
CRC pass entirely (ce_job_prepare_cached) — the disk file becomes
durability-only on the steady-state compaction chain.

The reference re-iterates TableReaders per input on every job even when
the block cache is warm (ref: db/compaction_job.cc:442 heap merge over
table/merger.cc:51 iterators, each paying per-entry decode); here the
per-entry work was already paid when the run was produced.

Memory lives in C++ (one registry per process); this class is the LRU
accountant over it, namespaced per DB exactly like DeviceSlabCache.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from yugabyte_tpu.utils import flags

flags.define_flag("compaction_run_cache_mb", 512,
                  "host RAM budget for the packed-run cache (0 disables); "
                  "holds flush/compaction outputs decoded so steady-state "
                  "compactions skip input read+decode")

CacheKey = Tuple[str, int]  # (namespace, file_id), as DeviceSlabCache


class NativeRunCache:
    """Process-wide LRU over native run-cache ids."""

    def __init__(self, capacity_bytes: Optional[int] = None):
        from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
        self._cap_override = capacity_bytes
        self._map: "OrderedDict[CacheKey, Tuple[int, int]]" = OrderedDict()
        self._used = 0
        self._lock = threading.Lock()
        # per-instance ints (tests diff them) + registry counters for the
        # scrapeable hit ratio
        self.hits = 0
        self.misses = 0
        e = ROOT_REGISTRY.entity("server", "run_cache")
        self._c_hits = e.counter("run_cache_hits_total",
                                 "decoded-run cache hits")
        self._c_misses = e.counter("run_cache_misses_total",
                                   "decoded-run cache misses")

    @property
    def capacity(self) -> int:
        if self._cap_override is not None:
            return self._cap_override
        return flags.get_flag("compaction_run_cache_mb") << 20

    def get(self, key: CacheKey) -> Optional[int]:
        with self._lock:
            ent = self._map.get(key)
            if ent is None:
                self.misses += 1
                self._c_misses.increment()
                return None
            self._map.move_to_end(key)
            self.hits += 1
            self._c_hits.increment()
            return ent[0]

    def contains(self, key: CacheKey) -> bool:
        with self._lock:
            return key in self._map

    def put(self, key: CacheKey, run_id: int, nbytes: int) -> None:
        from yugabyte_tpu.storage import native_engine
        dead = []
        with self._lock:
            prior = self._map.pop(key, None)
            if prior is not None:
                # replace, never shadow: a reused file id must not serve
                # stale rows (same rule as DeviceSlabCache.put)
                self._used -= prior[1]
                dead.append(prior[0])
            self._map[key] = (run_id, nbytes)
            self._used += nbytes
            # may evict the entry just inserted: a single run larger than
            # the whole budget must not pin RAM past the configured cap
            # (callers re-probe under contains()+add_cached pinning)
            while self._used > self.capacity and self._map:
                _, (old_id, old_bytes) = self._map.popitem(last=False)
                self._used -= old_bytes
                dead.append(old_id)
        for rid in dead:
            native_engine.runcache_drop(rid)

    def drop(self, key: CacheKey) -> None:
        from yugabyte_tpu.storage import native_engine
        with self._lock:
            ent = self._map.pop(key, None)
            if ent is not None:
                self._used -= ent[1]
        if ent is not None:
            native_engine.runcache_drop(ent[0])

    def drop_namespace(self, namespace: str) -> None:
        from yugabyte_tpu.storage import native_engine
        with self._lock:
            dead = [k for k in self._map if k[0] == namespace]
            ids = []
            for k in dead:
                rid, nbytes = self._map.pop(k)
                self._used -= nbytes
                ids.append(rid)
        for rid in ids:
            native_engine.runcache_drop(rid)

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used


def export_reader(run_cache, file_id: int, reader) -> None:
    """Retain one on-disk SST decoded in the run cache — what flush
    write-through does for freshly written files (used by the bench and
    by tests to reconstruct the steady state for pre-existing files)."""
    from yugabyte_tpu.storage import native_engine
    with native_engine.NativeCompactionJob() as job:
        with open(reader.data_path, "rb") as f:
            job.add_input(f.read(), reader.block_handles)
        n = job.prepare()
        job.sort_all()  # identity survivors: the file is one sorted run
        rid = job.export_run(0, n, b"X")
        run_cache.put(file_id, rid, native_engine.runcache_entry_bytes(rid))


_shared: Optional[NativeRunCache] = None
_shared_lock = threading.Lock()


def shared_run_cache() -> Optional[NativeRunCache]:
    """The process-wide cache, or None when disabled / no native engine."""
    from yugabyte_tpu.storage import native_engine
    if flags.get_flag("compaction_run_cache_mb") <= 0:
        return None
    if not native_engine.available():
        return None
    global _shared
    with _shared_lock:
        if _shared is None:
            _shared = NativeRunCache()
        return _shared


class NamespacedRunCache:
    """Per-DB view (bare file ids), mirroring NamespacedSlabCache."""

    def __init__(self, shared: NativeRunCache, namespace: str):
        self._shared = shared
        self.namespace = namespace

    def get(self, file_id: int) -> Optional[int]:
        return self._shared.get((self.namespace, file_id))

    def contains(self, file_id: int) -> bool:
        return self._shared.contains((self.namespace, file_id))

    def put(self, file_id: int, run_id: int, nbytes: int) -> None:
        self._shared.put((self.namespace, file_id), run_id, nbytes)

    def drop(self, file_id: int) -> None:
        self._shared.drop((self.namespace, file_id))

    def drop_all(self) -> None:
        self._shared.drop_namespace(self.namespace)

    @property
    def hits(self) -> int:
        return self._shared.hits

    @property
    def misses(self) -> int:
        return self._shared.misses
