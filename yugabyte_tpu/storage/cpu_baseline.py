"""ctypes bridge to the native CPU compaction baseline.

Builds native/compaction_baseline.cc on first use (g++ -O3). The baseline is
the reference's architecture — heap merge + sequential filter — and serves
as (a) the vs_baseline denominator in bench.py, (b) a third differential
implementation in tests.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Sequence, Tuple

import numpy as np

from yugabyte_tpu.ops.slabs import KVSlab

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "compaction_baseline.cc")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libcompaction_baseline.so")

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from yugabyte_tpu.utils.native_build import build_native_lib
    _lib = ctypes.CDLL(build_native_lib("compaction_baseline.cc",
                                        "libcompaction_baseline.so"))
    _lib.compact_baseline.restype = ctypes.c_int64
    return _lib


def compact_cpu_baseline(slab: KVSlab, run_offsets: Sequence[int],
                         history_cutoff_ht: int, is_major: bool,
                         retain_deletes: bool = False
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Run the native baseline. Runs are [run_offsets[i], run_offsets[i+1])
    slices of the slab, each already sorted in internal-key order.

    Returns (order, keep, make_tombstone) like merge_and_gc_device (without
    padding)."""
    lib = _load()
    n = slab.n
    stride = slab.width_words * 4
    keys = np.ascontiguousarray(slab.key_words).astype(">u4").tobytes()
    keys_buf = np.frombuffer(keys, dtype=np.uint8)
    key_len = np.ascontiguousarray(slab.key_len, dtype=np.int32)
    dkl = np.ascontiguousarray(slab.doc_key_len, dtype=np.int32)
    ht = np.ascontiguousarray(
        (slab.ht_hi.astype(np.uint64) << 32) | slab.ht_lo.astype(np.uint64))
    wid = np.ascontiguousarray(slab.write_id, dtype=np.uint32)
    flags = np.ascontiguousarray(slab.flags, dtype=np.uint8)
    ttl = np.ascontiguousarray(slab.ttl_ms, dtype=np.int64)
    offs = np.ascontiguousarray(run_offsets, dtype=np.int64)
    keep = np.zeros(n, dtype=np.uint8)
    mk = np.zeros(n, dtype=np.uint8)
    order = np.zeros(n, dtype=np.int64)

    def p(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    lib.compact_baseline(
        ctypes.c_int32(len(offs) - 1), p(offs, ctypes.c_int64),
        ctypes.c_int64(n), ctypes.c_int32(stride),
        p(keys_buf, ctypes.c_uint8), p(key_len, ctypes.c_int32),
        p(dkl, ctypes.c_int32), p(ht, ctypes.c_uint64),
        p(wid, ctypes.c_uint32), p(flags, ctypes.c_uint8),
        p(ttl, ctypes.c_int64),
        ctypes.c_uint64(history_cutoff_ht), ctypes.c_int32(int(is_major)),
        ctypes.c_int32(int(retain_deletes)),
        p(keep, ctypes.c_uint8), p(mk, ctypes.c_uint8),
        p(order, ctypes.c_int64))
    return order, keep.astype(bool), mk.astype(bool)
