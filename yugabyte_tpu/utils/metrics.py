"""Metrics: counters, gauges, histograms, with JSON + Prometheus exposition.

Capability parity with the reference metric system (ref: src/yb/util/metrics.h:
Counter, AtomicGauge :713, Histogram; WriteForPrometheus :449-518). Entities
(server/table/tablet) each own a registry; registries aggregate into a root
MetricRegistry for the /metrics endpoints.

Naming convention (enforced by tools/lint_metric_names.py in tier-1):
snake_case, with a unit suffix — counters end `_total`; histograms end
`_ms`/`_us`/`_bytes`/`_rows`; gauges end in a unit or count suffix. This
keeps the namespace scrapeable as the instrumented surface grows.
"""

from __future__ import annotations

import contextlib
import json
import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple


class Counter:
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def increment(self, by: int = 1) -> None:
        with self._lock:
            self._value += by

    def value(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = "", initial: float = 0.0):
        self.name = name
        self.help = help
        self._value = initial
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def increment(self, by: float = 1.0) -> None:
        with self._lock:
            self._value += by

    def decrement(self, by: float = 1.0) -> None:
        self.increment(-by)

    def value(self) -> float:
        return self._value


class Histogram:
    """Log-bucketed histogram (2% default precision), like the reference's HdrHistogram.

    Observations may carry an *exemplar* — an opaque reference (here: a
    trace id) tying a recorded value back to its origin. Exemplar storage
    is bounded: the `_EXEMPLAR_KEEP` most recent plus the one attached to
    the largest observation so far, so a p99 outlier on /servez stays
    click-through to /tracez no matter how much traffic followed it.
    Exemplars surface ONLY in the JSON exposition: the classic Prometheus
    text format 0.0.4 has no exemplar syntax, so keeping them out of
    `to_prometheus` is what keeps exemplar-bearing histograms
    grammar-valid there.
    """

    _EXEMPLAR_KEEP = 5

    __slots__ = ("name", "help", "_counts", "_lock", "_total_sum", "_total_count",
                 "_min", "_max", "_growth", "_exemplars", "_max_exemplar")

    def __init__(self, name: str, help: str = "", growth: float = 1.02):
        self.name = name
        self.help = help
        self._growth = math.log(growth)
        self._counts: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._total_sum = 0.0
        self._total_count = 0
        self._min = math.inf
        self._max = -math.inf
        self._exemplars: List[Dict[str, object]] = []
        self._max_exemplar: Optional[Dict[str, object]] = None

    def _bucket(self, v: float) -> int:
        if v <= 0:
            return -1
        return int(math.log(v) / self._growth)

    def increment(self, v: float, exemplar: Optional[str] = None) -> None:
        b = self._bucket(v)
        with self._lock:
            self._counts[b] = self._counts.get(b, 0) + 1
            self._total_sum += v
            self._total_count += 1
            self._min = min(self._min, v)
            self._max = max(self._max, v)
            if exemplar is not None:
                ex = {"value": v, "trace_id": exemplar}
                self._exemplars.append(ex)
                if len(self._exemplars) > self._EXEMPLAR_KEEP:
                    del self._exemplars[0]
                if self._max_exemplar is None or v >= self._max_exemplar["value"]:
                    self._max_exemplar = ex

    def exemplars(self) -> List[Dict[str, object]]:
        """Bounded exemplar snapshot: recent observations first, the
        max-valued one guaranteed present (it may also be recent)."""
        with self._lock:
            out = list(self._exemplars)
            if self._max_exemplar is not None and self._max_exemplar not in out:
                out.append(self._max_exemplar)
            return out

    def percentile(self, p: float) -> float:
        with self._lock:
            if self._total_count == 0:
                return 0.0
            target = p / 100.0 * self._total_count
            seen = 0
            for b in sorted(self._counts):
                seen += self._counts[b]
                if seen >= target:
                    return math.exp((b + 0.5) * self._growth) if b >= 0 else 0.0
            return self._max

    def mean(self) -> float:
        return self._total_sum / self._total_count if self._total_count else 0.0

    def snapshot_dict(self) -> Dict[str, object]:
        """JSON-ready point-in-time summary (observability pages that
        render one histogram inline rather than a whole registry)."""
        out = {
            "count": self.count(), "sum": round(self._total_sum, 3),
            "mean": round(self.mean(), 3), "min": self.min(),
            "max": self.max(),
            "p50": round(self.percentile(50), 3),
            "p95": round(self.percentile(95), 3),
            "p99": round(self.percentile(99), 3),
        }
        ex = self.exemplars()
        if ex:
            out["exemplars"] = ex
        return out

    def count(self) -> int:
        return self._total_count

    def min(self) -> float:
        return self._min if self._total_count else 0.0

    def max(self) -> float:
        return self._max if self._total_count else 0.0


@contextlib.contextmanager
def timed_ms(hist: Histogram):
    """Record the wall time of a with-block into `hist`, in milliseconds."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        hist.increment((time.monotonic() - t0) * 1e3)


class MetricEntity:
    """One metric-owning entity: a server, table, or tablet (ref: metrics.h entities)."""

    def __init__(self, entity_type: str, entity_id: str, attributes: Optional[Dict[str, str]] = None):
        self.entity_type = entity_type
        self.entity_id = entity_id
        self.attributes = attributes or {}
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "", initial: float = 0.0) -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help, initial))

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help))

    def _get_or_create(self, name, factory):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = factory()
            return self._metrics[name]

    def metrics_snapshot(self) -> Dict[str, object]:
        """Point-in-time copy of the entity's metric map (observability
        pages that enumerate dynamically-named counters)."""
        with self._lock:
            return dict(self._metrics)


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline (tablet attributes can contain any of them today)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    """HELP-line escaping: backslash and newline."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(labels: Dict[str, str]) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"'
                    for k, v in labels.items())


class MetricRegistry:
    def __init__(self):
        self._entities: Dict[str, MetricEntity] = {}
        self._lock = threading.Lock()

    def entity(self, entity_type: str, entity_id: str,
               attributes: Optional[Dict[str, str]] = None) -> MetricEntity:
        key = f"{entity_type}:{entity_id}"
        with self._lock:
            if key not in self._entities:
                self._entities[key] = MetricEntity(entity_type, entity_id, attributes)
            return self._entities[key]

    def _snapshot(self):
        with self._lock:
            ents = list(self._entities.values())
        out = []
        for ent in ents:
            with ent._lock:
                out.append((ent, list(ent._metrics.values())))
        return out

    def to_json(self) -> str:
        return json.dumps(registries_to_json_obj([self]), indent=1)

    def to_prometheus(self) -> str:
        """Prometheus text exposition (ref: metrics.h WriteForPrometheus :449-518)."""
        return registries_to_prometheus([self])


def registries_to_json_obj(registries: Iterable[MetricRegistry]) -> list:
    seen = set()
    out = []
    for reg in registries:
        if id(reg) in seen:
            continue
        seen.add(id(reg))
        for ent, ent_metrics in reg._snapshot():
            metrics = []
            for m in ent_metrics:
                if isinstance(m, Histogram):
                    entry = {
                        "name": m.name, "total_count": m.count(), "mean": m.mean(),
                        "min": m.min(), "max": m.max(),
                        "percentile_50": m.percentile(50),
                        "percentile_95": m.percentile(95), "percentile_99": m.percentile(99),
                    }
                    ex = m.exemplars()
                    if ex:
                        entry["exemplars"] = ex
                    metrics.append(entry)
                else:
                    metrics.append({"name": m.name, "value": m.value()})
            out.append({"type": ent.entity_type, "id": ent.entity_id,
                        "attributes": ent.attributes, "metrics": metrics})
    return out


def registries_to_prometheus(registries: Iterable[MetricRegistry]) -> str:
    """Valid Prometheus text-format exposition over one or more registries.

    Grammar obligations the naive per-entity dump violated (and the
    exposition test now enforces line-by-line):
      - every metric FAMILY gets exactly one `# TYPE` line, emitted before
        any of its samples, even when the same name appears under many
        entities (or several registries);
      - label values are escaped (quotes, backslashes, newlines);
      - histograms expose as summaries (quantile samples + _sum/_count)
        plus separate `<name>_min`/`<name>_max` gauge families (a summary
        family itself may only carry the quantile/_sum/_count samples);
      - histogram exemplars are NOT emitted here: text format 0.0.4 has
        no exemplar syntax (`# {...}` trailers are an OpenMetrics-only
        extension), so exemplar-bearing histograms expose exactly like
        plain ones and the output stays grammar-valid. Exemplars ride
        the JSON exposition (`registries_to_json_obj`) instead.
    """
    # family name -> (type, help, [sample lines])
    families: "Dict[str, Tuple[str, str, List[str]]]" = {}
    order: List[str] = []

    def fam(name: str, mtype: str, help: str) -> List[str]:
        if name not in families:
            families[name] = (mtype, help, [])
            order.append(name)
        return families[name][2]

    seen = set()
    for reg in registries:
        if id(reg) in seen:
            continue  # the webserver merges the per-server registry with
        seen.add(id(reg))  # the process ROOT_REGISTRY; never dump one twice
        for ent, ent_metrics in reg._snapshot():
            labels = {"metric_type": ent.entity_type,
                      "metric_id": ent.entity_id}
            labels.update(ent.attributes)
            ls = _label_str(labels)
            for m in ent_metrics:
                if isinstance(m, Histogram):
                    lines = fam(m.name, "summary", m.help)
                    for p in (50, 95, 99):
                        lines.append(f'{m.name}{{{ls},quantile="0.{p}"}} '
                                     f'{m.percentile(p)}')
                    lines.append(f"{m.name}_sum{{{ls}}} {m._total_sum}")
                    lines.append(f"{m.name}_count{{{ls}}} {m.count()}")
                    fam(f"{m.name}_min", "gauge",
                        f"minimum observed {m.name}").append(
                        f"{m.name}_min{{{ls}}} {m.min()}")
                    fam(f"{m.name}_max", "gauge",
                        f"maximum observed {m.name}").append(
                        f"{m.name}_max{{{ls}}} {m.max()}")
                else:
                    mtype = "counter" if isinstance(m, Counter) else "gauge"
                    prior = families.get(m.name)
                    if prior is not None and prior[0] != mtype:
                        mtype = "untyped"  # conflicting kinds across entities
                        families[m.name] = (mtype, prior[1], prior[2])
                    fam(m.name, mtype, m.help).append(
                        f"{m.name}{{{ls}}} {m.value()}")
    out: List[str] = []
    for name in order:
        mtype, help, lines = families[name]
        if help:
            out.append(f"# HELP {name} {_escape_help(help)}")
        out.append(f"# TYPE {name} {mtype}")
        out.extend(lines)
    return "\n".join(out) + "\n"


ROOT_REGISTRY = MetricRegistry()


def kernel_metrics() -> MetricEntity:
    """The process-wide entity every JAX-kernel dispatch site records into
    (ops/ code has no server registry in scope; the webserver merges
    ROOT_REGISTRY into each server's exposition)."""
    return ROOT_REGISTRY.entity("server", "kernels")


def serve_path_metrics() -> MetricEntity:
    """The process-wide entity of the batched serve path: group-commit
    writes (tablet/tablet.py), client-batcher coalescing, and
    follower-read gating (tablet/tablet_peer.py). Surfaced as the
    serve-path block on /servez."""
    return ROOT_REGISTRY.entity("server", "serve_path")


def serve_path_snapshot() -> Dict[str, object]:
    """JSON-ready snapshot of the serve-path counters/histograms for
    /servez: group-commit totals + batch-size distribution + follower-
    read accept/reject accounting."""
    e = serve_path_metrics()
    batch = e.histogram("write_batch_rows",
                        "rows per group-committed write batch")
    return {
        "write_group_commit_total": e.counter(
            "write_group_commit_total",
            "write batches replicated as ONE raft entry").value(),
        "write_batch_coalesced_ops_total": e.counter(
            "write_batch_coalesced_ops_total",
            "ops that rode a multi-op group commit").value(),
        "write_batch_rows": {
            "count": batch.count(), "mean": round(batch.mean(), 2),
            "max": batch.max(),
            "p50": round(batch.percentile(50), 1),
            "p99": round(batch.percentile(99), 1)},
        "follower_reads_total": e.counter(
            "follower_reads_total",
            "reads served by a vouched follower replica").value(),
        "follower_read_unvouched_rejects_total": e.counter(
            "follower_read_unvouched_rejects_total",
            "follower reads refused because the replica holds no live "
            "digest vouch").value(),
        "follower_read_vouches_total": e.counter(
            "follower_read_vouches_total",
            "digest-exchange vouches granted to this server's "
            "replicas").value(),
    }


def publish_compile_surface(counts: Dict[str, int]) -> None:
    """Per-kernel-family compile-surface gauges from the committed
    manifest (tools/analysis/kernel_manifest.json): how many distinct
    executables each family's declared bucket lattice mints. Reported
    next to the compile_bucket hit/miss counters so a bench run (or
    /metrics scrape) can prove the warm cache covers exactly the
    manifest surface — misses beyond the surface mean the lattice has
    sprung a leak."""
    e = kernel_metrics()
    total = 0
    for family, n in sorted(counts.items()):
        e.gauge(f"kernel_compile_surface_{family}_buckets_count",
                f"declared compile-surface executables of the {family} "
                "kernel family (committed manifest)").set(n)
        total += n
    e.gauge("kernel_compile_surface_buckets_count",
            "declared compile-surface executables across all kernel "
            "families (committed manifest)").set(total)


_PIPELINE_STAGES = ("host", "device", "write", "shadow", "decode",
                    "encode")


def record_pipeline_stage(stage: str, ms: float) -> None:
    """One slice of compaction-pipeline wall time: `stage` is where the
    time went — 'host' (raw-byte ingest + column packing + decision
    decode), 'device' (kernel compute + H2D/D2H transfer waits),
    'write' (SST output I/O), 'shadow' (sampled oracle verification),
    'decode' (device block-codec ingest: raw-word upload + decode
    dispatch) or 'encode' (device block-codec output: span encode
    dispatch + download + block assembly). Per-stage histograms plus
    a cumulative-ms gauge feed /compactionz and bench.py's stage report,
    so a stalled pipeline shows WHICH stage is the bottleneck."""
    e = kernel_metrics()
    e.histogram(f"compaction_pipeline_stage_{stage}_ms",
                f"compaction pipeline {stage}-stage wall time per "
                "slice").increment(max(ms, 0.0))
    e.gauge(f"compaction_pipeline_stage_{stage}_total_ms",
            f"cumulative compaction pipeline {stage}-stage wall "
            "time").increment(max(ms, 0.0))


def pipeline_stage_totals() -> Dict[str, float]:
    """Cumulative per-stage pipeline milliseconds (host/device/write) —
    the snapshot bench.py diffs around a run to report where the wall
    time of the offloaded compactions went."""
    e = kernel_metrics()
    return {s: float(e.gauge(
        f"compaction_pipeline_stage_{s}_total_ms").value())
        for s in _PIPELINE_STAGES}


def record_kernel_dispatch(kind: str, n_rows: int, n_pad: int,
                           duration_ms: Optional[float] = None) -> None:
    """One JAX-kernel dispatch: invocation counter, wall-time histogram,
    batch-size histogram, and the padding-waste gauges the shape-bucketing
    design makes interesting (padded slots are pure device work). `kind`
    is the kernel family, e.g. 'kernel_merge_gc' / 'kernel_scan'."""
    e = kernel_metrics()
    e.counter(kind + "_dispatch_total",
              f"{kind} device dispatches").increment()
    if duration_ms is not None:
        e.histogram(kind + "_duration_ms",
                    f"{kind} dispatch wall time").increment(duration_ms)
    e.histogram(kind + "_batch_rows",
                f"{kind} real rows per dispatch").increment(max(n_rows, 1))
    e.gauge("kernel_batch_rows",
            "real rows in the most recent kernel dispatch").set(n_rows)
    e.gauge("kernel_pad_waste_rows",
            "padded-but-dead rows in the most recent kernel dispatch "
            "(shape-bucket overhead)").set(max(0, n_pad - n_rows))
