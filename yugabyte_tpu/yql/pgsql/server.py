"""PostgreSQL wire-protocol (v3) server for the YSQL layer.

Any client speaking the PG v3 simple-query protocol (psql, drivers in
simple-query mode) can connect: startup handshake (incl. SSLRequest
refusal), AuthenticationOk, ParameterStatus, simple 'Q' queries answered
with RowDescription/DataRow/CommandComplete, ErrorResponse with SQLSTATE,
and transaction-aware ReadyForQuery status. Replaces the role of the
reference's forked-postgres frontend process (ref: yql/pgwrapper/
pg_wrapper.cc launching postgres; the protocol itself is implemented by
the PG11 fork there — here it is a native part of the framework).

Message formats follow the protocol spec exactly; see each _send_* helper.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import List, Optional, Tuple

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.client.transaction import (TransactionError,
                                             TransactionManager)
from yugabyte_tpu.common.schema import DataType
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.yql.pgsql.executor import (PgError, PgResult, PgSession,
                                             _pg_error, pg_micros_text)
from yugabyte_tpu.utils import ybsan

PROTOCOL_V3 = 196608          # 3.0
SSL_REQUEST_CODE = 80877103
CANCEL_REQUEST_CODE = 80877102
GSS_REQUEST_CODE = 80877104


def _cstr(s: str) -> bytes:
    return s.encode("utf-8") + b"\x00"


def _read_cstr(buf: bytes, off: int):
    end = buf.index(b"\x00", off)
    return buf[off:end].decode("utf-8"), end + 1


# bind-parameter typing: PG type oid <-> framework DataType
PG_OID_TYPES = {16: DataType.BOOL, 20: DataType.INT64, 21: DataType.INT32,
                23: DataType.INT32, 25: DataType.STRING,
                1043: DataType.STRING, 700: DataType.FLOAT,
                701: DataType.DOUBLE, 17: DataType.BINARY,
                1114: DataType.TIMESTAMP, 1184: DataType.TIMESTAMP}


def _type_oid(dt: Optional[DataType]) -> int:
    # one authority for type->oid: the executor's RowDescription map, so
    # ParameterDescription and RowDescription always agree
    from yugabyte_tpu.yql.pgsql.executor import PG_OIDS
    return PG_OIDS.get(dt, 25)


def _decode_param(raw: Optional[bytes], fmt: int,
                  dt: Optional[DataType]) -> object:
    """Bind-parameter decode: text (fmt 0) or binary (fmt 1), converted
    per the statement's inferred marker type (exec_bind_message)."""
    if raw is None:
        return None
    if fmt == 1:  # binary format
        if dt in (DataType.INT32,):
            return struct.unpack(">i", raw)[0] if len(raw) == 4 else \
                struct.unpack(">q", raw)[0]
        if dt in (DataType.INT64, DataType.TIMESTAMP):
            return struct.unpack(">q", raw)[0] if len(raw) == 8 else \
                struct.unpack(">i", raw)[0]
        if dt == DataType.BOOL:
            return raw != b"\x00"
        if dt == DataType.DOUBLE:
            return struct.unpack(">d", raw)[0]
        if dt == DataType.FLOAT:
            return struct.unpack(">f", raw)[0]
        if dt == DataType.BINARY:
            return raw
        return raw.decode("utf-8")
    text = raw.decode("utf-8")
    if dt in (DataType.INT32, DataType.INT64):
        return int(text)
    if dt == DataType.TIMESTAMP:
        # drivers send timestamps as text ('2026-07-30 12:00:00') OR as
        # epoch integers; store whichever arrived
        try:
            return int(text)
        except ValueError:
            return text
    if dt in (DataType.DOUBLE, DataType.FLOAT):
        return float(text)
    if dt == DataType.BOOL:
        return text in ("t", "true", "TRUE", "1", "on")
    if dt == DataType.BINARY:
        if text.startswith("\\x"):
            return bytes.fromhex(text[2:])
        return text.encode()
    return text


def _encode_text(v: object, oid: Optional[int] = None) -> Optional[bytes]:
    """PG text-format value encoding."""
    if v is None:
        return None
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, bytes):
        return b"\\x" + v.hex().encode()
    if isinstance(v, float):
        return repr(v).encode()
    if oid in (1114, 1184) and isinstance(v, int):
        # timestamp columns store epoch micros; clients read date text
        return pg_micros_text(v).encode()
    return str(v).encode("utf-8")


class _Conn:
    def __init__(self, sock: socket.socket, server: "PgServer"):
        self.sock = sock
        self.server = server
        self.session: Optional[PgSession] = None
        # extended query protocol state (ref: PG backend's prepared
        # statements + portals; exec_parse_message/exec_bind_message)
        self._prepared: dict = {}   # name -> (stmt, param DataTypes)
        self._portals: dict = {}    # name -> (stmt, bound params)
        self._ext_error = False     # error sent; discard until Sync

    # ------------------------------------------------------------- framing
    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client disconnected")
            buf += chunk
        return buf

    def _send(self, type_byte: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(type_byte + struct.pack(">I", len(payload) + 4)
                          + payload)

    # ------------------------------------------------------------- startup
    def handshake(self) -> bool:
        while True:
            (length,) = struct.unpack(">I", self._recv_exact(4))
            payload = self._recv_exact(length - 4)
            (code,) = struct.unpack_from(">I", payload, 0)
            if code == SSL_REQUEST_CODE or code == GSS_REQUEST_CODE:
                self.sock.sendall(b"N")  # SSL/GSS not supported; retry plain
                continue
            if code == CANCEL_REQUEST_CODE:
                return False  # cancel keys are not tracked; just close
            if code != PROTOCOL_V3:
                self._send_error("08P01",
                                 f"unsupported protocol {code >> 16}."
                                 f"{code & 0xFFFF}")
                return False
            params = {}
            parts = payload[4:].split(b"\x00")
            for k, v in zip(parts[::2], parts[1::2]):
                if k:
                    params[k.decode()] = v.decode()
            database = params.get("database") or params.get("user") \
                or "postgres"
            try:
                self.session = PgSession(self.server.client,
                                         self.server.txn_manager, database)
            except PgError as e:
                self._send_error(e.sqlstate, e.status.message)
                return False
            except StatusError as e:
                self._send_error("XX000", e.status.message)
                return False
            # AuthenticationOk
            self._send(b"R", struct.pack(">I", 0))
            for k, v in (("server_version", "11.2 (yugabyte-tpu)"),
                         ("server_encoding", "UTF8"),
                         ("client_encoding", "UTF8"),
                         ("DateStyle", "ISO, MDY"),
                         ("integer_datetimes", "on"),
                         ("standard_conforming_strings", "on")):
                self._send(b"S", _cstr(k) + _cstr(v))
            # BackendKeyData (pid, secret) — cancel is accepted-and-ignored
            self._send(b"K", struct.pack(">II", threading.get_ident()
                                         & 0x7FFFFFFF, 0))
            self._send_ready()
            return True

    # ------------------------------------------------------------ messages
    def _send_ready(self) -> None:
        status = self.session.transaction_status() if self.session else "I"
        self._send(b"Z", status.encode())

    def _send_error(self, sqlstate: str, message: str) -> None:
        fields = (b"S" + _cstr("ERROR") + b"V" + _cstr("ERROR")
                  + b"C" + _cstr(sqlstate) + b"M" + _cstr(message)
                  + b"\x00")
        self._send(b"E", fields)

    def _send_one_row(self, row, oids=None) -> None:
        body = struct.pack(">H", len(row))
        for i, v in enumerate(row):
            enc = _encode_text(v, oids[i] if oids else None)
            if enc is None:
                body += struct.pack(">i", -1)
            else:
                body += struct.pack(">I", len(enc)) + enc
        self._send(b"D", body)

    @staticmethod
    def _result_oids(r: PgResult):
        return [oid for _n, oid in r.columns] if r.columns else None

    def _send_data_rows(self, r: PgResult) -> None:
        oids = self._result_oids(r)
        for row in r.rows:
            self._send_one_row(row, oids)

    def _send_result(self, r: PgResult) -> None:
        if r.columns is not None:
            desc = struct.pack(">H", len(r.columns))
            for name, oid in r.columns:
                desc += (_cstr(name) + struct.pack(">IHIhih", 0, 0, oid,
                                                   -1, -1, 0))
            self._send(b"T", desc)
            self._send_data_rows(r)
        self._send(b"C", _cstr(r.tag))

    # ---------------------------------------------------------------- loop
    def run(self) -> None:
        try:
            if not self.handshake():
                return
            while True:
                t = self._recv_exact(1)
                (length,) = struct.unpack(">I", self._recv_exact(4))
                payload = self._recv_exact(length - 4)
                if t == b"X":
                    return
                if t == b"Q":
                    self._ext_error = False
                    self._simple_query(payload[:-1].decode("utf-8"))
                elif t in (b"P", b"B", b"D", b"E", b"C"):
                    # extended query protocol; after an error, discard
                    # until the client's Sync (per-protocol recovery)
                    if self._ext_error:
                        continue
                    try:
                        self._extended(t, payload)
                    except PgError as e:
                        self._send_error(e.sqlstate, e.status.message)
                        self._ext_error = True
                    except StatusError as e:
                        self._send_error("XX000", e.status.message)
                        self._ext_error = True
                    except (ValueError, KeyError, TypeError,
                            struct.error) as e:
                        self._send_error("08P01", str(e))
                        self._ext_error = True
                elif t == b"S":  # Sync: ends an extended-protocol cycle
                    self._ext_error = False
                    self._send_ready()
                elif t == b"H":  # Flush: responses are unbuffered already
                    pass
                else:
                    self._send_error("08P01",
                                     f"unknown message type {t!r}")
                    self._send_ready()
        except (ConnectionError, OSError):
            pass
        finally:
            if self.session is not None:
                self.session.close()
            try:
                self.sock.close()
            except OSError:
                pass

    # ------------------------------------------- extended query protocol
    _OID_TO_TYPE = {16: "bool", 20: "int", 21: "int", 23: "int",
                    25: "text", 1043: "text", 700: "float", 701: "float",
                    17: "bytea"}

    def _extended(self, t: bytes, payload: bytes) -> None:
        from yugabyte_tpu.yql.pgsql import parser as P
        if t == b"P":     # Parse
            name, off = _read_cstr(payload, 0)
            sql, off = _read_cstr(payload, off)
            (n_oids,) = struct.unpack_from(">H", payload, off)
            off += 2
            oids = list(struct.unpack_from(f">{n_oids}i", payload, off)) \
                if n_oids else []
            stmts = P.parse_script(sql)
            if len(stmts) > 1:
                raise PgError(Status.InvalidArgument(
                    "cannot insert multiple commands into a prepared "
                    "statement"), "42601")
            stmt = stmts[0] if stmts else None
            types = (self.session.param_types(stmt)
                     if stmt is not None else [])
            # explicit Parse oids override inferred types
            for i, oid in enumerate(oids):
                if oid and i < len(types):
                    types[i] = None if oid not in PG_OID_TYPES \
                        else PG_OID_TYPES[oid]
            self._prepared[name] = (stmt, types)
            self._send(b"1")  # ParseComplete
        elif t == b"B":   # Bind
            portal, off = _read_cstr(payload, 0)
            sname, off = _read_cstr(payload, off)
            if sname not in self._prepared:
                raise PgError(Status.InvalidArgument(
                    f'prepared statement "{sname}" does not exist'),
                    "26000")
            stmt, types = self._prepared[sname]
            (n_fmt,) = struct.unpack_from(">H", payload, off)
            off += 2
            fmts = list(struct.unpack_from(f">{n_fmt}H", payload, off))
            off += 2 * n_fmt
            (n_params,) = struct.unpack_from(">H", payload, off)
            off += 2
            params = []
            for i in range(n_params):
                (ln,) = struct.unpack_from(">i", payload, off)
                off += 4
                raw: Optional[bytes] = None
                if ln >= 0:
                    raw = payload[off: off + ln]
                    off += ln
                fmt = (fmts[i] if i < len(fmts)
                       else (fmts[0] if len(fmts) == 1 else 0))
                dt = types[i] if i < len(types) else None
                params.append(_decode_param(raw, fmt, dt))
            # result format codes are read but text is always sent
            self._portals[portal] = {"stmt": stmt, "params": params,
                                     "iter": None, "count": 0}
            self._send(b"2")  # BindComplete
        elif t == b"D":   # Describe
            kind = payload[:1]
            name, _ = _read_cstr(payload, 1)
            if kind == b"S":
                stmt, types = self._prepared.get(name, (None, []))
                self._send(b"t", struct.pack(">H", len(types)) + b"".join(
                    struct.pack(">I", _type_oid(dt)) for dt in types))
                self._describe_stmt(stmt)
            else:
                state = self._portals.get(name) or {"stmt": None}
                self._describe_stmt(state["stmt"])
        elif t == b"E":   # Execute
            portal, off = _read_cstr(payload, 0)
            if portal not in self._portals:
                raise PgError(Status.InvalidArgument(
                    f'portal "{portal}" does not exist'), "34000")
            state = self._portals[portal]
            (max_rows,) = struct.unpack_from(">i", payload, off)
            self._execute_portal(portal, state, max_rows)
        elif t == b"C":   # Close
            kind = payload[:1]
            name, _ = _read_cstr(payload, 1)
            (self._prepared if kind == b"S" else self._portals).pop(
                name, None)
            self._send(b"3")  # CloseComplete

    def _execute_portal(self, name: str, state: dict, max_rows: int) -> None:
        """Execute with a row limit: send up to max_rows DataRows, then
        PortalSuspended if the portal has more (the client re-Executes to
        continue) or CommandComplete when drained (PG protocol §55.2.3;
        a suspended portal holds only a lazy iterator — bounded memory)."""
        stmt = state["stmt"]
        if stmt is None:
            self._send(b"I")
            return
        if state.get("done"):
            # a portal runs AT MOST once (PG §55.2.3): Execute after
            # completion re-reports CommandComplete without re-running —
            # re-Executing an INSERT portal must not insert twice
            self._send(b"C", _cstr(state.get("done_tag", "SELECT 0")))
            return
        it = state["iter"]
        if it is not None and state.get("epoch") != self.session.txn_epoch:
            # the portal's iterator is pinned to a finished transaction's
            # snapshot/overlay — PG destroys such portals at txn end
            self._portals.pop(name, None)
            raise PgError(Status.InvalidArgument(
                f'portal "{name}" does not exist'), "34000")
        if it is None:
            result = self.session.execute_bound(stmt, state["params"],
                                                stream=True)
            if result.columns is None:
                # row-less statement (DML/DDL): ran once, portal complete
                state["done"] = True
                state["done_tag"] = result.tag
                self._send(b"C", _cstr(result.tag))
                return
            it = result.row_iter if result.row_iter is not None \
                else iter(result.rows)
            state["iter"] = it
            state["oids"] = self._result_oids(result)
            state["count"] = 0
            state["select"] = result.tag.startswith("SELECT")
            state["tag"] = result.tag
            state["epoch"] = self.session.txn_epoch
        sent = 0
        done = False
        try:
            while max_rows <= 0 or sent < max_rows:
                try:
                    row = next(it)
                except StopIteration:
                    done = True
                    break
                self._send_one_row(row, state.get("oids"))
                sent += 1
        except PgError:
            state["iter"] = None
            self.session._fail_txn()
            raise
        except TransactionError as e:
            state["iter"] = None
            self.session._fail_txn()
            raise PgError(e.status, "40001") from e
        except StatusError as e:
            state["iter"] = None
            self.session._fail_txn()
            raise _pg_error(e) from e
        state["count"] += sent
        if done:
            state["iter"] = None
            tag = (f"SELECT {state['count']}" if state.get("select")
                   else state.get("tag", "SELECT 0"))
            state["done"] = True
            state["done_tag"] = tag
            self._send(b"C", _cstr(tag))
        else:
            self._send(b"s")  # PortalSuspended

    def _describe_stmt(self, stmt) -> None:
        cols = (self.session.describe_columns(stmt)
                if stmt is not None else None)
        if cols is None:
            self._send(b"n")  # NoData
            return
        desc = struct.pack(">H", len(cols))
        for name, oid in cols:
            desc += _cstr(name) + struct.pack(">IHIhih", 0, 0, oid, -1,
                                              -1, 0)
        self._send(b"T", desc)

    def _simple_query(self, sql: str) -> None:
        if not sql.strip():
            self._send(b"I")  # EmptyQueryResponse
            self._send_ready()
            return
        try:
            for result in self.session.execute(sql):
                self._send_result(result)
        except PgError as e:
            self._send_error(e.sqlstate, e.status.message)
        except StatusError as e:
            self._send_error("XX000", e.status.message)
        except (ConnectionError, OSError):
            raise  # socket gone: nothing to report to the client
        except Exception as e:  # noqa: BLE001 — a statement bug must fail
            # THE QUERY, not the connection (PG reports XX000 and stays up)
            self._send_error("XX000", f"{type(e).__name__}: {e}")
        self._send_ready()


@ybsan.shadow(_shutdown=ybsan.SINGLE_WRITER)
class PgServer:
    """Listens for PG-protocol connections, thread per connection (the
    reference runs one postgres backend process per connection;
    ref pg_wrapper.cc)."""

    def __init__(self, client: YBClient, host: str = "127.0.0.1",
                 port: int = 0):
        self.client = client
        self.txn_manager = TransactionManager(client)
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._shutdown = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="pg-accept")
        self._accept_thread.start()
        TRACE("pg server listening on %s:%d", self.host, self.port)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._shutdown:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=_Conn(sock, self).run, daemon=True,
                             name="pg-conn").start()

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            self._listener.close()
        except OSError:
            pass
