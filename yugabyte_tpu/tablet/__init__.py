"""Replicated tablet layer: Tablet, MvccManager, WriteQuery pipeline.

Capability parity with src/yb/tablet (ref: tablet/tablet.h:124,
tablet/write_query.cc, tablet/mvcc.h). One Tablet = one shard, holding TWO
LSM instances — regular and intents (ref: tablet/tablet.h:856-857) — plus
the MVCC safe-time machinery that makes snapshot reads consistent.
"""
