"""Per-request tracing.

Capability parity with yb::Trace (ref: src/yb/util/trace.h:62-137): a Trace
collects timestamped messages for one request; traces dump on slow operations
(ref: LongOperationTracker usage, tserver/read_query.cc:500). A contextvar
carries the current trace, so deep call stacks need no plumbing.
"""

from __future__ import annotations

import contextvars
import time
from typing import List, Optional, Tuple

_current_trace: contextvars.ContextVar[Optional["Trace"]] = contextvars.ContextVar(
    "ybtpu_trace", default=None)


class Trace:
    __slots__ = ("entries", "start", "children", "name", "record",
                 "_token")

    def __init__(self, name: str = "", record: bool = True):
        self.entries: List[Tuple[float, str]] = []
        self.start = time.monotonic()
        self.children: List["Trace"] = []
        self.name = name
        # record=False: a child attached to a parent trace — it renders
        # inside the parent's /tracez entry, not as its own
        self.record = record

    def message(self, msg: str) -> None:
        self.entries.append((time.monotonic() - self.start, msg))

    def dump(self) -> str:
        lines = [f"{dt * 1e3:10.3f}ms {msg}" for dt, msg in self.entries]
        for child in self.children:
            lines.append("  [child trace]")
            lines.extend("  " + l for l in child.dump().splitlines())
        return "\n".join(lines)

    def __enter__(self) -> "Trace":
        self._token = _current_trace.set(self)
        return self

    def __exit__(self, *exc) -> None:
        _current_trace.reset(self._token)
        # children count as content: a request whose only activity is a
        # nested local-bypass call must still appear in /tracez
        if self.record and (self.entries or self.children):
            _record_tracez(self)


def TRACE(msg: str, *args) -> None:
    """Append to the current request trace, if any (ref: TRACE() macro, trace.h)."""
    t = _current_trace.get()
    if t is not None:
        t.message(msg % args if args else msg)


def current_trace() -> Optional[Trace]:
    return _current_trace.get()


# ------------------------------------------------------------- /tracez
# Ring of recently completed traces (ref: the reference's /tracez page
# over yb::Trace sampling). Completed scoped Traces with any entries
# land here; the webserver serves them as JSON.
_tracez_lock = __import__("threading").Lock()
_TRACEZ: List[dict] = []
_TRACEZ_CAP = 64


def _record_tracez(t: Trace) -> None:
    entry = {"name": t.name or "request",
             "wall_ts": time.time(),
             "duration_ms": round((time.monotonic() - t.start) * 1e3, 3),
             "dump": t.dump()}
    with _tracez_lock:
        _TRACEZ.append(entry)
        if len(_TRACEZ) > _TRACEZ_CAP:
            del _TRACEZ[: len(_TRACEZ) - _TRACEZ_CAP]


def tracez() -> List[dict]:
    with _tracez_lock:
        return list(reversed(_TRACEZ))


def threadz() -> List[dict]:
    """Live thread stack dump (the reference exposes /pprof + /threadz
    from the stack-trace collector, util/debug-util.cc)."""
    import sys
    import threading as _t
    import traceback
    frames = sys._current_frames()
    out = []
    for th in _t.enumerate():
        fr = frames.get(th.ident)
        out.append({
            "name": th.name,
            "ident": th.ident,
            "daemon": th.daemon,
            "stack": traceback.format_stack(fr) if fr is not None else [],
        })
    return out


class LongOperationTracker:
    """Warns (collects) when an operation exceeds a threshold (ref: util/long_operation_tracker.h)."""

    def __init__(self, name: str, threshold_ms: float = 1000.0):
        self.name = name
        self.threshold_ms = threshold_ms

    def __enter__(self):
        self._start = time.monotonic()
        return self

    def __exit__(self, *exc):
        elapsed_ms = (time.monotonic() - self._start) * 1e3
        if elapsed_ms > self.threshold_ms:
            TRACE("LongOperation %s took %.1fms (threshold %.1fms)",
                  self.name, elapsed_ms, self.threshold_ms)
