"""Raft membership change tests (ref: the reference covers this surface in
consensus/raft_consensus_quorum-test.cc and integration-tests/
raft_consensus-itest.cc: add/remove server, leader removal, config
persistence across restart)."""

import threading
import time

import pytest

from yugabyte_tpu.consensus.log import Log
from yugabyte_tpu.consensus.raft import (
    OP_WRITE, ConfigAlreadyApplied, ConfigChangeInProgress, RaftConfig,
    RaftConsensus, Role)
from yugabyte_tpu.consensus.transport import LocalTransport


def make_node(tmp_path, transport, applied, peer, members, timer=False):
    d = tmp_path / peer.replace("/", "_")
    d.mkdir(exist_ok=True)
    cfg = RaftConfig(peer_id=peer, peer_ids=tuple(members))
    node = RaftConsensus(cfg, Log(str(d / "wal")), transport,
                         apply_cb=lambda m, p=peer: applied[p].append(m),
                         meta_path=str(d / "meta.json"))
    transport.register(peer, node)
    node.start(election_timer=timer)
    return node


def wait_for(cond, timeout=10, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timeout waiting for {msg}"
        time.sleep(0.01)


@pytest.fixture
def group(tmp_path):
    transport = LocalTransport()
    members = ["a/t", "b/t", "c/t"]
    applied = {p: [] for p in ["a/t", "b/t", "c/t", "d/t"]}
    nodes = {p: make_node(tmp_path, transport, applied, p, members)
             for p in members}
    nodes["a/t"].start_election(ignore_lease=True)
    wait_for(nodes["a/t"].is_leader, msg="leader election")
    yield tmp_path, transport, nodes, applied
    for n in nodes.values():
        n.shutdown()


def test_add_server(group):
    tmp_path, transport, nodes, applied = group
    leader = nodes["a/t"]
    for i in range(5):
        leader.replicate(OP_WRITE, i + 1, b"w%d" % i)
    # New peer starts from the pre-change config (what a remote bootstrap
    # would have copied) and learns of its own membership via AppendEntries.
    nodes["d/t"] = make_node(tmp_path, transport, applied, "d/t",
                             ["a/t", "b/t", "c/t"])
    leader.change_config(add=["d/t"])
    assert set(leader.config.peer_ids) == {"a/t", "b/t", "c/t", "d/t"}
    leader.replicate(OP_WRITE, 6, b"after-add")
    wait_for(lambda: len(applied["d/t"]) == 6, msg="new peer catch-up")
    assert [m.payload for m in applied["d/t"]] == \
        [b"w0", b"w1", b"w2", b"w3", b"w4", b"after-add"]
    assert set(nodes["d/t"].config.peer_ids) == {"a/t", "b/t", "c/t", "d/t"}
    # Idempotent retry surfaces as ConfigAlreadyApplied.
    with pytest.raises(ConfigAlreadyApplied):
        leader.change_config(add=["d/t"])


def test_remove_server_and_majority(group):
    tmp_path, transport, nodes, applied = group
    leader = nodes["a/t"]
    leader.change_config(remove=["c/t"])
    assert set(leader.config.peer_ids) == {"a/t", "b/t"}
    # c is gone AND b is enough for majority (2 of 2).
    transport.isolate("c/t")
    leader.replicate(OP_WRITE, 1, b"post-remove", timeout_s=10)
    wait_for(lambda: len(applied["b/t"]) == 1, msg="b apply")


def test_leader_self_removal_steps_down(group):
    tmp_path, transport, nodes, applied = group
    leader = nodes["a/t"]
    leader.replicate(OP_WRITE, 1, b"w")
    leader.change_config(remove=["a/t"])
    wait_for(lambda: not leader.is_leader(), msg="leader step-down")
    nodes["b/t"].start_election(ignore_lease=True)
    wait_for(lambda: nodes["b/t"].is_leader() or nodes["c/t"].is_leader(),
             msg="new leader among remaining")
    new_leader = nodes["b/t"] if nodes["b/t"].is_leader() else nodes["c/t"]
    new_leader.replicate(OP_WRITE, 2, b"after", timeout_s=10)
    assert set(new_leader.config.peer_ids) == {"b/t", "c/t"}


def test_only_one_pending_change(group):
    tmp_path, transport, nodes, applied = group
    leader = nodes["a/t"]
    # Cut both followers: the change can append but never commit.
    transport.partition("a/t", "b/t")
    transport.partition("a/t", "c/t")
    # The first change must be IN FLIGHT while we try the second; whether
    # it ultimately times out (still partitioned) or commits (after the
    # heal below) is irrelevant — asserting a timeout here raced the heal
    # and intermittently failed inside the thread.
    outcome = {}

    def attempt_first_change():
        try:
            leader.change_config(remove=["c/t"], timeout_s=2)
            outcome["result"] = "committed"
        except Exception as e:  # noqa: BLE001 — either way is fine
            outcome["result"] = f"raised {type(e).__name__}"

    t = threading.Thread(target=attempt_first_change, daemon=True)
    t.start()
    time.sleep(0.3)  # let the first change append
    with pytest.raises(ConfigChangeInProgress):
        leader.change_config(remove=["b/t"], timeout_s=1)
    transport.heal()
    t.join(timeout=10)
    assert "result" in outcome


def test_config_survives_restart(group, tmp_path):
    tmp_path_, transport, nodes, applied = group
    leader = nodes["a/t"]
    nodes["d/t"] = make_node(tmp_path_, transport, applied, "d/t",
                             ["a/t", "b/t", "c/t"])
    leader.change_config(add=["d/t"])
    leader.replicate(OP_WRITE, 1, b"x")
    wait_for(lambda: len(applied["d/t"]) == 1, msg="d caught up")
    nodes["d/t"].shutdown()
    transport.heal()
    # Recreate d from disk with the STALE initial config; the persisted
    # config (cmeta + WAL) must win.
    applied["d/t"] = []
    nodes["d/t"] = make_node(tmp_path_, transport, applied, "d/t",
                             ["a/t", "b/t", "c/t"])
    assert set(nodes["d/t"].config.peer_ids) == \
        {"a/t", "b/t", "c/t", "d/t"}
