"""jit-trace-safety: no host syncs, tracer branches or lattice-widening
static args inside jit-traced code.

The offload pipeline lives or dies on two properties of its jitted
kernels (ops/run_merge.py, ops/merge_gc.py, ops/scan.py):

  1. nothing inside a traced function forces a host sync — `.item()`,
     `np.asarray(...)`/`float(...)`/`int(...)`/`bool(...)` on a tracer,
     or `print` of a tracer all block the async dispatch queue and stall
     the stage-overlapped compaction pipeline;
  2. the compile-key lattice stays small — a Python `if`/`while` on a
     tracer raises ConcretizationError at trace time, and a non-hashable
     (or un-quantized) static argument either fails or mints a fresh
     executable per distinct value, the recompile storm the shape-bucket
     lattice in run_merge.py exists to prevent.

Mechanics (per file, no cross-file resolution — conservative misses,
not false positives):

- jit roots: functions decorated `@jax.jit` / `@jit` /
  `@functools.partial(jax.jit, ...)` / `@partial(jax.jit, ...)`, and
  module-level wrappers `w = jax.jit(f, ...)` or
  `w = functools.partial(jax.jit, ...)(f)`. Static parameters come from
  `static_argnames=` / `static_argnums=` constants.
- taint: non-static parameters of a root are tracers; assignment
  propagates taint intra-function; calls to same-module functions
  propagate taint from actual arguments to formal parameters (so helper
  functions reached from a jit root are checked against the tracer-ness
  of what each call site actually passes).
- tracer-ness stops at shape metadata: `x.shape` / `x.ndim` / `x.dtype`
  / `x.size` / `len(x)` of a tracer are static — branching on them is
  fine and common.

Waive a deliberate violation with `# yblint: disable=jit-trace-safety`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis.core import AnalysisPass, FileContext, Finding

PASS_NAME = "jit-trace-safety"

# attributes of a tracer that are static Python values at trace time
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize",
                 "aval", "sharding", "device"}
# builtins whose call on a tracer forces a concretization / host sync
_HOST_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
# numpy converters that force a device->host transfer of a tracer
_NUMPY_CONVERTERS = {"asarray", "array", "asanyarray", "ascontiguousarray"}
_NUMPY_MODULE_NAMES = {"np", "numpy", "onp"}
# calls through which taint does NOT flow to the result / the test
_TAINT_STOPPERS = {"len", "isinstance", "hasattr", "getattr", "type",
                   "id", "repr"}


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute chains / Names; '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_callable(node: ast.AST) -> bool:
    return _dotted(node) in ("jax.jit", "jit")


def _jit_partial_call(node: ast.AST) -> Optional[ast.Call]:
    """`functools.partial(jax.jit, ...)` / `partial(jax.jit, ...)` -> the
    Call node (whose keywords carry the static arg spec)."""
    if (isinstance(node, ast.Call)
            and _dotted(node.func) in ("functools.partial", "partial")
            and node.args and _is_jit_callable(node.args[0])):
        return node
    return None


def _static_names_from_call(call: ast.Call, params: Sequence[str],
                            const_env: Optional[Dict[str, Set[str]]] = None
                            ) -> Set[str]:
    """static_argnames/static_argnums constants -> parameter names.
    A bare Name (e.g. `static_argnames=_FUSED_STATICS`) resolves through
    the module-level string-tuple constants in const_env."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Name) and const_env \
                    and kw.value.id in const_env:
                out |= const_env[kw.value.id]
                continue
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    out.add(c.value)
        elif kw.arg == "static_argnums":
            for c in ast.walk(kw.value):
                if isinstance(c, ast.Constant) and isinstance(c.value, int):
                    if 0 <= c.value < len(params):
                        out.add(params[c.value])
    return out


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
    names += [p.arg for p in a.kwonlyargs]
    return names


class _FnInfo:
    __slots__ = ("node", "params", "tainted_params", "is_root")

    def __init__(self, node: ast.AST):
        self.node = node
        self.params = _param_names(node)
        self.tainted_params: Set[str] = set()
        self.is_root = False


class JitTraceSafetyPass(AnalysisPass):
    name = PASS_NAME

    def run(self, ctx: FileContext) -> List[Finding]:
        fns: Dict[str, _FnInfo] = {}
        for node in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            # module-level and class-level defs are callable by name;
            # nested defs only from their parent (still indexed — call
            # resolution is by bare name, shadowing is rare in this tree)
            fns.setdefault(node.name, _FnInfo(node))

        statics_of: Dict[str, Set[str]] = {}
        jit_wrappers: Dict[str, str] = {}  # wrapper name -> function name
        self._const_env = self._module_str_constants(ctx)
        self._find_roots(ctx, fns, statics_of, jit_wrappers)
        if not any(i.is_root for i in fns.values()):
            return []

        self._propagate(ctx, fns)

        findings: List[Finding] = []
        for info in fns.values():
            if info.tainted_params:
                findings.extend(self._check_function(ctx, info))
        findings.extend(self._check_static_call_sites(
            ctx, fns, statics_of, jit_wrappers))
        return findings

    # ------------------------------------------------------------ roots
    def _module_str_constants(self, ctx: FileContext) -> Dict[str, Set[str]]:
        """Module-level `NAME = ("a", "b", ...)` string tuples (the idiom
        for shared static_argnames specs)."""
        env: Dict[str, Set[str]] = {}
        for stmt in ctx.tree.body:
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1 \
                    or not isinstance(stmt.targets[0], ast.Name):
                continue
            v = stmt.value
            if isinstance(v, (ast.Tuple, ast.List)) and v.elts and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in v.elts):
                env[stmt.targets[0].id] = {e.value for e in v.elts}
        return env

    def _find_roots(self, ctx: FileContext, fns: Dict[str, _FnInfo],
                    statics_of: Dict[str, Set[str]],
                    jit_wrappers: Dict[str, str]) -> None:
        for name, info in fns.items():
            for dec in info.node.decorator_list:
                statics: Optional[Set[str]] = None
                if _is_jit_callable(dec):
                    statics = set()
                elif isinstance(dec, ast.Call) and _is_jit_callable(dec.func):
                    statics = _static_names_from_call(dec, info.params,
                                                     self._const_env)
                elif _jit_partial_call(dec) is not None:
                    statics = _static_names_from_call(
                        _jit_partial_call(dec), info.params,
                        self._const_env)
                if statics is not None:
                    info.is_root = True
                    info.tainted_params |= (
                        set(info.params) - statics
                        - {"self", "cls"})
                    statics_of[name] = statics
        # wrapper assignments: w = jax.jit(f, ...) or
        # w = functools.partial(jax.jit, ...)(f)
        for asn in ctx.nodes_of(ast.Assign):
            v = asn.value
            target_fn: Optional[str] = None
            statics: Set[str] = set()
            if isinstance(v, ast.Call) and _is_jit_callable(v.func) \
                    and v.args and isinstance(v.args[0], ast.Name):
                target_fn = v.args[0].id
                if target_fn in fns:
                    statics = _static_names_from_call(
                        v, fns[target_fn].params, self._const_env)
            elif isinstance(v, ast.Call) \
                    and _jit_partial_call(v.func) is not None \
                    and v.args and isinstance(v.args[0], ast.Name):
                target_fn = v.args[0].id
                if target_fn in fns:
                    statics = _static_names_from_call(
                        _jit_partial_call(v.func), fns[target_fn].params,
                        self._const_env)
            if target_fn and target_fn in fns:
                info = fns[target_fn]
                info.is_root = True
                info.tainted_params |= (set(info.params) - statics
                                        - {"self", "cls"})
                statics_of[target_fn] = statics
                for t in asn.targets:
                    if isinstance(t, ast.Name):
                        jit_wrappers[t.id] = target_fn

    # ------------------------------------------------- taint propagation
    def _propagate(self, ctx: FileContext, fns: Dict[str, _FnInfo]) -> None:
        """Fixpoint over call edges: tainted actual -> tainted formal."""
        for _ in range(len(fns) + 2):
            changed = False
            for info in fns.values():
                if not info.tainted_params:
                    continue
                local = self._local_taint(ctx, info)
                for call in ast.walk(info.node):
                    if not isinstance(call, ast.Call):
                        continue
                    callee = call.func.id \
                        if isinstance(call.func, ast.Name) else None
                    if callee not in fns or callee == info.node.name:
                        continue
                    tgt = fns[callee]
                    for i, arg in enumerate(call.args):
                        if i < len(tgt.params) \
                                and self._tracer_expr(arg, local) \
                                and tgt.params[i] not in tgt.tainted_params:
                            tgt.tainted_params.add(tgt.params[i])
                            changed = True
                    for kw in call.keywords:
                        if kw.arg and kw.arg in tgt.params \
                                and self._tracer_expr(kw.value, local) \
                                and kw.arg not in tgt.tainted_params:
                            tgt.tainted_params.add(kw.arg)
                            changed = True
            if not changed:
                return

    def _local_taint(self, ctx: FileContext, info: _FnInfo) -> Set[str]:
        """Tainted local names: params + assignment-propagated values."""
        tainted = set(info.tainted_params)
        for _ in range(8):
            changed = False
            for node in ast.walk(info.node):
                targets: List[ast.AST] = []
                value: Optional[ast.AST] = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if value is None or not self._tracer_expr(value, tainted):
                    continue
                for t in targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
            if not changed:
                break
        return tainted

    def _tracer_expr(self, node: ast.AST, tainted: Set[str]) -> bool:
        """Does evaluating this expression touch a tracer VALUE (as
        opposed to static metadata like .shape / len())?"""
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._tracer_expr(node.value, tainted)
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname in _TAINT_STOPPERS:
                return False
            # method calls on tracers (x.astype, x.reshape) keep taint
            return (self._tracer_expr(node.func, tainted)
                    or any(self._tracer_expr(a, tainted)
                           for a in node.args)
                    or any(self._tracer_expr(k.value, tainted)
                           for k in node.keywords))
        if isinstance(node, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            return False  # `x is None` is an identity check, no sync
        if isinstance(node, (ast.BinOp, ast.BoolOp, ast.UnaryOp,
                             ast.Compare, ast.Subscript, ast.IfExp,
                             ast.Tuple, ast.List, ast.Starred)):
            return any(self._tracer_expr(c, tainted)
                       for c in ast.iter_child_nodes(node)
                       if isinstance(c, ast.expr))
        return False

    # ------------------------------------------------------------ checks
    def _check_function(self, ctx: FileContext,
                        info: _FnInfo) -> List[Finding]:
        tainted = self._local_taint(ctx, info)
        out: List[Finding] = []
        own_nested = {n for fn in ast.walk(info.node)
                      if isinstance(fn, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                      and fn is not info.node
                      for n in ast.walk(fn)}
        for node in ast.walk(info.node):
            if node in own_nested:
                continue  # nested defs are analyzed via call-site taint
            if isinstance(node, ast.Call):
                out.extend(self._check_call(ctx, node, tainted))
            elif isinstance(node, (ast.If, ast.While)):
                if self._tracer_expr(node.test, tainted):
                    out.append(ctx.finding(
                        self.name, "tracer-branch", node,
                        "Python branch on a tracer value inside jit-traced "
                        "code — use jnp.where/lax.cond, or branch on "
                        "static metadata (.shape/len) instead"))
            elif isinstance(node, ast.Assert):
                if self._tracer_expr(node.test, tainted):
                    out.append(ctx.finding(
                        self.name, "tracer-branch", node,
                        "assert on a tracer value inside jit-traced code "
                        "concretizes at trace time"))
        return out

    def _check_call(self, ctx: FileContext, node: ast.Call,
                    tainted: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        f = node.func
        # x.item() / x.tolist() on a tracer
        if isinstance(f, ast.Attribute) and f.attr in ("item", "tolist") \
                and self._tracer_expr(f.value, tainted):
            out.append(ctx.finding(
                self.name, "host-sync", node,
                f".{f.attr}() on a tracer forces a device->host sync "
                "inside jit-traced code"))
            return out
        fname = _dotted(f)
        # float(x) / int(x) / bool(x) on a tracer
        if fname in _HOST_SYNC_BUILTINS and node.args \
                and self._tracer_expr(node.args[0], tainted):
            out.append(ctx.finding(
                self.name, "host-sync", node,
                f"{fname}() on a tracer concretizes it (host sync / "
                "ConcretizationError) inside jit-traced code"))
            return out
        # np.asarray(x) and friends on a tracer
        if "." in fname:
            mod, _, leaf = fname.rpartition(".")
            if mod in _NUMPY_MODULE_NAMES and leaf in _NUMPY_CONVERTERS \
                    and node.args \
                    and self._tracer_expr(node.args[0], tainted):
                out.append(ctx.finding(
                    self.name, "host-sync", node,
                    f"{fname}() on a tracer downloads it to host inside "
                    "jit-traced code — keep it jnp, or hoist out of jit"))
                return out
        # print of a tracer
        if fname == "print" and any(self._tracer_expr(a, tainted)
                                    for a in node.args):
            out.append(ctx.finding(
                self.name, "print-tracer", node,
                "print of a tracer inside jit-traced code (host sync at "
                "trace/run time) — use jax.debug.print"))
        return out

    # --------------------------------------------- static-arg call sites
    def _check_static_call_sites(self, ctx: FileContext,
                                 fns: Dict[str, _FnInfo],
                                 statics_of: Dict[str, Set[str]],
                                 jit_wrappers: Dict[str, str]
                                 ) -> List[Finding]:
        """Call sites of known jit callables: a static arg passed a
        list/dict/set literal is unhashable and fails (or forces object-
        identity caching) at dispatch."""
        out: List[Finding] = []
        callables: Dict[str, str] = {}
        for name, statics in statics_of.items():
            if statics:
                callables[name] = name
        for wname, fname in jit_wrappers.items():
            if statics_of.get(fname):
                callables[wname] = fname
        if not callables:
            return out
        for call in ctx.nodes_of(ast.Call):
            cname = call.func.id if isinstance(call.func, ast.Name) else None
            if cname not in callables:
                continue
            statics = statics_of[callables[cname]]
            for kw in call.keywords:
                if kw.arg in statics and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    out.append(ctx.finding(
                        self.name, "unhashable-static", kw.value,
                        f"static arg {kw.arg!r} of {cname} passed a "
                        f"{type(kw.value).__name__.lower()} literal — "
                        "statics must be hashable (use a tuple)"))
        return out
