"""MemTable: the in-memory sorted run.

Capability parity with the reference's skiplist memtable (ref:
src/yb/rocksdb/db/memtable.cc, memtable/skiplistrep.cc). Python design:
an append log + lazily-sorted key list — appends are O(1), and sorting a
mostly-sorted list on first read after a write burst is near-linear
(timsort). Entries are keyed by full internal key (key_prefix + HT suffix),
which is unique per write. Flush emits a KVSlab directly (the flush job's
entire output path stays columnar).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from yugabyte_tpu.common.hybrid_time import DocHybridTime
from yugabyte_tpu.docdb.doc_key import split_key_and_ht
from yugabyte_tpu.docdb.value_type import ValueType
from yugabyte_tpu.ops.slabs import KVSlab, pack_doc_ht, pack_kvs


def make_internal_key(key_prefix: bytes, dht: DocHybridTime) -> bytes:
    return key_prefix + bytes([ValueType.kHybridTime]) + dht.encoded()


class MemTable:
    def __init__(self):
        self._data: Dict[bytes, bytes] = {}
        self._keys: List[bytes] = []
        self._sorted_upto = 0
        self._dups_possible = False
        self._bytes = 0
        self.version = 0  # bumped per mutation: packed-run cache key
        self._lock = threading.Lock()
        # monotonic time of the first write — the global-memstore arbiter
        # flushes the tablet holding the OLDEST mutable data first
        # (ref: tserver/tablet_memory_manager.cc TabletToFlush)
        self._first_write_s: Optional[float] = None

    def add(self, key_prefix: bytes, dht: DocHybridTime, value: bytes) -> None:
        ikey = make_internal_key(key_prefix, dht)
        with self._lock:
            if ikey not in self._data:
                self._keys.append(ikey)
            self._data[ikey] = value
            self._bytes += len(ikey) + len(value)
            self.version += 1
            if self._first_write_s is None:
                self._first_write_s = time.monotonic()

    def add_batch(self, items) -> None:
        """Bulk insert of (key_prefix, dht, value) triples — one lock
        acquisition, C-speed dict.update, and deferred key dedup (the
        sorted-snapshot pass dedups; the write-path hot loop, ref:
        db/memtable.cc Add)."""
        ikeys = [make_internal_key(k, dht) for k, dht, _ in items]
        vals = [v for _, _, v in items]
        nbytes = sum(map(len, ikeys)) + sum(map(len, vals))
        with self._lock:
            self._data.update(zip(ikeys, vals))
            # may append keys already present; _sorted_snapshot dedups
            self._keys.extend(ikeys)
            self._dups_possible = True
            self._bytes += nbytes
            self.version += 1
            if self._first_write_s is None:
                self._first_write_s = time.monotonic()

    def point_get(self, seek: bytes, boundary: bytes
                  ) -> Optional[Tuple[bytes, bytes]]:
        """First (internal_key, value) at or after `seek` that still starts
        with `boundary`, without copying the key list (the per-point-read
        snapshot copy dominated hot gets on large memtables)."""
        with self._lock:
            self._ensure_sorted_locked()
            idx = bisect.bisect_left(self._keys, seek)
            if idx < len(self._keys):
                k = self._keys[idx]
                if k.startswith(boundary):
                    return k, self._data[k]
        return None

    def entries_range(self, lower: bytes,
                      upper: bytes) -> List[Tuple[bytes, bytes]]:
        """(internal_key, value) with lower <= key < upper (the bounded
        per-row probe of the batched read path; same contract as
        NativeMemTable.entries_range)."""
        with self._lock:
            self._ensure_sorted_locked()
            lo = bisect.bisect_left(self._keys, lower)
            hi = bisect.bisect_left(self._keys, upper)
            return [(k, self._data[k]) for k in self._keys[lo:hi]]

    def point_get_many(self, probes) -> List[Optional[Tuple[bytes, bytes]]]:
        """Batched point_get over [(seek, boundary), ...]: one lock/sort
        for the whole probe list (the batched read path's per-key probe)."""
        out: List[Optional[Tuple[bytes, bytes]]] = [None] * len(probes)
        with self._lock:
            self._ensure_sorted_locked()
            keys = self._keys
            n = len(keys)
            for j, (seek, boundary) in enumerate(probes):
                idx = bisect.bisect_left(keys, seek)
                if idx < n and keys[idx].startswith(boundary):
                    out[j] = (keys[idx], self._data[keys[idx]])
        return out

    def _ensure_sorted_locked(self) -> None:
        if self._sorted_upto != len(self._keys):
            # add_batch defers duplicate-key suppression to here: one
            # set() pass at sort time beats a per-row `in` probe per write
            self._keys = sorted(set(self._keys)) if self._dups_possible \
                else sorted(self._keys)
            self._dups_possible = False
            self._sorted_upto = len(self._keys)

    @property
    def oldest_write_s(self) -> Optional[float]:
        return self._first_write_s

    @property
    def n_entries(self) -> int:
        return len(self._data)

    @property
    def approximate_bytes(self) -> int:
        return self._bytes

    @property
    def empty(self) -> bool:
        return not self._data

    def _sorted_snapshot(self) -> List[bytes]:
        """Sorted key list safe to iterate without the lock.

        Sorting REPLACES the list (never in-place), so earlier snapshots are
        never mutated; concurrent adds append to the current list but the
        snapshot's returned length bound hides them.
        """
        with self._lock:
            self._ensure_sorted_locked()
            return self._keys[:]  # cheap vs re-sort; isolates from appends

    def iter_from(self, seek_key: bytes = b"") -> Iterator[Tuple[bytes, bytes]]:
        """Yield (internal_key, value) in memcmp order from seek_key."""
        snap = self._sorted_snapshot()
        idx = bisect.bisect_left(snap, seek_key)
        for i in range(idx, len(snap)):
            k = snap[i]
            yield k, self._data[k]

    def to_slab(self) -> KVSlab:
        """Flush path: produce a sorted slab (ref: db/flush_job.cc)."""
        snap = self._sorted_snapshot()
        triples = []
        for ikey in snap:
            prefix, dht = split_key_and_ht(ikey)
            triples.append((prefix, pack_doc_ht(dht), self._data[ikey]))
        return pack_kvs(triples)

    def to_packed(self):
        """Sorted packed-run arrays for the native flush encoder
        (native/compaction_engine.cc ce_job_add_raw): (keys_blob, key_offs,
        ht, wid, vals_blob, val_offs). The 13-byte internal-key suffix is
        fixed width, so the split is pure slicing and the DocHybridTime
        columns decode in two vectorized complement passes."""
        import numpy as np
        from yugabyte_tpu.common.hybrid_time import ENCODED_DOC_HT_SIZE
        snap = self._sorted_snapshot()
        n = len(snap)
        s = ENCODED_DOC_HT_SIZE + 1  # kHybridTime byte + 12-byte suffix
        prefixes = [k[:-s] for k in snap]
        keys_blob = b"".join(prefixes)
        key_offs = np.zeros(n + 1, dtype=np.int64)
        if n:
            np.cumsum([len(p) for p in prefixes], out=key_offs[1:])
        suffix = b"".join(k[-ENCODED_DOC_HT_SIZE:] for k in snap)
        rec = (np.frombuffer(suffix, dtype=np.uint8).reshape(n, 12)
               if n else np.zeros((0, 12), dtype=np.uint8))
        ht = (np.ascontiguousarray(rec[:, :8]).view(">u8").ravel()
              ^ np.uint64(0xFFFFFFFFFFFFFFFF)).astype(np.uint64)
        wid = (np.ascontiguousarray(rec[:, 8:]).view(">u4").ravel()
               ^ np.uint32(0xFFFFFFFF)).astype(np.uint32)
        data = self._data
        vals = [data[k] for k in snap]
        vals_blob = b"".join(vals)
        val_offs = np.zeros(n + 1, dtype=np.int64)
        if n:
            np.cumsum([len(v) for v in vals], out=val_offs[1:])
        return keys_blob, key_offs, ht, wid, vals_blob, val_offs


# --------------------------------------------------------------------------
# Native memtable arena (native/memtable_arena.cc): the same interface at
# memcpy speed — append-only C++ arena of full internal keys, sort-on-
# demand index, latest-insert-wins dedup (ref: db/memtable.cc arena).

import ctypes as _ct

import numpy as _np

_U64 = 0xFFFFFFFFFFFFFFFF
_U32 = 0xFFFFFFFF
_mt_lib = None
_mt_lib_lock = threading.Lock()
_i64p = _ct.POINTER(_ct.c_int64)
_u64p = _ct.POINTER(_ct.c_uint64)
_u32p = _ct.POINTER(_ct.c_uint32)
_u8p = _ct.POINTER(_ct.c_uint8)


def _load_mt_lib():
    global _mt_lib
    with _mt_lib_lock:
        if _mt_lib is not None:
            return _mt_lib
        from yugabyte_tpu.utils.native_build import build_native_lib
        path = build_native_lib("memtable_arena.cc", "libmemtable_arena.so",
                                deps=())
        lib = _ct.CDLL(path)
        lib.mt_new.restype = _ct.c_void_p
        lib.mt_free.argtypes = [_ct.c_void_p]
        lib.mt_add_batch.argtypes = [_ct.c_void_p, _ct.c_char_p, _i64p,
                                     _ct.c_char_p, _ct.c_char_p, _i64p,
                                     _ct.c_int64]
        lib.mt_n.restype = _ct.c_int64
        lib.mt_n.argtypes = [_ct.c_void_p]
        lib.mt_bytes.restype = _ct.c_int64
        lib.mt_bytes.argtypes = [_ct.c_void_p]
        lib.mt_raw_n.restype = _ct.c_int64
        lib.mt_raw_n.argtypes = [_ct.c_void_p]
        lib.mt_lower_bound.restype = _ct.c_int64
        lib.mt_lower_bound.argtypes = [_ct.c_void_p, _ct.c_char_p,
                                       _ct.c_int32]
        lib.mt_range_sizes.argtypes = [_ct.c_void_p, _ct.c_int64,
                                       _ct.c_int64, _ct.c_int32, _i64p,
                                       _i64p]
        lib.mt_export_range.argtypes = [_ct.c_void_p, _ct.c_int64,
                                        _ct.c_int64, _ct.c_int32, _u8p,
                                        _i64p, _u64p, _u32p, _u8p, _i64p]
        _mt_lib = lib
        return lib


def native_memtable_available() -> bool:
    try:
        _load_mt_lib()
        return True
    except Exception:  # noqa: BLE001  # yblint: contained(feature probe — no toolchain means the Python memtable)
        return False


def _encode_suffixes(ht_vals: _np.ndarray, wids: _np.ndarray) -> bytes:
    """Vectorized DocHybridTime.encoded() for a column: 12 bytes/row of
    big-endian complement (desc order), concatenated."""
    n = len(ht_vals)
    out = _np.empty((n, 12), dtype=_np.uint8)
    out[:, :8] = (
        (ht_vals.astype(_np.uint64) ^ _np.uint64(_U64))
        .astype(">u8").view(_np.uint8).reshape(n, 8))
    out[:, 8:] = (
        (wids.astype(_np.uint32) ^ _np.uint32(_U32))
        .astype(">u4").view(_np.uint8).reshape(n, 4))
    return out.tobytes()


class NativeMemTable:
    """Drop-in MemTable twin backed by the C++ arena."""

    def __init__(self):
        self._lib = _load_mt_lib()
        self._h = self._lib.mt_new()
        self._lock = threading.Lock()
        self.version = 0
        self._first_write_s: Optional[float] = None
        # reusable export buffers + pre-cast pointers for the batched
        # point-probe path (per-call numpy allocation + ctypes casts
        # dominated multi-row reads); guarded-by: _lock
        self._scratch = None

    def __del__(self):
        try:
            if self._h:
                self._lib.mt_free(self._h)
                self._h = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    # ------------------------------------------------------------- write
    def add(self, key_prefix: bytes, dht: DocHybridTime, value: bytes) -> None:
        self.add_batch([(key_prefix, dht, value)])

    def add_batch(self, items) -> None:
        keys = [k for k, _d, _v in items]
        vals = [v for _k, _d, v in items]
        n = len(items)
        ht = _np.fromiter((d.ht.value for _k, d, _v in items),
                          dtype=_np.uint64, count=n)
        wid = _np.fromiter((d.write_id for _k, d, _v in items),
                           dtype=_np.uint32, count=n)
        self._add_packed(keys, ht, wid, vals)

    def add_columns(self, keys: List[bytes], ht: _np.ndarray,
                    wid: _np.ndarray, values: List[bytes]) -> None:
        """Columnar bulk write (the batched-RPC apply / bulk-load shape):
        parallel lists/arrays, one native call."""
        self._add_packed(keys, _np.asarray(ht, dtype=_np.uint64),
                         _np.asarray(wid, dtype=_np.uint32), values)

    def _add_packed(self, keys, ht, wid, vals) -> None:
        n = len(keys)
        if n == 0:
            return
        if not (len(ht) == len(wid) == len(vals) == n):
            # the C side trusts n: a mismatch would read past the suffix
            # buffer and store garbage MVCC timestamps
            raise ValueError(
                f"column length mismatch: keys={n} ht={len(ht)} "
                f"wid={len(wid)} values={len(vals)}")
        keys_blob = b"".join(keys)
        koffs = _np.zeros(n + 1, dtype=_np.int64)
        _np.cumsum([len(k) for k in keys], out=koffs[1:])
        vals_blob = b"".join(vals)
        voffs = _np.zeros(n + 1, dtype=_np.int64)
        _np.cumsum([len(v) for v in vals], out=voffs[1:])
        sfx = _encode_suffixes(ht, wid)
        with self._lock:
            self._lib.mt_add_batch(
                self._h, keys_blob, koffs.ctypes.data_as(_i64p), sfx,
                vals_blob, voffs.ctypes.data_as(_i64p), _ct.c_int64(n))
            self.version += 1
            if self._first_write_s is None:
                self._first_write_s = time.monotonic()

    # -------------------------------------------------------------- read
    def _export(self, start: int, end: int, include_suffix: bool):
        kb = _ct.c_int64()
        vb = _ct.c_int64()
        inc = _ct.c_int32(1 if include_suffix else 0)
        self._lib.mt_range_sizes(self._h, start, end, inc,
                                 _ct.byref(kb), _ct.byref(vb))
        n = end - start
        keys = _np.empty(max(1, kb.value), dtype=_np.uint8)
        koffs = _np.zeros(n + 1, dtype=_np.int64)
        ht = _np.empty(max(1, n), dtype=_np.uint64)
        wid = _np.empty(max(1, n), dtype=_np.uint32)
        vals = _np.empty(max(1, vb.value), dtype=_np.uint8)
        voffs = _np.zeros(n + 1, dtype=_np.int64)
        self._lib.mt_export_range(
            self._h, start, end, inc, keys.ctypes.data_as(_u8p),
            koffs.ctypes.data_as(_i64p), ht.ctypes.data_as(_u64p),
            wid.ctypes.data_as(_u32p), vals.ctypes.data_as(_u8p),
            voffs.ctypes.data_as(_i64p))
        return keys, koffs, ht, wid, vals, voffs

    def _export_one_locked(self, idx: int) -> Tuple[bytes, bytes]:
        """Single-entry export through the reusable scratch buffers;
        caller holds _lock. Returns (internal_key, value) copies."""
        kb = _ct.c_int64()
        vb = _ct.c_int64()
        self._lib.mt_range_sizes(self._h, idx, idx + 1, _ct.c_int32(1),
                                 _ct.byref(kb), _ct.byref(vb))
        sc = self._scratch
        if sc is None or sc[0].size < kb.value or sc[2].size < vb.value:
            keys = _np.empty(max(4096, kb.value * 2), dtype=_np.uint8)
            koffs = _np.zeros(2, dtype=_np.int64)
            vals = _np.empty(max(65536, vb.value * 2), dtype=_np.uint8)
            voffs = _np.zeros(2, dtype=_np.int64)
            ht = _np.empty(1, dtype=_np.uint64)
            wid = _np.empty(1, dtype=_np.uint32)
            sc = self._scratch = (
                keys, koffs, vals, voffs, ht, wid,
                (keys.ctypes.data_as(_u8p), koffs.ctypes.data_as(_i64p),
                 ht.ctypes.data_as(_u64p), wid.ctypes.data_as(_u32p),
                 vals.ctypes.data_as(_u8p), voffs.ctypes.data_as(_i64p)))
        kp, kop, htp, widp, vp, vop = sc[6]
        self._lib.mt_export_range(self._h, idx, idx + 1, _ct.c_int32(1),
                                  kp, kop, htp, widp, vp, vop)
        return (sc[0][: sc[1][1]].tobytes(), sc[2][: sc[3][1]].tobytes())

    def point_get(self, seek: bytes, boundary: bytes
                  ) -> Optional[Tuple[bytes, bytes]]:
        with self._lock:
            idx = int(self._lib.mt_lower_bound(self._h, seek, len(seek)))
            if idx >= int(self._lib.mt_n(self._h)):
                return None
            ikey, val = self._export_one_locked(idx)
        if not ikey.startswith(boundary):
            return None
        return ikey, val

    def point_get_many(self, probes) -> List[Optional[Tuple[bytes, bytes]]]:
        """Batched point_get over [(seek, boundary), ...]: ONE lock
        acquisition and scratch-buffer exports for the whole probe list
        (the batched row read probes the memtable once per enumerated
        key; per-call locking + allocation dominated it)."""
        out: List[Optional[Tuple[bytes, bytes]]] = [None] * len(probes)
        with self._lock:
            total = int(self._lib.mt_n(self._h))
            if total == 0:
                return out
            for j, (seek, boundary) in enumerate(probes):
                idx = int(self._lib.mt_lower_bound(self._h, seek,
                                                   len(seek)))
                if idx >= total:
                    continue
                ikey, val = self._export_one_locked(idx)
                if ikey.startswith(boundary):
                    out[j] = (ikey, val)
        return out

    def entries_range(self, lower: bytes,
                      upper: bytes) -> List[Tuple[bytes, bytes]]:
        """(internal_key, value) with lower <= key < upper in ONE bounded
        export. The batched row probe calls this once per row; iter_from
        would export a full 4096-entry batch to answer a range that holds
        a handful of entries, which dominated the multi-row read wall
        time."""
        with self._lock:
            lo = int(self._lib.mt_lower_bound(self._h, lower, len(lower)))
            hi = int(self._lib.mt_lower_bound(self._h, upper, len(upper)))
            if lo >= hi:
                return []
            keys, koffs, _ht, _wid, vals, voffs = \
                self._export(lo, hi, True)
        return [(keys[koffs[i]: koffs[i + 1]].tobytes(),
                 vals[voffs[i]: voffs[i + 1]].tobytes())
                for i in range(hi - lo)]

    def iter_from(self, seek_key: bytes = b""
                  ) -> Iterator[Tuple[bytes, bytes]]:
        """(internal_key, value) in memcmp order from seek_key; batched
        exports re-seek by last key, so concurrent adds never tear."""
        batch = 4096
        seek = seek_key
        strict = False
        while True:
            with self._lock:
                idx = int(self._lib.mt_lower_bound(self._h, seek, len(seek)))
                total = int(self._lib.mt_n(self._h))
                end = min(idx + batch, total)
                if idx >= end:
                    return
                keys, koffs, _ht, _wid, vals, voffs = \
                    self._export(idx, end, True)
            last = None
            for i in range(end - idx):
                ikey = keys[koffs[i]: koffs[i + 1]].tobytes()
                if strict and ikey == seek:
                    continue
                yield ikey, vals[voffs[i]: voffs[i + 1]].tobytes()
                last = ikey
            if end >= total and last is None:
                return
            if last is not None:
                seek = last
                strict = True
            if end >= total:
                # may have grown concurrently; one more probe past `last`
                with self._lock:
                    if int(self._lib.mt_lower_bound(
                            self._h, seek, len(seek))) + 1 >= \
                            int(self._lib.mt_n(self._h)):
                        return

    # ------------------------------------------------------------- stats
    @property
    def oldest_write_s(self) -> Optional[float]:
        return self._first_write_s

    @property
    def n_entries(self) -> int:
        with self._lock:
            return int(self._lib.mt_n(self._h))

    @property
    def approximate_bytes(self) -> int:
        with self._lock:
            return int(self._lib.mt_bytes(self._h))

    @property
    def empty(self) -> bool:
        with self._lock:
            return int(self._lib.mt_raw_n(self._h)) == 0

    # ------------------------------------------------------------- flush
    def to_packed(self):
        """Sorted packed-run columns for the native flush encoder — one
        C++ export, no Python joins (ref: db/flush_job.cc)."""
        with self._lock:
            n = int(self._lib.mt_n(self._h))
            keys, koffs, ht, wid, vals, voffs = self._export(0, n, False)
        return keys.tobytes(), koffs, ht, wid, vals.tobytes(), voffs

    def to_slab(self) -> KVSlab:
        with self._lock:
            n = int(self._lib.mt_n(self._h))
            keys, koffs, ht, wid, vals, voffs = self._export(0, n, False)
        triples = []
        for i in range(n):
            packed = (int(ht[i]) << 32) | int(wid[i])
            triples.append((keys[koffs[i]: koffs[i + 1]].tobytes(), packed,
                            vals[voffs[i]: voffs[i + 1]].tobytes()))
        return pack_kvs(triples)


def new_memtable():
    """Factory: the native arena when the toolchain is available and the
    flag allows, else the Python MemTable."""
    from yugabyte_tpu.utils import flags as _flags
    try:
        use_native = _flags.get_flag("memtable_native")
    except KeyError:  # yblint: contained(flag not registered in this process — default native)
        use_native = True
    if use_native and native_memtable_available():
        return NativeMemTable()
    return MemTable()
