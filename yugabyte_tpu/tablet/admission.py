"""Write admission: one unified write-pressure state machine per tablet.

Capability parity with the reference's write throttling (ref:
tserver/tablet_service.cc:1510 the SST-file rejection score,
tserver/tserver.cc memstore soft-limit rejection via the MemTracker
tree, and the reference's "leader side backpressure" WAL gating), but
unified: before PR 12 only SST-file count gated writes, while the
memstore MemTracker and the WAL appender queue could grow without
bound under sustained overload.

Three measured signals feed one state machine, evaluated at every
write entry point (tablet.py write / write_transactional /
apply_external_batch):

- **sst**: live SST files between ``--sst_files_soft_limit`` and
  ``--sst_files_hard_limit`` (compactions need bandwidth to catch up);
- **memstore**: the server-wide memstore MemTracker
  (tserver/tablet_memory_manager.py binds it onto every hosted
  tablet) — pressure starts at the soft percentage
  (``--memory_limit_soft_percentage``) and rejects at
  ``--memstore_reject_fraction`` of the limit, BELOW 1.0 on purpose:
  admission sees consumption before the incoming batch lands, so the
  headroom between the reject fraction and the limit is what keeps
  in-flight admitted writes from pushing the tracker past its limit
  while flushes catch up;
- **wal**: the group-commit appender's queued-entry backlog
  (consensus/log.py backlog(); tablet_peer.py binds it) between
  ``--wal_backlog_soft_entries`` and ``--wal_backlog_hard_entries`` —
  appends arriving faster than fsync drains them.

States: HEALTHY admits immediately; SOFT delays each write
proportionally to the worst signal's score (up to
``--write_backpressure_max_delay_ms``); HARD rejects retryably with a
typed Overloaded error whose extras carry the throttling signal and a
score-scaled ``retry_after_ms`` hint the client backoff honors.
Snapshots surface as the per-tablet write_pressure arm of the /servez
overload block.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, List, Optional

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.trace import TRACE

flags.define_flag("memstore_reject_fraction", 0.95,
                  "hard write rejection when the memstore MemTracker "
                  "reaches this fraction of its limit; kept under 1.0 "
                  "so in-flight admitted writes cannot push consumption "
                  "past the tracker limit")
flags.define_flag("wal_backlog_soft_entries", 512,
                  "writes start delaying when this many WAL entries are "
                  "queued behind the appender's fsync")
flags.define_flag("wal_backlog_hard_entries", 4096,
                  "writes are rejected (retryably) at this many queued "
                  "WAL entries")


class PressureState(enum.Enum):
    HEALTHY = "healthy"
    SOFT = "soft"
    HARD = "hard"


class _Signal:
    __slots__ = ("name", "hard", "score", "detail")

    def __init__(self, name: str, hard: bool, score: float, detail: str):
        self.name = name
        self.hard = hard
        self.score = score
        self.detail = detail


class WriteAdmission:
    """Per-tablet write-pressure evaluator. Construction binds the SST
    signal; the WAL and memstore signals are bound by the layers that
    own them (TabletPeer / TabletMemoryManager) — an unbound signal
    simply reads healthy, so a bare Tablet in a unit test behaves
    exactly like the old SST-only backpressure."""

    def __init__(self, tablet_id: str,
                 sst_files_fn: Callable[[], int],
                 rejection_counter=None):
        self.tablet_id = tablet_id
        self._sst_files_fn = sst_files_fn
        self._memstore_tracker = None
        self._wal_backlog_fn: Optional[Callable[[], int]] = None
        # the tablet's write_rejections_total counter (kept for metric
        # continuity with the pre-unification SST backpressure)
        self._rejection_counter = rejection_counter
        self._lock = threading.Lock()
        self._state = PressureState.HEALTHY  # guarded-by: _lock
        self._state_signal = ""              # guarded-by: _lock
        self.delays_total = 0                # guarded-by: _lock
        self.rejections_total = 0            # guarded-by: _lock
        self._rejections_by: dict = {}       # guarded-by: _lock

    # ------------------------------------------------------------- binding
    def bind_memstore(self, tracker) -> None:
        """TabletMemoryManager hands the server-wide memstore MemTracker
        to every hosted tablet (idempotent, re-applied each arbiter
        round so late-created tablets get bound too)."""
        self._memstore_tracker = tracker

    def bind_wal(self, backlog_fn: Callable[[], int]) -> None:
        self._wal_backlog_fn = backlog_fn

    # ------------------------------------------------------------- signals
    def signals(self) -> List[_Signal]:
        out = [self._sst_signal()]
        mem = self._memstore_signal()
        if mem is not None:
            out.append(mem)
        wal = self._wal_signal()
        if wal is not None:
            out.append(wal)
        return out

    def _sst_signal(self) -> _Signal:
        soft = flags.get_flag("sst_files_soft_limit")
        hard = flags.get_flag("sst_files_hard_limit")
        files = self._sst_files_fn()
        if files < soft:
            return _Signal("sst", False, 0.0, f"{files} live SST files")
        score = (files - soft + 1) / max(1, hard - soft)
        return _Signal("sst", files >= hard, score,
                       f"{files} live SST files (soft {soft} hard {hard})")

    def _memstore_signal(self) -> Optional[_Signal]:
        tracker = self._memstore_tracker
        if tracker is None or tracker.limit <= 0:
            return None
        pct = tracker.consumption() / tracker.limit
        soft_pct = flags.get_flag("memory_limit_soft_percentage") / 100.0
        reject_pct = flags.get_flag("memstore_reject_fraction")
        if pct < soft_pct:
            return _Signal("memstore", False, 0.0,
                           f"memstore at {pct:.0%} of tracker limit")
        score = (pct - soft_pct) / max(1e-9, reject_pct - soft_pct)
        return _Signal(
            "memstore", pct >= reject_pct, score,
            f"memstore at {pct:.0%} of tracker limit "
            f"(soft {soft_pct:.0%} reject {reject_pct:.0%})")

    def _wal_signal(self) -> Optional[_Signal]:
        fn = self._wal_backlog_fn
        if fn is None:
            return None
        soft = flags.get_flag("wal_backlog_soft_entries")
        hard = flags.get_flag("wal_backlog_hard_entries")
        backlog = fn()
        if backlog < soft:
            return _Signal("wal", False, 0.0,
                           f"{backlog} WAL entries awaiting fsync")
        score = (backlog - soft + 1) / max(1, hard - soft)
        return _Signal("wal", backlog >= hard, score,
                       f"{backlog} WAL entries awaiting fsync "
                       f"(soft {soft} hard {hard})")

    # ----------------------------------------------------------- admission
    def _worst(self) -> _Signal:
        worst = None
        for s in self.signals():
            if worst is None or (s.hard, s.score) > (worst.hard,
                                                     worst.score):
                worst = s
        return worst

    def _set_state(self, state: PressureState, signal_name: str) -> None:
        with self._lock:
            prev = self._state
            self._state = state
            self._state_signal = (signal_name
                                  if state is not PressureState.HEALTHY
                                  else "")
        if prev is not state:
            TRACE("tablet %s write pressure %s -> %s (%s)",
                  self.tablet_id, prev.value, state.value,
                  signal_name or "-")

    def admit(self) -> None:
        """Gate one write: no-op when healthy, proportional delay under
        soft pressure, typed retryable rejection under hard pressure.
        Raises Overloaded (Code.BUSY, retryable, throttle extras) —
        message keeps the historical 'retry later' phrasing."""
        worst = self._worst()
        if worst.hard:
            self._note_rejection(worst)
            from yugabyte_tpu.rpc.messenger import Overloaded
            raise Overloaded(
                f"tablet {self.tablet_id} write-pressure hard limit "
                f"({worst.name}: {worst.detail}); retry later",
                retry_after_ms=self._retry_after_ms(worst),
                throttle=worst.name)
        if worst.score <= 0.0:
            self._set_state(PressureState.HEALTHY, "")
            return
        self._set_state(PressureState.SOFT, worst.name)
        with self._lock:
            self.delays_total += 1
        delay = min(1.0, worst.score) * flags.get_flag(
            "write_backpressure_max_delay_ms") / 1000.0
        if delay > 0:
            time.sleep(delay)

    def _note_rejection(self, worst: _Signal) -> None:
        self._set_state(PressureState.HARD, worst.name)
        with self._lock:
            self.rejections_total += 1
            self._rejections_by[worst.name] = \
                self._rejections_by.get(worst.name, 0) + 1
        if self._rejection_counter is not None:
            self._rejection_counter.increment()
        from yugabyte_tpu.utils.metrics import serve_path_metrics
        m = serve_path_metrics()
        m.counter("write_throttle_rejections_total",
                  "writes rejected retryably by the write-pressure "
                  "state machine").increment()
        m.counter(f"write_throttle_{worst.name}_rejections_total",
                  f"writes rejected by {worst.name} pressure"
                  ).increment()

    @staticmethod
    def _retry_after_ms(worst: _Signal) -> int:
        """Score-scaled hint: deeper overshoot past the hard line means
        flushes/compactions need longer to relieve it. Derived from the
        measured score, clamped to [50ms, 2s]."""
        base = flags.get_flag("write_backpressure_max_delay_ms")
        return int(min(2000.0, max(50.0, base * (1.0 + worst.score))))

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        sigs = self.signals()
        with self._lock:
            state, state_sig = self._state, self._state_signal
            delays, rejections = self.delays_total, self.rejections_total
            by = dict(self._rejections_by)
        return {
            "tablet_id": self.tablet_id,
            "state": state.value,
            "signal": state_sig,
            "signals": {s.name: {"hard": s.hard,
                                 "score": round(s.score, 3),
                                 "detail": s.detail} for s in sigs},
            "write_throttle_delays_total": delays,
            "write_throttle_rejections_total": rejections,
            "rejections_by_signal": by,
        }
