"""Device-resident LSM chain: compaction outputs feed the next level
from HBM with zero re-decode.

The chained L0->L1->L2 path must (a) produce SSTs byte-identical to the
sequential native path with the decode counters FLAT across the warm
chain (run-cache ingest + resident slabs mean no SST byte is re-read),
(b) install each output's cache entry under the output file id AS its
span completes, at one residency level below the deepest input, (c)
never let capacity eviction touch a pinned in-flight input, (d) drop
slabs when their files become obsolete (and on DB close), and (e) fall
back natively under an injected device fault with the cache left
coherent and zero leaked pins.
"""

import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_run_merge import _make_run  # noqa: E402

from yugabyte_tpu.ops import device_faults  # noqa: E402
from yugabyte_tpu.ops.slabs import ValueArray  # noqa: E402
from yugabyte_tpu.storage import compaction as compaction_mod  # noqa: E402
from yugabyte_tpu.storage import integrity  # noqa: F401,E402 (registers
#   shadow_verify_sample — without it the file only passes when another
#   test module imported integrity first)
from yugabyte_tpu.storage import native_engine  # noqa: E402
from yugabyte_tpu.storage import offload_policy  # noqa: E402
from yugabyte_tpu.storage.device_cache import DeviceSlabCache  # noqa: E402
from yugabyte_tpu.storage.run_cache import (NamespacedRunCache,  # noqa: E402
                                            NativeRunCache)
from yugabyte_tpu.storage.sst import (Frontier, SSTReader,  # noqa: E402
                                      SSTWriter, _block_decode_counter)
from yugabyte_tpu.utils import flags  # noqa: E402

pytestmark = pytest.mark.skipif(not native_engine.available(),
                                reason="native engine unavailable")

CUTOFF = (10_000_000 << 12)


@pytest.fixture(autouse=True)
def _clean_state():
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()
    yield
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()


def _device():
    import jax
    return jax.devices()[0]


def _mk_run(rng, n, key_space, value_bytes=16):
    slab = _make_run(rng, n, key_space)
    data = rng.integers(0, 256, size=n * value_bytes, dtype=np.uint8)
    offs = np.arange(n + 1, dtype=np.int64) * value_bytes
    slab.values = ValueArray(data, offs)
    return slab


def _write_runs(workdir, runs):
    readers = []
    for i, slab in enumerate(runs):
        p = os.path.join(workdir, f"in{i:03d}.sst")
        SSTWriter(p).write(slab, Frontier())
        readers.append(SSTReader(p))
    return readers


def _run_chain_job(readers, out_dir, cache, input_ids, run_cache=None,
                   first_id=100, is_major=True):
    os.makedirs(out_dir, exist_ok=True)
    ids = iter(range(first_id, first_id + 500))
    return compaction_mod.run_compaction_job_device_native(
        readers, out_dir, lambda: next(ids), CUTOFF, is_major,
        device=_device(), device_cache=cache, input_ids=input_ids,
        run_cache=run_cache)


def _ingest_counter():
    return compaction_mod._ingest_decode_counter()


def _sst_bytes(outputs):
    out = []
    for _fid, base_path, _props in outputs:
        with open(base_path + ".sblock.0", "rb") as f:
            out.append(f.read())
    return out


# ---------------------------------------------------------------------------
# the chain itself


def test_chained_l0_l1_l2_byte_identical_zero_decode(tmp_path):
    """L0->L1->L2 through the resident chain == the sequential native
    path, and the WARM chained jobs re-decode nothing: both the block
    decode counter and the native-shell ingest counter stay flat."""
    rng = np.random.default_rng(21)
    runs_a = [_mk_run(rng, 700, 450) for _ in range(2)]
    runs_b = [_mk_run(rng, 700, 450) for _ in range(2)]
    cache = DeviceSlabCache(device=_device())
    rc = NamespacedRunCache(NativeRunCache(capacity_bytes=1 << 30), "t")

    os.makedirs(str(tmp_path / "a"))
    os.makedirs(str(tmp_path / "b"))
    readers_a = _write_runs(str(tmp_path / "a"), runs_a)
    readers_b = _write_runs(str(tmp_path / "b"), runs_b)
    # steady state: flush write-through staged the inputs (level 0) and
    # retained the packed runs, exactly as DB.flush does
    for fid, r in zip((0, 1), readers_a):
        cache.stage(fid, r.read_all(), level=0)
    for fid, r in zip((2, 3), readers_b):
        cache.stage(fid, r.read_all(), level=0)
    from yugabyte_tpu.storage.run_cache import export_reader
    for fid, r in zip((0, 1), readers_a):
        export_reader(rc, fid, r)
    for fid, r in zip((2, 3), readers_b):
        export_reader(rc, fid, r)

    # the decode/ingest counters are PROCESS-global: daemon threads a
    # prior suite leaked (remote-bootstrap readers, CDC pollers winding
    # down) can still be decoding blocks when this test starts. Open the
    # flat-counter window only after one quiet 250ms interval.
    deadline = time.monotonic() + 10.0
    blocks0 = _block_decode_counter().value()
    ingest0 = _ingest_counter().value()
    while time.monotonic() < deadline:
        time.sleep(0.25)
        cur = (_block_decode_counter().value(), _ingest_counter().value())
        if cur == (blocks0, ingest0):
            break
        blocks0, ingest0 = cur

    # deflake: the SAMPLED shadow verifier's oracle legitimately decodes
    # the inputs when a job is drawn (default 2%/job) — pin sampling off
    # so the flat-counter assertion only sees real leaks
    old_shadow = flags.get_flag("shadow_verify_sample")
    flags.set_flag("shadow_verify_sample", 0.0)
    try:
        # L0 -> L1 (two jobs), chained straight into L1 -> L2
        res_a = _run_chain_job(readers_a, str(tmp_path / "oa"), cache,
                               [0, 1], run_cache=rc, first_id=100)
        res_b = _run_chain_job(readers_b, str(tmp_path / "ob"), cache,
                               [2, 3], run_cache=rc, first_id=200)
        l1_outputs = res_a.outputs + res_b.outputs
        l1_readers = [SSTReader(p) for _, p, _ in l1_outputs]
        l1_ids = [fid for fid, _, _ in l1_outputs]
        res_l2 = _run_chain_job(l1_readers, str(tmp_path / "l2"), cache,
                                l1_ids, run_cache=rc, first_id=300)
    finally:
        flags.set_flag("shadow_verify_sample", old_shadow)

    # zero re-decode across the whole warm chain: every input came from
    # the HBM slab cache (decisions) + the packed-run cache (bytes)
    assert _block_decode_counter().value() == blocks0, \
        "warm chained compaction decoded SST blocks"
    assert _ingest_counter().value() == ingest0, \
        "warm chained compaction re-ingested SST files"

    # residency levels: L1 outputs sit one above their L0 inputs, the
    # L2 output one above those
    for fid in l1_ids:
        assert cache.level_of(fid) == 1
    for fid, _p, _props in res_l2.outputs:
        assert cache.level_of(fid) == 2

    # byte-identity vs the sequential native path over the same L1 files
    os.makedirs(str(tmp_path / "ref"))
    ids = iter(range(400, 500))
    ref = compaction_mod.run_compaction_job(
        l1_readers, str(tmp_path / "ref"), lambda: next(ids), CUTOFF,
        True, device="native")
    assert res_l2.rows_out == ref.rows_out
    assert _sst_bytes(res_l2.outputs) == _sst_bytes(ref.outputs)
    for r in l1_readers + readers_a + readers_b:
        r.close()


def test_per_span_install_as_spans_complete(tmp_path, monkeypatch):
    """Each output file's cache entry is installed the moment its span's
    SST exists — observed from inside the writer callback, before the
    job finishes."""
    rng = np.random.default_rng(22)
    runs = [_mk_run(rng, 900, 4000) for _ in range(2)]  # few dups: big out
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    for fid, r in zip((0, 1), readers):
        cache.stage(fid, r.read_all())

    seen = []
    orig = compaction_mod._ResidentSpanInstaller.on_span

    def spy(self, fid, base_path, start, end):
        orig(self, fid, base_path, start, end)
        seen.append((fid, cache.contains(fid)))

    monkeypatch.setattr(compaction_mod._ResidentSpanInstaller, "on_span",
                        spy)
    old = flags.get_flag("compaction_max_output_entries_per_sst")
    flags.set_flag("compaction_max_output_entries_per_sst", 500)
    try:
        res = _run_chain_job(readers, str(tmp_path / "out"), cache, [0, 1])
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old)
    assert len(res.outputs) >= 2, "expected a multi-file split"
    assert len(seen) == len(res.outputs)
    assert all(installed for _fid, installed in seen), \
        "a span completed without its cache entry installed"
    for r in readers:
        r.close()


def test_digest_mismatch_drops_entry(tmp_path, monkeypatch):
    """A write-through entry that fails the sampled digest check is
    dropped, never installed — the job itself still succeeds (the file
    bytes are host truth)."""
    from yugabyte_tpu.storage import integrity

    rng = np.random.default_rng(23)
    runs = [_mk_run(rng, 600, 400) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    for fid, r in zip((0, 1), readers):
        cache.stage(fid, r.read_all())

    flags.set_flag("resident_digest_sample", 1.0)
    mm0 = integrity.resident_digest_mismatch_counter().value()
    real_verify = integrity.verify_resident_entry

    def broken_verify(staged, base_path):
        errs = real_verify(staged, base_path)
        return errs + ["synthetic divergence"]

    monkeypatch.setattr(integrity, "verify_resident_entry", broken_verify)
    try:
        res = _run_chain_job(readers, str(tmp_path / "out"), cache, [0, 1])
    finally:
        flags.set_flag("resident_digest_sample", 0.02)
    assert res.outputs
    for fid, _p, _props in res.outputs:
        assert not cache.contains(fid), \
            "digest-mismatched entry was installed anyway"
    assert integrity.resident_digest_mismatch_counter().value() > mm0
    for r in readers:
        r.close()


def test_digest_check_passes_clean_entries(tmp_path):
    """With sampling forced on, clean write-through entries verify and
    install (the check against real decoded bytes holds)."""
    from yugabyte_tpu.storage import integrity

    rng = np.random.default_rng(24)
    runs = [_mk_run(rng, 600, 400) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    for fid, r in zip((0, 1), readers):
        cache.stage(fid, r.read_all())
    flags.set_flag("resident_digest_sample", 1.0)
    checked0 = integrity.resident_digest_snapshot()["checked"]
    mm0 = integrity.resident_digest_snapshot()["mismatches"]
    try:
        res = _run_chain_job(readers, str(tmp_path / "out"), cache, [0, 1])
    finally:
        flags.set_flag("resident_digest_sample", 0.02)
    assert res.outputs
    for fid, _p, _props in res.outputs:
        assert cache.contains(fid)
    snap = integrity.resident_digest_snapshot()
    assert snap["checked"] > checked0
    assert snap["mismatches"] == mm0


def test_cold_chain_flat_decode_counters_with_device_codec(tmp_path):
    """A COLD L0->L1->L2 chain (empty device cache, empty run cache)
    with the device codec enabled: neither sst_block_decode_total nor
    compaction_ingest_decode_total moves at any point — the initial
    ingest is a raw-byte upload + device decode (block_decode_fused),
    not a host decode — and the L2 output is byte-identical to the
    sequential native path (the ISSUE-14 acceptance criterion; the warm
    test above proves the run-cache/shell flavor)."""
    assert os.environ.get("YBTPU_DEVICE_CODEC", "1") not in ("0", "false")
    rng = np.random.default_rng(26)
    runs_a = [_mk_run(rng, 700, 450) for _ in range(2)]
    runs_b = [_mk_run(rng, 700, 450) for _ in range(2)]
    cache = DeviceSlabCache(device=_device())   # EMPTY: nothing pre-staged
    os.makedirs(str(tmp_path / "a"))
    os.makedirs(str(tmp_path / "b"))
    readers_a = _write_runs(str(tmp_path / "a"), runs_a)
    readers_b = _write_runs(str(tmp_path / "b"), runs_b)

    # determinism: the SAMPLED shadow/digest checks legitimately decode
    # host blocks when they fire — pin them off so any counter movement
    # is a real codec leak
    old_shadow = flags.get_flag("shadow_verify_sample")
    old_digest = flags.get_flag("resident_digest_sample")
    flags.set_flag("shadow_verify_sample", 0.0)
    flags.set_flag("resident_digest_sample", 0.0)
    blocks0 = _block_decode_counter().value()
    ingest0 = _ingest_counter().value()
    from yugabyte_tpu.ops.block_codec import codec_metrics
    dev_decode0 = codec_metrics()["decode_blocks"].value()
    dev_encode0 = codec_metrics()["encode_blocks"].value()
    try:
        res_a = _run_chain_job(readers_a, str(tmp_path / "oa"), cache,
                               [0, 1], first_id=100)
        res_b = _run_chain_job(readers_b, str(tmp_path / "ob"), cache,
                               [2, 3], first_id=200)
        l1_outputs = res_a.outputs + res_b.outputs
        l1_readers = [SSTReader(p) for _, p, _ in l1_outputs]
        l1_ids = [fid for fid, _, _ in l1_outputs]
        res_l2 = _run_chain_job(l1_readers, str(tmp_path / "l2"), cache,
                                l1_ids, first_id=300)
    finally:
        flags.set_flag("shadow_verify_sample", old_shadow)
        flags.set_flag("resident_digest_sample", old_digest)

    # flat across the WHOLE cold chain, including the initial raw-byte
    # upload: the device codec never routes bytes through decode_block
    # or the native shell ingest
    assert _block_decode_counter().value() == blocks0, \
        "cold chained compaction decoded SST blocks on the host"
    assert _ingest_counter().value() == ingest0, \
        "cold chained compaction ingested through the native shell"
    # the L0 ingest ran on the decode family; outputs on the encode one
    assert codec_metrics()["decode_blocks"].value() > dev_decode0
    assert codec_metrics()["encode_blocks"].value() > dev_encode0
    # the L1->L2 job found its inputs resident (write-through): only the
    # four L0 files ever paid a decode dispatch
    assert codec_metrics()["decode_blocks"].value() - dev_decode0 == 4

    for fid in l1_ids:
        assert cache.level_of(fid) == 1
    for fid, _p, _props in res_l2.outputs:
        assert cache.level_of(fid) == 2

    os.makedirs(str(tmp_path / "ref"))
    ids = iter(range(400, 500))
    ref = compaction_mod.run_compaction_job(
        l1_readers, str(tmp_path / "ref"), lambda: next(ids), CUTOFF,
        True, device="native")
    assert res_l2.rows_out == ref.rows_out
    assert _sst_bytes(res_l2.outputs) == _sst_bytes(ref.outputs)
    for r in l1_readers + readers_a + readers_b:
        r.close()


# ---------------------------------------------------------------------------
# residency policy: pins + levels + gauge


def test_eviction_never_evicts_pinned():
    from tests.test_storage import make_slab
    cache = DeviceSlabCache(capacity_bytes=1)  # evict aggressively
    cache.stage(1, make_slab(100))
    assert cache.pin(1)
    cache.stage(2, make_slab(100))
    cache.stage(3, make_slab(100))
    # pinned entry survives every eviction pass; unpinned ones go
    assert cache.contains(1)
    cache.unpin(1)
    assert cache.pinned_count() == 0
    cache.stage(4, make_slab(100))
    assert not cache.contains(1)  # unpinned: evictable again


def test_eviction_prefers_shallow_levels():
    from tests.test_storage import make_slab
    big = make_slab(200)
    cache = DeviceSlabCache(capacity_bytes=1 << 62)
    cache.stage(10, big, level=2)          # oldest, deep
    cache.stage(11, make_slab(200), level=0)
    cache.stage(12, make_slab(200), level=1)
    cache.capacity = cache.snapshot()["used_bytes"] - 1
    cache.stage(13, make_slab(50), level=0)
    # L0 entries evict before the (older) L2 base run
    assert cache.contains(10), "deep entry evicted before shallow ones"
    assert not cache.contains(11)


def test_pin_miss_returns_false():
    cache = DeviceSlabCache()
    assert not cache.pin(999)
    cache.unpin(999)  # no-op, never raises
    assert cache.pinned_count() == 0


def test_used_gauge_tracks_every_mutation():
    """drop/drop_namespace/eviction must update the used-bytes gauge,
    not just put (the stale-gauge satellite fix)."""
    from tests.test_storage import make_slab
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    g = ROOT_REGISTRY.entity("server", "device_cache").gauge(
        "device_cache_used_bytes", "")
    cache = DeviceSlabCache()
    cache.stage(("ns", 1), make_slab(100))
    cache.stage(("ns", 2), make_slab(100))
    cache.stage(("other", 3), make_slab(100))
    assert g.value() == cache.snapshot()["used_bytes"] > 0
    cache.drop(("ns", 1))
    assert g.value() == cache.snapshot()["used_bytes"]
    cache.drop_namespace("ns")
    assert g.value() == cache.snapshot()["used_bytes"]
    cache.drop_namespace("other")
    assert g.value() == 0
    # eviction path: shrink capacity and re-stage
    cache.capacity = 1
    cache.stage(("ns", 4), make_slab(100))
    cache.stage(("ns", 5), make_slab(100))
    assert g.value() == cache.snapshot()["used_bytes"]
    assert cache.evictions > 0


def test_snapshot_levels_block():
    from tests.test_storage import make_slab
    cache = DeviceSlabCache()
    cache.stage(1, make_slab(50), level=0)
    cache.stage(2, make_slab(50), level=1)
    cache.pin(2)
    snap = cache.snapshot()
    assert snap["entries"] == 2 and snap["pinned"] == 1
    assert snap["levels"]["L0"]["entries"] == 1
    assert snap["levels"]["L1"]["pinned"] == 1
    cache.unpin(2)


# ---------------------------------------------------------------------------
# lifecycle: obsolete files + close drop slabs


def test_obsolete_and_close_drop_slabs(tmp_path):
    from yugabyte_tpu.common.hybrid_time import HybridTime
    from yugabyte_tpu.docdb.value import Value
    from yugabyte_tpu.storage.db import DB, DBOptions
    from tests.test_storage import key_for, ht

    cache = DeviceSlabCache()
    ns = os.path.abspath(str(tmp_path / "db"))
    db = DB(str(tmp_path / "db"),
            DBOptions(block_entries=128, auto_compact=False,
                      device_cache=cache,
                      retention_policy=lambda: HybridTime.kMax.value))
    for gen in range(4):
        for r in range(60):
            db.write_batch([(key_for(r), ht(1000 * (gen + 1)),
                             Value(primitive=f"g{gen}").encode())])
        db.flush()
    in_fids = [fm.file_id for fm in db.versions.live_files()]
    assert all(cache.contains((ns, fid)) for fid in in_fids)
    db.compact_all()
    # obsolete-file deletion dropped every input slab
    for fid in in_fids:
        assert not cache.contains((ns, fid))
    live_id = db.versions.live_files()[0].file_id
    assert cache.contains((ns, live_id))
    db.close()
    # DB close frees the whole namespace's residency
    assert not cache.contains((ns, live_id))
    assert cache.snapshot()["used_bytes"] == 0


# ---------------------------------------------------------------------------
# device-fault fallback: coherent cache, zero leaked pins


@pytest.mark.parametrize("site", ["dispatch", "result"])
def test_fault_fallback_cache_coherent_zero_pins(tmp_path, site):
    """A chained job under an injected persistent device fault completes
    natively (byte-identical), drops any partially installed output
    entries, keeps the INPUT slabs resident, and leaks zero pins."""
    rng = np.random.default_rng(25)
    runs = [_mk_run(rng, 600, 400) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    cache = DeviceSlabCache(device=_device())
    for fid, r in zip((0, 1), readers):
        cache.stage(fid, r.read_all())

    device_faults.arm("runtime", site=site, count=100)  # persistent
    try:
        res = _run_chain_job(readers, str(tmp_path / "out"), cache, [0, 1])
    finally:
        device_faults.disarm_all()
    assert res.outputs, "fallback produced no outputs"
    # native fallback wrote the files; no output entry may be resident
    # (the device attempt's partials were deleted + dropped)
    for fid, _p, _props in res.outputs:
        assert not cache.contains(fid), \
            "cache entry survived for a deleted partial output"
    assert cache.pinned_count() == 0, "leaked pins after fault fallback"
    assert cache.contains(0) and cache.contains(1), \
        "input slabs were dropped by the fallback"
    # byte-identity with the pure-native job
    os.makedirs(str(tmp_path / "ref"))
    ids = iter(range(700, 800))
    ref = compaction_mod.run_compaction_job(
        readers, str(tmp_path / "ref"), lambda: next(ids), CUTOFF, True,
        device="native")
    assert _sst_bytes(res.outputs) == _sst_bytes(ref.outputs)
    for r in readers:
        r.close()


# ---------------------------------------------------------------------------
# scans over resident slabs


def test_scan_over_resident_slabs_matches_and_skips_decode(tmp_path):
    """A DB scan whose SSTs are cache-resident filters the resident
    matrix: results identical to the decode path, and only the blocks
    holding survivors are decoded (a narrow range touches ~1 block, not
    the whole file)."""
    from yugabyte_tpu.common.hybrid_time import HybridTime
    from yugabyte_tpu.docdb.value import Value
    from yugabyte_tpu.storage.db import DB, DBOptions
    from tests.test_storage import key_for, ht

    cache = DeviceSlabCache()
    opts = DBOptions(block_entries=64, auto_compact=False,
                     device_cache=cache,
                     retention_policy=lambda: HybridTime.kMax.value)
    db = DB(str(tmp_path / "db"), opts)
    n = 512
    for r in range(n):
        db.write_batch([(key_for(r), ht(1000 + r),
                         Value(primitive=r).encode())])
    db.flush()

    read_ht = HybridTime.kMax.value - 1
    full = list(db.scan_visible(read_ht))
    assert len(full) == n

    # narrow range over the resident file: only the survivor blocks
    # (block_entries=64 -> one or two of 8 blocks) decode
    blocks0 = _block_decode_counter().value()
    lo, hi = key_for(100), key_for(120)
    narrow = list(db.scan_visible(read_ht, lower_key=lo, upper_key=hi))
    decoded = _block_decode_counter().value() - blocks0
    assert [k for k, _v, _ht in narrow] == \
        sorted(k for k, _v, _ht in full if lo <= k < hi)
    assert 0 < decoded <= 2, \
        f"narrow resident scan decoded {decoded} blocks (expected <= 2)"

    # uncached reference: same results
    cache.drop_namespace(os.path.abspath(str(tmp_path / "db")))
    narrow2 = list(db.scan_visible(read_ht, lower_key=lo, upper_key=hi))
    assert narrow == narrow2
    db.close()
