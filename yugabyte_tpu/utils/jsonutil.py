"""JSON with a tagged escape for raw bytes.

Catalog and tablet metadata carry raw partition-bound / key bytes; the
reference persists protobuf superblocks (no such problem), here JSON sidecars
need `{"__bytes__": hex}` tagging.
"""

from __future__ import annotations

import json


def jsonable(obj):
    if isinstance(obj, bytes):
        return {"__bytes__": obj.hex()}
    if isinstance(obj, dict):
        return {k: jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonable(v) for v in obj]
    return obj


def unjsonable(obj):
    if isinstance(obj, dict):
        if set(obj) == {"__bytes__"}:
            return bytes.fromhex(obj["__bytes__"])
        return {k: unjsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [unjsonable(v) for v in obj]
    return obj


def dumps(obj, **kw) -> str:
    return json.dumps(jsonable(obj), **kw)


def loads(s: str):
    return unjsonable(json.loads(s))


def write_atomic(path: str, obj) -> None:
    """Write-fsync-rename of a JSON document (superblocks, consensus
    metadata sidecars)."""
    import os
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(dumps(obj))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_file(path: str):
    with open(path) as f:
        return loads(f.read())
