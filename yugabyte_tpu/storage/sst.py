"""SST files: split base/data layout, slab blocks, bloom, frontiers.

Capability parity with the reference's BlockBasedTable (ref:
src/yb/rocksdb/table/block_based_table_reader.cc:387 Open,
block_based_table_builder.cc) including YB's split-SST layout — a small base
file with metadata/index/filter plus a separate data file
(ref: table/block_based_table_factory.h:65 IsSplitSstForWriteSupported,
db/filename.h:92 TableBaseToDataFileName) — and per-file UserFrontiers
(ref: rocksdb/metadata.h UserFrontier, docdb/consensus_frontier.h:35).

Base file layout:
    [index block][bloom bytes][props json]
    footer: <Q index_off><I index_len><Q bloom_off><I bloom_len>
            <Q props_off><I props_len><Q data_size><I crc><Q magic>

The index is itself a slab block whose keys are each data block's LAST key
and whose values pack (data_offset, size, n_entries). Data file is a plain
concatenation of slab blocks (block_format.py).
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from yugabyte_tpu.common.hybrid_time import DocHybridTime
from yugabyte_tpu.ops.slabs import KVSlab, concat_slabs
from yugabyte_tpu.storage import block_format
from yugabyte_tpu.storage.bloom import BloomFilter, BloomFilterBuilder, fnv64_masked
from yugabyte_tpu.utils import flags as _sst_flags
from yugabyte_tpu.utils.status import Status, StatusError

_sst_flags.define_flag("sst_block_entries", 4096,
                       "rows per SST block (fixed row count, not byte "
                       "size: device transfers like uniform shapes; ref "
                       "block_size docdb_rocksdb_util.cc)")
_sst_flags.define_flag("sst_compression", "none",
                       "SST block compression: 'none' or 'zlib' (ref "
                       "compression_type)",
                       validator=lambda v: v in ("none", "zlib"))
_sst_flags.define_flag("sst_bloom_bits_per_key", 10,
                       "doc-key bloom filter density (ref "
                       "BlockBasedTableOptions::filter_policy)")
_sst_flags.define_flag("sst_learned_index", True,
                       "fit a learned per-SST index at write time "
                       "(storage/learned_index.py) and persist it in the "
                       "properties block; ADVISORY ONLY — readers verify "
                       "predictions and fall back to the exact seek")


def sst_compression_enabled() -> bool:
    """Single authority for the compression-flag read (three writer
    paths share it); the codec name validates at set time."""
    return _sst_flags.get_flag("sst_compression") == "zlib"

SST_MAGIC = 0x59425453535431  # "YBTSST1"
_FOOTER = struct.Struct("<QIQIQIQIQ")


def _block_decode_counter():
    """Decode-flatness meter for the device-resident chain: resident-slab
    scans and run-cache-fed compactions must leave this flat — any
    increment on the warm path means host bytes were re-decoded that the
    HBM/run caches were supposed to make unnecessary."""
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    return ROOT_REGISTRY.entity("server", "storage").counter(
        "sst_block_decode_total",
        "SST blocks decoded from file bytes (block-cache hits and "
        "resident-slab scans skip this)")


def data_file_name(base_path: str) -> str:
    """ref: TableBaseToDataFileName (db/filename.h:92)."""
    return base_path + ".sblock.0"


@dataclass
class Frontier:
    """Per-SST consensus frontier (ref: docdb/consensus_frontier.h:35)."""
    op_id_min: Tuple[int, int] = (0, 0)  # (term, index)
    op_id_max: Tuple[int, int] = (0, 0)
    ht_min: int = 0
    ht_max: int = 0
    history_cutoff: int = 0

    def to_json(self) -> dict:
        return {"op_id_min": list(self.op_id_min), "op_id_max": list(self.op_id_max),
                "ht_min": self.ht_min, "ht_max": self.ht_max,
                "history_cutoff": self.history_cutoff}

    @staticmethod
    def from_json(d: dict) -> "Frontier":
        return Frontier(tuple(d["op_id_min"]), tuple(d["op_id_max"]),
                        d["ht_min"], d["ht_max"], d["history_cutoff"])


@dataclass
class SSTProps:
    n_entries: int = 0
    first_key: bytes = b""
    last_key: bytes = b""
    frontier: Frontier = field(default_factory=Frontier)
    data_size: int = 0
    base_size: int = 0
    # Whole-file TTL drop metadata (ref: docdb/compaction_file_filter.h:60):
    # microseconds-physical time at which the LAST entry expires, or 0 when
    # any entry lacks a TTL (file never fully expires).
    max_expire_us: int = 0
    # any entry addresses a document deeper than row+column (FLAG_DEEP):
    # lets the compaction dispatcher decide device routing WITHOUT
    # decoding the file (the fused kernel handles depth-2 only)
    has_deep: bool = False
    # learned per-SST index (storage/learned_index.py) — OPTIONAL and
    # advisory: absent in pre-model files (reads fall back to the exact
    # binary seek), ignored as an unknown JSON key by pre-model readers
    lindex: Optional[dict] = None

    def to_json(self) -> dict:
        d = {"n_entries": self.n_entries, "first_key": self.first_key.hex(),
             "last_key": self.last_key.hex(), "frontier": self.frontier.to_json(),
             "data_size": self.data_size, "base_size": self.base_size,
             "max_expire_us": self.max_expire_us,
             "has_deep": self.has_deep}
        if self.lindex is not None:
            d["lindex"] = self.lindex
        return d

    @staticmethod
    def from_json(d: dict) -> "SSTProps":
        return SSTProps(d["n_entries"], bytes.fromhex(d["first_key"]),
                        bytes.fromhex(d["last_key"]), Frontier.from_json(d["frontier"]),
                        d["data_size"], d["base_size"],
                        d.get("max_expire_us", 0),
                        # files from before this field conservatively count
                        # as deep (native routing is always correct)
                        bool(d.get("has_deep", True)),
                        d.get("lindex"))


class SSTWriter:
    """Writes one SST from an already-sorted slab.

    Blocks are cut every `block_entries` rows (slab blocks favor a fixed row
    count over the reference's fixed byte size: device transfers like uniform
    shapes; 4096 rows * ~20B keys ~ 100-200KB blocks).
    """

    def __init__(self, base_path: str, block_entries: Optional[int] = None,
                 compress: Optional[bool] = None,
                 bits_per_key: Optional[int] = None,
                 fit_lindex: bool = True):
        self.base_path = base_path
        # None = take the server-wide tuning flags (the reference's LSM
        # option surface, docdb_rocksdb_util.cc:62-140)
        self.block_entries = (block_entries if block_entries is not None
                              else _sst_flags.get_flag("sst_block_entries"))
        self.compress = (compress if compress is not None
                         else sst_compression_enabled())
        self.bits_per_key = (bits_per_key if bits_per_key is not None
                             else _sst_flags.get_flag(
                                 "sst_bloom_bits_per_key"))
        # compaction output writers pass False: models on compaction
        # outputs come only from the device-native fit hook, so the
        # python/native/device output paths stay byte-identical
        self.fit_lindex = fit_lindex

    def write(self, slab: KVSlab, frontier: Optional[Frontier] = None) -> SSTProps:
        n = slab.n
        data_path = data_file_name(self.base_path)
        index_items: List[Tuple[bytes, int, int, int]] = []
        data_off = 0
        key_raw = slab.key_words.astype(">u4").tobytes()
        stride = slab.width_words * 4

        def key_at(i: int) -> bytes:
            return key_raw[i * stride: i * stride + int(slab.key_len[i])]

        from yugabyte_tpu.utils.env import get_env
        if os.path.exists(data_path):
            os.remove(data_path)  # never append to a stale data file
        df = get_env().open_append(data_path)
        try:
            for start in range(0, n, self.block_entries):
                end = min(start + self.block_entries, n)
                blk = block_format.encode_block(slab, start, end, self.compress)
                df.append(blk)
                index_items.append((key_at(end - 1), data_off, len(blk),
                                    end - start))
                data_off += len(blk)
            df.flush(fsync=True)
        finally:
            df.close()
        if n:
            u8 = np.frombuffer(key_raw, dtype=np.uint8).reshape(n, stride)
            hashes = fnv64_masked(u8, slab.doc_key_len.astype(np.int64))
        else:
            hashes = np.zeros(0, dtype=np.uint64)
        # whole-file expiry: meaningful only if EVERY entry carries a TTL
        from yugabyte_tpu.ops.slabs import FLAG_HAS_TTL
        max_expire_us = 0
        if n and bool(((slab.flags & FLAG_HAS_TTL) != 0).all()):
            ht_phys = ((slab.ht_hi.astype(np.uint64) << 32)
                       | slab.ht_lo.astype(np.uint64)) >> 12
            max_expire_us = int(
                (ht_phys + slab.ttl_ms.astype(np.uint64) * 1000).max())
        from yugabyte_tpu.ops.slabs import FLAG_DEEP
        lindex = None
        if self.fit_lindex and _sst_flags.get_flag("sst_learned_index"):
            from yugabyte_tpu.storage import learned_index
            lindex = learned_index.fit_from_slab(slab)
        return write_base_file(
            self.base_path, index_items, n, hashes,
            key_at(0) if n else b"", key_at(n - 1) if n else b"",
            frontier, data_off, self.bits_per_key,
            max_expire_us=max_expire_us,
            has_deep=bool(n) and bool(((slab.flags & FLAG_DEEP) != 0).any()),
            lindex=lindex)


def write_sst_from_packed(base_path: str, keys_blob: bytes, key_offs,
                          ht, wid, vals_blob: bytes, val_offs,
                          frontier: Optional[Frontier] = None,
                          block_entries: Optional[int] = None,
                          compress: Optional[bool] = None,
                          presorted_hint: bool = True,
                          run_cache=None,
                          file_id: Optional[int] = None) -> SSTProps:
    """Native-encoded SST from one packed run (the flush / bulk-load hot
    path, ref: db/flush_job.cc WriteLevel0Table + memtable.cc iteration).
    Block encode, bloom hashing and doc-key parsing run in C++
    (ce_job_add_raw → ce_job_sort_all → ce_job_write_output); Python
    assembles the base file as usual. Caller guarantees native_engine is
    available."""
    import numpy as np
    from yugabyte_tpu.storage import native_engine
    if block_entries is None:
        block_entries = _sst_flags.get_flag("sst_block_entries")
    if compress is None:
        compress = sst_compression_enabled()
    n = len(key_offs) - 1
    data_path = data_file_name(base_path)
    if os.path.exists(data_path):
        os.remove(data_path)  # never append to a stale data file
    with native_engine.NativeCompactionJob() as job:
        job.add_raw(keys_blob, key_offs, ht, wid, vals_blob, val_offs)
        job.sort_all()
        size, index, hashes, first_key, last_key = job.write_output(
            0, n, data_path, block_entries, compress, b"X")
        max_expire_us, has_deep = job.props()
        if run_cache is not None and file_id is not None and n:
            # run-cache write-through (storage/run_cache.py): the first
            # compaction over this flush output starts zero-decode
            rid = job.export_run(0, n, b"X")
            run_cache.put(file_id, rid,
                          native_engine.runcache_entry_bytes(rid))
    ht_arr = np.asarray(ht, dtype=np.uint64)
    fr = frontier or Frontier()
    if n and fr.ht_min == 0 and fr.ht_max == 0:
        fr.ht_min = int(ht_arr.min())
        fr.ht_max = int(ht_arr.max())
    lindex = None
    if _sst_flags.get_flag("sst_learned_index"):
        # the packed run may arrive unsorted (bulk ingest) — the fit's
        # key coordinate is a monotone transform of memcmp order, so
        # sorting the coordinates reproduces the written-order sequence
        from yugabyte_tpu.storage import learned_index
        lindex = learned_index.fit_from_packed_keys(keys_blob, key_offs)
    return write_base_file(base_path, index, n, hashes, first_key, last_key,
                           fr, size, max_expire_us=max_expire_us,
                           has_deep=has_deep, lindex=lindex)


def write_base_file(base_path: str,
                    index_items: List[Tuple[bytes, int, int, int]],
                    n_entries: int, bloom_hashes: np.ndarray,
                    first_key: bytes, last_key: bytes,
                    frontier: Optional[Frontier], data_size: int,
                    bits_per_key: Optional[int] = None,
                    max_expire_us: int = 0,
                    has_deep: bool = False,
                    lindex: Optional[dict] = None) -> SSTProps:
    """Assemble the base (metadata) file from precomputed parts.

    index_items: (last_key, data_offset, block_size, n_entries) per data
    block. Shared by the Python SSTWriter and the native compaction shell
    (storage/native_engine.py), which produces the parts in C++.
    """
    if bits_per_key is None:
        bits_per_key = _sst_flags.get_flag("sst_bloom_bits_per_key")
    bloom = BloomFilterBuilder(max(n_entries, 1), bits_per_key)
    if n_entries:
        bloom.add_hashes(np.asarray(bloom_hashes, dtype=np.uint64))
    bloom_bytes = bloom.finish()
    index_bytes = _encode_index(
        [it[0] for it in index_items],
        [struct.pack("<QII", it[1], it[2], it[3]) for it in index_items])
    props = SSTProps(
        n_entries=n_entries,
        first_key=first_key,
        last_key=last_key,
        frontier=frontier or Frontier(),
        data_size=data_size,
        max_expire_us=max_expire_us,
        has_deep=has_deep,
        lindex=lindex,
    )
    props_bytes = json.dumps(props.to_json()).encode()
    from yugabyte_tpu.utils.env import get_env
    index_off = 0
    bloom_off = len(index_bytes)
    props_off = bloom_off + len(bloom_bytes)
    crc = zlib.crc32(index_bytes) ^ zlib.crc32(bloom_bytes) ^ zlib.crc32(props_bytes)
    blob = (index_bytes + bloom_bytes + props_bytes
            + _FOOTER.pack(index_off, len(index_bytes), bloom_off,
                           len(bloom_bytes), props_off, len(props_bytes),
                           data_size, crc, SST_MAGIC))
    get_env().write_file(base_path, blob)
    props.base_size = len(blob)
    return props


def _encode_index(keys: List[bytes], vals: List[bytes]) -> bytes:
    parts = [struct.pack("<I", len(keys))]
    for k, v in zip(keys, vals):
        parts.append(struct.pack("<HH", len(k), len(v)))
        parts.append(k)
        parts.append(v)
    return b"".join(parts)


def _decode_index(data: bytes) -> Tuple[List[bytes], List[Tuple[int, int, int]]]:
    (count,) = struct.unpack_from("<I", data, 0)
    off = 4
    keys, handles = [], []
    for _ in range(count):
        klen, vlen = struct.unpack_from("<HH", data, off)
        off += 4
        keys.append(data[off: off + klen])
        off += klen
        handles.append(struct.unpack_from("<QII", data, off))
        off += vlen
    return keys, handles


class SSTReader:
    """Random and sequential access to one SST (ref: BlockBasedTable::Open)."""

    def __init__(self, base_path: str, block_cache: Optional["BlockCache"] = None):
        from yugabyte_tpu.utils.env import get_env
        self.base_path = base_path
        self.data_path = data_file_name(base_path)
        self.block_cache = block_cache
        raw = get_env().read_file(base_path)
        if len(raw) < _FOOTER.size:
            raise StatusError(Status.Corruption(f"SST base file too small: {base_path}"))
        (index_off, index_len, bloom_off, bloom_len, props_off, props_len,
         data_size, crc, magic) = _FOOTER.unpack_from(raw, len(raw) - _FOOTER.size)
        if magic != SST_MAGIC:
            raise StatusError(Status.Corruption(f"bad SST magic: {base_path}"))
        index_bytes = raw[index_off: index_off + index_len]
        bloom_bytes = raw[bloom_off: bloom_off + bloom_len]
        props_bytes = raw[props_off: props_off + props_len]
        if crc != (zlib.crc32(index_bytes) ^ zlib.crc32(bloom_bytes) ^ zlib.crc32(props_bytes)):
            raise StatusError(Status.Corruption(f"SST base checksum mismatch: {base_path}"))
        self.index_keys, self.block_handles = _decode_index(index_bytes)
        self.bloom_raw = bloom_bytes  # native read engine parses it in place
        self.bloom = BloomFilter(bloom_bytes)
        self.props = SSTProps.from_json(json.loads(props_bytes))
        # Env random-access handle (position-less preads are safe under
        # concurrent readers; decrypts transparently at rest).
        self._data = get_env().open_random(self.data_path)

    def close(self) -> None:
        if self._data is not None:
            self._data.close()
            self._data = None

    @property
    def n_blocks(self) -> int:
        return len(self.block_handles)

    def read_block(self, block_idx: int) -> KVSlab:
        if self.block_cache is not None:
            cached = self.block_cache.get((self.base_path, block_idx))
            if cached is not None:
                return cached
        off, size, _ = self.block_handles[block_idx]
        slab = block_format.decode_block(self._data.pread(size, off))
        _block_decode_counter().increment()
        if self.block_cache is not None:
            self.block_cache.put((self.base_path, block_idx), slab, size)
        return slab

    def read_all(self) -> KVSlab:
        """Whole-file slab (compaction input path)."""
        return concat_slabs([self.read_block(i) for i in range(self.n_blocks)]) \
            if self.n_blocks else _empty_slab()

    def read_raw(self) -> bytes:
        """Whole data-file bytes via the Env (decrypts at rest, no block
        decode, no counter movement) — the device-codec ingest path:
        ops/block_codec.parse_raw_file splits these into CRC-checked raw
        block regions using self.block_handles."""
        from yugabyte_tpu.utils.env import get_env
        return get_env().read_file(self.data_path)

    def may_contain_doc(self, doc_key_prefix: bytes) -> bool:
        return self.bloom.may_contain(doc_key_prefix)

    def seek_block(self, key: bytes) -> int:
        """First block whose last_key >= key (binary search the index)."""
        lo, hi = 0, len(self.index_keys)
        while lo < hi:
            mid = (lo + hi) // 2
            if self.index_keys[mid] < key:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def iter_entries(self, start_block: int = 0) -> Iterator[Tuple[bytes, DocHybridTime, bytes, int]]:
        """Yield (key_prefix, doc_ht, value, flags) in slab order."""
        for b in range(start_block, self.n_blocks):
            slab = self.read_block(b)
            raw = slab.key_words.astype(">u4").tobytes()
            stride = slab.width_words * 4
            for i in range(slab.n):
                yield (raw[i * stride: i * stride + int(slab.key_len[i])],
                       slab.doc_ht(i), slab.values[int(slab.value_idx[i])],
                       int(slab.flags[i]))


class BlockCache:
    """LRU cache of decoded blocks (ref: util/lru_cache.cc,
    db/table_cache.cc). Shared server-wide across all tablets' DBs (keys
    embed the SST path, so file-id collisions between DBs are impossible);
    locked because every tablet's read and compaction threads hit it."""

    def __init__(self, capacity_bytes: int = 256 * 1024 * 1024):
        import threading
        from collections import OrderedDict
        self.capacity = capacity_bytes
        self.used = 0
        self._map: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()

    def get(self, key):
        with self._lock:
            item = self._map.get(key)
            if item is None:
                return None
            self._map.move_to_end(key)
            return item[0]

    def put(self, key, slab: KVSlab, size: int) -> None:
        with self._lock:
            if key in self._map:
                return
            self._map[key] = (slab, size)
            self.used += size
            while self.used > self.capacity and self._map:
                self._pop_lru_locked()

    def _pop_lru_locked(self) -> int:
        _, (_, sz) = self._map.popitem(last=False)
        self.used -= sz
        return sz

    def evict(self, required: int) -> int:
        """LRU-evict at least ``required`` bytes; the MemTracker GC hook
        (ref: tserver/tablet_memory_manager.cc InitBlockCache registers a
        GarbageCollector on the block-based-table tracker). Returns freed."""
        freed = 0
        with self._lock:
            while freed < required and self._map:
                freed += self._pop_lru_locked()
        return freed


def _empty_slab() -> KVSlab:
    from yugabyte_tpu.ops.slabs import pack_kvs
    return pack_kvs([])
