"""CQLServer: the network face of the YCQL layer.

The reference speaks the CQL binary protocol v4 (ref: src/yb/yql/cql/
cqlserver/ — CQLServer cql_server.h:58, CQLProcessor cql_processor.h:63,
prepared-statement cache in cql_service.cc). Here the wire is the
framework's own RPC codec — service "cql" with execute/batch calls carrying
statement text + bind params — because every in-framework client already
speaks it; the statement surface and execution semantics are the parser/
executor's (yql/cql/parser.py, executor.py), shared with any future binary
protocol front end. Per-session keyspace state keys off a client-supplied
session id, like the reference's per-connection processors.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from yugabyte_tpu.client.client import YBClient
from yugabyte_tpu.client.transaction import TransactionManager
from yugabyte_tpu.rpc.messenger import Messenger
from yugabyte_tpu.yql.cql.executor import QLProcessor

CQL_SERVICE = "cql"


class CQLServiceImpl:
    def __init__(self, client: YBClient):
        self._client = client
        self._txns = TransactionManager(client)
        self._processors: Dict[str, QLProcessor] = {}
        self._lock = threading.Lock()

    def _processor(self, session: str) -> QLProcessor:
        with self._lock:
            p = self._processors.get(session)
            if p is None:
                p = QLProcessor(self._client, self._txns)
                self._processors[session] = p
            return p

    def execute(self, stmt: str, params: Optional[List] = None,
                session: str = "") -> dict:
        rs = self._processor(session).execute(stmt, params or [])
        return {"columns": rs.columns, "rows": rs.rows}

    def batch(self, stmts: List[str], session: str = "") -> int:
        p = self._processor(session)
        for s in stmts:
            p.execute(s)
        return len(stmts)


class CQLServer:
    """Standalone CQL endpoint: own messenger + a YBClient to the cluster
    (the reference runs the cqlserver inside the tserver process; here it
    can also ride a tserver's messenger via `attach`)."""

    def __init__(self, master_addrs: List[str],
                 bind_host: str = "127.0.0.1", port: int = 0):
        self.messenger = Messenger("cqlserver", bind_host=bind_host,
                                   port=port)
        self.client = YBClient(master_addrs, messenger=self.messenger)
        self.service = CQLServiceImpl(self.client)
        self.messenger.register_service(CQL_SERVICE, self.service)

    @property
    def address(self) -> str:
        return self.messenger.address

    @staticmethod
    def attach(messenger: Messenger, client: YBClient) -> CQLServiceImpl:
        """Register the CQL service on an existing server's messenger."""
        svc = CQLServiceImpl(client)
        messenger.register_service(CQL_SERVICE, svc)
        return svc

    def shutdown(self) -> None:
        self.messenger.shutdown()
