#!/usr/bin/env python
"""North-star benchmark: L0->L1 compaction merge+GC rows/sec on TPU.

Measures the fused TPU merge+MVCC-GC kernel (ops/merge_gc.py) against the
native C++ CPU baseline (native/compaction_baseline.cc) which implements the
reference's stock CompactionJob architecture — binary-heap k-way merge
(ref: rocksdb/table/merger.cc:51) + sequential per-entry GC filter
(ref: docdb/docdb_compaction_filter.cc) — on one core, i.e. one
subcompaction thread (ref: compaction_job.cc:456-468).

Workload: YCSB-A-shaped tablet — K_RUNS overlapping sorted runs (L0 SSTs)
of uniform-random row updates plus row tombstones, major-compacted with the
history cutoff above all writes (pure dedup-to-latest + tombstone GC).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value       = TPU end-to-end rows/s (host pack + transfer + kernel + fetch)
vs_baseline = value / CPU-baseline rows/s
Device-resident rate (inputs already in HBM — the steady state once flush
write-through caching keeps slabs on device) is reported on stderr.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def synth_ycsb_runs(n_total: int, n_runs: int, key_space: int, seed: int = 42,
                    tombstone_frac: float = 0.05):
    """Vectorized YCSB-A-like slab: n_runs sorted runs of row writes.

    Key layout (DocDB encoding, docdb/doc_key.py): root = 'S' 'user%08d'
    00 00 '!' (16B); column write = root + 'K' + 2B col id (19B).
    """
    from yugabyte_tpu.ops.slabs import KVSlab, FLAG_TOMBSTONE

    rng = np.random.default_rng(seed)
    per_run = n_total // n_runs
    stride = 20  # 19B padded to 4B words -> w=5
    all_parts = []
    offsets = [0]
    for g in range(n_runs):
        ids = rng.integers(0, key_space, size=per_run)
        is_tomb = rng.random(per_run) < tombstone_frac
        keys = np.zeros((per_run, stride), dtype=np.uint8)
        keys[:, 0] = ord("S")
        keys[:, 1:5] = np.frombuffer(b"user", dtype=np.uint8)
        digits = ids[:, None] // (10 ** np.arange(7, -1, -1)[None, :]) % 10
        keys[:, 5:13] = (digits + ord("0")).astype(np.uint8)
        keys[:, 13] = 0
        keys[:, 14] = 0
        keys[:, 15] = ord("!")
        # column writes address col 0; tombstones hit the row root
        col_part = np.where(is_tomb[:, None],
                            np.zeros((per_run, 3), np.uint8),
                            np.array([[ord("K"), 0, 0]], np.uint8))
        keys[:, 16:19] = col_part
        key_len = np.where(is_tomb, 16, 19).astype(np.int32)
        dkl = np.full(per_run, 16, dtype=np.int32)
        ht = (1_000_000 * (g + 1) + rng.permutation(per_run)).astype(np.uint64) << 12
        flags = np.where(is_tomb, FLAG_TOMBSTONE, 0).astype(np.uint32)
        # sort run by (key, ht desc): lexsort minor->major
        sort_cols = [~ht] + [keys[:, j] for j in range(stride - 1, -1, -1)]
        order = np.lexsort(sort_cols)
        all_parts.append((keys[order], key_len[order], dkl[order], ht[order],
                          flags[order]))
        offsets.append(offsets[-1] + per_run)
    keys = np.concatenate([p[0] for p in all_parts])
    n = keys.shape[0]
    kw = keys.reshape(n, stride // 4, 4)
    key_words = ((kw[:, :, 0].astype(np.uint32) << 24)
                 | (kw[:, :, 1].astype(np.uint32) << 16)
                 | (kw[:, :, 2].astype(np.uint32) << 8)
                 | kw[:, :, 3].astype(np.uint32))
    ht = np.concatenate([p[3] for p in all_parts])
    slab = KVSlab(
        key_words=key_words,
        key_len=np.concatenate([p[1] for p in all_parts]),
        doc_key_len=np.concatenate([p[2] for p in all_parts]),
        ht_hi=(ht >> 32).astype(np.uint32),
        ht_lo=(ht & 0xFFFFFFFF).astype(np.uint32),
        write_id=np.zeros(n, dtype=np.uint32),
        flags=np.concatenate([p[4] for p in all_parts]),
        ttl_ms=np.zeros(n, dtype=np.int64),
        value_idx=np.arange(n, dtype=np.int32),
        values=[b""] * n,
    )
    return slab, offsets


def main():
    n_total = int(os.environ.get("YBTPU_BENCH_N", 1 << 22))
    n_runs = 4
    key_space = max(1, n_total // 2)
    cutoff = (10_000_000 << 12)  # above all writes

    log(f"generating {n_total} rows in {n_runs} sorted runs ...")
    t0 = time.time()
    slab, offsets = synth_ycsb_runs(n_total, n_runs, key_space)
    log(f"  gen: {time.time()-t0:.1f}s")

    # ---- CPU baseline (reference architecture, 1 core = 1 subcompaction) --
    from yugabyte_tpu.storage.cpu_baseline import compact_cpu_baseline
    t0 = time.time()
    order, keep_cpu, _ = compact_cpu_baseline(slab, offsets, cutoff, True)
    cpu_s = time.time() - t0
    cpu_rate = n_total / cpu_s
    log(f"  CPU baseline: {cpu_s:.2f}s = {cpu_rate/1e6:.2f}M rows/s "
        f"(kept {int(keep_cpu.sum())})")

    # ---- TPU fused kernel --------------------------------------------------
    import jax
    from yugabyte_tpu.ops.merge_gc import (
        GCParams, merge_and_gc_device, stage_slab)
    dev = jax.devices()[0]
    log(f"  device: {dev}")
    params = GCParams(cutoff, True)
    # warm-up / compile
    t0 = time.time()
    merge_and_gc_device(slab, params, device=dev)
    log(f"  TPU first call (compile): {time.time()-t0:.1f}s")
    t0 = time.time()
    perm, keep_tpu, _ = merge_and_gc_device(slab, params, device=dev)
    tpu_s = time.time() - t0
    tpu_rate = n_total / tpu_s
    log(f"  TPU end-to-end: {tpu_s:.2f}s = {tpu_rate/1e6:.2f}M rows/s "
        f"(kept {int(keep_tpu.sum())})")

    # correctness cross-check: same survivors as the CPU baseline
    assert int(keep_tpu.sum()) == int(keep_cpu.sum()), (
        f"survivor mismatch: tpu {int(keep_tpu.sum())} cpu {int(keep_cpu.sum())}")

    # ---- TPU device-resident (block-cache steady state) -------------------
    staged = stage_slab(slab, dev)
    jax.block_until_ready(staged.cols_dev)
    merge_and_gc_device(None, params, device=dev, staged=staged)
    t0 = time.time()
    merge_and_gc_device(None, params, device=dev, staged=staged)
    res_s = time.time() - t0
    log(f"  TPU device-resident: {res_s:.2f}s = {n_total/res_s/1e6:.2f}M rows/s "
        f"({staged.n_sort} sort passes)")

    # ---- TPU scan kernel (device-resident, read_ht = cutoff) --------------
    from yugabyte_tpu.ops.scan import scan_visible
    scan_visible(staged, cutoff)  # compile
    t0 = time.time()
    _, keep_scan = scan_visible(staged, cutoff)
    scan_s = time.time() - t0
    log(f"  TPU snapshot scan: {scan_s:.2f}s = {n_total/scan_s/1e6:.2f}M rows/s "
        f"({int(keep_scan.sum())} visible)")

    print(json.dumps({
        "metric": "l0_compaction_merge_gc_rows_per_sec",
        "value": round(tpu_rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(tpu_rate / cpu_rate, 3),
    }))


if __name__ == "__main__":
    main()
