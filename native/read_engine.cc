// Native read engine: the serving-path counterpart of compaction_engine.cc.
//
// The reference serves reads through BlockBasedTable (ref:
// src/yb/rocksdb/table/block_based_table_reader.cc:1144-1286 — index seek,
// bloom gate, in-block binary search) and merges sources through
// MergingIterator (table/merger.cc:51). Round 4 measured the Python read
// loop at 25 MB/s seq scan / 2.4K point reads/s — two to three orders below
// reference class — because every entry paid Python block decode + tuple
// construction. This engine keeps the whole byte path native:
//
//   - rs_open: one handle per SST over the raw data-file bytes (Python
//     owns the buffer; the env layer already decrypted it). Blocks are
//     viewed IN PLACE — the columnar block layout (block_format.py) needs
//     no row reassembly, so an uncompressed block costs zero copies to
//     serve; zlib blocks decompress once into a cached owned buffer.
//   - rs_multi_get: bloom-gated point lookup across many SSTs in ONE
//     native call (fnv hash once, per-SST index seek + in-place binary
//     search, newest-visible-version wins).
//   - rs_scan_*: k-way heap merge over SST cursors plus an optional
//     packed memtable overlay run, streaming batches of entries into
//     caller buffers. Mode 0 emits the raw merged stream (iter_from);
//     mode 1 resolves MVCC visibility inline (the native twin of
//     DocRowwiseIterator._resolve_visible: first version <= read_ht per
//     doc path wins; tombstone / TTL / overwrite shadowing applied).
//
// Build: g++ -O3 -shared -fPIC -o libread_engine.so read_engine.cc -lz
#include <zlib.h>

#include <atomic>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "merge_gc_core.h"

namespace {

using ybtpu::doc_key_len;

constexpr uint32_t kBlockMagic = 0x53425459;  // "YTBS"
constexpr int kHeaderLen = 24;

inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}
inline uint16_t rd_u16(const uint8_t* p) {
  uint16_t v;
  memcpy(&v, p, 2);
  return v;
}

// FNV-1a over the first len bytes — matches storage/bloom.py fnv64_masked.
inline uint64_t fnv1a(const uint8_t* p, int32_t len) {
  uint64_t h = 0xCBF29CE484222325ULL;
  for (int32_t i = 0; i < len; ++i) h = (h ^ p[i]) * 0x100000001B3ULL;
  return h;
}

// ---- in-place block view ---------------------------------------------------
struct View {
  std::atomic<bool> ready{false};
  const uint8_t* keys = nullptr;
  const uint8_t* klq = nullptr;
  const uint8_t* dklq = nullptr;
  const uint8_t* hthq = nullptr;
  const uint8_t* htlq = nullptr;
  const uint8_t* widq = nullptr;
  const uint8_t* flq = nullptr;
  const uint8_t* ttlq = nullptr;
  const uint8_t* voq = nullptr;
  const uint8_t* vb = nullptr;
  uint32_t n = 0;
  uint32_t stride = 0;
  std::unique_ptr<std::vector<uint8_t>> owned;  // decompressed body

  inline const uint8_t* key_ptr(uint32_t i) const { return keys + (int64_t)i * stride; }
  inline int32_t key_len(uint32_t i) const { return rd_u16(klq + 2 * i); }
  inline int32_t dkl(uint32_t i) const { return rd_u16(dklq + 2 * i); }
  inline uint64_t ht(uint32_t i) const {
    return ((uint64_t)rd_u32(hthq + 4 * i) << 32) | rd_u32(htlq + 4 * i);
  }
  inline uint32_t wid(uint32_t i) const { return rd_u32(widq + 4 * i); }
  inline uint8_t flags(uint32_t i) const { return flq[i]; }
  inline int64_t ttl_ms(uint32_t i) const {
    int64_t t;
    memcpy(&t, ttlq + 8 * i, 8);
    return t;
  }
  inline const uint8_t* val_ptr(uint32_t i) const { return vb + rd_u32(voq + 4 * i); }
  inline uint32_t val_len(uint32_t i) const {
    return rd_u32(voq + 4 * (i + 1)) - rd_u32(voq + 4 * i);
  }
};

struct BlockHandle {
  int64_t off;
  int32_t size;
  int32_t count;
};

struct Reader {
  const uint8_t* data;
  int64_t size;
  std::vector<BlockHandle> handles;
  const uint8_t* index_blob;          // concatenated per-block last keys
  std::vector<int32_t> index_offs;    // n_blocks + 1
  uint32_t bloom_k = 0;
  uint64_t bloom_m = 0;
  const uint8_t* bloom_bits = nullptr;
  std::vector<View> views;
  std::mutex mu;  // guards view fill
  std::string error;

  const uint8_t* index_key(int32_t b, int32_t* len) const {
    *len = index_offs[b + 1] - index_offs[b];
    return index_blob + index_offs[b];
  }

  bool may_contain(uint64_t h) const {
    if (!bloom_bits || bloom_m == 0) return true;
    uint64_t h1 = h & 0xFFFFFFFFULL;
    uint64_t h2 = (h >> 32) | 1ULL;
    for (uint32_t i = 0; i < bloom_k; ++i) {
      uint64_t pos = (h1 + (uint64_t)i * h2) % bloom_m;
      if (!((bloom_bits[pos >> 3] >> (pos & 7)) & 1)) return false;
    }
    return true;
  }

  // Parse + (if needed) decompress block b; idempotent and thread-safe.
  View* view(int32_t b) {
    View* v = &views[b];
    if (v->ready.load(std::memory_order_acquire)) return v;
    std::lock_guard<std::mutex> lock(mu);
    if (v->ready.load(std::memory_order_relaxed)) return v;
    const BlockHandle& h = handles[b];
    if (h.off + kHeaderLen > size) { error = "handle oob"; return nullptr; }
    const uint8_t* p = data + h.off;
    uint32_t magic = rd_u32(p), n = rd_u32(p + 4), stride = rd_u32(p + 8);
    uint32_t bflags = rd_u32(p + 12), body_len = rd_u32(p + 16),
             raw_len = rd_u32(p + 20);
    if (magic != kBlockMagic || (int32_t)n != h.count ||
        (int64_t)kHeaderLen + body_len + 4 > h.size) {
      error = "bad block header";
      return nullptr;
    }
    const uint8_t* stored = p + kHeaderLen;
    uint32_t crc = rd_u32(stored + body_len);
    uint32_t want = crc32(0, p + 4, kHeaderLen - 4);
    want = crc32(want, stored, body_len);
    if (crc != want) { error = "block crc mismatch"; return nullptr; }
    const uint8_t* body = stored;
    if (bflags & 1) {  // zlib: decompress once into an owned buffer
      v->owned = std::make_unique<std::vector<uint8_t>>(raw_len);
      uLongf dlen = raw_len;
      if (uncompress(v->owned->data(), &dlen, stored, body_len) != Z_OK ||
          dlen != raw_len) {
        error = "block decompress failure";
        v->owned.reset();
        return nullptr;
      }
      body = v->owned->data();
    }
    const uint8_t* q = body;
    v->keys = q;  q += (int64_t)n * stride;
    v->klq = q;   q += 2 * (int64_t)n;
    v->dklq = q;  q += 2 * (int64_t)n;
    v->hthq = q;  q += 4 * (int64_t)n;
    v->htlq = q;  q += 4 * (int64_t)n;
    v->widq = q;  q += 4 * (int64_t)n;
    v->flq = q;   q += (int64_t)n;
    v->ttlq = q;  q += 8 * (int64_t)n;
    v->voq = q;   q += 4 * ((int64_t)n + 1);
    v->vb = q;
    if (q - body > raw_len) { error = "block body oob"; return nullptr; }
    v->n = n;
    v->stride = stride;
    v->ready.store(true, std::memory_order_release);
    return v;
  }

  // First block whose last_key >= key.
  int32_t seek_block(const uint8_t* key, int32_t klen) const {
    int32_t lo = 0, hi = (int32_t)handles.size();
    while (lo < hi) {
      int32_t mid = (lo + hi) / 2;
      int32_t il;
      const uint8_t* ik = index_key(mid, &il);
      int32_t m = il < klen ? il : klen;
      int r = memcmp(ik, key, m);
      if (r < 0 || (r == 0 && il < klen)) lo = mid + 1;
      else hi = mid;
    }
    return lo;
  }
};

inline int cmp_keys(const uint8_t* a, int32_t la, const uint8_t* b, int32_t lb) {
  int32_t m = la < lb ? la : lb;
  int r = memcmp(a, b, m);
  if (r) return r;
  return la < lb ? -1 : (la > lb ? 1 : 0);
}

// Locate the newest version of `key` with ht <= read_ht in one SST.
// Returns 1 + fills (*vp, *ip) on a match, 0 when absent, -1 on corruption.
int reader_point_get(Reader* r, const uint8_t* key, int32_t klen,
                     uint64_t read_ht, View** vp, uint32_t* ip) {
  int32_t b = r->seek_block(key, klen);
  while (b < (int32_t)r->handles.size()) {
    View* v = r->view(b);
    if (!v) return -1;
    // first i with NOT (key_i < key  ||  (key_i == key && ht_i > read_ht))
    uint32_t lo = 0, hi = v->n;
    while (lo < hi) {
      uint32_t mid = (lo + hi) / 2;
      int c = cmp_keys(v->key_ptr(mid), v->key_len(mid), key, klen);
      bool less = c < 0 || (c == 0 && v->ht(mid) > read_ht);
      if (less) lo = mid + 1;
      else hi = mid;
    }
    if (lo < v->n) {
      if (cmp_keys(v->key_ptr(lo), v->key_len(lo), key, klen) == 0) {
        *vp = v;
        *ip = lo;
        return 1;
      }
      return 0;  // seek landed past the key: not in this SST
    }
    ++b;  // whole block below the seek point (version chain spans blocks)
  }
  return 0;
}

// ---- scan: k-way merge + optional MVCC visibility --------------------------
struct Cursor {
  // SST source
  Reader* r = nullptr;
  int32_t b = 0;
  uint32_t i = 0;
  View* v = nullptr;
  // packed overlay source (memtable)
  const uint8_t* xkeys = nullptr;
  const int64_t* xkoffs = nullptr;
  const uint64_t* xht = nullptr;
  const uint32_t* xwid = nullptr;
  const uint8_t* xflags = nullptr;
  const int64_t* xttl = nullptr;
  const int32_t* xdkl = nullptr;
  const uint8_t* xvals = nullptr;
  const int64_t* xvoffs = nullptr;
  int64_t xn = 0, xpos = 0;

  // current entry (refreshed by load())
  const uint8_t* k = nullptr;
  int32_t klen = 0, dkl = 0;
  uint64_t ht = 0;
  uint32_t wid = 0;
  uint8_t flags = 0;
  int64_t ttl = 0;
  const uint8_t* val = nullptr;
  uint32_t vlen = 0;
  bool done = false;
  bool err = false;  // block corruption: surfaced, never silent EOF

  bool load() {
    if (r) {
      while (true) {
        if (b >= (int32_t)r->handles.size()) { done = true; return false; }
        v = r->view(b);
        if (!v) { done = true; err = true; return false; }
        if (i < v->n) break;
        ++b;
        i = 0;
      }
      k = v->key_ptr(i);
      klen = v->key_len(i);
      dkl = v->dkl(i);
      ht = v->ht(i);
      wid = v->wid(i);
      flags = v->flags(i);
      ttl = v->ttl_ms(i);
      val = v->val_ptr(i);
      vlen = v->val_len(i);
      return true;
    }
    if (xpos >= xn) { done = true; return false; }
    int64_t p = xpos;
    k = xkeys + xkoffs[p];
    klen = (int32_t)(xkoffs[p + 1] - xkoffs[p]);
    dkl = xdkl[p];
    ht = xht[p];
    wid = xwid[p];
    flags = xflags[p];
    ttl = xttl[p];
    val = xvals + xvoffs[p];
    vlen = (uint32_t)(xvoffs[p + 1] - xvoffs[p]);
    return true;
  }

  void advance() {
    if (r) ++i;
    else ++xpos;
    load();
  }
};

// internal-key order: key asc, ht desc, wid desc
inline bool cursor_less(const Cursor* a, const Cursor* b) {
  int c = cmp_keys(a->k, a->klen, b->k, b->klen);
  if (c) return c < 0;
  if (a->ht != b->ht) return a->ht > b->ht;
  return a->wid > b->wid;
}

struct Scan {
  std::vector<std::unique_ptr<Cursor>> cursors;
  std::vector<Cursor*> heap;
  int mode = 0;  // 0 = raw merged stream, 1 = MVCC-visible entries
  uint64_t read_ht = ~0ULL;
  std::vector<uint8_t> upper;
  bool has_upper = false;
  bool done = false;
  std::string error;

  // raw modes: last emitted entry, for exact-duplicate suppression — a
  // flush racing the Python-side overlay snapshot can surface the same
  // (key, ht, wid) from both the memtable run and the fresh SST; legit
  // data never repeats a full internal key (one DocHybridTime per write)
  std::vector<uint8_t> last_key;
  uint64_t last_ht = 0;
  uint32_t last_wid = 0;
  bool have_last = false;

  // visibility state (mode 1) — twin of DocRowwiseIterator._resolve_visible
  std::vector<uint8_t> cur_doc;
  bool have_doc = false;
  // overwrite-point stack over subpath prefixes: every newest-visible
  // entry replaces the older subtree at its path (collection replace
  // markers / column tombstones shadow older elements)
  struct OvPoint {
    std::string sub;
    uint64_t ht;
    uint32_t wid;
  };
  std::vector<OvPoint> ov_stack;
  std::vector<std::string> seen_paths;

  void heap_init() {
    for (auto& c : cursors)
      if (!c->done) heap.push_back(c.get());
    for (int64_t i = (int64_t)heap.size() / 2 - 1; i >= 0; --i) sift_down(i);
  }
  void sift_down(int64_t i) {
    int64_t sz = (int64_t)heap.size();
    for (;;) {
      int64_t l = 2 * i + 1, r = l + 1, s = i;
      if (l < sz && cursor_less(heap[l], heap[s])) s = l;
      if (r < sz && cursor_less(heap[r], heap[s])) s = r;
      if (s == i) break;
      std::swap(heap[i], heap[s]);
      i = s;
    }
  }
  // advance the top cursor and restore heap order; false on corruption
  bool pop_advance() {
    Cursor* c = heap[0];
    c->advance();
    if (c->err) {
      error = c->r && !c->r->error.empty() ? c->r->error
                                           : "block corruption in scan";
      return false;
    }
    if (c->done) {
      heap[0] = heap.back();
      heap.pop_back();
    }
    if (!heap.empty()) sift_down(0);
    return true;
  }
};

// Seek one SST cursor to the first entry with key >= lower (any version).
void cursor_seek(Cursor* c, const uint8_t* lower, int32_t llen) {
  if (c->r) {
    c->b = llen ? c->r->seek_block(lower, llen) : 0;
    c->i = 0;
    if (!c->load()) return;
    if (!llen) return;
    while (true) {
      View* v = c->v;
      uint32_t lo = 0, hi = v->n;
      while (lo < hi) {
        uint32_t mid = (lo + hi) / 2;
        if (cmp_keys(v->key_ptr(mid), v->key_len(mid), lower, llen) < 0)
          lo = mid + 1;
        else
          hi = mid;
      }
      if (lo < v->n) {
        c->i = lo;
        c->load();
        return;
      }
      ++c->b;
      c->i = 0;
      if (!c->load()) return;
    }
  } else {
    // packed overlay: binary search the key offsets
    int64_t lo = 0, hi = c->xn;
    while (lo < hi) {
      int64_t mid = (lo + hi) / 2;
      const uint8_t* k = c->xkeys + c->xkoffs[mid];
      int32_t kl = (int32_t)(c->xkoffs[mid + 1] - c->xkoffs[mid]);
      if (cmp_keys(k, kl, lower, llen) < 0) lo = mid + 1;
      else hi = mid;
    }
    c->xpos = lo;
    c->load();
  }
}

}  // namespace

extern "C" {

// data / index_blob / bloom bytes stay Python-owned for the reader lifetime.
void* rs_open(const uint8_t* data, int64_t size, const int64_t* offs,
              const int32_t* sizes, const int32_t* counts, int32_t n_blocks,
              const uint8_t* index_blob, const int32_t* index_offs,
              const uint8_t* bloom, int64_t bloom_len) {
  Reader* r = new Reader();
  r->data = data;
  r->size = size;
  r->handles.reserve(n_blocks);
  for (int32_t b = 0; b < n_blocks; ++b)
    r->handles.push_back({offs[b], sizes[b], counts[b]});
  r->index_blob = index_blob;
  r->index_offs.assign(index_offs, index_offs + n_blocks + 1);
  if (bloom && bloom_len >= 12) {
    // storage/bloom.py layout: <I k><Q m_bits><bits>
    memcpy(&r->bloom_k, bloom, 4);
    memcpy(&r->bloom_m, bloom + 4, 8);
    r->bloom_bits = bloom + 12;
  }
  r->views = std::vector<View>(n_blocks);
  return r;
}

void rs_close(void* rp) { delete (Reader*)rp; }

const char* rs_error(void* rp) { return ((Reader*)rp)->error.c_str(); }

int32_t rs_doc_key_len(const uint8_t* key, int32_t len) {
  return doc_key_len(key, len);
}

// Point lookup across SSTs: newest version with ht <= read_ht wins.
// Returns value length (copied into out up to cap), -1 when absent, or
// -2 on block corruption (fetch detail via rs_error on each reader).
int64_t rs_multi_get(void** readers, int32_t n_readers, const uint8_t* key,
                     int32_t klen, int32_t dkl, uint64_t read_ht,
                     uint8_t* out, int64_t cap, uint64_t* out_ht,
                     uint32_t* out_wid, uint8_t* out_flags) {
  if (dkl <= 0 || dkl > klen) dkl = doc_key_len(key, klen);
  uint64_t h = fnv1a(key, dkl);
  View* best_v = nullptr;
  uint32_t best_i = 0;
  uint64_t best_ht = 0;
  uint32_t best_wid = 0;
  bool found = false;
  for (int32_t ri = 0; ri < n_readers; ++ri) {
    Reader* r = (Reader*)readers[ri];
    if (!r->may_contain(h)) continue;
    View* v;
    uint32_t i;
    int rc = reader_point_get(r, key, klen, read_ht, &v, &i);
    if (rc < 0) return -2;
    if (rc == 0) continue;
    uint64_t ht = v->ht(i);
    uint32_t wid = v->wid(i);
    if (!found || ht > best_ht || (ht == best_ht && wid > best_wid)) {
      found = true;
      best_v = v;
      best_i = i;
      best_ht = ht;
      best_wid = wid;
    }
  }
  if (!found) return -1;
  *out_ht = best_ht;
  *out_wid = best_wid;
  *out_flags = best_v->flags(best_i);
  uint32_t vlen = best_v->val_len(best_i);
  if ((int64_t)vlen <= cap) memcpy(out, best_v->val_ptr(best_i), vlen);
  return vlen;
}

// Build a scan over n_readers SSTs plus an optional packed overlay run
// (pass xn = 0 for none). mode 0 = raw merged stream; mode 1 = visible.
void* rs_scan_new(void** readers, int32_t n_readers, const uint8_t* xkeys,
                  const int64_t* xkoffs, const uint64_t* xht,
                  const uint32_t* xwid, const uint8_t* xflags,
                  const int64_t* xttl, const int32_t* xdkl,
                  const uint8_t* xvals, const int64_t* xvoffs, int64_t xn,
                  const uint8_t* lower, int32_t llen, const uint8_t* upper,
                  int32_t ulen, uint64_t read_ht, int32_t mode) {
  Scan* s = new Scan();
  s->mode = mode;
  s->read_ht = read_ht;
  if (ulen > 0) {
    s->upper.assign(upper, upper + ulen);
    s->has_upper = true;
  }
  for (int32_t i = 0; i < n_readers; ++i) {
    auto c = std::make_unique<Cursor>();
    c->r = (Reader*)readers[i];
    cursor_seek(c.get(), lower, llen);
    if (c->err && s->error.empty())
      s->error = !c->r->error.empty() ? c->r->error
                                      : "block corruption at scan seek";
    s->cursors.push_back(std::move(c));
  }
  if (xn > 0) {
    auto c = std::make_unique<Cursor>();
    c->xkeys = xkeys;
    c->xkoffs = xkoffs;
    c->xht = xht;
    c->xwid = xwid;
    c->xflags = xflags;
    c->xttl = xttl;
    c->xdkl = xdkl;
    c->xvals = xvals;
    c->xvoffs = xvoffs;
    c->xn = xn;
    cursor_seek(c.get(), lower, llen);
    s->cursors.push_back(std::move(c));
  }
  s->heap_init();
  return s;
}

void rs_scan_free(void* sp) { delete (Scan*)sp; }

const char* rs_scan_error(void* sp) { return ((Scan*)sp)->error.c_str(); }

// Fill caller buffers with up to max_rows entries. Returns rows written;
// 0 = exhausted; -1 = error (single entry larger than the buffer caps).
int64_t rs_scan_next(void* sp, int64_t max_rows, uint8_t* keys_out,
                     int64_t key_cap, int32_t* key_offs, uint8_t* vals_out,
                     int64_t val_cap, int64_t* val_offs, uint64_t* ht_out,
                     uint32_t* wid_out, uint8_t* flags_out,
                     int32_t* dkl_out) {
  Scan* s = (Scan*)sp;
  if (!s->error.empty()) return -1;
  if (s->done) return 0;
  int64_t n = 0, kpos = 0, vpos = 0;
  key_offs[0] = 0;
  val_offs[0] = 0;
  while (n < max_rows && !s->heap.empty()) {
    Cursor* c = s->heap[0];
    const uint8_t* k = c->k;
    int32_t klen = c->klen, dkl = c->dkl;
    uint64_t ht = c->ht;
    uint32_t wid = c->wid;
    uint8_t fl = c->flags;
    int64_t ttl = c->ttl;
    const uint8_t* val = c->val;
    uint32_t vlen = c->vlen;

    bool emit = false;
    if (s->mode != 1) {
      if (s->has_upper &&
          cmp_keys(k, klen, s->upper.data(), (int32_t)s->upper.size()) >= 0) {
        s->done = true;
        break;
      }
      emit = !(s->have_last && ht == s->last_ht && wid == s->last_wid &&
               (int32_t)s->last_key.size() == klen &&
               memcmp(s->last_key.data(), k, klen) == 0);
      if (emit) {
        s->last_key.assign(k, k + klen);
        s->last_ht = ht;
        s->last_wid = wid;
        s->have_last = true;
      }
    } else {
      // MVCC visibility (DocRowwiseIterator._resolve_visible semantics)
      int32_t d = dkl < klen ? dkl : klen;
      if (s->has_upper &&
          cmp_keys(k, d, s->upper.data(), (int32_t)s->upper.size()) >= 0) {
        s->done = true;
        break;
      }
      if (ht <= s->read_ht) {
        if (!s->have_doc || (int32_t)s->cur_doc.size() != d ||
            memcmp(s->cur_doc.data(), k, d) != 0) {
          s->cur_doc.assign(k, k + d);
          s->have_doc = true;
          s->ov_stack.clear();
          s->seen_paths.clear();
        }
        std::string sub((const char*)k + d, (size_t)(klen - d));
        bool seen = false;
        for (const auto& p : s->seen_paths)
          if (p == sub) { seen = true; break; }
        if (!seen) {
          // pop overwrite points that are not a prefix of this path
          while (!s->ov_stack.empty()) {
            const std::string& anc = s->ov_stack.back().sub;
            if (sub.size() >= anc.size() &&
                memcmp(sub.data(), anc.data(), anc.size()) == 0)
              break;
            s->ov_stack.pop_back();
          }
          bool shadowed = false;
          for (const auto& o : s->ov_stack) {
            if (ht < o.ht || (ht == o.ht && wid < o.wid)) {
              shadowed = true;
              break;
            }
          }
          bool expired = (fl & 4) &&
              (s->read_ht >> 12) >= (ht >> 12) + (uint64_t)ttl * 1000;
          bool dead = (fl & 1) || shadowed || expired;
          // EVERY newest-visible entry is an overwrite point for its
          // subtree (matches _resolve_visible / read_subdocument)
          s->ov_stack.push_back({sub, ht, wid});
          s->seen_paths.push_back(std::move(sub));
          emit = !dead;
        }
      }
    }

    if (emit) {
      int32_t ksz = s->mode == 2 ? klen + 13 : klen;
      if (kpos + ksz > key_cap || vpos + vlen > val_cap) {
        if (n == 0) return -3;  // transient: retry with larger buffers
        return n;  // batch full; entry stays current for the next call
      }
      memcpy(keys_out + kpos, k, klen);
      kpos += klen;
      if (s->mode == 2) {
        // append the internal-key suffix: kHybridTime + descending
        // 12-byte DocHybridTime (common/hybrid_time.py encoded())
        uint8_t* q = keys_out + kpos;
        q[0] = '#';
        uint64_t hc = ~ht;
        uint32_t wc = ~wid;
        for (int j = 0; j < 8; ++j) q[1 + j] = (uint8_t)(hc >> (56 - 8 * j));
        for (int j = 0; j < 4; ++j) q[9 + j] = (uint8_t)(wc >> (24 - 8 * j));
        kpos += 13;
      }
      key_offs[n + 1] = (int32_t)kpos;
      memcpy(vals_out + vpos, val, vlen);
      vpos += vlen;
      val_offs[n + 1] = vpos;
      ht_out[n] = ht;
      wid_out[n] = wid;
      flags_out[n] = fl;
      dkl_out[n] = dkl;
      ++n;
    }
    if (!s->pop_advance()) return -1;  // corruption mid-scan
  }
  if (s->heap.empty()) s->done = true;
  return n;
}

}  // extern "C"
