"""Table schema: typed columns, hash/range key split.

Capability parity with yb::Schema / ColumnSchema (ref: src/yb/common/schema.h)
and the QL type system (ref: src/yb/common/ql_type.h), trimmed to the types the
doc store supports in round 1.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class DataType(enum.Enum):
    INT32 = "int32"
    INT64 = "int64"
    FLOAT = "float"
    DOUBLE = "double"
    STRING = "string"
    BINARY = "binary"
    BOOL = "bool"
    TIMESTAMP = "timestamp"


class SortingType(enum.Enum):
    ASC = "asc"
    DESC = "desc"


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: DataType
    nullable: bool = True
    sorting: SortingType = SortingType.ASC


@dataclass
class Schema:
    """Columns split into hash-key, range-key and value columns.

    Mirrors the reference's key layout: a 16-bit hash over the hashed columns
    prefixes the key, then hashed columns, then range columns, then value
    columns addressed by column id (ref: docdb/doc_key.h:42-82).
    """

    columns: List[ColumnSchema]
    num_hash_key_columns: int = 0
    num_range_key_columns: int = 0

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError("duplicate column names")
        # Column ids: stable small ints, value columns only (keys are positional).
        nk = self.num_key_columns
        self._column_ids: Dict[str, int] = {
            c.name: i - nk for i, c in enumerate(self.columns) if i >= nk
        }

    @property
    def num_key_columns(self) -> int:
        return self.num_hash_key_columns + self.num_range_key_columns

    @property
    def hash_columns(self) -> List[ColumnSchema]:
        return self.columns[: self.num_hash_key_columns]

    @property
    def range_columns(self) -> List[ColumnSchema]:
        return self.columns[self.num_hash_key_columns: self.num_key_columns]

    @property
    def value_columns(self) -> List[ColumnSchema]:
        return self.columns[self.num_key_columns:]

    def column_id(self, name: str) -> int:
        return self._column_ids[name]

    def column_by_id(self, cid: int) -> ColumnSchema:
        return self.columns[self.num_key_columns + cid]

    def column(self, name: str) -> ColumnSchema:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)
