"""Bucket-health board (PR: robustness): the live device-vs-native
routing authority that replaced the frozen calibration file.

One health record per (kernel family, shape bucket) runs the state
machine COLD -> WARMING -> HEALTHY <-> DEGRADED -> QUARANTINED ->
PROBATION -> HEALTHY, fed by measured rows/s EWMAs, fault events and
sticky shadow mismatches. These tests drive the machine directly on
private board instances (injectable clock for the probe timing), stress
the quarantine registry's timed-decay under churn, round-trip the
persisted board, and — the nemesis proof — throttle ONE shape bucket's
device dispatch with the 'slow' fault kind and watch the full
self-healing cycle: demote, complete natively byte-identical, re-promote
via a winning probe.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_device_fault_containment import (_mk_run, _native_reference,  # noqa: E402
                                           _run_device_native, _sst_bytes,
                                           _write_runs)

from yugabyte_tpu.ops import device_faults, run_merge  # noqa: E402
from yugabyte_tpu.storage import native_engine, offload_policy  # noqa: E402
from yugabyte_tpu.storage.bucket_health import (BucketHealthBoard,  # noqa: E402
                                                health_board)
from yugabyte_tpu.storage.device_cache import host_staging_pool  # noqa: E402
from yugabyte_tpu.utils import flags  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_state():
    device_faults.disarm_all()
    health_board().reset()
    yield
    device_faults.disarm_all()
    health_board().reset()


def _warm(board, fam, b, device_rate=1000.0, native_rate=100.0):
    """Walk a key to a settled post-warmup state: HEALTHY when the
    device rate wins, DEGRADED when native does."""
    board.record_native(fam, b, int(native_rate), 1.0)
    for _ in range(int(flags.get_flag("bucket_health_warmup_obs"))):
        board.record_device(fam, b, int(device_rate), 1.0)
    return board


# -- state machine -----------------------------------------------------


def test_cold_routes_native_then_first_result_warms():
    board = BucketHealthBoard()
    fam, b = "run_merge_fused", (4, 2048)
    assert board.state(fam, b) == "cold"
    # policy gate: COLD routes native (compile cost not amortized)...
    assert not board.use_device(fam, b, est_rows=1000)
    # ...but the containment gate passes — the dispatch IS the warmup
    assert board.allow_device(fam, b)
    board.record_device(fam, b, 1000, 1.0)
    assert board.state(fam, b) == "warming"
    assert board.use_device(fam, b, est_rows=1000)


def test_warmup_guard_blocks_single_sample_demotion():
    board = BucketHealthBoard()
    fam, b = "run_merge_fused", (8, 2048)
    warmup = int(flags.get_flag("bucket_health_warmup_obs"))
    board.record_native(fam, b, 10**6, 1.0)
    for i in range(warmup - 1):
        board.record_device(fam, b, 100, 1.0)  # measured 10,000x slower
        assert board.state(fam, b) == "warming", \
            f"obs {i + 1} < warmup must not demote (cold-compile sample)"
    board.record_device(fam, b, 100, 1.0)
    assert board.state(fam, b) == "degraded"
    assert not board.use_device(fam, b, est_rows=1000)
    assert board.snapshot()["counters"]["demotions"] == 1


def test_healthy_demotes_when_native_ewma_overtakes():
    board = _warm(BucketHealthBoard(), "scan_agg", (1, 4096))
    assert board.state("scan_agg", (1, 4096)) == "healthy"
    assert board.use_device("scan_agg", (1, 4096))
    # the native path speeds up (host upgrade, lighter load): the next
    # native completions overtake the device EWMA and demote the bucket
    for _ in range(3):
        board.record_native("scan_agg", (1, 4096), 10**7, 1.0)
    assert board.state("scan_agg", (1, 4096)) == "degraded"
    snap = board.snapshot()
    assert snap["counters"]["demotions"] == 1
    assert any(t["to"] == "degraded" for t in snap["transitions"])


def test_per_key_isolation():
    board = _warm(BucketHealthBoard(), "run_merge_fused", (4, 2048),
                  device_rate=10.0, native_rate=10**6)  # degraded
    _warm(board, "run_merge_fused", (8, 2048))          # healthy
    assert board.state("run_merge_fused", (4, 2048)) == "degraded"
    assert board.state("run_merge_fused", (8, 2048)) == "healthy"
    assert board.use_device("run_merge_fused", (8, 2048))
    assert not board.use_device("run_merge_fused", (4, 2048))
    # same bucket under another family is its own record
    assert board.state("block_decode", (4, 2048)) == "cold"


# -- probe gate --------------------------------------------------------


def test_probe_gate_single_flight_backoff_and_native_gap():
    tnow = [1000.0]
    board = BucketHealthBoard(clock=lambda: tnow[0])
    fam, b = "scan_filtered", (1, 4096)
    _warm(board, fam, b, device_rate=100.0, native_rate=10**9)
    assert board.state(fam, b) == "degraded"
    interval = float(flags.get_flag("bucket_health_probe_interval_s"))

    # demotion stamps last_probe_t: the first probe waits a full interval
    assert not board.allow_device(fam, b)
    tnow[0] += interval + 1
    assert board.allow_device(fam, b), "probe slot must open"
    # single flight: a concurrent thread is refused while it's pending...
    got = []
    t = threading.Thread(target=lambda: got.append(board.allow_device(fam, b)))
    t.start()
    t.join()
    assert got == [False]
    # ...but the claiming thread (the probing job re-checks) passes
    assert board.allow_device(fam, b)

    # the probe LOSES: backoff doubles and a native gap is forced
    board.record_device(fam, b, 100, 1.0)
    assert not board.allow_device(fam, b), "native gap after a lost probe"
    tnow[0] += interval + 1
    assert not board.allow_device(fam, b), "backoff x2 not yet elapsed"
    tnow[0] += interval + 1
    assert board.allow_device(fam, b), "second probe after 2x interval"
    board.record_device(fam, b, 100, 1.0)  # loses again -> backoff x4

    # the probe WINS: backoff resets and the bucket is promoted
    tnow[0] += 4 * interval + 1
    assert not board.allow_device(fam, b)  # the forced native gap
    assert board.allow_device(fam, b)
    board.record_device(fam, b, 10**12, 0.001)
    assert board.state(fam, b) == "healthy"
    snap = board.snapshot()["counters"]
    assert snap["probes"] == 3
    assert snap["probe_failures"] == 2
    assert snap["promotions"] == 1


def test_probe_timeout_releases_wedged_slot():
    tnow = [1000.0]
    board = BucketHealthBoard(clock=lambda: tnow[0])
    fam, b = "point_read_locate", (1, 2048)
    _warm(board, fam, b, device_rate=100.0, native_rate=10**9)
    interval = float(flags.get_flag("bucket_health_probe_interval_s"))
    tnow[0] += interval + 1
    assert board.allow_device(fam, b)  # probe claimed, then the job dies
    from yugabyte_tpu.storage import bucket_health as bh
    tnow[0] += bh._PROBE_TIMEOUT_S + 1
    got = []
    t = threading.Thread(target=lambda: got.append(board.allow_device(fam, b)))
    t.start()
    t.join()
    assert got == [True], "a silently-dead probe must not wedge the bucket"


# -- fault / quarantine / mismatch ------------------------------------


def test_fault_quarantine_decays_to_probation_then_healthy():
    board = BucketHealthBoard()
    fam, b = "point_read_locate", (1, 2048)
    board.record_device(fam, b, 1000, 1.0)  # warming
    board.record_fault(fam, b, "RESOURCE_EXHAUSTED: hbm oom", ttl_s=0.05)
    assert board.state(fam, b) == "quarantined"
    assert not board.allow_device(fam, b)
    time.sleep(0.08)
    assert board.allow_device(fam, b), "decayed window re-proves on device"
    assert board.state(fam, b) == "probation"
    for _ in range(int(flags.get_flag("bucket_health_probation_obs"))):
        board.record_device(fam, b, 1000, 1.0)
    assert board.state(fam, b) == "healthy"
    snap = board.snapshot()["counters"]
    assert snap["quarantines"] == 1
    assert snap["promotions"] == 1


def test_fault_during_probation_requarantines():
    board = BucketHealthBoard()
    fam, b = "block_encode", (1, 4096)
    board.record_fault(fam, b, "boom", ttl_s=0.05)
    time.sleep(0.08)
    assert board.allow_device(fam, b)
    assert board.state(fam, b) == "probation"
    board.record_fault(fam, b, "boom again", ttl_s=60.0)
    assert board.state(fam, b) == "quarantined"
    assert not board.allow_device(fam, b)
    snap = board.snapshot()
    assert snap["counters"]["quarantines"] == 2
    assert snap["keys"][0]["faults"] == 2


def test_mismatch_sticky_until_operator_clear():
    old = flags.get_flag("device_fault_quarantine_s")
    flags.set_flag("device_fault_quarantine_s", 0.05)
    board = BucketHealthBoard()
    fam, b = "block_decode", (1, 4096)
    try:
        board.record_mismatch(fam, b, "digest mismatch vs native oracle")
        assert board.state(fam, b) == "quarantined"
        assert not board.allow_device(fam, b)
        time.sleep(0.08)  # the TIMED window decays...
        assert not board.allow_device(fam, b), \
            "sticky mismatch must outlive the timed quarantine window"
        assert board.state(fam, b) == "quarantined"
        assert board.clear_mismatch() == 1
        assert board.state(fam, b) == "probation"
        assert board.allow_device(fam, b)
        for _ in range(int(flags.get_flag("bucket_health_probation_obs"))):
            board.record_device(fam, b, 1000, 1.0)
        assert board.state(fam, b) == "healthy"
        assert board.snapshot()["counters"]["mismatch"] == 1
    finally:
        flags.set_flag("device_fault_quarantine_s", old)


def test_quarantine_rearm_survives_decay_churn():
    """PR 16 timed-decay race regression: is_quarantined used to read
    the clock OUTSIDE the registry lock, letting a decay check race a
    concurrent re-arm. Under heavy churn of expiring windows, a freshly
    re-armed LONG window must never be reported open-for-device."""
    q = offload_policy.BucketQuarantine()
    b = (4, 2048)
    stop = threading.Event()

    def churn():
        while not stop.is_set():
            q.open_window(b)

    threads = [threading.Thread(target=churn) for _ in range(4)]
    for t in threads:
        t.start()
    lost_at = None
    try:
        for i in range(200):
            q.quarantine(b, "short", ttl_s=0.0003)
            time.sleep(0.0006)  # decays under churn
            q.quarantine(b, "long", ttl_s=60.0)
            if not q.is_quarantined(b):
                lost_at = i
                break
            q.clear()
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert lost_at is None, \
        f"round {lost_at}: churn deleted a freshly re-armed window"


def test_legacy_quarantine_clear_resets_whole_board():
    """Every legacy fixture isolates itself with
    bucket_quarantine().clear() — that must wipe the WHOLE board, not
    leave buckets demoted from the previous test."""
    board = health_board()
    _warm(board, "run_merge_fused", (4, 2048),
          device_rate=10.0, native_rate=10**6)
    board.record_fault("scan_agg", (1, 4096), "boom", ttl_s=60.0)
    assert board.state("run_merge_fused", (4, 2048)) == "degraded"
    offload_policy.bucket_quarantine().clear()
    assert board.state("run_merge_fused", (4, 2048)) == "cold"
    assert board.state("scan_agg", (1, 4096)) == "cold"
    snap = board.snapshot()
    assert snap["keys"] == [] and snap["quarantine"] == []
    assert all(v == 0 for v in snap["counters"].values())


# -- persistence -------------------------------------------------------


def test_persistence_round_trip(tmp_path):
    path = str(tmp_path / "bucket_health.json")
    b1 = BucketHealthBoard()
    # a HEALTHY key with live rates
    _warm(b1, "run_merge_fused", (4, 2048))
    # a QUARANTINED key mid-window
    b1.record_fault("scan_filtered", (1, 4096), "hbm oom", ttl_s=60.0)
    # a sticky-mismatch key
    b1.record_mismatch("block_decode", (1, 8192), "digest mismatch")
    # a COLD key that only saw traffic
    assert not b1.use_device("dist_compact", (4, 1 << 20), est_rows=10)
    b1.save(path)

    b2 = BucketHealthBoard()
    assert b2.load(path) == 4
    # quarantine resumes its remaining decay window
    assert b2.state("scan_filtered", (1, 4096)) == "quarantined"
    assert not b2.allow_device("scan_filtered", (1, 4096))
    # sticky mismatch stays sticky (no timed decay)
    assert b2.state("block_decode", (1, 8192)) == "quarantined"
    assert not b2.allow_device("block_decode", (1, 8192))
    snap = {(k["family"], tuple(k["bucket"])): k
            for k in b2.snapshot()["keys"]}
    assert "mismatch" in snap[("block_decode", (1, 8192))]
    # the healthy key restarts WARMING with rates CLEARED — a restarted
    # process re-measures instead of routing on last run's numbers
    assert b2.state("run_merge_fused", (4, 2048)) == "warming"
    rec = snap[("run_merge_fused", (4, 2048))]
    assert rec["device_obs"] == 0 and rec["device_rows_per_sec"] == 0.0
    assert rec["native_obs"] == 0 and rec["native_rows_per_sec"] == 0.0
    # COLD stays COLD; fault/traffic tallies survive
    assert b2.state("dist_compact", (4, 1 << 20)) == "cold"
    assert snap[("dist_compact", (4, 1 << 20))]["traffic"] == 1
    assert snap[("scan_filtered", (1, 4096))]["faults"] == 1


def test_load_missing_or_corrupt_is_cold_start(tmp_path):
    board = BucketHealthBoard()
    assert board.load(str(tmp_path / "nope.json")) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert board.load(str(bad)) == 0
    assert board.snapshot()["keys"] == []


# -- prewarm feed ------------------------------------------------------


def test_prewarm_priorities_traffic_order_and_prewarmed_transition():
    board = BucketHealthBoard()
    for _ in range(3):
        board.use_device("run_merge_fused", (8, 2048))
    board.use_device("run_merge_fused", (4, 2048))
    for _ in range(2):
        board.use_device("scan_filtered", (1, 4096))
    pri = board.prewarm_priorities()
    assert pri[0] == ("run_merge_fused", (8, 2048))
    assert pri[1] == ("scan_filtered", (1, 4096))
    # the prewarm op pays the compile: COLD -> WARMING, off the list,
    # and the policy gate stops forcing native
    board.record_prewarmed("run_merge_fused", (8, 2048))
    assert board.state("run_merge_fused", (8, 2048)) == "warming"
    assert ("run_merge_fused", (8, 2048)) not in board.prewarm_priorities()
    assert board.use_device("run_merge_fused", (8, 2048))


# -- the 'slow' nemesis kind ------------------------------------------


def test_slow_kind_bucket_pinning():
    device_faults.arm("slow", "dispatch", count=1, delay_s=0.05,
                      bucket=(4, 2048))
    # bucket-less call sites skip pinned entries
    t0 = time.monotonic()
    device_faults.maybe_fault("dispatch")
    assert time.monotonic() - t0 < 0.04
    assert device_faults.armed_count() == 1
    # wrong bucket: skipped
    device_faults.maybe_fault("dispatch", bucket=(8, 2048))
    assert device_faults.armed_count() == 1
    # match: sleeps without raising, consumed
    t0 = time.monotonic()
    device_faults.maybe_fault("dispatch", bucket=(4, 2048))
    assert time.monotonic() - t0 >= 0.045
    assert device_faults.armed_count() == 0
    # an unpinned slow entry fires anywhere
    device_faults.arm("slow", "dispatch", count=1, delay_s=0.05)
    t0 = time.monotonic()
    device_faults.maybe_fault("dispatch")
    assert time.monotonic() - t0 >= 0.045
    assert device_faults.armed_count() == 0


def test_slow_stacks_with_loud_fault():
    """A slow AND faulty device is expressible: the slow entry sleeps,
    then the loud entry raises on the SAME call; both are consumed."""
    device_faults.arm("slow", "dispatch", count=1, delay_s=0.05)
    device_faults.arm("runtime", "dispatch", count=1)
    t0 = time.monotonic()
    with pytest.raises(Exception):
        device_faults.maybe_fault("dispatch")
    assert time.monotonic() - t0 >= 0.045
    assert device_faults.armed_count() == 0


# -- the self-healing cycle, end to end -------------------------------


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_slow_bucket_demotes_completes_native_and_repromotes(tmp_path):
    """The nemesis proof: throttle ONE shape bucket's device dispatch
    (no exception — just latency), watch the board demote it on the
    measured rate crossover, verify the parked job completes natively
    BYTE-IDENTICAL without touching the device, then clear the
    slowness and watch a winning probe re-promote the bucket."""
    board = health_board()
    rng = np.random.default_rng(21)
    runs = [_mk_run(rng, 1200, 5000) for _ in range(4)]
    readers = _write_runs(str(tmp_path), runs)
    qkey = offload_policy.bucket_key(
        run_merge.packed_run_ns([r.props.n_entries for r in readers]))
    old_interval = flags.get_flag("bucket_health_probe_interval_s")
    try:
        res_native = _native_reference(readers, str(tmp_path / "native"))
        # seed an astronomically fast native EWMA so the throttled
        # device path deterministically loses the rate race
        board.record_native("run_merge_fused", qkey, 10**9, 1.0)
        device_faults.arm("slow", "dispatch", count=1000, delay_s=0.05,
                          bucket=qkey)
        warmup = int(flags.get_flag("bucket_health_warmup_obs"))
        for i in range(warmup):
            res = _run_device_native(readers, str(tmp_path / f"slow{i}"),
                                     first_id=1000 * (i + 1))
            assert _sst_bytes(res.outputs) == _sst_bytes(res_native.outputs)
        assert board.state("run_merge_fused", qkey) == "degraded"
        assert device_faults.armed_count() < 1000, \
            "the pinned slow nemesis must actually have fired"

        # DEGRADED parks the next job at the containment gate: native
        # completion, byte-identical, and the still-armed slow entries
        # never fire — proof no device dispatch happened
        armed_before = device_faults.armed_count()
        res_parked = _run_device_native(readers, str(tmp_path / "parked"),
                                        first_id=7000)
        assert _sst_bytes(res_parked.outputs) == _sst_bytes(res_native.outputs)
        assert device_faults.armed_count() == armed_before, \
            "a parked job must not dispatch the device"
        assert host_staging_pool().outstanding() == 0

        # the device recovers: drag the seeded native EWMA back below
        # the measured device rate, then let a probe run and win
        device_faults.disarm_all()
        for _ in range(80):
            board.record_native("run_merge_fused", qkey, 1, 100.0)
        flags.set_flag("bucket_health_probe_interval_s", 0.0)
        res_probe = _run_device_native(readers, str(tmp_path / "probe"),
                                       first_id=9000)
        assert _sst_bytes(res_probe.outputs) == _sst_bytes(res_native.outputs)
        assert board.state("run_merge_fused", qkey) == "healthy", \
            "the winning probe must re-promote the bucket"
        tally = board.snapshot()["counters"]
        assert tally["demotions"] >= 1
        assert tally["probes"] >= 1
        assert tally["promotions"] >= 1
        assert host_staging_pool().outstanding() == 0
    finally:
        device_faults.disarm_all()
        flags.set_flag("bucket_health_probe_interval_s", old_interval)
        for r in readers:
            r.close()
