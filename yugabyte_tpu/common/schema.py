"""Table schema: typed columns, hash/range key split.

Capability parity with yb::Schema / ColumnSchema (ref: src/yb/common/schema.h)
and the QL type system (ref: src/yb/common/ql_type.h), trimmed to the types the
doc store supports in round 1.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class DataType(enum.Enum):
    INT32 = "int32"
    INT64 = "int64"
    FLOAT = "float"
    DOUBLE = "double"
    STRING = "string"
    BINARY = "binary"
    BOOL = "bool"
    TIMESTAMP = "timestamp"
    # JSONB documents: stored as canonical compact JSON text (object keys
    # sorted) — the functional equivalent of the reference's binary jsonb
    # serialization, which also sorts object keys for searchability
    # (ref: src/yb/common/jsonb.h:40-44). Path navigation happens in the
    # query layer (-> / ->> operators).
    JSONB = "jsonb"


class SortingType(enum.Enum):
    ASC = "asc"
    DESC = "desc"


@dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: DataType
    nullable: bool = True
    sorting: SortingType = SortingType.ASC
    # ALTER TABLE DROP COLUMN keeps the slot (PG's attisdropped): value
    # columns are addressed by POSITION-derived ids, so removing the slot
    # would shift every later column onto its neighbor's stored data
    dropped: bool = False
    # YCQL collection columns (LIST<T>/SET<T>/MAP<K,V>): ("list", "INT"),
    # ("set", "TEXT"), ("map", "TEXT", "INT"). Storage rides subdocuments
    # (docdb/subdocument.py); `type` stays the element-agnostic BINARY
    # (ref: common/ql_type.h collection types)
    collection: Optional[Tuple[str, ...]] = None
    # SERIAL columns: name of the master-backed sequence supplying the
    # default when an INSERT omits the column (ref: PG pg_attrdef +
    # sequence.c; YSQL's serial -> nextval default)
    default_seq: Optional[str] = None


@dataclass
class Schema:
    """Columns split into hash-key, range-key and value columns.

    Mirrors the reference's key layout: a 16-bit hash over the hashed columns
    prefixes the key, then hashed columns, then range columns, then value
    columns addressed by column id (ref: docdb/doc_key.h:42-82).
    """

    columns: List[ColumnSchema]
    num_hash_key_columns: int = 0
    num_range_key_columns: int = 0

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise ValueError("duplicate column names")
        # Column ids: stable small ints, value columns only (keys are
        # positional). Dropped slots keep their position (so ids of later
        # columns never shift) but are not addressable by name.
        nk = self.num_key_columns
        self._column_ids: Dict[str, int] = {
            c.name: i - nk for i, c in enumerate(self.columns)
            if i >= nk and not c.dropped
        }

    @property
    def num_key_columns(self) -> int:
        return self.num_hash_key_columns + self.num_range_key_columns

    @property
    def hash_columns(self) -> List[ColumnSchema]:
        return self.columns[: self.num_hash_key_columns]

    @property
    def range_columns(self) -> List[ColumnSchema]:
        return self.columns[self.num_hash_key_columns: self.num_key_columns]

    @property
    def value_columns(self) -> List[ColumnSchema]:
        return [c for c in self.columns[self.num_key_columns:]
                if not c.dropped]

    def column_id(self, name: str) -> int:
        return self._column_ids[name]

    def column_by_id(self, cid: int) -> ColumnSchema:
        return self.columns[self.num_key_columns + cid]

    def column(self, name: str) -> ColumnSchema:
        for c in self.columns:
            if c.name == name and not c.dropped:
                return c
        raise KeyError(name)

    # ------------------------------------------------- schema evolution
    def with_added_column(self, name: str, type: DataType,
                          nullable: bool = True) -> "Schema":
        """ALTER TABLE ADD COLUMN: appended at the end — existing
        position-derived column ids are untouched, so no data rewrite
        (ref: the reference's online schema change, catalog_manager
        AlterTable + per-tablet schema version)."""
        if any(c.name == name and not c.dropped for c in self.columns):
            raise ValueError(f'column "{name}" already exists')
        return Schema(columns=self.columns + [ColumnSchema(name, type,
                                                           nullable)],
                      num_hash_key_columns=self.num_hash_key_columns,
                      num_range_key_columns=self.num_range_key_columns)

    def with_dropped_column(self, name: str) -> "Schema":
        """ALTER TABLE DROP COLUMN: the slot stays, tombstoned under a
        mangled unique name (PG attisdropped), so later columns keep their
        ids and a future ADD COLUMN may reuse the visible name."""
        from dataclasses import replace as _replace
        nk = self.num_key_columns
        out = list(self.columns)
        for i, c in enumerate(out):
            if c.name == name and not c.dropped:
                if i < nk:
                    raise ValueError(f'cannot drop key column "{name}"')
                out[i] = _replace(c, name=f"!dropped!{i}!{name}",
                                  dropped=True)
                return Schema(columns=out,
                              num_hash_key_columns=self.num_hash_key_columns,
                              num_range_key_columns=self.num_range_key_columns)
        raise KeyError(name)
