"""Minimal CQL native protocol v4 client — the test-side counterpart of
yql/cql/binary_server.py, speaking the same frames a Cassandra driver
would (STARTUP/QUERY/PREPARE/EXECUTE/BATCH with typed values)."""

from __future__ import annotations

import socket
import struct
from typing import Dict, List, Optional, Tuple

from yugabyte_tpu.common.schema import DataType
from yugabyte_tpu.yql.cql import wire as W


class CqlError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"[{code:#06x}] {message}")
        self.code = code


class Rows:
    def __init__(self, columns, types, rows, paging_state=None):
        self.columns = columns
        self.types = types
        self.rows = rows
        self.paging_state = paging_state


class CqlWireClient:
    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port), timeout=30)
        self._stream = 0
        body = W.w_string_map({"CQL_VERSION": "3.4.4"})
        op, _ = self._request(W.OP_STARTUP, body)
        assert op == W.OP_READY, f"unexpected startup response {op:#x}"

    def close(self) -> None:
        self._sock.close()

    # ------------------------------------------------------------ plumbing
    def _request(self, opcode: int, body: bytes = b"") -> Tuple[int, bytes]:
        self._stream = (self._stream + 1) % 32000
        self._sock.sendall(W.frame(W.VERSION_REQUEST, self._stream, opcode,
                                   body))
        version, stream, op, rbody = W.read_frame(self._sock)
        assert version == W.VERSION_RESPONSE and stream == self._stream
        if op == W.OP_ERROR:
            r = W.Reader(rbody)
            raise CqlError(r.i32(), r.string())
        return op, rbody

    @staticmethod
    def _read_metadata(r: W.Reader):
        flags = r.i32()
        n = r.i32()
        paging_state = r.bytes_() if flags & 0x02 else None
        global_spec = bool(flags & 0x01)
        if global_spec:
            r.string()
            r.string()
        cols = []
        for _ in range(n):
            if not global_spec:
                r.string()
                r.string()
            name = r.string()
            tid = r.u16()
            cols.append((name, tid))
        return cols, paging_state

    def _parse_result(self, body: bytes):
        r = W.Reader(body)
        kind = r.i32()
        if kind == W.RESULT_VOID:
            return None
        if kind == W.RESULT_SET_KEYSPACE:
            return r.string()
        if kind == W.RESULT_SCHEMA_CHANGE:
            return ("schema_change", r.string(), r.string())
        if kind == W.RESULT_PREPARED:
            pid = r.short_bytes()
            r.i32()  # flags
            n = r.i32()
            pk_count = r.i32()
            for _ in range(pk_count):
                r.u16()
            types = []
            for _ in range(n):
                r.string()
                r.string()
                r.string()
                types.append(r.u16())
            return ("prepared", pid, types)
        if kind == W.RESULT_ROWS:
            cols, paging_state = self._read_metadata(r)
            n_rows = r.i32()
            by_tid = {W.TYPE_INT: DataType.INT32,
                      W.TYPE_BIGINT: DataType.INT64,
                      W.TYPE_BOOLEAN: DataType.BOOL,
                      W.TYPE_DOUBLE: DataType.DOUBLE,
                      W.TYPE_FLOAT: DataType.FLOAT,
                      W.TYPE_BLOB: DataType.BINARY,
                      W.TYPE_TIMESTAMP: DataType.TIMESTAMP}
            rows = []
            for _ in range(n_rows):
                row = []
                for _name, tid in cols:
                    dt = by_tid.get(tid, DataType.STRING)
                    row.append(W.decode_value(r.bytes_(), dt))
                rows.append(row)
            return Rows([c for c, _ in cols], [t for _, t in cols], rows,
                        paging_state)
        raise AssertionError(f"unknown result kind {kind}")

    # ------------------------------------------------------------- surface
    def execute(self, query: str, params: Optional[List[Tuple[object,
                DataType]]] = None, page_size: Optional[int] = None,
                paging_state: Optional[bytes] = None):
        """params: (value, DataType) pairs, encoded exactly as a driver
        would from the prepared metadata (QUERY carries typed values).
        page_size/paging_state drive the v4 paging protocol."""
        flags = (0x01 if params else 0) | \
            (0x04 if page_size is not None else 0) | \
            (0x08 if paging_state is not None else 0)
        body = [W.w_long_string(query), struct.pack(">H", 1),  # consistency
                bytes([flags])]
        if params:
            body.append(struct.pack(">H", len(params)))
            for v, dt in params:
                body.append(W.w_bytes(W.encode_value(v, dt)))
        if page_size is not None:
            body.append(struct.pack(">i", page_size))
        if paging_state is not None:
            body.append(W.w_bytes(paging_state))
        op, rbody = self._request(W.OP_QUERY, b"".join(body))
        assert op == W.OP_RESULT
        return self._parse_result(rbody)

    def prepare(self, query: str):
        op, rbody = self._request(W.OP_PREPARE, W.w_long_string(query))
        assert op == W.OP_RESULT
        kind, pid, types = self._parse_result(rbody)
        assert kind == "prepared"
        return pid, types

    def execute_prepared(self, pid: bytes, values: List[Tuple[object,
                         DataType]]):
        body = [W.w_short_bytes(pid), struct.pack(">H", 1)]
        if values:
            body.append(bytes([0x01]))
            body.append(struct.pack(">H", len(values)))
            for v, dt in values:
                body.append(W.w_bytes(W.encode_value(v, dt)))
        else:
            body.append(bytes([0x00]))
        op, rbody = self._request(W.OP_EXECUTE, b"".join(body))
        assert op == W.OP_RESULT
        return self._parse_result(rbody)

    def batch(self, items: List[Tuple[str, List[Tuple[object, DataType]]]]
              ) -> None:
        body = [bytes([0]), struct.pack(">H", len(items))]
        for text, values in items:
            body.append(bytes([0]))
            body.append(W.w_long_string(text))
            body.append(struct.pack(">H", len(values)))
            for v, dt in values:
                body.append(W.w_bytes(W.encode_value(v, dt)))
        body.append(struct.pack(">H", 1))
        op, rbody = self._request(W.OP_BATCH, b"".join(body))
        assert op == W.OP_RESULT
        return self._parse_result(rbody)

    def options(self) -> Dict[str, List[str]]:
        op, rbody = self._request(W.OP_OPTIONS)
        assert op == W.OP_SUPPORTED
        r = W.Reader(rbody)
        out = {}
        for _ in range(r.u16()):
            k = r.string()
            out[k] = [r.string() for _ in range(r.u16())]
        return out
