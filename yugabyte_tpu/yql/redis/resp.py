"""RESP2 wire protocol: the real Redis framing.

Capability parity with the reference's parser (ref: src/yb/yql/redis/
redisserver/redis_parser.cc — inline and multi-bulk command forms;
responses as simple strings, errors, integers, bulk and arrays). Any
redis-cli / standard client library speaks this.
"""

from __future__ import annotations

from typing import List, Optional


class ProtocolError(Exception):
    pass


class Reader:
    """Incremental command reader over a socket file object."""

    def __init__(self, sock):
        self._f = sock.makefile("rb")

    def close(self) -> None:
        self._f.close()

    def _line(self) -> bytes:
        line = self._f.readline()
        if not line:
            raise ConnectionError("client closed")
        if not line.endswith(b"\r\n"):
            raise ProtocolError("line without CRLF")
        return line[:-2]

    def read_command(self) -> Optional[List[bytes]]:
        """One command as a list of byte arguments; None on clean EOF."""
        try:
            line = self._line()
        except ConnectionError:
            return None
        if not line:
            return []
        if line[0:1] == b"*":
            n = int(line[1:])
            args = []
            for _ in range(n):
                hdr = self._line()
                if hdr[0:1] != b"$":
                    raise ProtocolError(f"expected bulk, got {hdr!r}")
                size = int(hdr[1:])
                data = self._f.read(size + 2)
                if len(data) != size + 2:
                    raise ConnectionError("short read")
                args.append(data[:-2])
            return args
        # Inline command form (ref redis_parser.cc inline support).
        return line.split()


def simple(s: str) -> bytes:
    return b"+" + s.encode() + b"\r\n"


def error(msg: str) -> bytes:
    return b"-ERR " + msg.encode() + b"\r\n"


def integer(n: int) -> bytes:
    return b":" + str(n).encode() + b"\r\n"


def bulk(data: Optional[bytes]) -> bytes:
    if data is None:
        return b"$-1\r\n"
    return b"$" + str(len(data)).encode() + b"\r\n" + data + b"\r\n"


def array(items: Optional[List[bytes]]) -> bytes:
    """items are already-encoded RESP values."""
    if items is None:
        return b"*-1\r\n"
    return b"*" + str(len(items)).encode() + b"\r\n" + b"".join(items)
