"""DocDB value-type tag bytes.

Capability parity with the reference's ValueType enum (ref:
src/yb/docdb/value_type.h:56-150). Tag bytes are chosen with the same ordering
constraints the reference documents:
 - kGroupEnd ('!') sorts before everything, so a DocKey that is a prefix of
   another sorts first;
 - kHybridTime ('#') sorts below all primitive tags, so SubDocKeys with fewer
   subkeys sort above deeper ones;
 - ascending primitive tags are ordered Null < False < ... < numbers < string
   < True < Tombstone.
We keep only the tags the round-1 doc model needs; the byte values match the
reference where the tag exists there (so ordering reasoning transfers).
"""

from __future__ import annotations

import enum


class ValueType(enum.IntEnum):
    # Key structure markers
    kGroupEnd = ord("!")        # 33: end of hashed / range component group
    kHybridTime = ord("#")      # 35: DocHybridTime follows (end of key)
    # Primitive types, ascending order semantics
    kNullLow = ord("$")         # 36
    kFalse = ord("F")           # 70
    kUInt16Hash = ord("G")      # 71: 2-byte hash prefix of hash-partitioned keys
    kInt32 = ord("H")           # 72
    kInt64 = ord("I")           # 73
    kSystemColumnId = ord("J")  # 74: liveness column etc.
    kColumnId = ord("K")        # 75
    kDouble = ord("D")          # 68
    kFloat = ord("C")           # 67
    kString = ord("S")          # 83
    kTrue = ord("T")            # 84
    kBinary = ord("Y")          # 89: raw-bytes component (type-stable vs kString)
    kTombstone = ord("X")       # 88
    kArrayIndex = ord("[")      # 91
    kObject = ord("{")          # 123: subdocument container value
    kMergeFlags = ord("k")      # 107: value control field: merge flags
    kTTL = ord("t")             # 116: value control field: TTL follows
    kTransactionId = ord("x")   # 120: intent value: transaction id follows
    kWriteId = ord("w")         # 119: intent value control field
    kIntentTypeSet = ord("O")   # 79: intent key: intent type byte follows
    kMaxByte = 0xFF

    @property
    def is_primitive(self) -> bool:
        return self not in (ValueType.kGroupEnd, ValueType.kHybridTime,
                            ValueType.kMergeFlags, ValueType.kTTL,
                            ValueType.kTransactionId, ValueType.kWriteId,
                            ValueType.kIntentTypeSet, ValueType.kMaxByte)
