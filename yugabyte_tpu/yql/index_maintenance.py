"""Query-layer secondary-index maintenance + index-accelerated lookups.

Shared by the YCQL and YSQL executors. Placement mirrors the reference's
YSQL architecture: the query layer issues the index writes as separate ops
inside the statement's distributed transaction (ref:
src/yb/yql/pggate/pg_dml_write.cc building delete+insert index requests;
src/yb/docdb/pgsql_operation.cc applying them), with a read of the old row
first (read-modify-write) to compute which index entries change.
"""

from __future__ import annotations

import time
from typing import Callable, Iterator, List, Optional, Sequence, Tuple

from yugabyte_tpu.client.client import YBClient, YBTable
from yugabyte_tpu.client.transaction import (
    TransactionError, TransactionManager, YBTransaction)
from yugabyte_tpu.common.index import (
    STATE_READABLE, IndexInfo, main_doc_key_from_index_row,
    maintenance_ops)
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp


def table_indexes(table: YBTable) -> List[IndexInfo]:
    return [IndexInfo.from_wire(w) for w in table.indexes]


def txn_write_with_indexes(txn: YBTransaction, table: YBTable,
                           op: QLWriteOp,
                           open_table: Callable[[str], YBTable],
                           old_row_dict=None) -> None:
    """Apply one main-table DML op inside `txn`, maintaining every index
    attached to the table (write-and-delete mode applies from creation).

    old_row_dict: the row's current values when the caller already read
    them in this txn (LWT condition checks) — {} for a known-absent row;
    None means unknown, and the old values are read here."""
    idxs = table_indexes(table)
    old_values: dict = {}
    if idxs:
        if old_row_dict is not None:
            old_values = old_row_dict
        else:
            proj = sorted({c for i in idxs for c in i.columns})
            old = txn.read_row(table, op.doc_key, projection=proj)
            if old is not None:
                old_values = old.to_dict(table.schema)
    txn.write(table, [op])
    for idx in idxs:
        for mop in maintenance_ops(idx, op, old_values):
            txn.write(open_table(idx.index_name), [mop])


def run_in_implicit_txn(txn_manager: TransactionManager, existing_txn,
                        body: Callable, deadline_s: float = 30.0):
    """Statement-level transaction wrapper shared by the query layers.

    Inside an open transaction block, joins it (the block commits later);
    otherwise wraps `body(txn)` in an implicit transaction with the
    standard conflict-retry loop (ref: the reference routes all DML
    through one WriteQuery pipeline with conflict resolution,
    tablet/write_query.cc:412-464)."""
    if existing_txn is not None:
        return body(existing_txn)
    deadline = time.monotonic() + deadline_s
    while True:
        txn = txn_manager.begin()
        try:
            r = body(txn)
            txn.commit()
            return r
        except TransactionError:
            txn.abort()
            if time.monotonic() > deadline:
                raise
            time.sleep(0.02)
        except BaseException:
            txn.abort()
            raise


def write_with_indexes(client: YBClient, txn_manager: TransactionManager,
                       table: YBTable, op: QLWriteOp,
                       open_table: Callable[[str], YBTable],
                       deadline_s: float = 30.0) -> None:
    """Autocommit DML against an indexed table: wrap in an implicit
    distributed transaction (read old row -> main write -> index writes)
    with the standard conflict-retry loop. Tables without indexes take the
    plain single-shard write path."""
    if not table.indexes:
        client.write(table, [op])
        return
    run_in_implicit_txn(
        txn_manager, None,
        lambda txn: txn_write_with_indexes(txn, table, op, open_table),
        deadline_s)


def choose_index(table: YBTable, where: Sequence[Tuple[str, str, object]]
                 ) -> Optional[Tuple[IndexInfo, object, List[Tuple]]]:
    """Pick a readable index matching equality predicates.

    Returns (index, values_tuple, residual_filters) or None — the tuple
    covers the longest equality-bound PREFIX of the index's columns
    (which must include the first, hash-partitioning column). The
    longest usable prefix across candidate indexes wins; unconsumed
    predicates stay in the residual. Only '=' predicates use the index."""
    eq = {}
    for col, op, val in where:
        if op == "=" and isinstance(col, str) and col not in eq:
            eq[col] = val
    best = None
    for idx in table_indexes(table):
        if idx.state != STATE_READABLE or idx.columns[0] not in eq:
            continue
        prefix = []
        for c in idx.columns:
            if c not in eq:
                break
            prefix.append(c)
        if best is None or len(prefix) > len(best[1]):
            best = (idx, prefix)
    if best is None:
        return None
    idx, prefix = best
    consumed = set()
    for c in prefix:
        for j, (col, op, _v) in enumerate(where):
            if j not in consumed and op == "=" and col == c:
                consumed.add(j)
                break
    residual = [w for j, w in enumerate(where) if j not in consumed]
    return idx, tuple(eq[c] for c in prefix), residual


def index_lookup(client: YBClient, table: YBTable, index_table: YBTable,
                 idx: IndexInfo, values, read_ht=None) -> Iterator:
    """Yield main-table rows whose indexed columns equal `values` (a
    tuple over an equality-bound prefix of idx.columns; a bare scalar is
    the single-column form), via the index: one single-partition prefix
    scan of the index table, then point reads of the main rows (ref: the
    reference's index-scan path, pg_select.cc secondary-index request +
    docdb lookups).

    Re-checks the indexed values on the main row: with concurrent writers
    an index entry can be momentarily stale (the reference re-checks row
    versions the same way)."""
    if not isinstance(values, tuple):
        values = (values,)
    idx_schema = index_table.schema
    probe = DocKey(hash_components=(values[0],),
                   range_components=tuple(values[1:]))
    # strip the trailing group-end: entries extend the bound prefix with
    # further range components (remaining indexed cols + the main PK)
    prefix = probe.encode()[:-1]
    hash_probe = DocKey(hash_components=(values[0],))
    rows = client.scan_key_range(
        index_table, index_table.partition_key_for(hash_probe), prefix,
        prefix + b"\xff", read_ht=read_ht)
    cols = idx.columns[:len(values)]
    for irow in rows:
        d = irow.to_dict(idx_schema)
        main_dk = main_doc_key_from_index_row(d, table.schema, idx_schema)
        row = client.read_row(table, main_dk, read_ht=read_ht)
        if row is None:
            continue  # row deleted after the index entry was read
        rd = row.to_dict(table.schema)
        if tuple(rd.get(c) for c in cols) != values:
            continue  # stale entry: the row's values moved on
        yield row
