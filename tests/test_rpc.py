"""RPC layer tests: codec round-trips, messenger calls, error mapping,
concurrency, and a 3-peer Raft group replicating over real loopback sockets
(the reference exercises the same path in rpc/rpc-test.cc and
consensus/raft_consensus-itest)."""

import threading
import time

import pytest

from yugabyte_tpu.rpc.codec import dumps, loads
from yugabyte_tpu.rpc.messenger import (
    Messenger, Proxy, RemoteError, RpcTimeout, ServiceUnavailable)
from yugabyte_tpu.utils.status import Code, Status, StatusError


@pytest.mark.parametrize("obj", [
    None, True, False, 0, 1, -1, 2**64, -(2**70), 3.5, b"", b"\x00\xff" * 10,
    "", "héllo", [], [1, [2, [3]]], {}, {"a": 1, "b": [b"x", None]},
    {1: "int-key", b"b": "bytes-key"},
    {"nested": {"deep": {"deeper": [1.5, True, b"\x80"]}}},
])
def test_codec_roundtrip(obj):
    assert loads(dumps(obj)) == obj


def test_codec_tuple_becomes_list():
    assert loads(dumps((1, 2))) == [1, 2]


def test_codec_rejects_unknown_type():
    with pytest.raises(TypeError):
        dumps(object())


class EchoService:
    def echo(self, x):
        return x

    def add(self, a, b):
        return a + b

    def fail_status(self):
        raise StatusError(Status.NotFound("no such thing"))

    def fail_raise(self):
        raise ValueError("boom")

    def slow(self, delay_s):
        time.sleep(delay_s)
        return "done"


@pytest.fixture
def pair():
    server = Messenger("server")
    server.register_service("echo", EchoService())
    client = Messenger("client")
    yield server, client
    client.shutdown()
    server.shutdown()


def test_basic_call(pair):
    server, client = pair
    assert client.call(server.address, "echo", "add", a=2, b=3) == 5
    assert client.call(server.address, "echo", "echo",
                       x={"k": [b"v", 1]}) == {"k": [b"v", 1]}


def test_proxy(pair):
    server, client = pair
    proxy = Proxy(client, server.address, "echo")
    assert proxy.add(a=10, b=20) == 30


def test_local_bypass(pair):
    server, _ = pair
    # A call addressed to the messenger itself never touches a socket.
    assert server.call(server.address, "echo", "add", a=1, b=1) == 2


def test_status_error_crosses_wire(pair):
    server, client = pair
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "echo", "fail_status")
    assert ei.value.status.code == Code.NOT_FOUND


def test_exception_maps_to_remote_error(pair):
    server, client = pair
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "echo", "fail_raise")
    assert ei.value.status.code == Code.REMOTE_ERROR
    assert "boom" in ei.value.status.message


def test_unknown_service_and_method(pair):
    server, client = pair
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "nope", "x")
    assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
    with pytest.raises(RemoteError) as ei:
        client.call(server.address, "echo", "nope")
    assert ei.value.status.code == Code.NOT_SUPPORTED


def test_timeout_and_connection_survives(pair):
    server, client = pair
    with pytest.raises(RpcTimeout):
        client.call(server.address, "echo", "slow", timeout_s=0.2, delay_s=5)
    # The connection keeps working for later calls.
    assert client.call(server.address, "echo", "add", a=1, b=2) == 3


def test_unreachable_server():
    client = Messenger("client")
    try:
        with pytest.raises(ServiceUnavailable):
            client.call("127.0.0.1:1", "echo", "echo", x=1)
    finally:
        client.shutdown()


def test_concurrent_calls_multiplex(pair):
    server, client = pair
    results = []
    errors = []

    def worker(i):
        try:
            results.append(client.call(server.address, "echo", "add",
                                       a=i, b=i))
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert sorted(results) == [2 * i for i in range(32)]


def test_server_shutdown_fails_pending(pair):
    server, client = pair
    done = threading.Event()
    caught = []

    def worker():
        try:
            client.call(server.address, "echo", "slow", timeout_s=10,
                        delay_s=30)
        except (ServiceUnavailable, RpcTimeout) as e:
            caught.append(e)
        done.set()

    threading.Thread(target=worker, daemon=True).start()
    time.sleep(0.2)
    server.shutdown()
    assert done.wait(timeout=5)
    assert caught


def test_midcall_connection_teardown_fails_calls_immediately(pair):
    """Tearing the client connection down mid-call must fail every
    in-flight call with ServiceUnavailable NOW — a caller must never sit
    out its full timeout_s on a connection known to be dead."""
    server, client = pair
    results = {}
    started = threading.Event()

    def worker():
        t0 = time.monotonic()
        try:
            started.set()
            client.call(server.address, "echo", "slow", timeout_s=60,
                        delay_s=60)
            results["outcome"] = "returned"
        except ServiceUnavailable:
            results["outcome"] = "unavailable"
        except RpcTimeout:
            results["outcome"] = "timeout"
        results["elapsed"] = time.monotonic() - t0

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    started.wait()
    # wait until the call is registered in flight on the connection
    conn = None
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and conn is None:
        with client._conns_lock:
            for c in client._conns.values():
                with c.lock:
                    if c.pending:
                        conn = c
        time.sleep(0.01)
    assert conn is not None, "call never became in-flight"
    conn.close()  # mid-call teardown
    t.join(timeout=10)
    assert not t.is_alive(), "caller still blocked after teardown"
    assert results["outcome"] == "unavailable"
    assert results["elapsed"] < 10, \
        f"caller waited {results['elapsed']:.1f}s — should fail immediately"


# --------------------------------------------------------------- Raft on RPC

def test_raft_over_rpc(tmp_path):
    from yugabyte_tpu.consensus.log import Log
    from yugabyte_tpu.consensus.raft import (
        OP_WRITE, RaftConfig, RaftConsensus)
    from yugabyte_tpu.rpc.consensus_service import RpcTransport

    peers = ["a", "b", "c"]
    messengers = {p: Messenger(p) for p in peers}
    addr_map = {f"{p}/t1": messengers[p].address for p in peers}
    transports = {p: RpcTransport(messengers[p], addr_map.get)
                  for p in peers}

    applied = {p: [] for p in peers}
    nodes = {}
    for p in peers:
        d = tmp_path / p
        d.mkdir()
        cfg = RaftConfig(peer_id=f"{p}/t1",
                         peer_ids=tuple(f"{q}/t1" for q in peers))
        node = RaftConsensus(
            cfg, Log(str(d / "wal")), transports[p],
            apply_cb=lambda m, p=p: applied[p].append(m.payload),
            meta_path=str(d / "meta.json"))
        transports[p].register(cfg.peer_id, node)
        nodes[p] = node

    try:
        nodes["a"].start(election_timer=False)
        nodes["a"].start_election(ignore_lease=True)
        deadline = time.monotonic() + 10
        while not nodes["a"].is_leader():
            assert time.monotonic() < deadline, "leader election stalled"
            time.sleep(0.01)
        for i in range(20):
            nodes["a"].replicate(OP_WRITE, i + 1, b"payload-%d" % i,
                                 timeout_s=10)
        deadline = time.monotonic() + 10
        while any(len(applied[p]) < 20 for p in peers):
            assert time.monotonic() < deadline, \
                f"replication stalled: { {p: len(applied[p]) for p in peers} }"
            time.sleep(0.01)
        for p in peers:
            assert applied[p] == [b"payload-%d" % i for i in range(20)]
    finally:
        for node in nodes.values():
            node.shutdown()
        for m in messengers.values():
            m.shutdown()


class TestTLS:
    @pytest.fixture()
    def tls_flags(self, tmp_path):
        """Self-signed cert acting as its own CA; mutual TLS both ways."""
        import subprocess
        cert = str(tmp_path / "node.crt")
        key = str(tmp_path / "node.key")
        base = ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
                "-keyout", key, "-out", cert, "-days", "1",
                "-subj", "/CN=ybtpu-test"]
        # Both OpenSSL 1.1.1 and 3.x default `req -x509` to a CA:TRUE
        # cert. Passing -addext basicConstraints on 1.1.1 DUPLICATES the
        # extension (the default config also adds it) and chain
        # verification then rejects the cert — so generate plain, verify
        # it can act as its own issuer, and only add the extension
        # explicitly if some build leaves it out.
        subprocess.run(base, check=True, capture_output=True)
        ok = subprocess.run(["openssl", "verify", "-CAfile", cert, cert],
                            capture_output=True)
        if ok.returncode != 0:
            subprocess.run(
                base + ["-addext", "basicConstraints=critical,CA:TRUE"],
                check=True, capture_output=True)
        from yugabyte_tpu.utils import flags
        olds = {f: flags.get_flag(f) for f in
                ("rpc_use_tls", "rpc_tls_cert_file", "rpc_tls_key_file",
                 "rpc_tls_ca_file")}
        flags.set_flag("rpc_use_tls", True)
        flags.set_flag("rpc_tls_cert_file", cert)
        flags.set_flag("rpc_tls_key_file", key)
        flags.set_flag("rpc_tls_ca_file", cert)
        yield
        for f, v in olds.items():
            flags.set_flag(f, v)

    def test_mutual_tls_rpc(self, tls_flags):
        """Calls ride mutual TLS end-to-end (ref node-to-node encryption,
        rpc/secure_stream.cc)."""
        a = Messenger("tls-a")
        b = Messenger("tls-b")
        try:
            class Svc:
                def echo(self, x):
                    return {"got": x}
            b.register_service("s", Svc())
            assert a.call(b.address, "s", "echo", x=41) == {"got": 41}
            # multiple calls reuse the TLS connection
            for i in range(5):
                assert a.call(b.address, "s", "echo", x=i)["got"] == i
        finally:
            a.shutdown()
            b.shutdown()

    def test_plaintext_client_rejected(self, tls_flags):
        """A non-TLS peer cannot talk to a TLS server."""
        import socket as pysock
        import struct as pystruct
        b = Messenger("tls-only")
        try:
            class Svc:
                def echo(self, x):
                    return {"got": x}
            b.register_service("s", Svc())
            raw = pysock.create_connection((b.host, b.port), timeout=5)
            try:
                payload = b'{"id":1,"svc":"s","mth":"echo","args":{"x":1}}'
                raw.sendall(pystruct.pack("<I", len(payload)) + payload)
                raw.settimeout(2)
                with pytest.raises((ConnectionError, OSError)):
                    data = raw.recv(4)
                    if not data:
                        raise ConnectionError("closed")
            finally:
                raw.close()
        finally:
            b.shutdown()

    def test_tls_cluster_end_to_end(self, tls_flags, tmp_path):
        """A full MiniCluster (master + tserver + client) over mutual TLS."""
        from yugabyte_tpu.client.session import YBSession
        from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
        from yugabyte_tpu.docdb.doc_key import DocKey
        from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
        from yugabyte_tpu.integration.mini_cluster import (
            MiniCluster, MiniClusterOptions)
        from yugabyte_tpu.utils import flags as _flags
        old_rf = _flags.get_flag("replication_factor")
        _flags.set_flag("replication_factor", 1)
        c = MiniCluster(MiniClusterOptions(
            num_masters=1, num_tservers=1,
            fs_root=str(tmp_path / "fs"))).start()
        try:
            client = c.new_client()
            client.create_namespace("db")
            schema = Schema(columns=[ColumnSchema("k", DataType.STRING),
                                     ColumnSchema("v", DataType.STRING)],
                            num_hash_key_columns=1)
            table = client.create_table("db", "kv", schema, num_tablets=2)
            c.wait_all_replicas_running(table.table_id)
            s = YBSession(client)
            s.apply(table, QLWriteOp(WriteOpKind.INSERT,
                                     DocKey(hash_components=("tls",)),
                                     {"v": "secure"}))
            s.flush()
            row = client.read_row(table, DocKey(hash_components=("tls",)))
            assert row is not None
        finally:
            c.shutdown()
            _flags.set_flag("replication_factor", old_rf)

    def test_tls_concurrent_calls_one_connection(self, tls_flags):
        """Many in-flight calls multiplexed on ONE TLS connection: reads
        and writes interleave (OpenSSL forbids concurrent SSL_read/
        SSL_write on one SSL*; the duplex adapter serializes them)."""
        import threading as _t
        a = Messenger("tls-cc-a")
        b = Messenger("tls-cc-b")
        try:
            class Svc:
                def echo(self, x):
                    import time as _time
                    _time.sleep(0.002)
                    return {"got": x}
            b.register_service("s", Svc())
            errors = []

            def worker(base):
                try:
                    for i in range(25):
                        r = a.call(b.address, "s", "echo", x=base + i)
                        assert r["got"] == base + i
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
            threads = [_t.Thread(target=worker, args=(k * 1000,))
                       for k in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errors, errors
        finally:
            a.shutdown()
            b.shutdown()

    def test_tls_shutdown_closes_inbound(self, tls_flags):
        """shutdown() must tear down wrapped inbound TLS connections (the
        raw fd is detached by wrap_socket)."""
        a = Messenger("tls-sd-a")
        b = Messenger("tls-sd-b")

        class Svc:
            def echo(self, x):
                return {"got": x}
        b.register_service("s", Svc())
        assert a.call(b.address, "s", "echo", x=1)["got"] == 1
        assert all(getattr(c, "fileno", lambda: 1)() != -1
                   or True for c in b._inbound)  # sanity: list non-empty
        b.shutdown()
        # the client's next call must fail fast (connection actually died)
        with pytest.raises(Exception):
            a.call(b.address, "s", "echo", x=2, timeout_s=3.0)
        a.shutdown()


class TestSidecars:
    """Zero-copy bulk segments (ref: rpc/rpc_context.h AddRpcSidecar —
    remote bootstrap chunks, CDC batches, big scan pages)."""

    def test_codec_sidecar_roundtrip(self):
        from yugabyte_tpu.rpc.codec import (dumps_with_sidecars,
                                            loads_with_sidecars)
        big = b"\x01\x02" * 40_000
        obj = {"small": b"tiny", "big": big,
               "nested": [b"x" * 100_000, {"k": big}], "n": 7}
        payload, scs = dumps_with_sidecars(obj, 64 << 10)
        assert len(scs) == 3
        assert len(payload) < 200  # bulk never enters the tagged payload
        back = loads_with_sidecars(payload, [bytes(s) for s in scs])
        assert back == obj

    def test_codec_below_threshold_inline(self):
        from yugabyte_tpu.rpc.codec import dumps_with_sidecars
        payload, scs = dumps_with_sidecars({"v": b"x" * 100}, 64 << 10)
        assert scs == []
        assert loads(payload) == {"v": b"x" * 100}

    def test_big_payload_rides_segments(self, pair):
        from yugabyte_tpu.rpc import messenger as M
        server, client = pair
        blob = bytes(range(256)) * 4096  # 1 MB
        before = M.sidecar_frames_sent
        got = client.call(server.address, "echo", "echo", x=blob)
        assert got == blob
        # request AND response each moved the blob as a segment
        assert M.sidecar_frames_sent >= before + 2

    def test_remote_bootstrap_chunks_use_segment_path(self, tmp_path):
        """A bulk file fetch must take the sidecar path, not the tagged
        codec (VERDICT r4 #7: bootstrap paid full serialize/copy)."""
        import os
        from yugabyte_tpu.rpc import messenger as M
        from yugabyte_tpu.tserver.remote_bootstrap import FETCH_CHUNK

        class FileService:
            def fetch(self, path, offset, length):
                with open(path, "rb") as f:
                    f.seek(offset)
                    return f.read(min(length, FETCH_CHUNK))

        server = Messenger("src")
        server.register_service("files", FileService())
        client = Messenger("dst")
        try:
            src = tmp_path / "tablet.sst"
            src.write_bytes(os.urandom(4 << 20))  # 4 MB "tablet"
            before_frames = M.sidecar_frames_sent
            before_bytes = M.sidecar_bytes_sent
            out = bytearray()
            off = 0
            while True:
                chunk = client.call(server.address, "files", "fetch",
                                    path=str(src), offset=off,
                                    length=FETCH_CHUNK)
                if not chunk:
                    break
                out += chunk
                off += len(chunk)
            assert bytes(out) == src.read_bytes()
            assert M.sidecar_frames_sent > before_frames
            assert M.sidecar_bytes_sent - before_bytes >= 4 << 20
        finally:
            client.shutdown()
            server.shutdown()
