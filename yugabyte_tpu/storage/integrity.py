"""End-to-end data integrity: shadow verification + at-rest scrub.

Two independent defenses against SILENT corruption — the failure class
PR 6's loud-fault containment cannot see (HBM bit flips, donation bugs,
miscompiles on the accelerator side; disk bit rot on the at-rest side):

  - **Online shadow verification** of the device compaction path: a
    sampled fraction of device-native compaction jobs
    (``--shadow_verify_sample``) re-derives the survivor decisions
    through the native heap-merge oracle (storage/cpu_baseline.py — the
    differential-tested reference implementation) on a host thread that
    overlaps the device compute, and compares them CHUNK BY CHUNK as the
    device decisions stream into the writer. Any divergence raises
    ``ShadowMismatch`` before the outputs are installed; the compaction
    layer then unwinds the partial outputs, quarantines the shape bucket
    (offload_policy.BucketQuarantine) and re-runs the whole job natively
    — byte-identical to a healthy device run.

  - **At-rest scrub**: ``verify_sst`` deep-checks one SST (base-file
    footer + CRC, every data-block CRC, index/handle/bloom consistency)
    at a throttled byte rate; ``DB.scrub`` walks a DB's live files with
    it, and the ``ScrubTabletsOp`` maintenance op drives it per tablet
    on an interval, with a leader-driven cross-replica digest exchange
    (reusing the ``checksum_tablet`` RPC) on top. A corrupt SST is
    quarantined (renamed ``*.corrupt``), the DB parks with a STICKY
    Corruption background error (in-place retry cannot restore lost
    bytes), the tablet goes FAILED with ``failed_corrupt`` set, and the
    master rebuilds the replica in place from a healthy peer.

The ref for the scrub shape is the reference's block-checksum
verification on read (rocksdb/table/format.cc ReadBlockContents) plus
its ``CheckConsistency``/``VerifyChecksum`` sweeps; the shadow verify is
the online form of the differential tests that already pin the kernel
byte-identical to the native merge.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils import ybsan

flags.define_flag("shadow_verify_sample", 0.02,
                  "fraction of device-native compaction jobs whose "
                  "survivor decisions are re-derived through the native "
                  "merge oracle and compared before install (0 disables; "
                  "1.0 verifies every job)")
flags.define_flag("scrub_interval_s", 600.0,
                  "target seconds between at-rest integrity scrubs of "
                  "each tablet's SSTs (0 disables the scrubber)")
flags.define_flag("scrub_bytes_per_sec", 32 << 20,
                  "token-bucket cap on scrub read bandwidth so the "
                  "scrubber cannot starve foreground I/O")
flags.define_flag("scrub_replica_fail_after", 2,
                  "consecutive cross-replica digest mismatches before "
                  "the diverged follower is marked FAILED for rebuild "
                  "(>1 absorbs transient replication-lag noise)")


def integrity_metrics():
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    return ROOT_REGISTRY.entity("server", "integrity")


def _counter(name: str, help: str):
    return integrity_metrics().counter(name, help)


def shadow_mismatch_counter():
    """The alarm: device survivor decisions diverged from the native
    oracle — silent-corruption class, never expected in a healthy run."""
    return _counter("device_shadow_mismatch_total",
                    "device-native compaction jobs whose survivor "
                    "decisions diverged from the native merge oracle "
                    "(caught pre-install by shadow verification)")


# ---------------------------------------------------------------------------
# Online shadow verification of device compaction decisions


class ShadowMismatch(Exception):
    """Device survivor decisions diverged from the native oracle."""


def maybe_shadow_verifier(inputs, history_cutoff_ht: int, is_major: bool,
                          retain_deletes: bool) -> Optional["ShadowVerifier"]:
    """Sampling gate for the device-native compaction path: returns a
    verifier (its oracle thread already running) for a sampled job, else
    None. Inputs are the FILTERED SSTReaders in shell-ingest order — the
    domain the device survivor indexes address."""
    sample = float(flags.get_flag("shadow_verify_sample"))
    if sample <= 0:
        return None
    if sample < 1.0:
        import random
        if random.random() >= sample:
            return None
    return ShadowVerifier(inputs, history_cutoff_ht, is_major,
                          retain_deletes)


@ybsan.shadow(_surv=ybsan.PUBLISHER_CONSUMER,
              _mk=ybsan.PUBLISHER_CONSUMER,
              _oracle_err=ybsan.PUBLISHER_CONSUMER,
              _ms=ybsan.PUBLISHER_CONSUMER)
class ShadowVerifier:
    """Re-derives one compaction job's survivor decisions through the
    native heap-merge oracle and compares the device decisions against
    them chunk by chunk.

    The oracle runs on its own thread from construction so its disk
    reads + native merge overlap the device staging/compute; the first
    ``check_chunk`` blocks until it lands. Oracle FAILURES (native lib
    unavailable, concurrent file teardown) disable verification for the
    job — they are not evidence of corruption; only a successful oracle
    run that DISAGREES raises ShadowMismatch."""

    def __init__(self, inputs, history_cutoff_ht: int, is_major: bool,
                 retain_deletes: bool):
        self._inputs = list(inputs)
        self._cutoff = history_cutoff_ht
        self._is_major = is_major
        self._retain = retain_deletes
        self._surv: Optional[np.ndarray] = None
        self._mk: Optional[np.ndarray] = None
        self._oracle_err: Optional[BaseException] = None
        self._off = 0
        self._ms = 0.0
        self._thread = threading.Thread(target=self._run_oracle,
                                        name="compaction-shadow",
                                        daemon=True)
        self._thread.start()

    def _run_oracle(self) -> None:
        import time as _time
        t0 = _time.monotonic()
        try:
            from yugabyte_tpu.ops.slabs import concat_slabs
            from yugabyte_tpu.storage.cpu_baseline import \
                compact_cpu_baseline
            slabs = [r.read_all() for r in self._inputs]
            offsets = np.concatenate(
                ([0], np.cumsum([s.n for s in slabs]))).tolist()
            merged = concat_slabs(slabs)
            order, keep, mk = compact_cpu_baseline(
                merged, offsets, self._cutoff, self._is_major, self._retain)
            self._surv = order[keep]
            self._mk = mk[keep]
        except BaseException as e:  # noqa: BLE001  # yblint: contained(oracle failure disables shadow verify for this job — it is not corruption evidence; counted + TRACEd on the join path)
            self._oracle_err = e
        finally:
            self._ms = (_time.monotonic() - t0) * 1e3

    def _join(self) -> bool:
        """True when the oracle produced expected decisions; False when
        it failed (verification skipped, counted)."""
        self._thread.join()
        if self._oracle_err is not None:
            from yugabyte_tpu.utils.trace import TRACE
            TRACE("shadow verify: oracle failed (%r) — verification "
                  "skipped for this job", self._oracle_err)
            _counter("shadow_verify_skipped_total",
                     "sampled compaction jobs whose shadow oracle "
                     "failed (verification skipped, not corruption)"
                     ).increment()
            self._oracle_err = None
            self._surv = None
        return self._surv is not None

    def check_chunk(self, surv: np.ndarray, make_tomb: np.ndarray) -> None:
        """Compare one streamed decision chunk (global survivor indexes +
        tombstone flags, in merged order) against the oracle's span at
        the running offset. Raises ShadowMismatch on ANY divergence."""
        if not self._join():
            return
        lo, hi = self._off, self._off + len(surv)
        self._off = hi
        exp_s = self._surv[lo:hi]
        exp_m = self._mk[lo:hi]
        if len(exp_s) != len(surv) \
                or not np.array_equal(np.asarray(surv, dtype=np.int64),
                                      np.asarray(exp_s, dtype=np.int64)) \
                or not np.array_equal(np.asarray(make_tomb, dtype=bool),
                                      np.asarray(exp_m, dtype=bool)):
            bad = "chunk length"
            if len(exp_s) == len(surv):
                ds = np.nonzero(np.asarray(surv, dtype=np.int64)
                                != np.asarray(exp_s, dtype=np.int64))[0]
                dm = np.nonzero(np.asarray(make_tomb, dtype=bool)
                                != np.asarray(exp_m, dtype=bool))[0]
                bad = (f"survivor index at merged pos {lo + int(ds[0])}"
                       if len(ds) else
                       f"tombstone flag at merged pos {lo + int(dm[0])}")
            raise ShadowMismatch(
                f"device survivor decisions diverged from the native "
                f"oracle ({bad}; span [{lo}, {hi}) of "
                f"{len(self._surv)} expected survivors)")

    def finish(self, rows_out: int) -> None:
        """Final totals check + accounting; called after the last chunk,
        BEFORE the tail output files are written/installed."""
        from yugabyte_tpu.utils.metrics import record_pipeline_stage
        if self._join():
            if rows_out != len(self._surv) or self._off != rows_out:
                raise ShadowMismatch(
                    f"device survivor count {rows_out} (checked "
                    f"{self._off}) != native oracle {len(self._surv)}")
            _counter("shadow_verify_jobs_total",
                     "device-native compaction jobs fully shadow-"
                     "verified against the native merge oracle"
                     ).increment()
            _counter("shadow_verify_rows_total",
                     "survivor decisions compared by shadow "
                     "verification").increment(rows_out)
        record_pipeline_stage("shadow", self._ms)


def shadow_snapshot() -> dict:
    """Shadow-verification state for /integrityz."""
    e = integrity_metrics()
    return {
        "sample": float(flags.get_flag("shadow_verify_sample")),
        "jobs_verified": e.counter("shadow_verify_jobs_total", "").value(),
        "rows_verified": e.counter("shadow_verify_rows_total", "").value(),
        "mismatches": shadow_mismatch_counter().value(),
        "skipped": e.counter("shadow_verify_skipped_total", "").value(),
    }


# ---------------------------------------------------------------------------
# Resident-slab digest verification: the device-gathered cache entry vs
# the SST bytes the shell actually wrote. The chained L0->L1->L2 path
# FEEDS the next compaction from these entries without ever re-decoding
# the file, so a wrong entry would silently poison every downstream
# merge — this sampled check keeps the write-through honest against the
# host truth (the installed, CRC-covered SST), exactly the posture the
# shadow verifier holds over the survivor decisions.


flags.define_flag("resident_digest_sample", 0.02,
                  "fraction of device write-through cache installs whose "
                  "staged columns are re-derived from the written SST "
                  "bytes and compared (0 disables; a mismatched entry is "
                  "dropped, never installed)")


def resident_digest_mismatch_counter():
    return _counter("resident_digest_mismatch_total",
                    "device write-through cache entries that diverged "
                    "from a host re-stage of the installed SST bytes "
                    "(entry dropped before any chained merge could read "
                    "it)")


def verify_resident_entry(staged, base_path: str) -> List[str]:
    """Full check of one write-through cache entry against the decoded
    bytes of its installed SST. Costs a D2H fetch of the staged columns
    plus a host decode+pack — hence the sampling gate around it.
    Returns the (possibly empty) list of divergences."""
    from yugabyte_tpu.ops.merge_gc import pack_cols
    from yugabyte_tpu.storage.sst import SSTReader
    errors: List[str] = []
    reader = SSTReader(base_path)
    try:
        slab = reader.read_all()
    finally:
        reader.close()
    host_cols, n, _n_pad, _w = pack_cols(slab)
    if staged.n != n:
        return [f"row count: staged {staged.n} != decoded {n}"]
    dev_cols = np.asarray(staged.cols_dev)
    r_common = min(dev_cols.shape[0], host_cols.shape[0])
    if not np.array_equal(dev_cols[:r_common, :n], host_cols[:r_common, :n]):
        bad = np.nonzero(dev_cols[:r_common, :n]
                         != host_cols[:r_common, :n])
        errors.append(f"column words diverge at (row {int(bad[0][0])}, "
                      f"entry {int(bad[1][0])})")
    if dev_cols.shape[0] > r_common \
            and not (dev_cols[r_common:, :n] == 0).all():
        errors.append("staged width padding rows are not zero")
    return errors


def maybe_verify_resident_entry(staged, base_path: str) -> bool:
    """Sampling gate for the write-through install path: True when the
    entry may install (clean, or unsampled), False when the digest check
    found a divergence (counted; the caller drops the entry and lets the
    next reader re-stage from the file bytes)."""
    sample = float(flags.get_flag("resident_digest_sample"))
    if sample <= 0:
        return True
    if sample < 1.0:
        import random
        if random.random() >= sample:
            return True
    errors = verify_resident_entry(staged, base_path)
    _counter("resident_digest_checked_total",
             "device write-through cache installs digest-checked "
             "against the installed SST bytes").increment()
    if not errors:
        return True
    from yugabyte_tpu.utils.trace import TRACE
    resident_digest_mismatch_counter().increment()
    TRACE("resident digest: device-staged entry for %s diverges from the "
          "installed bytes (%s) — entry dropped, not installed",
          base_path, errors[0])
    return False


def resident_digest_snapshot() -> dict:
    """Write-through digest-check state for /integrityz."""
    e = integrity_metrics()
    return {
        "sample": float(flags.get_flag("resident_digest_sample")),
        "checked": e.counter("resident_digest_checked_total", "").value(),
        "mismatches": resident_digest_mismatch_counter().value(),
    }


# ---------------------------------------------------------------------------
# At-rest SST verification (the scrub + sst_dump/ldb --verify core)


@dataclass
class SSTVerifyReport:
    path: str
    n_blocks: int = 0
    n_entries: int = 0
    bytes_verified: int = 0
    errors: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors


def verify_sst(base_path: str, limiter=None,
               cancel=None) -> SSTVerifyReport:
    """Deep-check one SST: base-file footer magic + CRC (SSTReader open),
    index/handle geometry, every data-block CRC (full decode), per-block
    index-key agreement and bloom membership of each block's first doc
    key. Reads pace through ``limiter`` (a utils.rate_limiter.RateLimiter)
    when given. Returns a report; never raises for corruption — the
    caller routes it (DB.scrub parks the DB, the tools exit non-zero)."""
    from yugabyte_tpu.storage import block_format
    from yugabyte_tpu.storage.sst import SSTReader
    from yugabyte_tpu.utils.status import StatusError
    rep = SSTVerifyReport(path=base_path)
    try:
        r = SSTReader(base_path)
    except StatusError as e:  # yblint: contained(corruption captured into the verify report — the caller routes it to quarantine/background-error)
        rep.errors.append(f"base: {e}")
        return rep
    except OSError as e:  # yblint: contained(I/O failure captured into the verify report — the caller routes it)
        rep.errors.append(f"base io: {e}")
        return rep
    try:
        rep.n_blocks = r.n_blocks
        # index geometry: handles contiguous, sizes/counts consistent
        # with the props the footer vouched for
        off = 0
        n_sum = 0
        prev_key = None
        for i, (boff, bsize, bn) in enumerate(r.block_handles):
            if boff != off:
                rep.errors.append(
                    f"index: block {i} offset {boff} != expected {off}")
            off = boff + bsize
            n_sum += bn
            if prev_key is not None and r.index_keys[i] < prev_key:
                rep.errors.append(f"index: key order regresses at "
                                  f"block {i}")
            prev_key = r.index_keys[i]
        if n_sum != r.props.n_entries:
            rep.errors.append(f"index: entry counts sum {n_sum} != "
                              f"props n_entries {r.props.n_entries}")
        if off != r.props.data_size:
            rep.errors.append(f"index: handles cover {off} bytes != "
                              f"props data_size {r.props.data_size}")
        from yugabyte_tpu.ops.slabs import _doc_key_len
        for i, (boff, bsize, bn) in enumerate(r.block_handles):
            if cancel is not None:
                cancel.check()
            if limiter is not None:
                limiter.acquire(bsize)
            try:
                raw = r._data.pread(bsize, boff)
                if len(raw) < bsize:
                    rep.errors.append(
                        f"block {i}: short read {len(raw)} < {bsize}")
                    continue
                slab = block_format.decode_block(raw)
            except StatusError as e:  # yblint: contained(block corruption captured into the verify report — the caller routes it to quarantine/background-error)
                rep.errors.append(f"block {i}: {e}")
                continue
            except OSError as e:  # yblint: contained(I/O failure captured into the verify report — the caller routes it)
                rep.errors.append(f"block {i} io: {e}")
                continue
            rep.bytes_verified += bsize
            rep.n_entries += slab.n
            if slab.n != bn:
                rep.errors.append(f"block {i}: decoded {slab.n} entries, "
                                  f"index says {bn}")
                continue
            if slab.n:
                raw_keys = slab.key_words.astype(">u4").tobytes()
                stride = slab.width_words * 4
                last = raw_keys[(slab.n - 1) * stride:
                                (slab.n - 1) * stride
                                + int(slab.key_len[slab.n - 1])]
                if last != r.index_keys[i]:
                    rep.errors.append(
                        f"block {i}: last key disagrees with index")
                first = raw_keys[: int(slab.key_len[0])]
                try:
                    doc_key = first[: _doc_key_len(first)]
                    if not r.may_contain_doc(doc_key):
                        rep.errors.append(
                            f"block {i}: bloom filter denies a present "
                            f"doc key")
                except (ValueError, IndexError):  # yblint: contained(system keys have no doc-key prefix — the bloom probe simply does not apply)
                    pass  # undecodable system key: bloom probe n/a
    finally:
        r.close()
    return rep


# ---------------------------------------------------------------------------
# Quarantine registry: corrupt files set aside for forensics


_quar_lock = threading.Lock()
_quarantined: List[dict] = []   # guarded-by: _quar_lock


def quarantine_sst(base_path: str, reason: str = "") -> List[str]:
    """Set a corrupt SST aside: rename base + data files to ``*.corrupt``
    so nothing re-opens the bad bytes as live data (open fds keep
    working; the replica is parked and will be rebuilt). Records the
    quarantine for /integrityz. Returns the new paths."""
    from yugabyte_tpu.storage.sst import data_file_name
    from yugabyte_tpu.utils.trace import TRACE
    moved = []
    for p in (base_path, data_file_name(base_path)):
        q = p + ".corrupt"
        try:
            os.replace(p, q)
            moved.append(q)
        except OSError as e:
            # half-quarantined is still quarantined for the reader (the
            # base file rename alone breaks re-open); keep going + say so
            TRACE("integrity: cannot quarantine %s: %s", p, e)
    with _quar_lock:
        _quarantined.append({"path": base_path, "reason": reason,
                             "ts": time.time()})
    _counter("sst_quarantine_total",
             "corrupt SSTs set aside as *.corrupt files").increment()
    TRACE("integrity: quarantined corrupt SST %s (%s)", base_path, reason)
    return moved


def quarantined_files() -> List[dict]:
    with _quar_lock:
        return [dict(d) for d in _quarantined]


# ---------------------------------------------------------------------------
# Scrub pacing + accounting


_scrub_limiter = None        # guarded-by: _scrub_limiter_lock
_scrub_limiter_rate = 0      # guarded-by: _scrub_limiter_lock
_scrub_limiter_lock = threading.Lock()


def scrub_rate_limiter():
    """Process-wide scrub read throttle (one bucket across all tablets;
    rebuilt when the flag changes). None when unthrottled."""
    global _scrub_limiter, _scrub_limiter_rate
    rate = int(flags.get_flag("scrub_bytes_per_sec"))
    if rate <= 0:
        return None
    with _scrub_limiter_lock:
        if _scrub_limiter is None or _scrub_limiter_rate != rate:
            from yugabyte_tpu.utils.rate_limiter import RateLimiter
            _scrub_limiter = RateLimiter(rate)
            _scrub_limiter_rate = rate
        return _scrub_limiter


def record_scrub(files: int, blocks: int, nbytes: int,
                 corrupt: int) -> None:
    e = integrity_metrics()
    e.counter("sst_scrub_files_total",
              "SSTs deep-verified by the background scrubber"
              ).increment(files)
    e.counter("sst_scrub_bytes_total",
              "bytes read and CRC-verified by the background scrubber"
              ).increment(nbytes)
    if corrupt:
        e.counter("sst_scrub_corruption_total",
                  "corrupt SSTs detected by the background scrubber"
                  ).increment(corrupt)


def scrub_snapshot() -> dict:
    """Scrubber totals for /integrityz."""
    e = integrity_metrics()
    return {
        "interval_s": float(flags.get_flag("scrub_interval_s")),
        "bytes_per_sec": int(flags.get_flag("scrub_bytes_per_sec")),
        "files_verified": e.counter("sst_scrub_files_total", "").value(),
        "bytes_verified": e.counter("sst_scrub_bytes_total", "").value(),
        "corruption_detected": e.counter(
            "sst_scrub_corruption_total", "").value(),
        "replica_mismatches": e.counter(
            "scrub_replica_mismatch_total", "").value(),
        "quarantined": len(quarantined_files()),
    }


def replica_mismatch_counter():
    return _counter("scrub_replica_mismatch_total",
                    "cross-replica digest mismatches observed by the "
                    "leader-driven scrub digest exchange")
