"""In-process time-series history: the telemetry timebase.

Every observability surface before this module (/servez, /healthz,
/compactionz, /rpcz) is a point-in-time snapshot — a counter tells you
the total, never the RATE, and a regression between two moments is
invisible unless someone happened to scrape both. `TimeSeriesStore`
closes that gap in-process: a sampler thread self-scrapes the process
metric registries plus a set of pluggable snapshot sources (bucket
health, overload, device cache, compaction pool) every
`--timeseries_interval_s` (default 5s) into per-metric ring buffers of
`(wall_ts, value)` points.

Memory is PROVABLY bounded (acceptance criterion, asserted in
tests/test_telemetry.py): each ring holds at most
`--timeseries_ring_capacity` points in two preallocated fixed-size
lists, and the number of rings is capped at `--timeseries_max_metrics`
(series beyond the cap are dropped and counted, never grown) — so the
whole store holds at most `ring_capacity x metric_count` points.

Reads are snapshot-based: scrape sources take their own snapshots
(registry JSON dumps, board snapshots) and the store's lock guards only
its private ring map — nothing on the serve hot path ever takes or
waits on it (acceptance: zero new locks on the hot path).

Queries: `window` (raw points), `delta`/`rate` (counter movement over a
trailing window), and `page()` — the `/timeseriesz` JSON: per metric
the raw window, the rate over the window, and a sparkline-ready
downsample. `bench_snapshot()` is the compact form every bench round
embeds.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils import lock_rank
from yugabyte_tpu.utils import ybsan
from yugabyte_tpu.utils.metrics import (ROOT_REGISTRY, MetricRegistry,
                                        registries_to_json_obj)
from yugabyte_tpu.utils.trace import TRACE

flags.define_flag("timeseries_interval_s", 5.0,
                  "sampler period of the in-process time-series store "
                  "(seconds between self-scrapes)")
flags.define_flag("timeseries_ring_capacity", 240,
                  "points retained per metric series (ring buffer; at "
                  "the default 5s interval, 240 points = 20 minutes)")
flags.define_flag("timeseries_max_metrics", 1024,
                  "hard cap on distinct series the store will track; "
                  "series beyond it are dropped and counted, so store "
                  "memory stays bounded at capacity x max_metrics")


@ybsan.shadow(_n=ybsan.PUBLISHER_CONSUMER, _i=ybsan.PUBLISHER_CONSUMER)
class _Ring:
    """Fixed-capacity (ts, value) ring. Preallocated lists, so a ring's
    memory is its capacity regardless of how long the sampler runs.
    Cursor discipline (shadowed above): the sampler thread publishes
    `_i`/`_n` under the store lock; every reader must be HB-after the
    publishing write (it is — readers take the same tracked lock)."""

    __slots__ = ("cap", "_ts", "_vals", "_n", "_i")

    def __init__(self, cap: int):
        self.cap = max(2, int(cap))
        self._ts = [0.0] * self.cap
        self._vals = [0.0] * self.cap
        self._n = 0
        self._i = 0

    def push(self, ts: float, v: float) -> None:
        self._ts[self._i] = ts
        self._vals[self._i] = v
        self._i = (self._i + 1) % self.cap
        if self._n < self.cap:
            self._n += 1

    def __len__(self) -> int:
        return self._n

    def points(self) -> List[Tuple[float, float]]:
        """Chronological [(ts, value)] copy."""
        if self._n < self.cap:
            idx = range(self._n)
        else:
            idx = [(self._i + k) % self.cap for k in range(self.cap)]
        return [(self._ts[j], self._vals[j]) for j in idx]


def _downsample(vals: List[float], n: int) -> List[float]:
    """Sparkline-ready downsample: bucket means, at most n points."""
    if len(vals) <= n:
        return list(vals)
    out = []
    step = len(vals) / n
    for k in range(n):
        lo, hi = int(k * step), max(int((k + 1) * step), int(k * step) + 1)
        chunk = vals[lo:hi]
        out.append(sum(chunk) / len(chunk))
    return out


class TimeSeriesStore:
    """Bounded ring-buffer sampler over pluggable snapshot sources."""

    def __init__(self, interval_s: Optional[float] = None,
                 capacity: Optional[int] = None,
                 max_metrics: Optional[int] = None):
        self.interval_s = float(interval_s if interval_s is not None
                                else flags.get_flag("timeseries_interval_s"))
        self.capacity = int(capacity if capacity is not None
                            else flags.get_flag("timeseries_ring_capacity"))
        self.max_metrics = int(
            max_metrics if max_metrics is not None
            else flags.get_flag("timeseries_max_metrics"))
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "timeseries._lock")
        self._rings: Dict[str, _Ring] = {}      # guarded-by: _lock
        self._sources: List[Tuple[str, Callable[[], Dict[str, float]]]] = []  # guarded-by: _lock
        self._samples = 0                       # guarded-by: _lock
        self._sample_ms_total = 0.0             # guarded-by: _lock
        self._scrape_errors = 0                 # guarded-by: _lock
        self._dropped_series = 0                # guarded-by: _lock
        self._starts = 0                        # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock
        self._stop_evt = threading.Event()
        self._started_t: Optional[float] = None

    # ---- sources -----------------------------------------------------

    def register_source(self, label: str,
                        fn: Callable[[], Dict[str, float]]) -> None:
        """Register a snapshot source: a callable returning a flat
        {series_name: numeric} dict. Idempotent per label (a MiniCluster
        restarts servers; the new server's source replaces the old)."""
        with self._lock:
            self._sources = [(l, f) for (l, f) in self._sources
                             if l != label] + [(label, fn)]

    def register_registry(self, label: str, registry: MetricRegistry) -> None:
        """Scrape a metric registry as a source: counters/gauges become
        value series; histograms become `.count` and `.sum` series (the
        pair a rate query turns into observations/s and mean-ms-rate).
        Only server-scoped entities are sampled — per-tablet entities
        would multiply the series count per tablet."""

        def _scrape() -> Dict[str, float]:
            out: Dict[str, float] = {}
            for ent in registries_to_json_obj([registry]):
                if ent["type"] != "server":
                    continue
                eid = ent["id"]
                for m in ent["metrics"]:
                    name = f"{eid}.{m['name']}"
                    if "value" in m:
                        out[name] = m["value"]
                    else:
                        cnt = m.get("total_count", 0)
                        out[f"{name}.count"] = cnt
                        out[f"{name}.sum"] = m.get("mean", 0.0) * cnt
            return out

        self.register_source(label, _scrape)

    # ---- sampling ----------------------------------------------------

    def sample_once(self) -> int:
        """One self-scrape of every source into the rings. Returns the
        number of series sampled. Source snapshots run OUTSIDE the
        store lock; only the ring pushes hold it."""
        t0 = time.monotonic()
        wall = time.time()
        with self._lock:
            sources = list(self._sources)
        vals: Dict[str, float] = {}
        for label, fn in sources:
            try:
                d = fn()
            except Exception as e:  # yblint: contained(one broken scrape source must not kill the sampler; that source's series go stale, the failure is TRACEd and counted, every other source still samples)
                TRACE("timeseries: source %s scrape failed: %s", label, e)
                with self._lock:
                    self._scrape_errors += 1
                continue
            for k, v in (d or {}).items():
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                vals[f"{label}.{k}"] = float(v)
        with self._lock:
            for name, v in vals.items():
                r = self._rings.get(name)
                if r is None:
                    if len(self._rings) >= self.max_metrics:
                        self._dropped_series += 1
                        continue
                    r = _Ring(self.capacity)
                    self._rings[name] = r
                r.push(wall, v)
            self._samples += 1
            dur_ms = (time.monotonic() - t0) * 1e3
            self._sample_ms_total += dur_ms
        ent = ROOT_REGISTRY.entity("server", "timeseries")
        ent.counter("timeseries_samples_total",
                    "self-scrape ticks taken by the time-series "
                    "sampler").increment()
        ent.histogram("timeseries_sample_duration_ms",
                      "wall time of one time-series self-scrape tick "
                      "(the sampler-overhead budget: <1% of the "
                      "interval)").increment(dur_ms)
        return len(vals)

    # ---- sampler thread ----------------------------------------------

    def start(self, interval_s: Optional[float] = None) -> None:
        """Start (or ref-count a running) sampler thread. Multiple
        in-process servers share the store; the thread stops when every
        starter has called stop()."""
        with self._lock:
            self._starts += 1
            if self._thread is not None:
                return
            if interval_s is not None:
                self.interval_s = float(interval_s)
            self._stop_evt = threading.Event()
            if self._started_t is None:
                self._started_t = time.monotonic()
            t = threading.Thread(target=self._run, args=(self._stop_evt,),
                                 name="timeseries-sampler", daemon=True)
            self._thread = t
        t.start()

    def stop(self) -> None:
        with self._lock:
            if self._starts > 0:
                self._starts -= 1
            if self._starts > 0 or self._thread is None:
                return
            t, self._thread = self._thread, None
            evt = self._stop_evt
        evt.set()
        t.join(timeout=5.0)

    def stop_all(self) -> None:
        """Unconditional stop (test teardown / process shutdown)."""
        with self._lock:
            self._starts = 0
            t, self._thread = self._thread, None
            evt = self._stop_evt
        evt.set()
        if t is not None:
            t.join(timeout=5.0)

    def _run(self, stop_evt: threading.Event) -> None:
        while not stop_evt.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception as e:  # yblint: contained(the sampler is observability-only: a failed tick is TRACEd and the next tick proceeds; it must never terminate the thread or surface into a serving path)
                TRACE("timeseries: sample tick failed: %s", e)

    # ---- queries -----------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def window(self, name: str,
               window_s: Optional[float] = None) -> List[Tuple[float, float]]:
        """Chronological (ts, value) points of one series, optionally
        trimmed to the trailing `window_s` seconds."""
        with self._lock:
            r = self._rings.get(name)
            pts = r.points() if r is not None else []
        if window_s is not None and pts:
            cutoff = pts[-1][0] - window_s
            pts = [p for p in pts if p[0] >= cutoff]
        return pts

    def delta(self, name: str, window_s: Optional[float] = None) -> float:
        """Value movement over the trailing window (last - first)."""
        pts = self.window(name, window_s)
        if len(pts) < 2:
            return 0.0
        return pts[-1][1] - pts[0][1]

    def rate(self, name: str, window_s: Optional[float] = None) -> float:
        """Counter rate per second over the trailing window."""
        pts = self.window(name, window_s)
        if len(pts) < 2:
            return 0.0
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return 0.0
        return (v1 - v0) / (t1 - t0)

    # ---- bounds & overhead -------------------------------------------

    def metric_count(self) -> int:
        with self._lock:
            return len(self._rings)

    def total_points(self) -> int:
        with self._lock:
            return sum(len(r) for r in self._rings.values())

    def memory_bound_points(self) -> int:
        """The store's provable point bound: ring capacity x metric
        count (and metric count itself is capped at max_metrics)."""
        return self.capacity * self.metric_count()

    def overhead_ratio(self) -> float:
        """Fraction of wall time spent sampling since start — the <1%
        acceptance number bench.py snapshots on the YCSB rung."""
        with self._lock:
            total_ms = self._sample_ms_total
            t0 = self._started_t
        if t0 is None:
            return 0.0
        elapsed = time.monotonic() - t0
        return (total_ms / 1e3) / elapsed if elapsed > 0 else 0.0

    # ---- exposition --------------------------------------------------

    def page(self, window_s: Optional[float] = None,
             spark_points: int = 40) -> Dict[str, object]:
        """The /timeseriesz JSON: store meta plus, per series, the raw
        window, the rate over it, and a sparkline downsample."""
        with self._lock:
            rings = {name: r.points() for name, r in self._rings.items()}
            meta = {
                "interval_s": self.interval_s,
                "ring_capacity": self.capacity,
                "max_metrics": self.max_metrics,
                "metric_count": len(rings),
                "samples_total": self._samples,
                "scrape_errors_total": self._scrape_errors,
                "dropped_series_total": self._dropped_series,
                "sample_ms_total": round(self._sample_ms_total, 3),
            }
        meta["memory_bound_points"] = meta["ring_capacity"] * meta["metric_count"]
        meta["sampler_overhead_ratio"] = round(self.overhead_ratio(), 6)
        metrics: Dict[str, object] = {}
        for name in sorted(rings):
            pts = rings[name]
            if window_s is not None and pts:
                cutoff = pts[-1][0] - window_s
                pts = [p for p in pts if p[0] >= cutoff]
            rate = 0.0
            if len(pts) >= 2 and pts[-1][0] > pts[0][0]:
                rate = (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0])
            metrics[name] = {
                "points": len(pts),
                "last": pts[-1][1] if pts else None,
                "window": [[round(t, 3), v] for t, v in pts],
                "rate_per_s": rate,
                "spark": _downsample([v for _, v in pts], spark_points),
            }
        meta["metrics"] = metrics
        return meta

    def bench_snapshot(self, spark_points: int = 16) -> Dict[str, object]:
        """Compact store snapshot every bench round embeds: the meta
        block plus per-series last value + rate (no raw windows)."""
        page = self.page(spark_points=spark_points)
        out = {k: v for k, v in page.items() if k != "metrics"}
        out["series"] = {
            name: {"last": m["last"], "rate_per_s": round(m["rate_per_s"], 4)}
            for name, m in page["metrics"].items()}
        return out


def _bucket_health_source() -> Dict[str, float]:
    """Per-state key counts of the process bucket-health board (the
    flap signal /healthz's point snapshot cannot show over time)."""
    from yugabyte_tpu.storage.bucket_health import health_board
    snap = health_board().snapshot()
    out: Dict[str, float] = {}
    for state, n in (snap.get("states") or {}).items():
        out[f"state_{state}.count"] = float(n)
    out["keys.count"] = float(len(snap.get("keys") or ()))
    for name, n in (snap.get("counters") or {}).items():
        out[f"{name}.total"] = float(n)
    return out


_STORE: Optional[TimeSeriesStore] = None  # guarded-by: _STORE_LOCK
_STORE_LOCK = threading.Lock()


def timeseries_store() -> TimeSeriesStore:
    """Process-wide store (one sampler per process; every in-process
    server registers its registry/sources onto it). Pre-registered
    sources: ROOT_REGISTRY (kernel dispatch, serve-path attribution,
    bucket-health counters, device/run cache counters) and the
    bucket-health board state histogram."""
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            s = TimeSeriesStore()
            s.register_registry("root", ROOT_REGISTRY)
            s.register_source("bucket_health", _bucket_health_source)
            _STORE = s
        return _STORE


def reset_timeseries_store() -> None:
    """Drop the process store (test isolation): stops any sampler
    thread and discards the rings."""
    global _STORE
    with _STORE_LOCK:
        s, _STORE = _STORE, None
    if s is not None:
        s.stop_all()
