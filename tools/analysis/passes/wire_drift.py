"""wire-drift: client-side RPC calls must agree with the server-side
handler — and to_wire/from_wire codec pairs must agree with each other.

The RPC layer is stringly-typed on purpose (dict args over the codec's
closed type set, handler methods invoked `method(**args)`), which means a
renamed request field, a dropped response key, or a wire-dict field
written on one side and never read on the other (the `trace_ctx` class
of bug) survives until the one integration test that exercises that
exact path — or until production. This pass closes the loop statically,
whole-program:

- services: every `register_service(<name>, <handler>)` is resolved —
  the name through string constants (cross-module via import aliases),
  the handler through the index's class-attribute types
  (`self.service = TabletServiceImpl(...)`) or a direct constructor —
  giving service name -> handler class (methods incl. base classes).
- client sites: `<anything>.call(addr, SVC, "method", k=v, ...)` where
  SVC resolves to a registered service; plus dispatch WRAPPERS,
  discovered to a fixpoint: any function that forwards one of its own
  parameters into the method slot of a known dispatcher is itself a
  dispatcher (this resolves `_master_call` -> `_master_call_traced` ->
  `messenger.call`, including the `**args` kwargs relay). Wrapper call
  sites with a literal method name are checked like direct ones.
- request checks: a kwarg the handler does not accept ->
  `unknown-request-field`; a required handler parameter the client
  never sends (and no `**` expansion in sight) ->
  `missing-request-field`; a method the handler class lacks ->
  `unknown-method`. `timeout_s` and `_underscore` control kwargs belong
  to the transport, not the wire.
- response checks: when the call result is bound to a single local and
  EVERY return of the handler is a literal dict, client subscripts /
  `.get()`s of keys outside the union of returned keys ->
  `drifted-response-field`.
- codec pairs: same-module `X_to_wire` / `X_from_wire` functions —
  a key the writer emits but the reader never touches ->
  `wire-field-never-read`; a key the reader requires (subscript, not
  `.get`) but the writer never emits -> `wire-field-never-written`.
- declared piggyback pairs: hand-rolled wire structures that ride
  INSIDE a request/response field (the heartbeat tablet report, the
  replication poller specs) drift below kwarg granularity, so their
  producer and consumers declare themselves:

      def generate_report(self):   # yblint: wire-pair(tablet_report, writes)
      def process_heartbeat(...):  # yblint: wire-pair(tablet_report, reads)

  The pass then unions the writer side's literal dict keys against
  every reader's key reads (cross-module) and flags keys written but
  never read anywhere -> `wire-field-never-read`. (Only that direction:
  readers also touch unrelated dicts, so the reverse would guess.)

Waive with `# yblint: disable=wire-drift`.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis.core import AnalysisPass, FileContext, Finding
from tools.analysis.project_index import (ClassInfo, FuncInfo,
                                          ProjectIndex, dotted_name)

PASS_NAME = "wire-drift"

_CONTROL_KWARGS = {"timeout_s"}
_WIRE_PAIR_RE = re.compile(
    r"#\s*yblint:\s*wire-pair\((\w+),\s*(writes|reads)\)")


class _Dispatcher:
    """A callable that sends an RPC: the ground `*.call(addr, svc, mth,
    **kw)` form, or a wrapper forwarding into one."""

    __slots__ = ("service", "method_param", "star_param", "fixed_kwargs",
                 "params", "defaults")

    def __init__(self, service: str, method_param: str,
                 star_param: Optional[str], fixed_kwargs: Set[str],
                 params: Sequence[str], defaults: int):
        self.service = service
        self.method_param = method_param
        self.star_param = star_param    # param **-expanded into the wire
        self.fixed_kwargs = fixed_kwargs
        self.params = list(params)      # excluding self
        self.defaults = defaults        # count of defaulted tail params


class _Services:
    def __init__(self) -> None:
        self.handlers: Dict[str, ClassInfo] = {}
        self.dispatchers: Dict[str, _Dispatcher] = {}  # func key -> spec


def _handler_params(fi: FuncInfo) -> Tuple[Set[str], Set[str], bool]:
    """(accepted, required, has_kwargs) of a handler method."""
    a = fi.node.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    accepted = set(names) | {p.arg for p in a.kwonlyargs}
    n_def = len(a.defaults)
    required = set(names[: len(names) - n_def] if n_def else names)
    required |= {p.arg for p, d in zip(a.kwonlyargs, a.kw_defaults)
                 if d is None}
    return accepted, required, a.kwarg is not None


def _params_wo_self(fn: ast.AST) -> Tuple[List[str], int]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names, len(a.defaults)


def _bind(params: List[str], n_defaults: int,
          call: ast.Call, skip_first: int = 0
          ) -> Dict[str, ast.AST]:
    """Map a call's args onto `params` (bound-method style: the call's
    receiver is implicit). Unmatchable calls return what did match."""
    out: Dict[str, ast.AST] = {}
    for i, a in enumerate(call.args[skip_first:]):
        if i < len(params):
            out[params[i]] = a
    for kw in call.keywords:
        if kw.arg and kw.arg in params:
            out[kw.arg] = kw.value
    return out


def _build_services(index: ProjectIndex) -> _Services:
    sv = _Services()
    # ---- pass 1: register_service(name, handler) ----------------------
    for mi in index.modules.values():
        for call in mi.ctx.nodes_of(ast.Call):
            f = call.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr == "register_service"
                    and len(call.args) >= 2):
                continue
            name = index.resolve_str_const(mi, call.args[0])
            if not name:
                continue
            ci = _handler_class(index, mi, call.args[1], call)
            if ci is not None:
                sv.handlers[name] = ci
    # ---- pass 2: ground dispatchers + wrapper fixpoint -----------------
    for fi in index.functions.values():
        mi = index.modules[fi.modname]
        for call in ast.walk(fi.node):
            if not isinstance(call, ast.Call):
                continue
            f = call.func
            if not (isinstance(f, ast.Attribute) and f.attr == "call"
                    and len(call.args) >= 2):
                continue
            svc = index.resolve_str_const(mi, call.args[1])
            if svc not in sv.handlers:
                continue
            mth = call.args[2] if len(call.args) >= 3 else None
            if isinstance(mth, ast.Name):
                params, n_def = _params_wo_self(fi.node)
                if mth.id in params:
                    star = next((dotted_name(kw.value)
                                 for kw in call.keywords if kw.arg is None
                                 and isinstance(kw.value, ast.Name)), None)
                    fixed = {kw.arg for kw in call.keywords
                             if kw.arg and kw.arg not in _CONTROL_KWARGS}
                    sv.dispatchers[fi.key] = _Dispatcher(
                        svc, mth.id, star, fixed, params, n_def)
    for _ in range(4):  # wrapper-of-wrapper fixpoint (chains are short)
        changed = False
        for fi in index.functions.values():
            if fi.key in sv.dispatchers:
                continue
            mi = index.modules[fi.modname]
            spec = _wrapper_spec(index, mi, fi, sv)
            if spec is not None:
                sv.dispatchers[fi.key] = spec
                changed = True
        if not changed:
            break
    return sv


def _handler_class(index: ProjectIndex, mi, expr: ast.AST,
                   call: ast.Call) -> Optional[ClassInfo]:
    # direct constructor: register_service(NAME, Handler(...))
    if isinstance(expr, ast.Call):
        return index.lookup_class(index.resolve(mi,
                                                dotted_name(expr.func)))
    d = dotted_name(expr)
    if d.startswith("self."):
        for a in mi.ctx.ancestors(call):
            if isinstance(a, ast.ClassDef):
                ci = index.lookup_class(mi.modname + "." + a.name)
                if ci is not None:
                    t = ci.attr_types.get(d.split(".", 1)[1])
                    return index.lookup_class(t)
        return None
    # plain local: svc = Handler(...); register_service(NAME, svc)
    fn = mi.ctx.enclosing_function(call)
    if fn is not None and isinstance(expr, ast.Name):
        fi = index.lookup_function(index.key_of(fn))
        if fi is not None:
            return index.lookup_class(index.local_types(fi).get(expr.id))
    return None


def _wrapper_spec(index: ProjectIndex, mi, fi: FuncInfo,
                  sv: _Services) -> Optional[_Dispatcher]:
    params, n_def = _params_wo_self(fi.node)
    star_name = fi.node.args.kwarg.arg if fi.node.args.kwarg else None
    for call in ast.walk(fi.node):
        if not isinstance(call, ast.Call):
            continue
        inner = _dispatcher_of_call(index, mi, fi, call, sv)
        if inner is None:
            continue
        bound = _bind(inner.params, inner.defaults, call)
        mval = bound.get(inner.method_param)
        if not (isinstance(mval, ast.Name) and mval.id in params):
            continue
        # does our **kwargs (or a dict param) reach the wire?
        star: Optional[str] = None
        if inner.star_param is not None:
            sval = bound.get(inner.star_param)
            if isinstance(sval, ast.Name):
                star = sval.id
        for kw in call.keywords:
            if kw.arg is None and isinstance(kw.value, ast.Name):
                star = kw.value.id
        fixed = set(inner.fixed_kwargs)
        if inner.star_param is not None:
            # keywords that are NOT inner params land in its **kwargs
            # relay and therefore reach the wire as request fields
            fixed |= {kw.arg for kw in call.keywords
                      if kw.arg and kw.arg not in inner.params}
        star_ok = star if (star == star_name or star in params) else None
        return _Dispatcher(inner.service, mval.id, star_ok,
                           {k for k in fixed if k not in _CONTROL_KWARGS},
                           params, n_def)
    return None


def _dispatcher_of_call(index: ProjectIndex, mi, fi: FuncInfo,
                        call: ast.Call,
                        sv: _Services) -> Optional[_Dispatcher]:
    f = call.func
    if isinstance(f, ast.Name):
        key = index.resolve(mi, f.id)
        return sv.dispatchers.get(key) if key else None
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if isinstance(recv, ast.Name) and recv.id in ("self", "cls") \
            and fi.cls is not None:
        target = index.find_method(fi.cls, f.attr)
        if target is not None:
            return sv.dispatchers.get(target.key)
    # self.<attr>.<wrapper>() through the attr's inferred type
    if isinstance(recv, ast.Attribute) and isinstance(recv.value, ast.Name) \
            and recv.value.id == "self" and fi.cls is not None:
        t = fi.cls.attr_types.get(recv.attr)
        target = index.find_method(index.lookup_class(t), f.attr) \
            if t else None
        if target is not None:
            return sv.dispatchers.get(target.key)
    return None


class WireDriftPass(AnalysisPass):
    name = PASS_NAME
    needs_index = True

    def applies_to(self, relpath: str) -> bool:
        return relpath.endswith(".py")

    def run(self, ctx: FileContext, index: Optional[ProjectIndex] = None
            ) -> List[Finding]:
        if index is None:
            index = ProjectIndex([ctx])
        mi = index.by_relpath.get(ctx.relpath)
        if mi is None:
            return []
        sv: _Services = index.memo("wire.services",
                                   lambda: _build_services(index))
        out: List[Finding] = []
        if sv.handlers:
            for fn in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
                out.extend(self._check_function(ctx, index, mi, fn, sv))
        out.extend(self._check_codec_pairs(ctx, mi))
        out.extend(self._check_declared_pairs(ctx, index))
        return out

    # ------------------------------------------------------- client sites
    def _site(self, index, mi, fi: Optional[FuncInfo], call: ast.Call,
              sv: _Services
              ) -> Optional[Tuple[str, str, Set[str], bool]]:
        """(service, method, fields, open) for a checkable client call."""
        f = call.func
        # direct `<x>.call(addr, SVC, "mth", ...)`
        if isinstance(f, ast.Attribute) and f.attr == "call" \
                and len(call.args) >= 3:
            svc = index.resolve_str_const(mi, call.args[1])
            mth = call.args[2]
            if svc in sv.handlers and isinstance(mth, ast.Constant) \
                    and isinstance(mth.value, str):
                fields = {kw.arg for kw in call.keywords
                          if kw.arg and kw.arg not in _CONTROL_KWARGS
                          and not kw.arg.startswith("_")}
                is_open = any(kw.arg is None for kw in call.keywords)
                return svc, mth.value, fields, is_open
            return None
        # wrapper call with a literal method
        disp = None
        if fi is not None:
            disp = _dispatcher_of_call(index, mi, fi, call, sv)
        if disp is None and isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name):
            # obj.wrapper(...) through a typed local/param
            if fi is not None:
                t = index.local_types(fi).get(f.value.id)
                target = index.find_method(index.lookup_class(t), f.attr) \
                    if t else None
                if target is not None:
                    disp = sv.dispatchers.get(target.key)
        if disp is None:
            return None
        bound = _bind(disp.params, disp.defaults, call)
        mval = bound.get(disp.method_param)
        if not (isinstance(mval, ast.Constant)
                and isinstance(mval.value, str)):
            return None
        fields = set(disp.fixed_kwargs)
        fields |= {kw.arg for kw in call.keywords
                   if kw.arg and kw.arg not in disp.params
                   and kw.arg not in _CONTROL_KWARGS
                   and not kw.arg.startswith("_")}
        is_open = any(kw.arg is None for kw in call.keywords)
        return disp.service, mval.value, fields, is_open

    def _check_function(self, ctx, index, mi, fn, sv) -> List[Finding]:
        fi = index.lookup_function(index.key_of(fn))
        out: List[Finding] = []
        bind_counts: Dict[str, int] = {}
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        bind_counts[t.id] = bind_counts.get(t.id, 0) + 1
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            site = self._site(index, mi, fi, node, sv)
            if site is None:
                continue
            svc, mth, fields, is_open = site
            handler = sv.handlers[svc]
            method = index.find_method(handler, mth)
            if method is None or mth.startswith("_"):
                out.append(ctx.finding(
                    self.name, "unknown-method", node,
                    f"client calls {svc}.{mth} but handler "
                    f"{handler.name} has no such method"))
                continue
            accepted, required, has_kwargs = _handler_params(method)
            if not has_kwargs:
                for extra in sorted(fields - accepted):
                    out.append(ctx.finding(
                        self.name, "unknown-request-field", node,
                        f"request field {extra!r} of {svc}.{mth} is not "
                        f"accepted by {handler.name}.{mth} — it would "
                        "TypeError server-side (or silently drift)"))
            if not is_open:
                for missing in sorted(required - fields):
                    out.append(ctx.finding(
                        self.name, "missing-request-field", node,
                        f"required field {missing!r} of "
                        f"{handler.name}.{mth} is never sent by this "
                        f"{svc}.{mth} call"))
            out.extend(self._check_response(ctx, fn, node, svc, mth,
                                            method, bind_counts))
        return out

    # ---------------------------------------------------------- responses
    def _direct_walk(self, fn: ast.AST):
        """Descendants of fn excluding nested def bodies."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _handler_return_keys(self, method: FuncInfo) -> Optional[Set[str]]:
        keys: Set[str] = set()
        saw = False
        for n in self._direct_walk(method.node):
            if not isinstance(n, ast.Return) or n.value is None:
                continue
            saw = True
            if not isinstance(n.value, ast.Dict):
                return None
            for k in n.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                else:
                    return None
        return keys if saw else None

    def _check_response(self, ctx, fn, call, svc, mth, method,
                        bind_counts) -> List[Finding]:
        parent = ctx.parent(call)
        if not (isinstance(parent, ast.Assign) and len(parent.targets) == 1
                and isinstance(parent.targets[0], ast.Name)
                and parent.value is call):
            return []
        var = parent.targets[0].id
        if bind_counts.get(var, 0) != 1:
            return []  # rebound elsewhere: reads are ambiguous
        keys = self._handler_return_keys(method)
        if keys is None:
            return []
        out: List[Finding] = []
        for n in ast.walk(fn):
            read: Optional[str] = None
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.value, ast.Name) and n.value.id == var \
                    and isinstance(n.ctx, ast.Load) \
                    and isinstance(n.slice, ast.Constant) \
                    and isinstance(n.slice.value, str):
                read = n.slice.value
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "get" \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == var and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                read = n.args[0].value
            if read is not None and read not in keys:
                out.append(ctx.finding(
                    self.name, "drifted-response-field", n,
                    f"client reads response field {read!r} of {svc}.{mth} "
                    f"but the handler only returns "
                    f"{{{', '.join(sorted(keys))}}}"))
        return out

    # -------------------------------------------------------- codec pairs
    def _check_codec_pairs(self, ctx, mi) -> List[Finding]:
        out: List[Finding] = []
        fns = {n.name: n for n in ctx.nodes_of(ast.FunctionDef)}
        for name, to_fn in fns.items():
            if not name.endswith("_to_wire"):
                continue
            from_fn = fns.get(name[: -len("_to_wire")] + "_from_wire")
            if from_fn is None:
                continue
            written = self._written_keys(to_fn)
            req, opt = self._read_keys(from_fn)
            if written is None or (not req and not opt):
                continue
            for k in sorted(req - written):
                out.append(ctx.finding(
                    self.name, "wire-field-never-written", from_fn,
                    f"{from_fn.name} requires wire field {k!r} that "
                    f"{name} never writes"))
            for k in sorted(written - req - opt):
                out.append(ctx.finding(
                    self.name, "wire-field-never-read", to_fn,
                    f"{name} writes wire field {k!r} that "
                    f"{from_fn.name} never reads — dropped on the wire"))
        return out

    def _written_keys(self, fn: ast.AST) -> Optional[Set[str]]:
        ret_names: Set[str] = set()
        keys: Set[str] = set()
        analyzable = False
        for n in ast.walk(fn):
            if isinstance(n, ast.Return) and n.value is not None:
                if isinstance(n.value, ast.Name):
                    ret_names.add(n.value.id)
                elif isinstance(n.value, ast.Dict):
                    analyzable = True
                    for k in n.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            keys.add(k.value)
                        else:
                            return None
                else:
                    return None
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                t = n.targets[0]
                if isinstance(t, ast.Name) and t.id in ret_names \
                        and isinstance(n.value, ast.Dict):
                    analyzable = True
                    for k in n.value.keys:
                        if isinstance(k, ast.Constant) \
                                and isinstance(k.value, str):
                            keys.add(k.value)
                        else:
                            return None
                elif isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id in ret_names \
                        and isinstance(t.slice, ast.Constant) \
                        and isinstance(t.slice.value, str):
                    keys.add(t.slice.value)
        return keys if analyzable else None

    # --------------------------------------------- declared piggyback pairs
    def _declared_pairs(self, index: ProjectIndex) -> Dict[str, dict]:
        """pair name -> {'writes': [FuncInfo], 'reads': [FuncInfo]}."""
        pairs: Dict[str, dict] = {}
        for fi in index.functions.values():
            mi = index.modules[fi.modname]
            m = _WIRE_PAIR_RE.search(mi.ctx.line_text(fi.node.lineno))
            if m is None:
                # the annotation may sit on any line of a multi-line
                # signature
                for ln in range(fi.node.lineno,
                                fi.node.body[0].lineno):
                    m = _WIRE_PAIR_RE.search(mi.ctx.line_text(ln))
                    if m:
                        break
            if m is None:
                continue
            rec = pairs.setdefault(m.group(1), {"writes": [], "reads": []})
            rec[m.group(2)].append(fi)
        return pairs

    def _coarse_written(self, fn: ast.AST) -> Set[str]:
        keys: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Dict):
                for k in n.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        keys.add(k.value)
            elif isinstance(n, ast.Subscript) \
                    and isinstance(n.ctx, ast.Store) \
                    and isinstance(n.slice, ast.Constant) \
                    and isinstance(n.slice.value, str):
                keys.add(n.slice.value)
        return keys

    def _coarse_read(self, fn: ast.AST) -> Set[str]:
        keys: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.ctx, ast.Load) \
                    and isinstance(n.slice, ast.Constant) \
                    and isinstance(n.slice.value, str):
                keys.add(n.slice.value)
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "get" and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                keys.add(n.args[0].value)
            elif isinstance(n, ast.Compare) and isinstance(
                    n.left, ast.Constant) and isinstance(n.left.value, str) \
                    and any(isinstance(op, (ast.In, ast.NotIn))
                            for op in n.ops):
                keys.add(n.left.value)
        return keys

    def _check_declared_pairs(self, ctx, index: ProjectIndex
                              ) -> List[Finding]:
        pairs: Dict[str, dict] = index.memo(
            "wire.declared_pairs", lambda: self._declared_pairs(index))
        out: List[Finding] = []
        for name, rec in pairs.items():
            if not rec["reads"]:
                continue
            read: Set[str] = set()
            for fi in rec["reads"]:
                read |= self._coarse_read(fi.node)
            for fi in rec["writes"]:
                if index.modules[fi.modname].relpath != ctx.relpath:
                    continue  # report on the writer, in its own file
                for k in sorted(self._coarse_written(fi.node) - read):
                    out.append(ctx.finding(
                        self.name, "wire-field-never-read", fi.node,
                        f"wire-pair {name!r}: {fi.node.name} writes "
                        f"field {k!r} that no declared reader ever "
                        "consumes — dead wire weight (or a renamed "
                        "consumer-side key)"))
        return out

    def _read_keys(self, fn: ast.AST) -> Tuple[Set[str], Set[str]]:
        params, _ = _params_wo_self(fn)
        if not params:
            return set(), set()
        w = params[0]
        req: Set[str] = set()
        opt: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Subscript) \
                    and isinstance(n.value, ast.Name) and n.value.id == w \
                    and isinstance(n.slice, ast.Constant) \
                    and isinstance(n.slice.value, str):
                req.add(n.slice.value)
            elif isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr == "get" \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.value.id == w and n.args \
                    and isinstance(n.args[0], ast.Constant) \
                    and isinstance(n.args[0].value, str):
                opt.add(n.args[0].value)
        return req, opt
