"""The TPU seam is wired into the LIVE server by default (VERDICT r1 weak
#3): every tablet hosted by a TabletServer shares one ServerExecutionContext
— compaction pool, device handle, HBM slab cache, block cache — like the
reference's server-wide PriorityThreadPool + block cache
(ref: rocksdb/db/db_impl.cc:201-440, util/priority_thread_pool.h:61)."""

import pytest

from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.storage import offload_policy  # noqa: F401 (flag defs)
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING)],
    num_hash_key_columns=1)


@pytest.fixture
def small_memstore():
    old_mem = flags.get_flag("memstore_size_bytes")
    old_rf = flags.get_flag("replication_factor")
    old_mode = flags.get_flag("device_offload_mode")
    flags.set_flag("memstore_size_bytes", 4096)
    flags.set_flag("replication_factor", 1)
    # this test validates the device WIRING (shared pool + HBM slab
    # cache); the offload policy would route these tiny uncalibrated
    # compactions to the native path (tests/test_offload_policy.py owns
    # the routing behavior)
    flags.set_flag("device_offload_mode", "device")
    yield
    flags.set_flag("memstore_size_bytes", old_mem)
    flags.set_flag("replication_factor", old_rf)
    flags.set_flag("device_offload_mode", old_mode)


def test_server_shares_pool_and_device_cache(tmp_path, small_memstore):
    cluster = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path / "cluster"))).start()
    try:
        ts = cluster.tservers[0]
        # Default wiring: no custom factory -> the server built an
        # execution context and hands its options to every tablet.
        assert ts.exec_context is not None
        ctx = ts.exec_context

        client = cluster.new_client()
        client.create_namespace("ycsb")
        table = client.create_table("ycsb", "usertable", SCHEMA,
                                    num_tablets=2)
        cluster.wait_all_replicas_running(table.table_id)
        from yugabyte_tpu.client.session import YBSession
        session = YBSession(client)
        # YCSB-A-shaped load: small memstore forces many flushes, whose
        # write-through staging + universal compactions exercise the
        # shared pool and HBM slab cache.
        value = "x" * 100
        for i in range(400):
            session.apply(table, QLWriteOp(
                WriteOpKind.INSERT,
                DocKey(hash_components=(f"user{i % 97:04d}",)),
                {"v": f"{value}{i}"}))
            if i % 40 == 39:
                # periodic flushes produce overlapping sorted runs per
                # tablet (each exceeds the tiny memstore), so universal
                # compaction has real work
                session.flush()
        session.flush()
        for tid in ts.tablet_manager.tablet_ids():
            peer = ts.tablet_manager.get_tablet(tid)
            # every tablet got the SHARED objects, not per-tablet copies
            assert peer.tablet.opts.compaction_pool is ctx.pool
            assert peer.tablet.opts.block_cache is ctx.block_cache
            peer.tablet.flush()
        ctx.pool.wait_idle()

        # Compactions ran on the shared pool against the shared HBM cache.
        if ctx.device_cache is not None:
            assert ctx.device_cache.hits > 0, (
                "compactions never hit the shared device slab cache")
        compacted = False
        for tid in ts.tablet_manager.tablet_ids():
            peer = ts.tablet_manager.get_tablet(tid)
            db = peer.tablet.regular_db
            if db.versions.compactions_installed > 0:
                compacted = True
        assert compacted, "no background compaction ran via the shared pool"

        # Metrics exposure: queue depth gauge (per-server registry) +
        # cache hit counters (process ROOT_REGISTRY; the webserver merges
        # both into one exposition).
        from yugabyte_tpu.utils.metrics import (ROOT_REGISTRY,
                                                registries_to_prometheus)
        ctx.refresh_metrics()
        prom = registries_to_prometheus([ts.metrics, ROOT_REGISTRY])
        assert "compaction_pool_queue_depth" in prom
        assert "device_cache_hits_total" in prom

        # Data is intact after background compactions.
        row = client.read_row(table, DocKey(hash_components=("user0007",)))
        assert row is not None
    finally:
        cluster.shutdown()
