"""IntentAwareIterator: merged read view over regular + intents DBs.

Capability parity with the reference (ref: src/yb/docdb/
intent_aware_iterator.h:45-61 — reads see committed regular records PLUS
provisional records resolved at read time: the reading transaction's own
intents, and intents of transactions that already COMMITTED with a commit
hybrid time within the read snapshot but whose intents have not been moved
to the regular DB yet).

Implementation: the intents overlay for the scanned range is materialized
into synthetic internal-key entries (at the hybrid time each record becomes
visible — own write time for own intents, commit time for committed ones)
and merge-sorted with the regular DB's stream before the shared MVCC
resolution pass, so shadowing/tombstone semantics apply identically.
"""

from __future__ import annotations

import heapq
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.storage.memtable import make_internal_key
from yugabyte_tpu.docdb.intents import (
    decode_intent_value, latest_intents_in_range, make_status_cache)
from yugabyte_tpu.docdb.lock_manager import IntentType
from yugabyte_tpu.docdb.value_type import ValueType

StatusResolver = Callable[[str, bytes], dict]


def intent_overlay_entries(
        intents_db, read_ht: HybridTime,
        own_txn_id: Optional[bytes],
        status_resolver: Optional[StatusResolver],
        lower: bytes = b"",
        upper: Optional[bytes] = None) -> List[Tuple[bytes, bytes]]:
    """Synthetic (internal_key, value_bytes) entries for every provisional
    record visible at read_ht in [lower, upper)."""
    status_of = make_status_cache(status_resolver, read_ht.value)

    out: List[Tuple[bytes, bytes]] = []
    own: List[Tuple[DocHybridTime, bytes, bytes]] = []
    for subdoc_key, itype, dht, raw in latest_intents_in_range(
            intents_db, lower, upper):
        if itype != IntentType.kStrongWrite:
            continue  # weak intents carry no data
        txn_id, status_tablet, write_id, value_bytes = \
            decode_intent_value(raw)
        if own_txn_id is not None and txn_id == own_txn_id:
            own.append((dht, subdoc_key, value_bytes))
            continue
        st = status_of(txn_id, status_tablet)
        if st["status"] != "committed" or st.get("commit_ht") is None:
            continue  # pending/aborted: invisible to this snapshot
        if st["commit_ht"] > read_ht.value:
            continue
        visible_dht = DocHybridTime(HybridTime(st["commit_ht"]), write_id)
        out.append((make_internal_key(subdoc_key, visible_dht),
                    value_bytes))
    # Read-your-writes: a transaction sees ALL of its own provisional
    # records even though they were written after its read point (ref
    # intent_aware_iterator.h in_txn_limit semantics). Emit them AT the
    # read point, ordered by true write time via the write-id tiebreak, so
    # the MVCC resolver keeps them visible and the latest own write wins.
    own.sort(key=lambda e: e[0])
    for idx, (_true_dht, subdoc_key, value_bytes) in enumerate(own):
        out.append((make_internal_key(subdoc_key,
                                      DocHybridTime(read_ht, idx)),
                    value_bytes))
    out.sort()
    return out


def merged_entry_stream(regular_db, overlay: List[Tuple[bytes, bytes]],
                        lower: bytes = b""
                        ) -> Iterator[Tuple[bytes, bytes]]:
    """Regular DB stream merged with the intent overlay, in internal-key
    order (the reference's two-iterator seek dance collapses to a merge)."""
    return heapq.merge(regular_db.iter_from(lower), iter(overlay))
