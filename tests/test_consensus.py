"""Consensus tests: WAL, Raft elections/replication/failover, TabletPeer.

Models the reference's test strategy (ref: consensus/raft_consensus-test.cc,
log-test.cc, tablet bootstrap tests) at MiniCluster granularity: real
RaftConsensus instances over an in-process transport with fault injection.
"""

import os
import threading
import time

import pytest

from yugabyte_tpu.common.hybrid_time import HybridClock
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.consensus.log import Log, LogEntry, LogReader
from yugabyte_tpu.consensus.raft import (
    OP_NOOP, OP_WRITE, NotLeader, RaftConfig, RaftConsensus, Role)
from yugabyte_tpu.consensus.transport import LocalTransport
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.tablet.tablet_peer import TabletPeer, peer_address
from yugabyte_tpu.utils import flags


def wait_for(pred, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.005)
    raise TimeoutError(f"timed out waiting for {msg}")


def _elect_with_retry(raft_like, name, timeout=30.0):
    """Drive one node to leadership with EXPONENTIALLY-backed-off
    re-elections: a single attempt can silently die under full-suite CPU
    load (vote RPCs starve) and nothing retries it with election timers
    disabled — but re-issuing too eagerly is worse, because every new
    attempt bumps the term and INVALIDATES votes still in flight for the
    previous one (a livelock when vote threads need longer than the
    retry interval to get scheduled)."""
    deadline = time.monotonic() + timeout
    window = 2.0
    while time.monotonic() < deadline:
        raft_like.start_election(ignore_lease=True)
        attempt_end = min(time.monotonic() + window, deadline)
        while time.monotonic() < attempt_end:
            if raft_like.is_leader():
                return
            time.sleep(0.005)
        window *= 2
    # dump diagnostics so a CI flake is attributable: raft state plus
    # every thread's stack (is the vote path starved, deadlocked, ...?)
    import faulthandler
    import sys
    print(f"\n=== elect({name}) diagnostics: role={raft_like.role} "
          f"term={raft_like._meta.term} leader={raft_like.leader_id} "
          f"load={open('/proc/loadavg').read().strip()} ===",
          file=sys.stderr, flush=True)
    faulthandler.dump_traceback(file=sys.stderr)
    raise TimeoutError(f"timed out waiting for {name} leader")


@pytest.fixture(autouse=True)
def fast_raft():
    flags.set_flag("raft_heartbeat_interval_ms", 15)
    flags.set_flag("ht_lease_duration_ms", 1000)
    yield
    flags.reset_flag("raft_heartbeat_interval_ms")
    flags.reset_flag("ht_lease_duration_ms")


# ---------------------------------------------------------------------- WAL

class TestLog:
    def test_roundtrip_and_recovery(self, tmp_path):
        wal = str(tmp_path / "wal")
        log = Log(wal)
        entries = [LogEntry(1, i, f"payload-{i}".encode())
                   for i in range(1, 51)]
        log.append_sync(entries)
        assert log.last_op_id == (1, 50)
        log.close()

        log2 = Log(wal)  # recovery
        assert log2.last_op_id == (1, 50)
        got = list(LogReader(wal).read_all())
        assert [e.index for e in got] == list(range(1, 51))
        assert got[10].payload == b"payload-11"
        log2.close()

    def test_segment_rollover_and_gc(self, tmp_path):
        flags.set_flag("log_segment_size_bytes", 512)
        try:
            wal = str(tmp_path / "wal")
            log = Log(wal)
            for i in range(1, 101):
                log.append_sync([LogEntry(1, i, b"x" * 64)])
            segs = LogReader(wal).segments()
            assert len(segs) > 3
            removed = log.gc_up_to(60)
            assert removed > 0
            remaining = [e.index for e in LogReader(wal).read_all()]
            assert 100 in remaining
            assert remaining == sorted(remaining)
            # everything >= 60 must survive
            assert set(range(60, 101)) <= set(remaining)
            log.close()
        finally:
            flags.reset_flag("log_segment_size_bytes")

    def test_torn_tail_dropped(self, tmp_path):
        wal = str(tmp_path / "wal")
        log = Log(wal)
        log.append_sync([LogEntry(1, i, b"data") for i in (1, 2, 3)])
        log.close()
        seg = LogReader(wal).segments()[0]
        with open(seg, "ab") as f:
            f.write(b"\x01\x02\x03garbage-partial-record")
        log2 = Log(wal)
        assert log2.last_op_id == (1, 3)
        # new appends after recovery land cleanly
        log2.append_sync([LogEntry(1, 4, b"after")])
        assert [e.index for e in LogReader(wal).read_all()] == [1, 2, 3, 4]
        log2.close()

    def test_truncate_after(self, tmp_path):
        wal = str(tmp_path / "wal")
        log = Log(wal)
        log.append_sync([LogEntry(1, i, b"d") for i in range(1, 11)])
        log.truncate_after(6)
        assert log.last_op_id == (1, 6)
        log.append_sync([LogEntry(2, 7, b"new7")])
        got = list(LogReader(wal).read_all())
        assert [e.op_id for e in got] == [(1, i) for i in range(1, 7)] + [(2, 7)]
        log.close()


# --------------------------------------------------------------------- Raft

class RaftHarness:
    def __init__(self, tmp_path, n=3, timers=False):
        self.transport = LocalTransport()
        self.applied = {f"p{i}": [] for i in range(n)}
        self.nodes = {}
        ids = tuple(f"p{i}" for i in range(n))
        for pid in ids:
            d = tmp_path / pid
            os.makedirs(d, exist_ok=True)
            log = Log(str(d / "wal"))
            node = RaftConsensus(
                RaftConfig(pid, ids), log, self.transport,
                apply_cb=lambda m, p=pid: self.applied[p].append(m),
                meta_path=str(d / "cmeta.json"),
                clock=HybridClock())
            self.transport.register(pid, node)
            node.start(election_timer=timers)
            self.nodes[pid] = node

    def leader(self):
        for n in self.nodes.values():
            if n.is_leader():
                return n
        return None

    def elect(self, pid):
        _elect_with_retry(self.nodes[pid], pid)
        return self.nodes[pid]

    def shutdown(self):
        for n in self.nodes.values():
            n.shutdown()


class TestRaft:
    def test_election_and_replication(self, tmp_path):
        h = RaftHarness(tmp_path)
        try:
            leader = h.elect("p0")
            for i in range(20):
                leader.replicate(OP_WRITE, 1000 + i, f"op{i}".encode())
            assert [m.payload for m in h.applied["p0"]] == \
                [f"op{i}".encode() for i in range(20)]
            # followers converge via heartbeats
            wait_for(lambda: len(h.applied["p1"]) == 20 and
                     len(h.applied["p2"]) == 20, msg="followers applied")
            assert [m.index for m in h.applied["p1"]] == \
                [m.index for m in h.applied["p0"]]
        finally:
            h.shutdown()

    def test_not_leader_rejected(self, tmp_path):
        h = RaftHarness(tmp_path)
        try:
            h.elect("p0")
            with pytest.raises(NotLeader):
                h.nodes["p1"].replicate(OP_WRITE, 1, b"nope")
        finally:
            h.shutdown()

    def test_follower_catchup_after_partition(self, tmp_path):
        h = RaftHarness(tmp_path)
        try:
            leader = h.elect("p0")
            leader.replicate(OP_WRITE, 1, b"a")
            h.transport.partition("p0", "p2")
            h.transport.partition("p1", "p2")
            for i in range(10):
                leader.replicate(OP_WRITE, 10 + i, b"b%d" % i)
            assert len(h.applied["p2"]) <= 1
            h.transport.heal()
            wait_for(lambda: len(h.applied["p2"]) == 11, msg="p2 catchup")
        finally:
            h.shutdown()

    def test_leader_failover_and_divergent_truncation(self, tmp_path):
        h = RaftHarness(tmp_path)
        try:
            old = h.elect("p0")
            old.replicate(OP_WRITE, 1, b"committed")
            wait_for(lambda: len(h.applied["p1"]) == 1
                     and len(h.applied["p2"]) == 1, msg="replicated")
            # Cut the leader off; its next append can't commit.
            h.transport.isolate("p0")
            from yugabyte_tpu.consensus.raft import ReplicationTimedOut
            with pytest.raises(ReplicationTimedOut):
                old.replicate(OP_WRITE, 2, b"orphan", timeout_s=0.3)
            new = h.elect("p1")
            new.replicate(OP_WRITE, 3, b"new-leader-op")
            wait_for(lambda: len(h.applied["p2"]) == 2, msg="p2 got new op")
            # Old leader rejoins: its orphan entry must be truncated away.
            h.transport.heal()
            wait_for(lambda: len(h.applied["p0"]) == 2, msg="p0 converged")
            assert h.applied["p0"][1].payload == b"new-leader-op"
            assert not old.is_leader()
        finally:
            h.shutdown()

    def test_auto_election_with_timers(self, tmp_path):
        h = RaftHarness(tmp_path, timers=True)
        try:
            wait_for(lambda: h.leader() is not None, msg="auto leader")
            leader = h.leader()
            leader.replicate(OP_WRITE, 1, b"x")
            # exactly one leader
            assert sum(1 for n in h.nodes.values() if n.is_leader()) == 1
        finally:
            h.shutdown()

    def test_leader_lease(self, tmp_path):
        flags.set_flag("ht_lease_duration_ms", 200)
        try:
            h = RaftHarness(tmp_path)
            try:
                leader = h.elect("p0")
                wait_for(leader.has_leader_lease, msg="lease acquired")
                h.transport.isolate("p0")
                time.sleep(0.4)
                assert not leader.has_leader_lease()
            finally:
                h.shutdown()
        finally:
            flags.reset_flag("ht_lease_duration_ms")

    def test_restart_recovers_log(self, tmp_path):
        h = RaftHarness(tmp_path)
        leader = h.elect("p0")
        for i in range(5):
            leader.replicate(OP_WRITE, 100 + i, b"v%d" % i)
        h.shutdown()
        # Fresh instances over the same dirs: log + term recovered.
        h2 = RaftHarness(tmp_path)
        try:
            n0 = h2.nodes["p0"]
            assert n0.last_op_id[1] >= 5
            assert n0.current_term >= 1
            leader = h2.elect("p1")
            # committed floor let bootstrap re-apply committed suffix
            wait_for(lambda: len(h2.applied["p1"]) + 0 >= 0)
            leader.replicate(OP_WRITE, 200, b"after-restart")
            wait_for(lambda: any(m.payload == b"after-restart"
                                 for m in h2.applied["p2"]), msg="p2 new op")
        finally:
            h2.shutdown()


# --------------------------------------------------------------- TabletPeer

def make_schema():
    return Schema(
        columns=[ColumnSchema("k", DataType.STRING),
                 ColumnSchema("v", DataType.INT64)],
        num_hash_key_columns=0, num_range_key_columns=1)


def write_op(schema, k, v):
    return QLWriteOp(WriteOpKind.INSERT, DocKey(range_components=(k,)),
                     {"v": v})


class PeerHarness:
    def __init__(self, tmp_path, n=3):
        self.transport = LocalTransport()
        self.schema = make_schema()
        self.tmp_path = tmp_path
        self.servers = tuple(f"ts{i}" for i in range(n))
        self.peers = {}
        for s in self.servers:
            self.peers[s] = TabletPeer(
                "t1", str(tmp_path / s), self.schema, s, self.servers,
                self.transport).start(election_timer=False)

    def elect(self, s):
        _elect_with_retry(self.peers[s].raft, s)
        return self.peers[s]

    def shutdown(self):
        for p in self.peers.values():
            p.shutdown()


class TestTabletPeer:
    def test_replicated_write_and_follower_read(self, tmp_path):
        h = PeerHarness(tmp_path)
        try:
            leader = h.elect("ts0")
            leader.write([write_op(h.schema, f"row{i}", i) for i in range(8)])
            row = leader.read_row(DocKey(range_components=("row3",)))
            assert row.to_dict(h.schema)["v"] == 3

            # Followers hold identical data, readable at propagated safe time
            # (vouch the replica first: PR-11 gates follower serving on
            # the digest exchange, which this bare harness doesn't run)
            follower = h.peers["ts1"]
            follower.grant_vouch(0)
            wait_for(lambda: follower.tablet.mvcc.safe_time_for_follower()
                     .value > 0, msg="propagated safe time")
            wait_for(lambda: (follower.read_row(
                DocKey(range_components=("row3",)), allow_follower=True)
                or None) is not None, msg="follower row visible")
            frow = follower.read_row(DocKey(range_components=("row3",)),
                                     allow_follower=True)
            assert frow.to_dict(h.schema)["v"] == 3
            # but followers reject leader-consistency reads and writes
            with pytest.raises(NotLeader):
                follower.write([write_op(h.schema, "x", 1)])
            with pytest.raises(NotLeader):
                follower.read_row(DocKey(range_components=("row3",)))
        finally:
            h.shutdown()

    def test_restart_bootstrap_replays_wal(self, tmp_path):
        h = PeerHarness(tmp_path)
        leader = h.elect("ts0")
        leader.write([write_op(h.schema, f"k{i}", 10 * i) for i in range(20)])
        h.shutdown()

        h2 = PeerHarness(tmp_path)
        try:
            leader = h2.elect("ts1")
            row = leader.read_row(DocKey(range_components=("k7",)))
            assert row is not None and row.to_dict(h2.schema)["v"] == 70
            # and the cluster still accepts writes
            leader.write([write_op(h2.schema, "new", 999)])
            assert leader.read_row(
                DocKey(range_components=("new",))).to_dict(h2.schema)["v"] == 999
        finally:
            h2.shutdown()

    def test_flush_then_restart_and_wal_gc(self, tmp_path):
        flags.set_flag("log_segment_size_bytes", 2048)
        try:
            h = PeerHarness(tmp_path)
            leader = h.elect("ts0")
            for i in range(30):
                leader.write([write_op(h.schema, f"k{i:03d}", i)])
            removed = leader.flush_and_gc_wal()
            assert removed >= 1
            h.shutdown()

            h2 = PeerHarness(tmp_path)
            try:
                leader = h2.elect("ts0")
                for i in (0, 15, 29):
                    row = leader.read_row(
                        DocKey(range_components=(f"k{i:03d}",)))
                    assert row is not None and row.to_dict(h2.schema)["v"] == i
            finally:
                h2.shutdown()
        finally:
            flags.reset_flag("log_segment_size_bytes")

    def test_timed_out_write_fate_resolves(self, tmp_path):
        """A write whose replication times out must NOT abort MVCC: it can
        still commit after the partition heals, and the row must then be
        visible (repeatable-read safety for unknown-outcome writes)."""
        from yugabyte_tpu.consensus.raft import OperationOutcomeUnknown
        h = PeerHarness(tmp_path)
        try:
            leader = h.elect("ts0")
            leader.write([write_op(h.schema, "pre", 1)])
            h.transport.partition("ts0/t1", "ts1/t1")
            h.transport.partition("ts0/t1", "ts2/t1")
            with pytest.raises(OperationOutcomeUnknown):
                leader.tablet.write([write_op(h.schema, "limbo", 42)],
                                    timeout_s=0.3)
            h.transport.heal()
            # Same leader, same term: the entry commits once peers ack.
            wait_for(lambda: leader.raft.op_fate(
                (leader.raft.current_term, 3)) == "committed",
                msg="limbo op committed")
            # The fate watcher resolves the MVCC registration async; the row
            # must then become visible at a consistent read point.
            wait_for(lambda: (leader.read_row(
                DocKey(range_components=("limbo",))) or None) is not None,
                msg="limbo row visible")
            row = leader.read_row(DocKey(range_components=("limbo",)))
            assert row.to_dict(h.schema)["v"] == 42
        finally:
            h.shutdown()

    def test_failover_preserves_data(self, tmp_path):
        h = PeerHarness(tmp_path)
        try:
            leader = h.elect("ts0")
            leader.write([write_op(h.schema, "stable", 1)])
            h.transport.isolate("ts0/t1")
            new = h.elect("ts1")
            def _caught_up():
                ci, la = new.raft.commit_progress()
                return la >= ci and ci >= 1
            wait_for(_caught_up, msg="new leader caught up")
            row = new.read_row(DocKey(range_components=("stable",)))
            assert row is not None and row.to_dict(h.schema)["v"] == 1
            new.write([write_op(h.schema, "after-failover", 2)])
            h.transport.heal()
            old = h.peers["ts0"]
            # PR-11 follower-read gate: vouch the rejoining replica (no
            # digest exchange runs in this harness)
            old.grant_vouch(0)
            wait_for(lambda: (old.read_row(
                DocKey(range_components=("after-failover",)),
                allow_follower=True) or None) is not None,
                msg="old leader converged", timeout=15)
        finally:
            h.shutdown()
