"""Metrics: counters, gauges, histograms, with JSON + Prometheus exposition.

Capability parity with the reference metric system (ref: src/yb/util/metrics.h:
Counter, AtomicGauge :713, Histogram; WriteForPrometheus :449-518). Entities
(server/table/tablet) each own a registry; registries aggregate into a root
MetricRegistry for the /metrics endpoints.
"""

from __future__ import annotations

import json
import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional


class Counter:
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = threading.Lock()

    def increment(self, by: int = 1) -> None:
        with self._lock:
            self._value += by

    def value(self) -> int:
        return self._value


class Gauge:
    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = "", initial: float = 0.0):
        self.name = name
        self.help = help
        self._value = initial
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def increment(self, by: float = 1.0) -> None:
        with self._lock:
            self._value += by

    def decrement(self, by: float = 1.0) -> None:
        self.increment(-by)

    def value(self) -> float:
        return self._value


class Histogram:
    """Log-bucketed histogram (2% default precision), like the reference's HdrHistogram."""

    __slots__ = ("name", "help", "_counts", "_lock", "_total_sum", "_total_count",
                 "_min", "_max", "_growth")

    def __init__(self, name: str, help: str = "", growth: float = 1.02):
        self.name = name
        self.help = help
        self._growth = math.log(growth)
        self._counts: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._total_sum = 0.0
        self._total_count = 0
        self._min = math.inf
        self._max = -math.inf

    def _bucket(self, v: float) -> int:
        if v <= 0:
            return -1
        return int(math.log(v) / self._growth)

    def increment(self, v: float) -> None:
        b = self._bucket(v)
        with self._lock:
            self._counts[b] = self._counts.get(b, 0) + 1
            self._total_sum += v
            self._total_count += 1
            self._min = min(self._min, v)
            self._max = max(self._max, v)

    def percentile(self, p: float) -> float:
        with self._lock:
            if self._total_count == 0:
                return 0.0
            target = p / 100.0 * self._total_count
            seen = 0
            for b in sorted(self._counts):
                seen += self._counts[b]
                if seen >= target:
                    return math.exp((b + 0.5) * self._growth) if b >= 0 else 0.0
            return self._max

    def mean(self) -> float:
        return self._total_sum / self._total_count if self._total_count else 0.0

    def count(self) -> int:
        return self._total_count


class MetricEntity:
    """One metric-owning entity: a server, table, or tablet (ref: metrics.h entities)."""

    def __init__(self, entity_type: str, entity_id: str, attributes: Optional[Dict[str, str]] = None):
        self.entity_type = entity_type
        self.entity_id = entity_id
        self.attributes = attributes or {}
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "", initial: float = 0.0) -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help, initial))

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help))

    def _get_or_create(self, name, factory):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = factory()
            return self._metrics[name]


class MetricRegistry:
    def __init__(self):
        self._entities: Dict[str, MetricEntity] = {}
        self._lock = threading.Lock()

    def entity(self, entity_type: str, entity_id: str,
               attributes: Optional[Dict[str, str]] = None) -> MetricEntity:
        key = f"{entity_type}:{entity_id}"
        with self._lock:
            if key not in self._entities:
                self._entities[key] = MetricEntity(entity_type, entity_id, attributes)
            return self._entities[key]

    def _snapshot(self):
        with self._lock:
            ents = list(self._entities.values())
        out = []
        for ent in ents:
            with ent._lock:
                out.append((ent, list(ent._metrics.values())))
        return out

    def to_json(self) -> str:
        out = []
        for ent, ent_metrics in self._snapshot():
            metrics = []
            for m in ent_metrics:
                if isinstance(m, Histogram):
                    metrics.append({
                        "name": m.name, "total_count": m.count(), "mean": m.mean(),
                        "percentile_95": m.percentile(95), "percentile_99": m.percentile(99),
                    })
                else:
                    metrics.append({"name": m.name, "value": m.value()})
            out.append({"type": ent.entity_type, "id": ent.entity_id,
                        "attributes": ent.attributes, "metrics": metrics})
        return json.dumps(out, indent=1)

    def to_prometheus(self) -> str:
        """Prometheus text exposition (ref: metrics.h WriteForPrometheus :449-518)."""
        lines: List[str] = []
        for ent, ent_metrics in self._snapshot():
            labels = {"metric_type": ent.entity_type, "metric_id": ent.entity_id}
            labels.update(ent.attributes)
            label_str = ",".join(f'{k}="{v}"' for k, v in labels.items())
            for m in ent_metrics:
                if isinstance(m, Histogram):
                    lines.append(f"{m.name}_count{{{label_str}}} {m.count()}")
                    lines.append(f"{m.name}_sum{{{label_str}}} {m._total_sum}")
                    for p in (50, 95, 99):
                        lines.append(f'{m.name}{{{label_str},quantile="0.{p}"}} {m.percentile(p)}')
                else:
                    lines.append(f"{m.name}{{{label_str}}} {m.value()}")
        return "\n".join(lines) + "\n"


ROOT_REGISTRY = MetricRegistry()
