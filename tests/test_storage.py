"""LSM storage engine tests: blocks, SSTs, memtable, DB, compaction, recovery.

Modeled on the reference's rocksdb/db/db_test.cc + compaction_job_test.cc
tiers (SURVEY.md section 4).
"""

import os
import random

import numpy as np
import pytest

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.doc_key import DocKey, SubDocKey
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.ops.slabs import pack_doc_ht, pack_kvs
from yugabyte_tpu.storage import block_format
from yugabyte_tpu.storage.bloom import BloomFilter, BloomFilterBuilder, fnv64_masked
from yugabyte_tpu.storage.db import DB, DBOptions
from yugabyte_tpu.storage.memtable import MemTable
from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter, data_file_name


def ht(us, w=0):
    return DocHybridTime(HybridTime.from_micros(us), w)


def key_for(row, col=None):
    dk = DocKey(range_components=(f"row{row:05d}",))
    if col is None:
        return dk.encode()
    return SubDocKey(dk, (("col", col),)).encode(include_ht=False)


def make_slab(n_rows, t0=100):
    entries = []
    for r in range(n_rows):
        entries.append((key_for(r), pack_doc_ht(ht(t0 + r)),
                        Value(primitive=f"val{r}").encode()))
    entries.sort(key=lambda e: (e[0], -e[1]))
    return pack_kvs(entries)


class TestBlockFormat:
    def test_roundtrip(self):
        slab = make_slab(100)
        blk = block_format.encode_block(slab, 10, 60)
        out = block_format.decode_block(blk)
        assert out.n == 50
        assert out.key_bytes(0) == slab.key_bytes(10)
        assert out.values[0] == slab.values[int(slab.value_idx[10])]
        np.testing.assert_array_equal(out.ht_lo, slab.ht_lo[10:60])

    def test_compression(self):
        slab = make_slab(200)
        raw = block_format.encode_block(slab, 0, 200, compress=False)
        comp = block_format.encode_block(slab, 0, 200, compress=True)
        assert len(comp) < len(raw)
        assert block_format.decode_block(comp).values == block_format.decode_block(raw).values

    def test_corruption_detected(self):
        slab = make_slab(10)
        blk = bytearray(block_format.encode_block(slab, 0, 10))
        blk[40] ^= 0xFF
        from yugabyte_tpu.utils.status import StatusError
        with pytest.raises(StatusError):
            block_format.decode_block(bytes(blk))


class TestBloom:
    def test_no_false_negatives(self):
        keys = [key_for(i) for i in range(1000)]
        arrs = np.zeros((1000, 64), dtype=np.uint8)
        lens = np.zeros(1000, dtype=np.int64)
        for i, k in enumerate(keys):
            arrs[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
            lens[i] = len(k)
        b = BloomFilterBuilder(1000)
        b.add_hashes(fnv64_masked(arrs, lens))
        f = BloomFilter(b.finish())
        for k in keys:
            assert f.may_contain(k)

    def test_low_false_positive_rate(self):
        keys = [key_for(i) for i in range(1000)]
        b = BloomFilterBuilder(1000)
        arrs = np.zeros((1000, 64), dtype=np.uint8)
        lens = np.zeros(1000, dtype=np.int64)
        for i, k in enumerate(keys):
            arrs[i, :len(k)] = np.frombuffer(k, dtype=np.uint8)
            lens[i] = len(k)
        b.add_hashes(fnv64_masked(arrs, lens))
        f = BloomFilter(b.finish())
        fp = sum(f.may_contain(key_for(i)) for i in range(5000, 7000))
        assert fp < 2000 * 0.05  # ~1% expected at 10 bits/key


class TestSST:
    def test_write_read_roundtrip(self, tmp_path):
        slab = make_slab(10_000)
        path = str(tmp_path / "000001.sst")
        props = SSTWriter(path, block_entries=512).write(
            slab, Frontier(op_id_max=(1, 42), ht_max=123))
        assert os.path.exists(path) and os.path.exists(data_file_name(path))
        r = SSTReader(path)
        assert r.props.n_entries == 10_000
        assert r.props.frontier.op_id_max == (1, 42)
        assert r.n_blocks == 10_000 // 512 + 1
        got = list(r.iter_entries())
        assert len(got) == 10_000
        assert got[0][0] == slab.key_bytes(0)
        assert got[-1][2] == slab.values[int(slab.value_idx[slab.n - 1])]
        r.close()

    def test_seek_block(self, tmp_path):
        slab = make_slab(5000)
        path = str(tmp_path / "000001.sst")
        SSTWriter(path, block_entries=100).write(slab)
        r = SSTReader(path)
        target = slab.key_bytes(2345)
        b = r.seek_block(target)
        blk = r.read_block(b)
        keys = [blk.key_bytes(i) for i in range(blk.n)]
        assert keys[0] <= target <= keys[-1]
        r.close()

    def test_bloom_on_reader(self, tmp_path):
        slab = make_slab(500)
        path = str(tmp_path / "000001.sst")
        SSTWriter(path).write(slab)
        r = SSTReader(path)
        assert r.may_contain_doc(key_for(42))
        missing = sum(r.may_contain_doc(key_for(i)) for i in range(1000, 2000))
        assert missing < 50
        r.close()


class TestMemTable:
    def test_sorted_iteration(self):
        m = MemTable()
        rng = random.Random(7)
        rows = list(range(50)) * 2
        rng.shuffle(rows)
        for i, r in enumerate(rows):
            m.add(key_for(r), ht(100 + i), Value(primitive=i).encode())
        out = [k for k, _ in m.iter_from()]
        assert out == sorted(out)
        assert m.n_entries == 100

    def test_to_slab_sorted(self):
        m = MemTable()
        for r in [5, 1, 3]:
            m.add(key_for(r), ht(100), Value(primitive=r).encode())
        slab = m.to_slab()
        keys = [slab.key_bytes(i) for i in range(slab.n)]
        assert keys == sorted(keys)

    def test_add_batch_duplicate_keys_dedup(self):
        """add_batch defers duplicate suppression to sort time; an
        overwrite of the same (key, dht) across batches must surface
        exactly once (latest value) in iteration, point_get and to_slab."""
        m = MemTable()
        batch = [(key_for(r), ht(100), Value(primitive=r).encode())
                 for r in [2, 0, 1]]
        m.add_batch(batch)
        # interleave a point_get (forces a sort) between duplicate batches
        assert m.point_get(key_for(1), key_for(1)) is not None
        m.add_batch([(key_for(1), ht(100), Value(primitive=99).encode()),
                     (key_for(3), ht(100), Value(primitive=3).encode())])
        out = list(m.iter_from())
        assert [k for k, _ in out] == sorted(set(k for k, _ in out))
        assert len(out) == 4 and m.n_entries == 4
        hit = m.point_get(key_for(1), key_for(1))
        assert Value.decode(hit[1]).primitive == 99
        slab = m.to_slab()
        assert slab.n == 4


class TestDB:
    def _mk_db(self, tmp_path, **kw):
        opts = DBOptions(block_entries=128, auto_compact=False, **kw)
        return DB(str(tmp_path / "db"), opts)

    def test_put_get(self, tmp_path):
        db = self._mk_db(tmp_path)
        db.write_batch([(key_for(1), ht(100), Value(primitive="a").encode())])
        db.write_batch([(key_for(1), ht(200), Value(primitive="b").encode())])
        dht, val = db.get(key_for(1))
        assert Value.decode(val).primitive == "b"
        # read at earlier time sees earlier version (MVCC)
        dht, val = db.get(key_for(1), HybridTime.from_micros(150))
        assert Value.decode(val).primitive == "a"
        assert db.get(key_for(2)) is None
        db.close()

    def test_get_after_flush(self, tmp_path):
        db = self._mk_db(tmp_path)
        for r in range(300):
            db.write_batch([(key_for(r), ht(100 + r), Value(primitive=r).encode())])
        db.flush()
        assert db.n_live_files == 1
        dht, val = db.get(key_for(250))
        assert Value.decode(val).primitive == 250
        db.close()

    def test_recovery_from_manifest(self, tmp_path):
        db = self._mk_db(tmp_path)
        for r in range(100):
            db.write_batch([(key_for(r), ht(100 + r), Value(primitive=r).encode())])
        db.flush()
        for r in range(100, 150):
            db.write_batch([(key_for(r), ht(100 + r), Value(primitive=r).encode())])
        db.flush()
        db.close()
        db2 = self._mk_db(tmp_path)
        assert db2.n_live_files == 2
        assert Value.decode(db2.get(key_for(120))[1]).primitive == 120
        db2.close()

    def test_compaction_merges_files(self, tmp_path):
        db = self._mk_db(tmp_path, retention_policy=lambda: HybridTime.from_micros(10**9).value)
        for gen in range(4):
            for r in range(50):
                db.write_batch([(key_for(r), ht(1000 * (gen + 1) + r),
                                 Value(primitive=f"g{gen}r{r}").encode())])
            db.flush()
        assert db.n_live_files == 4
        db.compact_all()
        assert db.n_live_files == 1
        # only newest versions survive (cutoff far in future, major compaction)
        dht, val = db.get(key_for(10))
        assert Value.decode(val).primitive == "g3r10"
        total = sum(1 for _ in db.iter_from())
        assert total == 50
        db.close()

    def test_tombstones_gone_after_major(self, tmp_path):
        db = self._mk_db(tmp_path, retention_policy=lambda: HybridTime.kMax.value)
        db.write_batch([(key_for(1), ht(100), Value(primitive="x").encode())])
        db.flush()
        db.write_batch([(key_for(1), ht(200), Value.tombstone().encode())])
        db.flush()
        db.compact_all()
        assert db.get(key_for(1)) is None
        assert sum(1 for _ in db.iter_from()) == 0
        db.close()

    def test_history_retention(self, tmp_path):
        """Versions above history cutoff survive compaction (MVCC reads work)."""
        db = self._mk_db(tmp_path, retention_policy=lambda: HybridTime.from_micros(150).value)
        db.write_batch([(key_for(1), ht(100), Value(primitive="old").encode())])
        db.flush()
        db.write_batch([(key_for(1), ht(200), Value(primitive="new").encode())])
        db.flush()
        db.compact_all()
        # both survive: 200 is above cutoff; 100 is the visible-at-cutoff version
        assert sum(1 for _ in db.iter_from()) == 2
        _, val = db.get(key_for(1), HybridTime.from_micros(120))
        assert Value.decode(val).primitive == "old"
        db.close()

    def test_checkpoint(self, tmp_path):
        db = self._mk_db(tmp_path)
        for r in range(100):
            db.write_batch([(key_for(r), ht(100), Value(primitive=r).encode())])
        db.flush()
        ckpt = str(tmp_path / "ckpt")
        db.checkpoint(ckpt)
        db.close()
        db2 = DB(ckpt, DBOptions(auto_compact=False))
        assert Value.decode(db2.get(key_for(50))[1]).primitive == 50
        db2.close()

    def test_auto_compaction_trigger(self, tmp_path):
        opts = DBOptions(block_entries=128, auto_compact=True,
                         retention_policy=lambda: HybridTime.kMax.value)
        db = DB(str(tmp_path / "db"), opts)
        for gen in range(5):
            for r in range(30):
                db.write_batch([(key_for(r), ht(1000 * (gen + 1)),
                                 Value(primitive=gen).encode())])
            db.flush()
        # trigger is 4 runs; auto compaction should have fired synchronously
        assert db.n_live_files < 5
        db.close()


class TestSeekAcrossBlocks:
    def test_version_chain_spanning_blocks(self, tmp_path):
        """A key's version chain spilling across block boundaries: seeking
        an old read time must binary-search THROUGH the blocks that hold
        only newer versions — yielding them unfiltered would make the
        point read see a too-new version first and return None."""
        from yugabyte_tpu.storage.db import DB, DBOptions
        db = DB(str(tmp_path / "db"),
                DBOptions(block_entries=4, auto_compact=False))
        # 20 versions of ONE key: 5 blocks of 4 versions after flush
        for v in range(20):
            db.write_batch([(key_for(7), ht(1000 + v * 10),
                             Value(primitive=v).encode())])
        # neighbours so the key is not alone in the file
        db.write_batch([(key_for(1), ht(1), Value(primitive="lo").encode())])
        db.write_batch([(key_for(9), ht(1), Value(primitive="hi").encode())])
        db.flush()
        assert db.n_live_files == 1
        # newest version
        dht, val = db.get(key_for(7))
        assert Value.decode(val).primitive == 19
        # every historical version is reachable at its own read time
        for v in range(20):
            dht, val = db.get(key_for(7), HybridTime.from_micros(1000 + v * 10))
            assert Value.decode(val).primitive == v, f"version {v}"
        # a read BELOW the oldest version finds nothing
        assert db.get(key_for(7), HybridTime.from_micros(500)) is None
        db.close()


class TestLSMOptionSurface:
    def test_block_entries_flag(self, tmp_path):
        from yugabyte_tpu.utils import flags
        old = flags.get_flag("sst_block_entries")
        flags.set_flag("sst_block_entries", 8)
        try:
            db = DB(str(tmp_path / "db"), DBOptions(auto_compact=False))
            for r in range(40):
                db.write_batch([(key_for(r), ht(100 + r),
                                 Value(primitive=r).encode())])
            db.flush()
            rdr = next(iter(db._readers.values()))
            assert rdr.n_blocks == 5   # 40 rows / 8 per block
            db.close()
        finally:
            flags.set_flag("sst_block_entries", old)

    def test_compression_flag_round_trips(self, tmp_path):
        from yugabyte_tpu.utils import flags
        old = flags.get_flag("sst_compression")
        flags.set_flag("sst_compression", "zlib")
        try:
            db = DB(str(tmp_path / "dbz"), DBOptions(auto_compact=False))
            val = Value(primitive="x" * 500).encode()
            for r in range(50):
                db.write_batch([(key_for(r), ht(100 + r), val)])
            db.flush()
            assert Value.decode(db.get(key_for(25))[1]).primitive == "x" * 500
            db.close()
        finally:
            flags.set_flag("sst_compression", old)

    def test_max_merge_width_caps_pick(self):
        from yugabyte_tpu.storage.compaction import pick_universal
        from yugabyte_tpu.storage.version_set import FileMeta
        from yugabyte_tpu.utils import flags
        from yugabyte_tpu.storage.sst import SSTProps
        files = [FileMeta(file_id=i, path=f"f{i}",
                          props=SSTProps(n_entries=10, data_size=1000))
                 for i in range(20)]
        old = flags.get_flag("universal_compaction_max_merge_width")
        flags.set_flag("universal_compaction_max_merge_width", 6)
        try:
            pick = pick_universal(files)
            assert pick is not None and len(pick.inputs) == 6
        finally:
            flags.set_flag("universal_compaction_max_merge_width", old)

    def test_always_include_small_runs(self):
        from yugabyte_tpu.storage.compaction import pick_universal
        from yugabyte_tpu.storage.version_set import FileMeta
        # a big base run would normally stop accumulation; a tiny file
        # below the always-include threshold still joins
        from yugabyte_tpu.storage.sst import SSTProps

        def fm(i, size):
            return FileMeta(file_id=i, path=f"f{i}",
                            props=SSTProps(n_entries=10, data_size=size))
        # a 32KB run fails the size-ratio test against a 100B
        # accumulation but sits under the always-include threshold, so
        # accumulation continues and the 4-run trigger is reached —
        # without always-include this layout never compacts
        files = [fm(1, 100), fm(2, 32 << 10), fm(3, 100), fm(4, 100)]
        pick = pick_universal(files)
        assert pick is not None and len(pick.inputs) == 4
        from yugabyte_tpu.utils import flags as _f
        old = _f.get_flag("universal_compaction_always_include_size_bytes")
        _f.set_flag("universal_compaction_always_include_size_bytes", 0)
        try:
            assert pick_universal(files) is None   # ratio rule alone stops
        finally:
            _f.set_flag(
                "universal_compaction_always_include_size_bytes", old)
