#!/usr/bin/env python
"""Thin CLI shim over yblint's metric-names pass.

The analysis itself moved to tools/analysis/passes/metric_names.py (one
parse of each file shared by every pass — run the full analyzer with
`python -m tools.analysis`). This module keeps the original entry point
and the check_file/check_paths API the tier-1 wiring
(tests/test_observability.py) uses.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.analysis.core import analyze_file  # noqa: E402
from tools.analysis.passes.metric_names import (  # noqa: E402
    DEFAULT_DIRS, MetricNamesPass)


class _Anywhere(MetricNamesPass):
    """check_file must lint ANY path (tests hand it tmp files)."""

    def applies_to(self, relpath: str) -> bool:
        return True


def check_file(path: str) -> List[Tuple[str, int, str]]:
    fs = analyze_file(path, path, [_Anywhere()])
    return [(f.path, f.line, f.message) for f in fs]


def check_paths(root: str, dirs=DEFAULT_DIRS) -> List[Tuple[str, int, str]]:
    offenses: List[Tuple[str, int, str]] = []
    for d in dirs:
        base = os.path.join(root, d)
        for dirpath, _dirs, files in os.walk(base):
            for fn in sorted(files):
                if fn.endswith(".py"):
                    offenses.extend(check_file(os.path.join(dirpath, fn)))
    return offenses


def main() -> int:
    offenses = check_paths(_ROOT)
    for path, lineno, msg in offenses:
        print(f"{os.path.relpath(path, _ROOT)}:{lineno}: {msg}")
    if offenses:
        print(f"{len(offenses)} metric-name offense(s)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
