"""Flag registry with tags and runtime mutation.

Capability parity with gflags + yb flag tags (ref: src/yb/util/flag_tags.h;
runtime mutation via SetFlag RPC, src/yb/server/generic_service.cc). Flags are
process-global, typed, taggable, and hot-mutable; `get_flag` is cheap enough
for hot paths (dict lookup).
"""

from __future__ import annotations

import enum
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


class FlagTag(enum.Enum):
    STABLE = "stable"
    EVOLVING = "evolving"
    UNSAFE = "unsafe"
    RUNTIME = "runtime"  # mutable at runtime without restart
    SENSITIVE = "sensitive"
    ADVANCED = "advanced"
    HIDDEN = "hidden"
    TEST = "test"  # TEST_ flags: fault injection / test hooks


@dataclass
class _Flag:
    name: str
    default: Any
    help: str
    type: type
    tags: List[FlagTag]
    value: Any
    validator: Optional[Callable[[Any], bool]] = None


_REGISTRY: Dict[str, _Flag] = {}
_LOCK = threading.Lock()


def define_flag(name: str, default: Any, help: str = "", tags: List[FlagTag] = (),
                validator: Optional[Callable[[Any], bool]] = None) -> None:
    with _LOCK:
        if name in _REGISTRY:
            # Idempotent re-definition with identical default is fine (module reloads).
            if _REGISTRY[name].default != default:
                raise ValueError(f"flag {name} already defined with different default")
            return
        value = default
        env = os.environ.get(f"YBTPU_{name.upper()}")
        if env is not None:
            value = _parse(env, type(default))
            if validator and not validator(value):
                raise ValueError(
                    f"invalid env value for flag {name}: YBTPU_{name.upper()}={env!r}")
        _REGISTRY[name] = _Flag(name, default, help, type(default), list(tags), value, validator)


def _parse(text: str, typ: type) -> Any:
    if typ is bool:
        return text.lower() in ("1", "true", "yes", "on")
    return typ(text)


def get_flag(name: str) -> Any:
    return _REGISTRY[name].value


def set_flag(name: str, value: Any) -> None:
    with _LOCK:
        flag = _REGISTRY[name]
        if not isinstance(value, flag.type):
            value = _parse(str(value), flag.type)
        if flag.validator and not flag.validator(value):
            raise ValueError(f"invalid value for flag {name}: {value!r}")
        flag.value = value


def all_flags() -> Dict[str, Any]:
    return {name: f.value for name, f in _REGISTRY.items()}


def reset_flag(name: str) -> None:
    with _LOCK:
        _REGISTRY[name].value = _REGISTRY[name].default
