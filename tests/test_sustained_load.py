"""Sustained-load invariant harness (VERDICT r3 #6): a rate-paced
linked-list workload against a real-process cluster while compactions,
a kill -9, a restart and a tablet split churn underneath — then a full
verification walk plus ysck and cross-replica checksums.

Scaled for CI (~45 s of load); YBTPU_LOAD_SECONDS=300 runs the full
5-minute soak the reference's linked_list-test targets.
ref: src/yb/integration-tests/linked_list-test.cc,
src/yb/util/load_generator.h.
"""

import io
import os
import time

import pytest

from yugabyte_tpu.integration.external_mini_cluster import (
    ExternalMiniCluster)
from yugabyte_tpu.integration.load_generator import (
    LINKED_LIST_SCHEMA, LinkedListLoadGenerator)
from yugabyte_tpu.tools import ysck


@pytest.mark.slow
def test_linked_list_under_churn(tmp_path):
    seconds = float(os.environ.get("YBTPU_LOAD_SECONDS", 45))
    c = ExternalMiniCluster(str(tmp_path / "cluster"), num_tservers=3,
                            rf=3).start()
    try:
        c.wait_tservers_alive(3)
        client = c.new_client()
        client.create_namespace("load")
        # small memstore via cluster flags would need restarts; default
        # flushes still occur from the volume of writes over the run
        table = client.create_table("load", "chains", LINKED_LIST_SCHEMA,
                                    num_tablets=4)
        # deflake: writers must not race the fresh tablets' first
        # elections (the known create-then-write leadership flake)
        c.wait_table_leaders(client, table.table_id)

        gen = LinkedListLoadGenerator(client, table, n_chains=4,
                                      ops_per_sec=120.0).start()
        third = seconds / 3.0
        time.sleep(third)

        # churn 1: kill -9 a tserver mid-load, writers keep going
        c.tservers[1].kill9()
        time.sleep(third / 2)
        # churn 2: restart it (remote bootstrap / catch-up underneath)
        c.tservers[1].start()
        c.wait_tservers_alive(3)
        time.sleep(third / 2)

        # churn 3: split one tablet of the loaded table mid-writes
        locs = client._master_call("get_table_locations",
                                   table_id=table.table_id)
        client._master_call("split_tablet",
                            tablet_id=locs[0]["tablet_id"])
        time.sleep(third)

        report = gen.stop()
        assert report.written_acked > seconds * 40, (
            f"load too slow to be meaningful: {report}")

        # full verification walk: no lost, no phantom, no broken chains
        counters = gen.verify(client)
        assert counters["present"] >= report.written_acked

        # cross-replica agreement + cluster health
        c.verify_replica_checksums(client, table)
        buf = io.StringIO()
        problems = ysck.check_cluster([c.master.address], out=buf)
        assert problems == 0, f"ysck found problems:\n{buf.getvalue()}"
        client.close()
    finally:
        c.shutdown()


@pytest.mark.slow
def test_ycsb_soak_stage_smoke(tmp_path, monkeypatch):
    """BASELINE config 5 harness smoke: the bench's cluster-soak stage
    produces a measured ops/s + p99 with churn underneath (short run)."""
    monkeypatch.setenv("YBTPU_BENCH_SOAK_SECONDS", "12")
    from bench import _cluster_soak_stage
    out = _cluster_soak_stage()
    assert out.get("cluster_ops_per_sec", 0) > 0
    assert out.get("cluster_p99_ms", 0) > 0
    assert out.get("cluster_soak_ops", 0) > 50
