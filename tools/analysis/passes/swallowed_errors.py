"""swallowed-errors: no silently swallowed errors in the storage-critical
layers (migrated from the standalone tools/lint_swallowed_errors.py; the
old module remains as a thin CLI shim over this pass).

The failure-containment design routes every background I/O error to the
DB background-error slot (storage/db.py), the WAL seal (consensus/log.py)
or at minimum a TRACE line — an `except Exception: pass` in storage/,
consensus/ or tablet/ is exactly the hole that turns an injected disk
fault into silent corruption instead of a contained FAILED tablet.

Flags every broad handler (bare `except:`, `except Exception`,
`except BaseException`) whose body only discards the error, unless it
routes the error (raise / TRACE(...) / background_error / mark_failed /
_fail / set_background_error), sits inside `__del__`, or the except line
carries `# lint: swallow-ok` (legacy) or
`# yblint: disable=swallowed-errors`.
"""

from __future__ import annotations

import ast
from typing import List

from tools.analysis.core import AnalysisPass, FileContext, Finding

PASS_NAME = "swallowed-errors"

DEFAULT_DIRS = ("yugabyte_tpu/storage", "yugabyte_tpu/consensus",
                "yugabyte_tpu/tablet")

_BROAD = {"Exception", "BaseException"}
_ROUTING_NAMES = ("TRACE", "trace")
_ROUTING_ATTRS = ("background_error", "set_background_error",
                  "mark_failed", "_fail")
_WAIVER = "lint: swallow-ok"


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    for node in ([t.elts] if isinstance(t, ast.Tuple) else [[t]])[0]:
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return any(n in _BROAD for n in names)


def _routes_error(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            if name in _ROUTING_NAMES or any(a in name
                                             for a in _ROUTING_ATTRS):
                return True
    return False


def _only_discards(handler: ast.ExceptHandler) -> bool:
    """Body is nothing but pass / continue / bare return — the error is
    dropped on the floor with no side channel."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Return) and stmt.value is None:
            continue
        return False
    return True


class SwallowedErrorsPass(AnalysisPass):
    name = PASS_NAME

    def __init__(self, dirs=DEFAULT_DIRS):
        self.dirs = tuple(d.rstrip("/") + "/" for d in dirs)

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith(self.dirs)

    def run(self, ctx: FileContext) -> List[Finding]:
        out: List[Finding] = []
        for node in ctx.nodes_of(ast.ExceptHandler):
            if not (_is_broad(node) and _only_discards(node)):
                continue
            if _routes_error(node):
                continue
            fn = ctx.enclosing_function(node)
            if fn is not None and fn.name == "__del__":
                continue  # teardown swallows are idiomatic and unroutable
            if ctx.line_comment_has(node.lineno, _WAIVER):
                continue
            out.append(ctx.finding(
                self.name, "swallowed", node,
                "broad except swallows the error (route it to the "
                "background-error slot or TRACE)"))
        return out
