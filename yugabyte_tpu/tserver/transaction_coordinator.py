"""TransactionCoordinator: the status-tablet state machine.

Capability parity with the reference (ref: src/yb/tablet/
transaction_coordinator.h:86 — per-status-tablet transaction records
PENDING/COMMITTED/ABORTED replicated through the tablet's Raft group,
client heartbeats keeping transactions alive, expired transactions aborted,
participants notified to apply/cleanup after resolution).

Status records are plain rows in the `system.transactions` table, written
through the ordinary WriteQuery/Raft/LSM pipeline — replication and
failover need no special handling. The coordinator layer adds the
check-and-set serialization (leader-local mutex per transaction) and the
participant notification fan-out.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from yugabyte_tpu.common.hybrid_time import HybridTime
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import Status, StatusError
from yugabyte_tpu.utils.trace import TRACE
from yugabyte_tpu.utils import lock_rank

flags.define_flag("transaction_timeout_ms", 10_000,
                  "a pending transaction whose last heartbeat is older than "
                  "this is aborted (ref transaction_abort_check_timeout_ms)")
flags.define_flag("txn_notify_attempts", 6,
                  "participant apply/cleanup notification retries")

TRANSACTIONS_TABLE = "transactions"
SYSTEM_NAMESPACE = "system"

TXN_STATUS_SCHEMA = Schema(
    columns=[
        ColumnSchema("txn_id", DataType.BINARY),
        ColumnSchema("status", DataType.STRING),
        ColumnSchema("commit_ht", DataType.INT64),
        ColumnSchema("participants", DataType.STRING),
        ColumnSchema("heartbeat_ms", DataType.INT64),
    ],
    num_hash_key_columns=1)

_COL_STATUS = TXN_STATUS_SCHEMA.column_id("status")
_COL_COMMIT_HT = TXN_STATUS_SCHEMA.column_id("commit_ht")
_COL_HEARTBEAT = TXN_STATUS_SCHEMA.column_id("heartbeat_ms")
_COL_PARTICIPANTS = TXN_STATUS_SCHEMA.column_id("participants")


def _now_ms() -> int:
    return int(time.time() * 1000)


class TransactionCoordinator:
    """Coordinator operations over locally hosted status tablets. Every
    method takes the status tablet's TabletPeer (leader-checked by the
    RPC layer above)."""

    def __init__(self, leader_resolver=None, messenger=None):
        # leader_resolver(tablet_id) -> addr for participant notification
        self._leader_resolver = leader_resolver or (lambda tid: None)
        self._messenger = messenger
        self._mutexes: Dict[bytes, threading.Lock] = {}  # guarded-by: _mutexes_lock
        self._mutexes_lock = lock_rank.tracked(
            threading.Lock(), "txn_coordinator._mutexes_lock")

    def _txn_mutex(self, txn_id: bytes) -> threading.Lock:
        with self._mutexes_lock:
            return self._mutexes.setdefault(txn_id, threading.Lock())

    def _drop_mutex(self, txn_id: bytes) -> None:
        """Terminal states prune the per-txn mutex (unbounded otherwise);
        a racing late op simply recreates it."""
        with self._mutexes_lock:
            self._mutexes.pop(txn_id, None)

    @staticmethod
    def _key(txn_id: bytes) -> DocKey:
        return DocKey(hash_components=(txn_id,))

    def _read(self, peer, txn_id: bytes) -> Optional[dict]:
        row = peer.tablet.read_row(self._key(txn_id))
        if row is None:
            return None
        return {"status": row.columns.get(_COL_STATUS),
                "commit_ht": row.columns.get(_COL_COMMIT_HT),
                "heartbeat_ms": row.columns.get(_COL_HEARTBEAT),
                "participants": row.columns.get(_COL_PARTICIPANTS)}

    # --------------------------------------------------------------- ops
    def create(self, peer, txn_id: bytes) -> dict:
        """Register a new pending transaction; returns its read point
        (the coordinator clock's now — all txn reads snapshot here)."""
        read_ht = peer.clock.now()
        peer.write([QLWriteOp(WriteOpKind.INSERT, self._key(txn_id),
                              {"status": "pending",
                               "heartbeat_ms": _now_ms()})])
        return {"read_ht": read_ht.value}

    def heartbeat(self, peer, txn_id: bytes) -> bool:
        with self._txn_mutex(txn_id):
            rec = self._read(peer, txn_id)
            if rec is None or rec["status"] != "pending":
                raise StatusError(Status.Expired(
                    f"txn {txn_id.hex()[:8]} is "
                    f"{rec['status'] if rec else 'unknown'}"))
            peer.write([QLWriteOp(WriteOpKind.UPDATE, self._key(txn_id),
                                  {"heartbeat_ms": _now_ms()})])
        return True

    def status(self, peer, txn_id: bytes,
               observing_read_ht: Optional[int] = None) -> dict:
        """Resolve a transaction's fate; lazily aborts expired pending
        transactions (ref coordinator expiration check).

        `observing_read_ht`: the reader's pinned snapshot. Folding it into
        this coordinator's hybrid clock BEFORE answering guarantees any
        LATER commit of this transaction gets commit_ht > the snapshot —
        so a 'pending' answer can never be torn by a subsequent commit
        landing inside the already-served snapshot (ref: the reference
        floors commit time above outstanding status-request times)."""
        if observing_read_ht:
            peer.clock.update(HybridTime(observing_read_ht))
        # The whole read runs under the per-txn mutex: commit() holds it
        # from picking commit_ht until the replicated write applies, so a
        # status read can never land inside that window and answer
        # 'pending' for a transaction about to commit at
        # commit_ht <= observing_read_ht (which would tear the snapshot —
        # the clock folding above only covers commits that START after us).
        with self._txn_mutex(txn_id):
            rec = self._read(peer, txn_id)
            if rec is None:
                # Never created here or already GC'd: treat as aborted
                # (the reference returns ABORTED for unknown transactions).
                return {"status": "aborted", "commit_ht": None}
            if rec["status"] == "pending":
                timeout = flags.get_flag("transaction_timeout_ms")
                if _now_ms() - (rec["heartbeat_ms"] or 0) > timeout:
                    # Lazy expiry: a concurrent heartbeat renewal can't be
                    # stomped by a stale-read abort — we hold the mutex.
                    self._abort_locked(peer, txn_id, [], rec)
                    self._drop_mutex(txn_id)
                    return {"status": "aborted", "commit_ht": None}
            return {"status": rec["status"], "commit_ht": rec["commit_ht"]}

    def commit(self, peer, txn_id: bytes,
               participants: List[List]) -> dict:
        """COMMIT: check-and-set pending -> committed with a commit hybrid
        time, then fan out apply notifications (ref
        TransactionCoordinator::ProcessReplicated COMMITTED branch)."""
        import json
        with self._txn_mutex(txn_id):
            rec = self._read(peer, txn_id)
            if rec is None:
                raise StatusError(Status.Expired(
                    f"txn {txn_id.hex()[:8]} unknown (expired?)"))
            if rec["status"] == "committed":
                return {"commit_ht": rec["commit_ht"]}  # idempotent retry
            if rec["status"] != "pending":
                raise StatusError(Status.Aborted(
                    f"txn {txn_id.hex()[:8]} already {rec['status']}"))
            commit_ht = peer.clock.now()
            peer.write([QLWriteOp(
                WriteOpKind.UPDATE, self._key(txn_id),
                {"status": "committed", "commit_ht": commit_ht.value,
                 "participants": json.dumps(participants)})])
        self._notify_async(txn_id, "apply_transaction", participants,
                           commit_ht.value)
        self._drop_mutex(txn_id)
        return {"commit_ht": commit_ht.value}

    def abort(self, peer, txn_id: bytes,
              participants: List[List]) -> bool:
        with self._txn_mutex(txn_id):
            rec = self._read(peer, txn_id)
            self._abort_locked(peer, txn_id, participants, rec)
        self._drop_mutex(txn_id)
        return True

    def _abort_locked(self, peer, txn_id: bytes,
                      participants: List[List],
                      rec: Optional[dict]) -> None:
        import json
        if rec is not None and rec["status"] == "committed":
            raise StatusError(Status.IllegalState(
                f"txn {txn_id.hex()[:8]} already committed"))
        if rec is not None and not participants and \
                rec.get("participants"):
            participants = json.loads(rec["participants"])
        peer.write([QLWriteOp(
            WriteOpKind.INSERT, self._key(txn_id),
            {"status": "aborted",
             "participants": json.dumps(participants or [])})])
        self._notify_async(txn_id, "cleanup_transaction", participants, 0)

    # -------------------------------------------------- participant fanout
    def _notify_async(self, txn_id: bytes, mth: str,
                      participants: List[List], commit_ht: int) -> None:
        if not participants or self._messenger is None:
            return
        threading.Thread(
            target=self._notify, daemon=True,
            name=f"txn-notify-{txn_id.hex()[:8]}",
            args=(txn_id, mth, participants, commit_ht)).start()

    def _notify(self, txn_id: bytes, mth: str, participants: List[List],
                commit_ht: int) -> None:
        pending = {tuple(p) for p in participants}
        for attempt in range(flags.get_flag("txn_notify_attempts")):
            for tablet_id, addr in list(pending):
                target = self._leader_resolver(tablet_id) or addr
                if target is None:
                    continue
                try:
                    self._messenger.call(
                        target, "tserver", mth, timeout_s=10.0,
                        tablet_id=tablet_id, txn_id=txn_id,
                        commit_ht=commit_ht)
                    pending.discard((tablet_id, addr))
                except StatusError:
                    pass
            if not pending:
                return
            time.sleep(0.3 * (attempt + 1))
        TRACE("txn %s: %s never reached %s", txn_id.hex()[:8], mth, pending)
