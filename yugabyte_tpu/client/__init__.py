"""Client library: partition-aware routing, op batching, DDL.

Capability parity with src/yb/client (ref: client.h:264 YBClient,
meta_cache.h:484, session.h:96 / batcher.h:148).
"""

from yugabyte_tpu.client.client import YBClient, YBTable
from yugabyte_tpu.client.session import YBSession

__all__ = ["YBClient", "YBTable", "YBSession"]
