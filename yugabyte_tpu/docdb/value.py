"""DocDB value encoding: control fields (TTL, merge flags) + primitive payload.

Capability parity with the reference's Value (ref: src/yb/docdb/value.h;
Value::DecodeControlFields used at docdb_compaction_filter.cc:222). An encoded
value is:

    [kMergeFlags + u32]?  [kTTL + i64 millis]?  <primitive payload>

where the payload is a PrimitiveValue encoding, kTombstone for deletes, or
kObject for an (empty) subdocument container marker.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional, Tuple

from yugabyte_tpu.docdb.doc_key import PrimitiveType, PrimitiveValue
from yugabyte_tpu.docdb.value_type import ValueType

kTtlFlag = 0x1  # merge flag marking a "TTL-only" merge record (redis EXPIRE)


@dataclass(frozen=True)
class Value:
    primitive: PrimitiveType = None           # payload (ignored for tombstone/object)
    is_tombstone: bool = False
    is_object: bool = False                   # object/subdocument init marker
    ttl_ms: Optional[int] = None              # relative TTL in milliseconds
    merge_flags: int = 0

    def encode(self) -> bytes:
        buf = bytearray()
        if self.merge_flags:
            buf.append(ValueType.kMergeFlags)
            buf += struct.pack(">I", self.merge_flags)
        if self.ttl_ms is not None:
            buf.append(ValueType.kTTL)
            buf += struct.pack(">q", self.ttl_ms)
        if self.is_tombstone:
            buf.append(ValueType.kTombstone)
        elif self.is_object:
            buf.append(ValueType.kObject)
        else:
            PrimitiveValue.encode(self.primitive, buf)
        return bytes(buf)

    @staticmethod
    def decode(data: bytes) -> "Value":
        merge_flags, ttl_ms, pos = decode_control_fields(data)
        if pos >= len(data):
            raise ValueError("empty value payload")
        tag = data[pos]
        if tag == ValueType.kTombstone:
            return Value(None, True, False, ttl_ms, merge_flags)
        if tag == ValueType.kObject:
            return Value(None, False, True, ttl_ms, merge_flags)
        prim, _ = PrimitiveValue.decode(data, pos)
        return Value(prim, False, False, ttl_ms, merge_flags)

    @staticmethod
    def tombstone() -> "Value":
        return Value(is_tombstone=True)


def decode_control_fields(data: bytes) -> Tuple[int, Optional[int], int]:
    """(merge_flags, ttl_ms, payload_offset) without decoding the payload.

    Mirrors Value::DecodeControlFields — the compaction filter peeks at TTL
    and merge flags without materializing values (docdb_compaction_filter.cc:222).
    """
    pos = 0
    merge_flags = 0
    ttl_ms = None
    if pos < len(data) and data[pos] == ValueType.kMergeFlags:
        (merge_flags,) = struct.unpack_from(">I", data, pos + 1)
        pos += 5
    if pos < len(data) and data[pos] == ValueType.kTTL:
        (ttl_ms,) = struct.unpack_from(">q", data, pos + 1)
        pos += 9
    return merge_flags, ttl_ms, pos
