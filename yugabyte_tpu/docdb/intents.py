"""Provisional records (intents) for distributed transactions.

Capability parity with the reference's intent format (ref:
src/yb/docdb/intent_aware_iterator.h:56 — `SubDocKey + IntentType +
HybridTime -> TxnId + value`; reverse index records keyed by transaction id
used by apply/cleanup, ref docdb/docdb.h:242 PrepareApplyIntentsBatch).

Layout here (internal keys get the write's DocHybridTime appended by the
storage layer, exactly like regular records):

  primary:  [subdoc_key][kIntentTypeSet][intent_type]  ->
            [kTransactionId][16B txn uuid][status_tablet utf8 len+bytes]
            [kWriteId][u32 write_id][value bytes]
  reverse:  [kTransactionId][16B txn uuid][u64 seq]    ->  [primary prefix]

Intent resolution (apply to regular DB at commit, or cleanup on abort)
writes TOMBSTONES over both records at the resolution hybrid time — the
storage layer has no point deletes (LSM + MVCC), and the normal compaction
GC reclaims resolved intents past the retention horizon.
"""

from __future__ import annotations

import struct
import uuid
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.doc_key import split_key_and_ht
from yugabyte_tpu.docdb.lock_manager import IntentType
from yugabyte_tpu.docdb.value import Value
from yugabyte_tpu.docdb.value_type import ValueType

_SEQ = struct.Struct(">Q")
_WRITE_ID = struct.Struct(">I")


@dataclass(frozen=True)
class TransactionMetadata:
    """Client-supplied txn identity attached to every transactional write
    (ref common/transaction.h TransactionMetadata)."""

    txn_id: bytes              # 16 raw bytes (uuid)
    status_tablet: str
    priority: int = 0
    read_ht: Optional[int] = None  # snapshot the txn reads at

    @staticmethod
    def new(status_tablet: str, read_ht: Optional[int] = None,
            priority: int = 0) -> "TransactionMetadata":
        return TransactionMetadata(uuid.uuid4().bytes, status_tablet,
                                   priority, read_ht)

    def to_wire(self) -> dict:
        return {"txn_id": self.txn_id, "status_tablet": self.status_tablet,
                "priority": self.priority, "read_ht": self.read_ht}

    @staticmethod
    def from_wire(w: dict) -> "TransactionMetadata":
        return TransactionMetadata(w["txn_id"], w["status_tablet"],
                                   w.get("priority", 0), w.get("read_ht"))


def make_status_cache(status_resolver, read_ht_value=None):
    """Memoizing wrapper over a status resolver — one coordinator lookup
    per transaction per operation. Resolver signature:
    (status_tablet, txn_id, read_ht=None) -> {"status", "commit_ht"};
    None resolves everything as conservatively pending."""
    statuses = {}

    def status_of(txn_id: bytes, status_tablet: str) -> dict:
        if txn_id not in statuses:
            if status_resolver is None:
                statuses[txn_id] = {"status": "pending", "commit_ht": None}
            else:
                statuses[txn_id] = status_resolver(status_tablet, txn_id,
                                                   read_ht_value)
        return statuses[txn_id]

    return status_of


# ------------------------------------------------------------------ encoding
def encode_intent_key(subdoc_key: bytes, intent_type: IntentType) -> bytes:
    return subdoc_key + bytes([ValueType.kIntentTypeSet, intent_type])


def decode_intent_key(key: bytes) -> Optional[Tuple[bytes, IntentType]]:
    """-> (subdoc_key, intent_type), or None if not an intent key."""
    if len(key) < 2 or key[-2] != ValueType.kIntentTypeSet:
        return None
    return key[:-2], IntentType(key[-1])


def encode_intent_value(meta: TransactionMetadata, write_id: int,
                        value_bytes: bytes) -> bytes:
    st = meta.status_tablet.encode("utf-8")
    return (bytes([ValueType.kTransactionId]) + meta.txn_id
            + struct.pack(">H", len(st)) + st
            + bytes([ValueType.kWriteId]) + _WRITE_ID.pack(write_id)
            + value_bytes)


def decode_intent_value(raw: bytes) -> Tuple[bytes, str, int, bytes]:
    """-> (txn_id, status_tablet, write_id, value_bytes)."""
    assert raw[0] == ValueType.kTransactionId, "not an intent value"
    txn_id = raw[1:17]
    (st_len,) = struct.unpack_from(">H", raw, 17)
    pos = 19
    status_tablet = raw[pos:pos + st_len].decode("utf-8")
    pos += st_len
    assert raw[pos] == ValueType.kWriteId
    (write_id,) = _WRITE_ID.unpack_from(raw, pos + 1)
    return txn_id, status_tablet, write_id, raw[pos + 5:]


def reverse_index_key(txn_id: bytes, seq: int) -> bytes:
    return bytes([ValueType.kTransactionId]) + txn_id + _SEQ.pack(seq)


def reverse_index_prefix(txn_id: bytes) -> bytes:
    return bytes([ValueType.kTransactionId]) + txn_id


def make_intent_batch(meta: TransactionMetadata,
                      kv_pairs: List[Tuple[bytes, bytes]],
                      lock_entries: List[Tuple[bytes, IntentType]],
                      write_id_base: int = 0
                      ) -> List[Tuple[bytes, bytes]]:
    """Flattened (key_prefix, value) pairs for the intents DB: one strong
    primary intent per written KV (carrying the provisional value), weak
    intents on the prefixes (empty payload), and a reverse-index record per
    primary intent. write_id_base + intra-batch index becomes the
    write_id: the base carries the transaction's STATEMENT sequence (the
    reference's IntraTxnWriteId), so a later statement's writes sort
    ABOVE an earlier statement's at the shared commit hybrid time — an
    UPDATE element must not be shadowed by the INSERT's collection
    marker (ref: docdb/intent.h IntraTxnWriteId)."""
    out: List[Tuple[bytes, bytes]] = []
    seq = 0
    for i, (subdoc_key, value_bytes) in enumerate(kv_pairs):
        write_id = write_id_base + i
        pk = encode_intent_key(subdoc_key, IntentType.kStrongWrite)
        out.append((pk, encode_intent_value(meta, write_id, value_bytes)))
        out.append((reverse_index_key(meta.txn_id, seq), pk))
        seq += 1
    seen = {k for k, _ in kv_pairs}
    for key, itype in lock_entries:
        if itype.is_strong or key in seen:
            continue
        wk = encode_intent_key(key, itype)
        out.append((wk, encode_intent_value(meta, 0xFFFFFFFF, b"")))
        out.append((reverse_index_key(meta.txn_id, seq), wk))
        seq += 1
    return out


# ----------------------------------------------------------------- scanning
def latest_intents_in_range(intents_db, lower: bytes,
                            upper: Optional[bytes] = None
                            ) -> Iterator[Tuple[bytes, IntentType,
                                                DocHybridTime, bytes]]:
    """Yield (subdoc_key, intent_type, write_dht, raw_intent_value) for the
    LATEST un-resolved version of every intent key in [lower, upper).
    Resolved intents (tombstoned by apply/cleanup) are skipped."""
    cur_prefix: Optional[bytes] = None
    for ikey, raw in intents_db.iter_from(lower):
        prefix, dht = split_key_and_ht(ikey)
        if dht is None:
            continue
        if prefix[:1] == bytes([ValueType.kTransactionId]):
            continue  # reverse-index region sorts separately
        if upper is not None and prefix >= upper:
            break
        if prefix == cur_prefix:
            continue  # older version of the same intent key
        cur_prefix = prefix
        decoded = decode_intent_key(prefix)
        if decoded is None:
            continue
        if raw[:1] == bytes([ValueType.kTombstone]):
            continue  # resolved (applied/cleaned up)
        subdoc_key, itype = decoded
        yield subdoc_key, itype, dht, raw


def txn_intents(intents_db, txn_id: bytes
                ) -> List[Tuple[bytes, DocHybridTime, bytes]]:
    """All unresolved primary/weak intents of one transaction, via the
    reverse index: (intent_key_prefix, write_dht, raw_intent_value)."""
    prefix = reverse_index_prefix(txn_id)
    upper = prefix + b"\xff" * 9
    out = []
    cur: Optional[bytes] = None
    for ikey, raw in intents_db.iter_from(prefix):
        rkey, dht = split_key_and_ht(ikey)
        if dht is None or not rkey.startswith(prefix) or rkey >= upper:
            break
        if rkey == cur:
            continue
        cur = rkey
        if raw[:1] == bytes([ValueType.kTombstone]):
            continue
        intent_key = raw
        got = intents_db.get(intent_key)
        if got is None:
            continue
        int_dht, int_raw = got
        if int_raw[:1] == bytes([ValueType.kTombstone]):
            continue
        # Ownership check: after this txn's intent at the key was resolved,
        # another txn may have legally written its own intent there
        # (conflict resolution permits overwriting aborted/committed
        # intents). Resolving that foreign intent as ours would tombstone
        # live data or publish uncommitted values — skip any record whose
        # embedded txn id is not ours.
        if (int_raw[:1] != bytes([ValueType.kTransactionId])
                or int_raw[1:17] != txn_id):
            continue
        out.append((intent_key, int_dht, int_raw))
    return out
