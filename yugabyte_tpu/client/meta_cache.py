"""MetaCache: tablet-location cache keyed by partition key.

Capability parity with the reference (ref: src/yb/client/meta_cache.h:484 —
per-table partition->RemoteTablet map filled from master
GetTableLocations, leader marking from follower NOT_THE_LEADER retries,
invalidation on stale lookups).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from yugabyte_tpu.common.partition import Partition, partition_for_key
from yugabyte_tpu.common.wire import partition_from_wire


@dataclass
class RemoteReplica:
    server_id: str
    addr: Optional[str]


class RemoteTablet:
    """ref meta_cache.h RemoteTablet"""

    def __init__(self, tablet_id: str, partition: Partition,
                 replicas: List[RemoteReplica], leader: Optional[str]):
        self.tablet_id = tablet_id
        self.partition = partition
        self.replicas = replicas
        self.leader = leader  # server_id

    def leader_addr(self) -> Optional[str]:
        for r in self.replicas:
            if r.server_id == self.leader:
                return r.addr
        return None

    def mark_leader(self, server_id: str) -> None:
        self.leader = server_id

    def candidate_addrs(self) -> List[str]:
        """Leader first, then the rest (the reference walks replicas the
        same way when the leader is unknown)."""
        out = []
        la = self.leader_addr()
        if la:
            out.append(la)
        for r in self.replicas:
            if r.addr and r.addr not in out:
                out.append(r.addr)
        return out


class MetaCache:
    def __init__(self, lookup_locations):
        """lookup_locations(table_id) -> wire locations from the master."""
        self._lookup = lookup_locations
        self._lock = threading.Lock()
        self._tables: Dict[str, List[RemoteTablet]] = {}

    def _refresh(self, table_id: str) -> List[RemoteTablet]:
        locs = self._lookup(table_id)
        tablets = [
            RemoteTablet(
                loc["tablet_id"], partition_from_wire(loc["partition"]),
                [RemoteReplica(r["server_id"], r["addr"])
                 for r in loc["replicas"]],
                loc["leader"])
            for loc in locs]
        with self._lock:
            self._tables[table_id] = tablets
        return tablets

    def lookup_tablet(self, table_id: str, partition_key: bytes,
                      refresh: bool = False) -> RemoteTablet:
        with self._lock:
            tablets = self._tables.get(table_id)
        if tablets is None or refresh:
            tablets = self._refresh(table_id)
        idx = partition_for_key([t.partition for t in tablets],
                                partition_key)
        return tablets[idx]

    def tablets(self, table_id: str,
                refresh: bool = False) -> List[RemoteTablet]:
        with self._lock:
            tablets = self._tables.get(table_id)
        if tablets is None or refresh:
            tablets = self._refresh(table_id)
        return list(tablets)

    def invalidate(self, table_id: str) -> None:
        with self._lock:
            self._tables.pop(table_id, None)
