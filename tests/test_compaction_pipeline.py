"""Stage-overlapped compaction offload pipeline (PR: perf_opt).

Covers the three-stage pipeline (host decode -> async chunked device
merge -> streaming native SST writer), the shape-bucketed compile cache
and the greedy run-packing of small runs into shared m-slots:

  - pipelined device jobs produce byte-identical SSTs to the unpipelined
    device path AND to the CPU/native fallback (the repo's standing
    equivalence bar, extended to the chunked + streaming writer);
  - shape-bucket quantization lands distinct widths/compare schedules on
    the canonical lattice, and the bucket hit counter increments when a
    second job reuses the executable;
  - run-packing with mixed-size runs preserves the exact survivor set;
  - the streaming survivor injection (append_survivors) equals the
    one-shot set_survivors.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from test_run_merge import _make_run  # noqa: E402

from yugabyte_tpu.ops import run_merge  # noqa: E402
from yugabyte_tpu.ops.merge_gc import GCParams  # noqa: E402
from yugabyte_tpu.ops.slabs import ValueArray, concat_slabs  # noqa: E402
from yugabyte_tpu.storage import compaction as compaction_mod  # noqa: E402
from yugabyte_tpu.storage import native_engine  # noqa: E402
from yugabyte_tpu.storage.cpu_baseline import compact_cpu_baseline  # noqa: E402
from yugabyte_tpu.storage.device_cache import DeviceSlabCache  # noqa: E402
from yugabyte_tpu.storage.sst import Frontier, SSTReader, SSTWriter  # noqa: E402
from yugabyte_tpu.utils import flags  # noqa: E402

CUTOFF = (10_000_000 << 12)


def _device():
    import jax
    return jax.devices()[0]


def _mk_run(rng, n, key_space, value_bytes=16, ttl_frac=0.0):
    slab = _make_run(rng, n, key_space, ttl_frac=ttl_frac)
    data = rng.integers(0, 256, size=n * value_bytes, dtype=np.uint8)
    offs = np.arange(n + 1, dtype=np.int64) * value_bytes
    slab.values = ValueArray(data, offs)
    return slab


def _write_runs(workdir, runs):
    readers = []
    for i, slab in enumerate(runs):
        p = os.path.join(workdir, f"in{i:03d}.sst")
        SSTWriter(p).write(slab, Frontier())
        readers.append(SSTReader(p))
    return readers


def _sst_bytes(outputs):
    """data-file bytes per output, in output order."""
    out = []
    for _fid, base_path, _props in outputs:
        with open(base_path + ".sblock.0", "rb") as f:
            out.append(f.read())
    return out


def _run_device_native(readers, out_dir, first_id=100, is_major=True):
    os.makedirs(out_dir, exist_ok=True)
    cache = DeviceSlabCache(device=_device())
    ids = list(range(len(readers)))
    for fid, r in zip(ids, readers):
        cache.stage(fid, r.read_all())
    gen = iter(range(first_id, first_id + 500))
    return compaction_mod.run_compaction_job_device_native(
        readers, out_dir, lambda: next(gen), CUTOFF, is_major,
        device=_device(), device_cache=cache, input_ids=ids)


# ---------------------------------------------------------------- pipeline


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_pipeline_vs_sequential_vs_cpu_byte_identical(tmp_path, monkeypatch):
    """The headline equivalence: chunked pipelined device job ==
    unpipelined device job == native CPU fallback, byte for byte,
    across a multi-file split."""
    rng = np.random.default_rng(21)
    runs = [_mk_run(rng, 1500, 6000) for _ in range(4)]
    readers = _write_runs(str(tmp_path), runs)
    old = flags.get_flag("compaction_max_output_entries_per_sst")
    flags.set_flag("compaction_max_output_entries_per_sst", 1000)
    monkeypatch.setenv("YBTPU_MERGE_CHUNK_ROWS", "2048")  # force chunking
    try:
        monkeypatch.setenv("YBTPU_PIPELINE", "1")
        res_pipe = _run_device_native(readers, str(tmp_path / "pipe"),
                                      first_id=100)
        monkeypatch.setenv("YBTPU_PIPELINE", "0")
        res_seq = _run_device_native(readers, str(tmp_path / "seq"),
                                     first_id=100)
        monkeypatch.delenv("YBTPU_PIPELINE")
        ids = iter(range(100, 600))
        os.makedirs(str(tmp_path / "cpu"))
        res_cpu = compaction_mod.run_compaction_job(
            readers, str(tmp_path / "cpu"), lambda: next(ids), CUTOFF,
            True, device="native")
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old)
    assert res_pipe.rows_out == res_seq.rows_out == res_cpu.rows_out
    assert len(res_pipe.outputs) >= 2, "expected a multi-file split"
    assert _sst_bytes(res_pipe.outputs) == _sst_bytes(res_seq.outputs)
    assert _sst_bytes(res_pipe.outputs) == _sst_bytes(res_cpu.outputs)
    assert res_pipe.tombstones_written == res_seq.tombstones_written
    for r in readers:
        r.close()


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_streaming_writer_overlaps_chunks(tmp_path, monkeypatch):
    """With chunking + a small file split, the streaming writer must
    emit at least one complete file BEFORE the last chunk's decisions
    are consumed (the actual overlap, not just the same outputs)."""
    rng = np.random.default_rng(22)
    runs = [_mk_run(rng, 1500, 8000) for _ in range(4)]
    readers = _write_runs(str(tmp_path), runs)
    # this test observes the SHELL's streaming stage C specifically; the
    # device codec writes outputs through its own writer, so pin it off
    monkeypatch.setenv("YBTPU_DEVICE_CODEC", "0")
    monkeypatch.setenv("YBTPU_MERGE_CHUNK_ROWS", "2048")
    old = flags.get_flag("compaction_max_output_entries_per_sst")
    flags.set_flag("compaction_max_output_entries_per_sst", 700)

    events = []
    orig_feed = compaction_mod._StreamingNativeWriter._write_span
    orig_iter = run_merge._ChunkedMergeGCHandle.result_iter

    def span_spy(self, start, end, more_coming):
        events.append(("write", start, end))
        return orig_feed(self, start, end, more_coming)

    def iter_spy(self):
        for x in orig_iter(self):
            events.append(("chunk",))
            yield x

    monkeypatch.setattr(compaction_mod._StreamingNativeWriter,
                        "_write_span", span_spy)
    monkeypatch.setattr(run_merge._ChunkedMergeGCHandle,
                        "result_iter", iter_spy)
    try:
        res = _run_device_native(readers, str(tmp_path / "out"))
    finally:
        flags.set_flag("compaction_max_output_entries_per_sst", old)
    n_chunks = sum(1 for e in events if e[0] == "chunk")
    assert n_chunks >= 2, "chunked launch did not engage"
    first_write = next(i for i, e in enumerate(events) if e[0] == "write")
    last_chunk = max(i for i, e in enumerate(events) if e[0] == "chunk")
    assert first_write < last_chunk, (
        "no output file was written before the final chunk's decisions "
        f"were consumed: {events}")
    assert len(res.outputs) >= 2
    for r in readers:
        r.close()


@pytest.mark.skipif(not native_engine.available(),
                    reason="native engine unavailable")
def test_append_survivors_equals_set_survivors(tmp_path):
    """The C++ streaming injection: appending chunk survivor spans must
    leave the job in exactly the state one set_survivors produces."""
    rng = np.random.default_rng(23)
    runs = [_mk_run(rng, 400, 300) for _ in range(2)]
    readers = _write_runs(str(tmp_path), runs)
    params = GCParams(CUTOFF, True, False)
    perm, keep, mk = run_merge.merge_and_gc_runs(
        [r.read_all() for r in readers], params)
    surv, mk_s = perm[keep], mk[keep]
    tomb = b"\x00"

    def ingest(job):
        for r in readers:
            with open(r.data_path, "rb") as f:
                job.add_input(f.read(), r.block_handles)
        job.prepare()

    with native_engine.NativeCompactionJob() as j1, \
            native_engine.NativeCompactionJob() as j2:
        ingest(j1)
        ingest(j2)
        j1.set_survivors(surv, mk_s)
        cut = len(surv) // 3
        j2.append_survivors(surv[:cut], mk_s[:cut])
        j2.append_survivors(surv[cut:], mk_s[cut:])
        assert j1.n_survivors == j2.n_survivors == len(surv)
        o1 = j1.write_output(0, len(surv), str(tmp_path / "a.dat"), 128,
                             compress=False, tombstone_value=tomb)
        o2 = j2.write_output(0, len(surv), str(tmp_path / "b.dat"), 128,
                             compress=False, tombstone_value=tomb)
        assert o1[0] == o2[0]
    with open(tmp_path / "a.dat", "rb") as fa, \
            open(tmp_path / "b.dat", "rb") as fb:
        assert fa.read() == fb.read()
    for r in readers:
        r.close()


# ----------------------------------------------------------- shape buckets


def test_quantize_width_lattice():
    assert run_merge.quantize_width(1) == 4
    assert run_merge.quantize_width(3) == 4
    assert run_merge.quantize_width(4) == 4
    assert run_merge.quantize_width(5) == 8
    assert run_merge.quantize_width(8) == 8
    assert run_merge.quantize_width(9) == 16


def test_cmp_schedule_lands_on_lattice():
    """Distinct pruned-comparator lengths quantize onto the n_cmp
    lattice, with the pad repeating the last row (a no-op compare)."""
    for n_live in range(1, 17):
        is_const = np.ones(64, dtype=bool)
        # leave exactly n_live key-word rows non-constant
        for j in range(n_live):
            is_const[run_merge._ROW_WORDS + j] = False
        rows, n_cmp = run_merge._cmp_schedule(w=32, is_const=is_const)
        assert n_cmp in run_merge._CMP_LATTICE
        assert n_cmp >= n_live
        assert len(rows) == n_cmp
        # padding repeats the final real row
        assert (rows[n_live:] == rows[n_live - 1]).all()


def test_staged_widths_share_bucket():
    """Runs of width 3 and width 4 must stage into the SAME (w) bucket
    so one executable serves both."""
    rng = np.random.default_rng(24)
    a = run_merge.stage_runs_from_slabs(
        [_make_run(rng, 300, 200, w=3) for _ in range(2)])
    b = run_merge.stage_runs_from_slabs(
        [_make_run(rng, 300, 200, w=4) for _ in range(2)])
    assert a.w == b.w == 4
    assert a.n_cmp in run_merge._CMP_LATTICE
    assert (a.m, a.k_pad) == (b.m, b.k_pad)


def test_bucket_hit_counter_increments():
    """Second job with the same quantized shape = a bucket hit."""
    from yugabyte_tpu.utils.metrics import kernel_metrics
    hits = kernel_metrics().counter(
        "kernel_compile_bucket_hits_total",
        "kernel launches that reused an already-compiled shape bucket")
    rng = np.random.default_rng(25)
    params = GCParams(CUTOFF, True, False)
    runs1 = [_make_run(rng, 300, 200) for _ in range(2)]
    runs2 = [_make_run(rng, 300, 200) for _ in range(2)]  # same shapes
    run_merge.merge_and_gc_runs(runs1, params)
    before = hits.value()
    run_merge.merge_and_gc_runs(runs2, params)
    assert hits.value() > before, (
        "identical-shape second job did not record a bucket hit")


def test_prewarm_buckets_compiles_and_marks_seen():
    """Prewarm compiles the requested buckets; the next real launch of
    that bucket is a recorded hit."""
    from yugabyte_tpu.utils.metrics import kernel_metrics
    hits = kernel_metrics().counter(
        "kernel_compile_bucket_hits_total",
        "kernel launches that reused an already-compiled shape bucket")
    rng = np.random.default_rng(26)
    runs = [_make_run(rng, 400, 300) for _ in range(2)]  # -> m=512, w->4
    staged = run_merge.stage_runs_from_slabs(runs)
    assert (staged.k_pad, staged.m, staged.w) == (2, 512, 4)
    assert staged.n_cmp in run_merge._CMP_LATTICE
    # prewarm the exact bucket this staging produced (staging records no
    # bucket; only launches do) — the real launch below must then be the
    # bucket's second sighting, i.e. a hit
    n = run_merge.prewarm_buckets(
        [(staged.k_pad, staged.m, staged.w, staged.n_cmp)])
    # both is_major variants of the one merge shape, plus the chained
    # write-through programs (survivor scan, span gather, restage concat)
    assert n == 5
    before = hits.value()
    run_merge.merge_and_gc_runs(runs, GCParams(CUTOFF, True, False),
                                staged=staged)
    assert hits.value() > before


def test_prewarm_maintenance_op_one_shot():
    from yugabyte_tpu.tserver.maintenance_manager import (
        MaintenanceOpStats, PrewarmKernelsOp)
    op = PrewarmKernelsOp(shapes=[(2, 512, 4, 8)], enabled_fn=lambda: True)
    s = MaintenanceOpStats()
    op.update_stats(s)
    assert s.runnable and s.perf_improvement > 0
    op.perform()
    s2 = MaintenanceOpStats()
    op.update_stats(s2)
    assert not s2.runnable, "prewarm op must be one-shot"


# ------------------------------------------------------------ run packing


def test_plan_run_packing_mixed_sizes():
    """One big run + small ones: smalls pack into shared slots and k_pad
    shrinks; evenly sized runs do not pack (no k_pad win)."""
    plan = run_merge.plan_run_packing([4000, 100, 90, 80, 70])  # k_pad 8
    assert plan is not None
    packed = run_merge.packed_run_ns([4000, 100, 90, 80, 70])
    m = run_merge.run_bucket(4000)
    assert all(s <= m for s in packed)
    assert len(packed) < 5
    k_pad_new = 1 << max(0, (len(packed) - 1).bit_length())
    assert k_pad_new < 8
    # every input run appears in exactly one bin
    flat = sorted(i for b in plan for i in b)
    assert flat == [0, 1, 2, 3, 4]
    assert run_merge.plan_run_packing([1000, 1000, 900, 950]) is None
    assert run_merge.plan_run_packing([500]) is None


def test_run_packing_survivors_match_unpacked():
    """Packed staging must keep exactly the survivors (input-row indexed)
    of the unpacked staging AND of the CPU baseline."""
    rng = np.random.default_rng(27)
    sizes = [3000, 200, 150, 120, 100]
    runs = [_make_run(rng, n, 800) for n in sizes]
    params = GCParams(CUTOFF, True, False)

    staged_packed = run_merge.stage_runs_from_slabs(runs, pack_runs=True)
    assert staged_packed.run_maps is not None, "packing did not engage"
    assert staged_packed.k_pad < 8
    p1, k1, m1 = run_merge.launch_merge_gc(staged_packed, params).result()

    staged_plain = run_merge.stage_runs_from_slabs(runs, pack_runs=False)
    p2, k2, m2 = run_merge.launch_merge_gc(staged_plain, params).result()

    assert np.array_equal(p1[k1], p2[k2])
    assert np.array_equal(p1[m1], p2[m2])

    merged = concat_slabs(runs)
    offsets = np.concatenate(([0], np.cumsum(sizes))).tolist()
    oc, kc, mc = compact_cpu_baseline(merged, offsets, CUTOFF, True, False)
    assert np.array_equal(p1[k1], oc[kc])


def test_run_packing_env_disable(monkeypatch):
    monkeypatch.setenv("YBTPU_RUN_PACKING", "0")
    rng = np.random.default_rng(28)
    runs = [_make_run(rng, n, 500) for n in (2000, 100, 90, 80)]
    staged = run_merge.stage_runs_from_slabs(runs)
    assert staged.run_maps is None
    assert staged.k_pad == 4


# -------------------------------------------------------- stage metrics


def test_pipeline_stage_totals_accumulate():
    from yugabyte_tpu.utils.metrics import (pipeline_stage_totals,
                                            record_pipeline_stage)
    before = pipeline_stage_totals()
    record_pipeline_stage("host", 5.0)
    record_pipeline_stage("device", 2.5)
    record_pipeline_stage("write", 1.0)
    after = pipeline_stage_totals()
    assert after["host"] >= before["host"] + 5.0 - 1e-6
    assert after["device"] >= before["device"] + 2.5 - 1e-6
    assert after["write"] >= before["write"] + 1.0 - 1e-6
