"""Secondary index metadata + entry construction, shared by the master
(DDL + backfill orchestration), tservers (tablet-side backfill) and the
query layers (transactional index maintenance + index-accelerated reads).

Design follows the reference's YSQL index architecture: the index is a
REGULAR table whose hash key is the indexed column and whose range keys are
the indexed table's primary key columns (ref: src/yb/master/
catalog_manager.cc index-table creation; src/yb/common/index.h IndexInfo).
Maintenance happens in the query layer inside the statement's distributed
transaction — the same placement as the reference's YSQL path, where the
postgres layer (pggate) issues index writes as separate ops in one
transaction (ref: src/yb/yql/pggate/pg_dml_write.cc) — rather than inside
the tablet write path.

States (ref index permissions, common/index.h:51): a freshly created index
is 'backfilling' — writers maintain it (write-and-delete mode) but readers
must not use it; after the master-orchestrated backfill completes it turns
'readable'.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from yugabyte_tpu.common.schema import ColumnSchema, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.utils import flags

flags.define_flag("table_cache_ttl_ms", 500,
                  "query-layer table-handle cache TTL — the schema/index "
                  "propagation window (the reference propagates schema "
                  "versions via heartbeats and rejects stale-version ops); "
                  "the master's index-backfill grace is derived from it")

STATE_BACKFILLING = "backfilling"
STATE_READABLE = "readable"


@dataclass
class IndexInfo:
    index_name: str
    index_table_id: str
    column: str
    state: str = STATE_BACKFILLING

    def to_wire(self) -> dict:
        return {"index_name": self.index_name,
                "index_table_id": self.index_table_id,
                "column": self.column, "state": self.state}

    @staticmethod
    def from_wire(w: dict) -> "IndexInfo":
        return IndexInfo(w["index_name"], w["index_table_id"], w["column"],
                         w.get("state", STATE_BACKFILLING))


def indexes_from_meta(table_meta: dict) -> List[IndexInfo]:
    return [IndexInfo.from_wire(w) for w in table_meta.get("indexes", [])]


def index_table_schema(main_schema: Schema, column: str) -> Schema:
    """Schema of the index table: indexed column hashes, main PK ranges."""
    col = main_schema.column(column)
    key_cols = (main_schema.hash_columns + main_schema.range_columns)
    if column in {c.name for c in key_cols}:
        raise ValueError(f"column {column!r} is already a key column")
    columns = [ColumnSchema(col.name, col.type, nullable=False)]
    for kc in key_cols:
        columns.append(ColumnSchema(f"pk_{kc.name}", kc.type,
                                    nullable=False))
    return Schema(columns=columns, num_hash_key_columns=1,
                  num_range_key_columns=len(key_cols))


def index_doc_key(value, main_doc_key: DocKey) -> DocKey:
    """Index entry key: (indexed value) -> (main table primary key)."""
    return DocKey(
        hash_components=(value,),
        range_components=tuple(main_doc_key.hash_components)
        + tuple(main_doc_key.range_components))


def main_doc_key_from_index_row(row_dict: dict, main_schema: Schema,
                                index_schema: Schema) -> DocKey:
    """Recover the main-table DocKey from a decoded index row."""
    vals = [row_dict[c.name] for c in index_schema.range_columns]
    nh = main_schema.num_hash_key_columns
    return DocKey(hash_components=tuple(vals[:nh]),
                  range_components=tuple(vals[nh:]))


def index_insert_op(value, main_doc_key: DocKey,
                    backfill_ht: Optional[int] = None) -> QLWriteOp:
    return QLWriteOp(WriteOpKind.INSERT, index_doc_key(value, main_doc_key),
                     {}, backfill_ht=backfill_ht)


def index_delete_op(value, main_doc_key: DocKey) -> QLWriteOp:
    return QLWriteOp(WriteOpKind.DELETE_ROW,
                     index_doc_key(value, main_doc_key))


def maintenance_ops(index: IndexInfo, op: QLWriteOp, old_value
                    ) -> List[QLWriteOp]:
    """Index writes implied by one main-table DML op.

    old_value: the row's current indexed value (None if absent) — the
    caller reads it inside the statement transaction (read-modify-write,
    ref pg_dml_write.cc building delete+insert index requests).
    """
    out: List[QLWriteOp] = []
    if op.kind in (WriteOpKind.INSERT, WriteOpKind.UPDATE):
        touches = index.column in op.values
        if not touches:
            return out
        new_value = op.values.get(index.column)
        if old_value == new_value:
            return out
        if old_value is not None:
            out.append(index_delete_op(old_value, op.doc_key))
        if new_value is not None:
            out.append(index_insert_op(new_value, op.doc_key))
    elif op.kind == WriteOpKind.DELETE_ROW:
        if old_value is not None:
            out.append(index_delete_op(old_value, op.doc_key))
    elif op.kind == WriteOpKind.DELETE_COLS:
        if index.column in op.columns_to_delete and old_value is not None:
            out.append(index_delete_op(old_value, op.doc_key))
    return out
