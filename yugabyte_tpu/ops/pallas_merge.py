"""Pallas merge-path compaction kernel: the round-4 flagship.

Round 3's bitonic merge network (ops/run_merge.py merge_network) runs
~log2(2L) compare-exchange stages PER LEVEL over the full [C, n] comparator
matrix, then pays one giant lane-axis gather (`cols[:, perm]`, ~180 MB/s on
TPU) to materialize the merged matrix for GC.  At 4M rows that is ~44 full-
array HBM passes + a >1 s gather: measured ~50x off the HBM roofline
(VERDICT r3).

This module replaces it with the classic *merge path* decomposition
(Green/McColl/Bader-style diagonal partitioning), reshaped for the TPU
memory hierarchy:

  level pass (log2(K) of them, pairwise tournament over the pre-sorted runs):
    1. split search (jnp): for every output tile boundary d = t*TILE, a
       vectorized binary search over the pair's diagonal finds how many
       elements come from run A vs run B.  O(n/TILE * log L) work with
       leading-axis gathers of a few KB - negligible.
    2. tile merge (pallas): each grid step loads the two aligned TILE-blocks
       covering its A-window and B-window into VMEM (scalar-prefetched block
       indices), aligns them with log-decomposed static rolls, masks
       out-of-window lanes to +inf sentinels, and bitonically merges
       2*TILE lanes IN VMEM (log2(2*TILE) VPU stages).  All payload rows
       ride along, so the merged matrix streams straight back to HBM -
       no global gather, ever.

HBM traffic per level: read n + write n of the [Rp, n] payload (plus the
tiny split-search reads).  Total: 2 * n * Rp * 4 B * log2(K) - tens of ms
at 4M rows on a v5e, vs >1 s for the network+gather formulation.

Ordering is the identical composite comparator the network uses (pruned
cmp rows, descending rows complemented, global index as final tiebreak), so
perm/keep/make-tombstone are byte-identical to ops/run_merge.py and the
native C++ baseline (differential-tested in tests/test_pallas_merge.py).

ref (what this replaces, architecture only): rocksdb/table/merger.cc:51
(MergingIterator min-heap), rocksdb/db/compaction_job.cc:442.
"""

from __future__ import annotations

import functools
import os
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from yugabyte_tpu.ops.merge_gc import (
    _ROW_HT_HI, _ROW_KEY_LEN, _ROW_WID, _ROW_WORDS, GCParams, PAD_SENTINEL,
    gc_over_sorted, pack_bits_u32 as _pack_group_bits)
from yugabyte_tpu.utils import jax_setup  # noqa: F401  (compilation cache)

_U32_MAX = np.uint32(0xFFFFFFFF)   # numpy scalar: inlines as a literal


def _inv_word(row: int) -> int:
    """Complement mask for descending comparator rows (ht_hi/ht_lo/wid)."""
    return 0xFFFFFFFF if _ROW_HT_HI <= row <= _ROW_WID else 0


def _lex_gt_rows(a, b, n_rows: int):
    """Strict lexicographic > over the leading axis (row-major keys).

    Operates on [1, n] row slices, never 1-D vectors: Mosaic cannot lower
    wide 1-D i1 vectors (arith.trunci vector<Nxi8> -> vector<Nxi1>), so
    every mask stays 2-D.  Returns [1, n] bool."""
    gt = jnp.zeros((1,) + a.shape[1:], dtype=bool)
    eq = jnp.ones((1,) + a.shape[1:], dtype=bool)
    for i in range(n_rows):
        gt = gt | (eq & (a[i:i + 1] > b[i:i + 1]))
        eq = eq & (a[i:i + 1] == b[i:i + 1])
    return gt


def _lex_gt_last(a, b, c: int):
    """Strict lexicographic > over the LAST axis (gathered key tuples)."""
    gt = jnp.zeros(a.shape[:-1], dtype=bool)
    eq = jnp.ones(a.shape[:-1], dtype=bool)
    for i in range(c):
        gt = gt | (eq & (a[..., i] > b[..., i]))
        eq = eq & (a[..., i] == b[..., i])
    return gt


def _compute_splits(s_t, L: int, tile: int, n_pairs: int, tpp: int, c: int):
    """Merge-path diagonal splits for one tournament level.

    s_t: [n, c] complemented comparator keys, transposed so the binary
    search gathers along the LEADING axis (the fast gather direction).
    Returns int32 [n_pairs * (tpp + 1)]: for pair p, boundary t, the number
    of A-run elements among the first t*tile merged elements.  Ties take A
    first (global index order - A's indices all precede B's), which the
    strict `keyA > keyB` predicate encodes exactly.
    """
    zeros = jnp.zeros((n_pairs, 1), jnp.int32)
    full = jnp.full((n_pairs, 1), L, jnp.int32)
    if tpp <= 1:
        return jnp.concatenate([zeros, full], axis=1).reshape(-1)
    d = (jnp.arange(1, tpp, dtype=jnp.int32) * tile)[None, :]
    pair = jnp.arange(n_pairs, dtype=jnp.int32)[:, None]
    base_a = pair * (2 * L)
    base_b = base_a + L
    d2 = jnp.broadcast_to(d, (n_pairs, tpp - 1))
    lo = jnp.maximum(0, d2 - L)
    hi = jnp.minimum(d2, L)

    def body(_, lh):
        lo, hi = lh
        live = lo < hi
        mid = (lo + hi) >> 1
        ka = s_t[base_a + mid]              # [n_pairs, tpp-1, c]
        kb = s_t[base_b + (d2 - mid - 1)]
        gt = _lex_gt_last(ka, kb, c)        # keyA[mid] > keyB[d-mid-1]
        lo = jnp.where(live & ~gt, mid + 1, lo)
        hi = jnp.where(live & gt, mid, hi)
        return lo, hi

    iters = max(1, int(L).bit_length() + 1)
    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return jnp.concatenate([zeros, lo, full], axis=1).reshape(-1)


def _rev_window_start(p, t, a0, L: int, tile: int, n: int):
    """Start of the B window in REVERSED-matrix coordinates.

    Shared by the kernel body (to derive the in-tile shift) and the
    BlockSpec index maps (to prefetch the covering blocks) — the two MUST
    agree exactly or the kernel shifts against the wrong blocks.  May be
    negative near the array end (wrapped lanes are always masked).
    """
    return n - tile - p * 2 * L - L - t * tile + a0


def _rev_block_lo(p, t, a0, L: int, tile: int, n: int):
    """Block index of the low prefetched block for the reversed B window."""
    rb0 = _rev_window_start(p, t, a0, L, tile, n)
    return jnp.clip(rb0 // tile, 0, n // tile - 1)


def _shift_left(buf, amt, max_shift: int):
    """buf[:, i] <- buf[:, i + amt] for dynamic amt in [0, max_shift):
    log-decomposed static rolls (guaranteed Mosaic lowering; a dynamic
    lane-axis slice is not)."""
    k = 1
    while k < max_shift:
        buf = jnp.where((amt & k) != 0, jnp.roll(buf, -k, axis=1), buf)
        k *= 2
    return buf


def _make_tile_kernel(L: int, tile: int, tpp: int, rp: int, n: int,
                      cmp_rows: Tuple[int, ...], idx_row: int):
    """Kernel body for one tournament level (closure over static config).

    The B window is loaded from a globally lane-REVERSED copy of the
    payload matrix (flipped outside the kernel — Mosaic has no lowering
    for the `rev` primitive, so `wb[:, ::-1]` inside the kernel fails on
    real TPU).  In reversed coordinates the window is a contiguous
    ascending slice whose keys run descending, which is exactly the
    bitonic layout the halving network needs.
    """
    c = len(cmp_rows)
    nblk = L // tile
    inv_consts = [_inv_word(r) for r in cmp_rows]

    def kernel(sa_ref, a_lo, a_hi, br_lo, br_hi, out_ref):
        p = pl.program_id(0)
        t = pl.program_id(1)
        base = p * (tpp + 1)
        a0 = sa_ref[base + t]
        a1 = sa_ref[base + t + 1]
        la = a1 - a0
        da = a0 - jnp.minimum(a0 // tile, nblk - 1) * tile
        rb0 = _rev_window_start(p, t, a0, L, tile, n)
        blk_lo = _rev_block_lo(p, t, a0, L, tile, n)
        dr = (rb0 - blk_lo * tile) & (2 * tile - 1)

        def window(lo_ref, hi_ref, shift, max_shift, valid_mask):
            # valid_mask is [1, tile]; all mask math stays 2-D for Mosaic
            buf = jnp.concatenate([lo_ref[:], hi_ref[:]], axis=1)
            buf = _shift_left(buf, shift, max_shift)[:, :tile]
            keys = [jnp.where(valid_mask, buf[r:r + 1] ^ jnp.uint32(iv),
                              _U32_MAX)
                    for r, iv in zip(cmp_rows, inv_consts)]
            keys.append(jnp.where(valid_mask, buf[idx_row:idx_row + 1],
                                  _U32_MAX))
            return jnp.concatenate(keys + [buf], axis=0)  # [c+1+rp, tile]

        lane = jax.lax.broadcasted_iota(jnp.int32, (1, tile), 1)
        wa = window(a_lo, a_hi, da, tile, lane < la)
        # valid B lanes are the LAST tile-la: reversed window keys descend
        wb = window(br_lo, br_hi, dr, 2 * tile, lane >= la)
        z = jnp.concatenate([wa, wb], axis=1)             # bitonic [., 2t]
        lane2 = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * tile), 1)
        s = tile
        while s >= 1:
            hi_half = (lane2 & s) != 0                    # [1, 2t]
            partner = jnp.where(hi_half, jnp.roll(z, s, axis=1),
                                jnp.roll(z, -s, axis=1))
            gt = _lex_gt_rows(z[:c + 1], partner[:c + 1], c + 1)
            # hi_half XOR gt == where(hi_half, ~gt, gt) but stays an i1
            # predicate: a select with BOOL OPERANDS materializes i8 bools
            # and Mosaic cannot truncate i8 vectors back to i1
            take = hi_half ^ gt                           # [1, 2t]
            z = jnp.where(take, partner, z)
            s //= 2
        out_ref[:] = z[c + 1:, :tile]

    return kernel


def _merge_level(p_mat, L: int, tile: int, cmp_rows: Tuple[int, ...],
                 idx_row: int, interpret: bool):
    """One tournament level: merge run pairs of length L into length 2L."""
    rp, n = p_mat.shape
    n_pairs = n // (2 * L)
    tpp = (2 * L) // tile
    nblk = L // tile
    c = len(cmp_rows)

    inv_vec = jnp.asarray([_inv_word(r) for r in cmp_rows], jnp.uint32)
    s_t = (p_mat[jnp.asarray(cmp_rows, jnp.int32), :]
           ^ inv_vec[:, None]).T                     # [n, c]
    sa = _compute_splits(s_t, L, tile, n_pairs, tpp, c)
    # Mosaic cannot lower `rev`, so the B windows load from a lane-flipped
    # copy produced here in XLA (one extra HBM pass per level)
    p_rev = jnp.flip(p_mat, axis=1)
    nb_total = n // tile

    def ima_lo(p, t, sa_ref):
        a0 = sa_ref[p * (tpp + 1) + t]
        return (0, p * 2 * nblk + jnp.minimum(a0 // tile, nblk - 1))

    def ima_hi(p, t, sa_ref):
        a0 = sa_ref[p * (tpp + 1) + t]
        return (0, p * 2 * nblk + jnp.minimum(a0 // tile + 1, nblk - 1))

    def imbr_lo(p, t, sa_ref):
        a0 = sa_ref[p * (tpp + 1) + t]
        return (0, _rev_block_lo(p, t, a0, L, tile, n))

    def imbr_hi(p, t, sa_ref):
        a0 = sa_ref[p * (tpp + 1) + t]
        return (0, jnp.minimum(_rev_block_lo(p, t, a0, L, tile, n) + 1,
                               nb_total - 1))

    def imo(p, t, sa_ref):
        return (0, p * 2 * nblk + t)

    kernel = _make_tile_kernel(L, tile, tpp, rp, n, cmp_rows, idx_row)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_pairs, tpp),
        in_specs=[pl.BlockSpec((rp, tile), ima_lo),
                  pl.BlockSpec((rp, tile), ima_hi),
                  pl.BlockSpec((rp, tile), imbr_lo),
                  pl.BlockSpec((rp, tile), imbr_hi)],
        out_specs=pl.BlockSpec((rp, tile), imo),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rp, n), jnp.uint32),
        interpret=interpret,
    )(sa, p_mat, p_mat, p_rev, p_rev)


@functools.partial(jax.jit, static_argnames=(
    "k_pad", "m", "w", "cmp_rows_t", "tile", "is_major", "retain_deletes",
    "snapshot", "interpret"))
def _pallas_merge_gc_fused(cols, pos,
                           cutoff_hi, cutoff_lo, cutoff_phys_hi,
                           cutoff_phys_lo,
                           k_pad: int, m: int, w: int,
                           cmp_rows_t: Tuple[int, ...], tile: int,
                           is_major: bool, retain_deletes: bool,
                           snapshot: bool, interpret: bool):
    """Fused tournament merge + GC + packed decision buffer.

    Same contract as run_merge._merge_gc_runs_fused: returns
    (packed_groups [n//32, 2+b], perm, keep, make_tombstone), with perm =
    run-major input index of each merged position, so MergeGCHandle and the
    write-through staging path work unchanged.
    """
    r = cols.shape[0]
    n = k_pad * m
    idx_row = r
    rp = ((r + 1 + 7) // 8) * 8
    p_mat = jnp.concatenate(
        [cols, pos.astype(jnp.uint32)[None, :],
         jnp.zeros((rp - r - 1, n), jnp.uint32)], axis=0)

    L = m
    while L < n:
        p_mat = _merge_level(p_mat, L, tile, cmp_rows_t, idx_row, interpret)
        L *= 2

    s = p_mat[:r]
    perm = p_mat[idx_row].astype(jnp.int32)
    keep, make_tomb = gc_over_sorted(
        s, w, cutoff_hi, cutoff_lo, cutoff_phys_hi, cutoff_phys_lo,
        is_major=is_major, retain_deletes=retain_deletes, snapshot=snapshot)
    keep = keep & (s[_ROW_KEY_LEN] != jnp.uint32(PAD_SENTINEL))

    groups = [_pack_group_bits(keep, n), _pack_group_bits(make_tomb, n)]
    b = max(1, (k_pad - 1).bit_length())
    src = (perm >> int(m).bit_length() - 1).astype(jnp.uint32)
    for t in range(b):
        groups.append(_pack_group_bits((src >> t) & 1, n))
    return jnp.stack(groups, axis=1), perm, keep, make_tomb


def default_tile(rp_rows: int) -> int:
    """VMEM-budgeted tile: 4 in-blocks + out + ~3x work values, 2x buffered."""
    t = int(os.environ.get("YBTPU_PALLAS_TILE", 0))
    if t:
        return t
    return 4096 if rp_rows <= 24 else 2048


def supported(staged) -> bool:
    """Pallas path preconditions: >=2 runs, tile-divisible power-of-two m."""
    if not _HAS_PLTPU or staged.k_pad < 2:
        return False
    rp = ((_ROW_WORDS + staged.w + 1 + 7) // 8) * 8
    tile = min(default_tile(rp), staged.m)
    if tile < 128 and not _interpret_mode():
        return False
    return staged.m % tile == 0


def _interpret_mode() -> bool:
    return jax.default_backend() != "tpu"


def launch_merge_gc_pallas(staged, params: GCParams, snapshot: bool = False,
                           host_async: bool = True):
    """Drop-in for run_merge.launch_merge_gc using the pallas tournament."""
    from yugabyte_tpu.ops.run_merge import MergeGCHandle
    cutoff = params.history_cutoff_ht
    cutoff_phys = cutoff >> 12
    pos = jnp.arange(staged.n_pad, dtype=jnp.int32)
    rp = ((_ROW_WORDS + staged.w + 1 + 7) // 8) * 8
    tile = min(default_tile(rp), staged.m)
    packed, perm, keep, mk = _pallas_merge_gc_fused(
        staged.cols_dev, pos,
        jnp.uint32(cutoff >> 32), jnp.uint32(cutoff & 0xFFFFFFFF),
        jnp.uint32(cutoff_phys >> 20), jnp.uint32(cutoff_phys & 0xFFFFF),
        k_pad=staged.k_pad, m=staged.m, w=staged.w,
        cmp_rows_t=tuple(int(x) for x in staged.cmp_rows), tile=tile,
        is_major=params.is_major_compaction,
        retain_deletes=params.retain_deletes, snapshot=snapshot,
        interpret=_interpret_mode())
    return MergeGCHandle(packed, staged, perm, keep, mk,
                         host_async=host_async)
