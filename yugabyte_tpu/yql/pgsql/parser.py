"""SQL-subset parser for the YSQL layer (PostgreSQL dialect).

Replaces the role of the PG11 parser for the supported surface (ref:
src/postgres/src/backend/parser; the supported subset mirrors what the
round's pggate-equivalent executes): CREATE/DROP DATABASE, CREATE/DROP
TABLE, INSERT (multi-row), SELECT with WHERE conjunctions / LIMIT /
COUNT(*), UPDATE, DELETE, BEGIN/COMMIT/ROLLBACK.

Reuses the token machinery of the CQL frontend (yql/cql/parser.py) — the
lexical grammar of the two dialects is identical for this subset.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from yugabyte_tpu.yql.cql.parser import ParseError, Parser as _BaseParser

# PG type name -> framework DataType name (common/schema.py)
PG_TYPES = {
    "SMALLINT": "INT64", "INT2": "INT64",
    "INT": "INT64", "INTEGER": "INT64", "INT4": "INT64",
    "BIGINT": "INT64", "INT8": "INT64",
    "TEXT": "STRING", "VARCHAR": "STRING", "CHAR": "STRING",
    "REAL": "DOUBLE", "FLOAT4": "DOUBLE", "FLOAT8": "DOUBLE",
    "FLOAT": "DOUBLE",
    "BOOLEAN": "BOOL", "BOOL": "BOOL",
    "BYTEA": "BINARY",
    # timestamps store epoch micros (the CQL layer's convention); literal
    # strings coerce at the executor boundary (executor.pg_coerce)
    "TIMESTAMP": "TIMESTAMP", "TIMESTAMPTZ": "TIMESTAMP",
    # DATE/TIME/UUID ride STRING: ISO-8601 text at fixed width sorts
    # chronologically, so range predicates and ORDER BY behave
    "DATE": "STRING", "TIME": "STRING", "UUID": "STRING",
    # NUMERIC/DECIMAL approximate as binary double (documented deviation
    # from PG's arbitrary precision; matches the framework value layer)
    "NUMERIC": "DOUBLE", "DECIMAL": "DOUBLE",
    # SERIAL/BIGSERIAL: INT64 + an implicit sequence default; the marker
    # survives to the executor which creates <table>_<col>_seq
    "SERIAL": "SERIAL", "BIGSERIAL": "SERIAL", "SMALLSERIAL": "SERIAL",
    # jsonb documents (canonical sorted-key json text storage,
    # common/jsonb.py); plain JSON maps to the same storage like the
    # reference's ycql layer treats both spellings
    "JSONB": "JSONB", "JSON": "JSONB",
}


@dataclass
class CreateDatabase:
    name: str


@dataclass
class DropDatabase:
    name: str


@dataclass
class CreateTable:
    name: str
    columns: List[Tuple[str, str]]     # (name, DataType name)
    pk: List[str]                      # primary key columns, order matters
    num_tablets: int = 4
    if_not_exists: bool = False


@dataclass
class DropTable:
    name: str
    if_exists: bool = False


@dataclass
class CreateIndex:
    index_name: Optional[str]
    table: str
    columns: List[str]
    if_not_exists: bool = False


@dataclass
class Insert:
    table: str
    columns: Optional[List[str]]
    rows: List[List[object]]
    # RETURNING * | col [, ...] (ref: PG returning_clause, gram.y;
    # executed like PG's ExecProcessReturning over the written rows)
    returning: Optional[List[str]] = None
    # ON CONFLICT upsert (ref: PG ExecOnConflictUpdate, gram.y
    # opt_on_conflict): ("nothing"|"update", target_cols_or_None,
    # [(col, literal | ("__excluded__", col))])
    on_conflict: Optional[tuple] = None


class Param:
    """A $n bind placeholder (PG extended query protocol, 1-based)."""

    __slots__ = ("idx",)

    def __init__(self, idx: int):
        self.idx = idx

    def __repr__(self):
        return f"${self.idx}"

    def __eq__(self, other):
        return isinstance(other, Param) and other.idx == self.idx

    def __hash__(self):
        return hash(("$param", self.idx))


@dataclass
class Join:
    table: str
    alias: Optional[str]
    kind: str                          # "inner" | "left"
    on: Tuple[str, str]                # (left_ref, right_ref), maybe "a.c"


@dataclass
class Select:
    table: str
    columns: Optional[List[str]]       # None = *
    # predicates: (col, op, value). op also includes "in"/"not in" (value
    # a tuple of literals or a Select subquery) and "exists"/"not exists"
    # (col "", value a Select). A Select as value with a comparison op is
    # a scalar subquery. (ref: src/postgres/.../parse_expr.c SubLink)
    where: List[Tuple[str, str, object]] = field(default_factory=list)
    limit: Optional[int] = None
    count_star: bool = False
    alias: Optional[str] = None        # FROM <table> [alias]
    joins: List[Join] = field(default_factory=list)
    # aggregate select list: (func, column or None for COUNT(*)); when
    # non-empty the output is one row per group (group_by) or one row
    aggregates: List[Tuple[str, Optional[str]]] = field(default_factory=list)
    # scalar-builtin select list (yql/bfunc.py registry): when non-empty,
    # the ORDERED output items — ("col", name) | ("func", fname, args),
    # args being ("col", name) | ("lit", value) | nested ("func", ...)
    scalar_items: List = field(default_factory=list)
    group_by: Optional[str] = None
    order_by: List[Tuple[str, bool]] = field(default_factory=list)  # (col, desc)
    distinct: bool = False             # SELECT DISTINCT
    # OR disjunction: when non-empty, `where` is [] and the predicate is
    # the union of these conjunction branches (PG: a AND b OR c)
    or_where: List[List[Tuple[str, str, object]]] = \
        field(default_factory=list)
    # HAVING conjunction: (item, op, literal) where item is
    # ("agg", FUNC, col_or_None) or ("col", name)
    having: List[Tuple[tuple, str, object]] = field(default_factory=list)
    offset: int = 0                    # LIMIT ... OFFSET n


@dataclass
class UnionSelect:
    """SELECT ... UNION [ALL] SELECT ... chains (left-associative).
    alls[i] is the ALL flag of the link between selects[i] and
    selects[i+1]; ORDER BY / LIMIT of the final member bind to the whole
    union, PG-style."""

    selects: List[Select]
    alls: List[bool]
    order_by: List[Tuple[str, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


@dataclass
class Update:
    table: str
    assignments: List[Tuple[str, object]]
    where: List[Tuple[str, str, object]]
    returning: Optional[List[str]] = None


@dataclass
class Delete:
    table: str
    where: List[Tuple[str, str, object]]
    returning: Optional[List[str]] = None


@dataclass
class CreateSequence:
    name: str
    start: int = 1
    if_not_exists: bool = False


@dataclass
class DropSequence:
    name: str
    if_exists: bool = False


@dataclass
class CreateView:
    """CREATE [OR REPLACE] VIEW name AS <select> — stored as the
    defining SELECT text (ref: PG DefineView / pg_rewrite)."""
    name: str
    sql: str                           # the SELECT text, re-parsed on use
    or_replace: bool = False


@dataclass
class DropView:
    name: str
    if_exists: bool = False


@dataclass
class PrepareStmt:
    """PREPARE name [(types)] AS <dml> (ref: PG PrepareQuery,
    commands/prepare.c). Parameter types are inferred at bind time."""
    name: str
    stmt: object


@dataclass
class ExecuteStmt:
    name: str
    params: List[object] = field(default_factory=list)


@dataclass
class DeallocateStmt:
    name: Optional[str]                # None = ALL


@dataclass
class Truncate:
    """TRUNCATE [TABLE] t [, ...] [RESTART IDENTITY] (ref: PG's
    ExecuteTruncate; YSQL routes it to per-tablet truncation)."""
    tables: List[str]
    restart_identity: bool = False


@dataclass
class Explain:
    """EXPLAIN [ANALYZE] <dml> — report the plan the executor would pick
    (ref: src/postgres/src/backend/commands/explain.c; YSQL EXPLAIN shows
    the pggate scan shape the same way)."""
    stmt: object
    analyze: bool = False


@dataclass
class TxnControl:
    kind: str                          # begin | commit | rollback


@dataclass
class Show:
    name: str


@dataclass
class AlterTable:
    table: str
    add_columns: List[Tuple[str, str]]  # (name, DataType name)
    drop_columns: List[str]


@dataclass
class DeclareCursor:
    name: str
    select: "Select"
    hold: bool = False       # WITH HOLD: survives COMMIT (PG semantics)


@dataclass
class FetchCursor:
    name: str
    count: Optional[int]               # None = ALL


@dataclass
class CloseCursor:
    name: str


Statement = Union[CreateDatabase, DropDatabase, CreateTable, DropTable,
                  Insert, Select, Update, Delete, TxnControl, Show,
                  AlterTable, DeclareCursor, FetchCursor, CloseCursor,
                  CreateSequence, DropSequence]


class PgParser(_BaseParser):
    def literal(self):
        tok = self.peek()
        if tok is not None and tok[0] == "param":
            self.next()
            return Param(int(tok[1][1:]))
        if tok is not None and tok[0] == "name" \
                and tok[1].lower() == "nextval" \
                and self._peek2() == ("op", "("):
            self.next()
            self.expect_op("(")
            seq = super().literal()
            self.expect_op(")")
            return ("__nextval__", seq)
        return super().literal()

    def parse_one(self) -> Optional[Statement]:
        if self.peek() is None:
            return None
        if self.accept_kw("CREATE", "DATABASE"):
            return CreateDatabase(self.name())
        if self.accept_kw("DROP", "DATABASE"):
            return DropDatabase(self.name())
        if self.accept_kw("CREATE", "TABLE"):
            return self._create_table()
        if self.accept_kw("CREATE", "SEQUENCE"):
            # ref: src/postgres sequence.c DefineSequence
            ine = bool(self.accept_kw("IF", "NOT", "EXISTS"))
            name = self.name()
            start = 1
            if self.accept_kw("START"):
                self.accept_kw("WITH")
                start = int(self.literal())
            return CreateSequence(name, start, ine)
        if self.accept_kw("DROP", "SEQUENCE"):
            ife = bool(self.accept_kw("IF", "EXISTS"))
            return DropSequence(self.name(), ife)
        if self.accept_kw("CREATE", "INDEX"):
            # CREATE INDEX [IF NOT EXISTS] [name] ON table (column)
            # (ref: YSQL index DDL, parsed by the PG grammar and executed
            # through master backfill, backfill_index.cc)
            ine = self.accept_kw("IF", "NOT", "EXISTS")
            index_name = None
            if not self.accept_kw("ON"):
                index_name = self.name()
                self.expect_kw("ON")
            table = self._table_name()
            self.expect_op("(")
            columns = [self.name()]
            while self.accept_op(","):
                columns.append(self.name())
            self.expect_op(")")
            return CreateIndex(index_name, table, columns, ine)
        if self.accept_kw("DROP", "TABLE"):
            if_exists = self.accept_kw("IF", "EXISTS")
            return DropTable(self._table_name(), if_exists)
        or_replace = self.accept_kw("CREATE", "OR", "REPLACE", "VIEW")
        if or_replace or self.accept_kw("CREATE", "VIEW"):
            name = self.name()
            self.expect_kw("AS")
            start = self.pos
            inner = self.parse_one()
            if not isinstance(inner, (Select, UnionSelect)):
                raise ParseError("CREATE VIEW requires a SELECT")
            sql = " ".join(t for _k, t in self.toks[start:self.pos])
            return CreateView(name, sql, or_replace)
        if self.accept_kw("DROP", "VIEW"):
            ife = self.accept_kw("IF", "EXISTS")
            return DropView(self.name(), ife)
        if self.accept_kw("PREPARE"):
            name = self.name()
            if self.accept_op("("):   # declared param types: ignored
                depth = 1             # typmods like numeric(10,2) nest
                while depth:
                    tok = self.next()
                    if tok == ("op", "("):
                        depth += 1
                    elif tok == ("op", ")"):
                        depth -= 1
            self.expect_kw("AS")
            inner = self.parse_one()
            if not isinstance(inner, (Select, UnionSelect, Insert,
                                      Update, Delete)):
                raise ParseError("PREPARE applies to DML statements")
            return PrepareStmt(name, inner)
        if self.accept_kw("EXECUTE"):
            name = self.name()
            params: List[object] = []
            if self.accept_op("("):
                params.append(self.literal())
                while self.accept_op(","):
                    params.append(self.literal())
                self.expect_op(")")
            return ExecuteStmt(name, params)
        if self.accept_kw("DEALLOCATE"):
            self.accept_kw("PREPARE")
            if self.accept_kw("ALL"):
                return DeallocateStmt(None)
            return DeallocateStmt(self.name())
        if self.accept_kw("TRUNCATE"):
            self.accept_kw("TABLE")
            tables = [self._table_name()]
            while self.accept_op(","):
                tables.append(self._table_name())
            restart = bool(self.accept_kw("RESTART", "IDENTITY"))
            if not restart:
                self.accept_kw("CONTINUE", "IDENTITY")
            self.accept_kw("CASCADE") or self.accept_kw("RESTRICT")
            return Truncate(tables, restart)
        if self.accept_kw("EXPLAIN"):
            analyze = bool(self.accept_kw("ANALYZE"))
            self.accept_kw("VERBOSE")
            inner = self.parse_one()
            if not isinstance(inner, (Select, UnionSelect, Insert,
                                      Update, Delete)):
                raise ParseError("EXPLAIN applies to DML statements")
            return Explain(inner, analyze)
        if self.accept_kw("INSERT", "INTO"):
            return self._insert()
        if self.accept_kw("SELECT"):
            return self._select_or_union()
        if self.accept_kw("UPDATE"):
            return self._update()
        if self.accept_kw("DELETE", "FROM"):
            return self._delete()
        if self.accept_kw("BEGIN") or self.accept_kw("START", "TRANSACTION"):
            # consume optional BEGIN modifiers (ISOLATION LEVEL ... etc.)
            while self.peek() and not self._at_semicolon():
                self.next()
            return TxnControl("begin")
        if self.accept_kw("COMMIT") or self.accept_kw("END"):
            return TxnControl("commit")
        if self.accept_kw("ROLLBACK") or self.accept_kw("ABORT"):
            return TxnControl("rollback")
        if self.accept_kw("SHOW"):
            return Show(self.name())
        if self.accept_kw("ALTER", "TABLE"):
            return self._alter_table()
        if self.accept_kw("DECLARE"):
            name = self.name()
            self.expect_kw("CURSOR")
            hold = bool(self.accept_kw("WITH", "HOLD"))
            self.accept_kw("WITHOUT", "HOLD")
            self.expect_kw("FOR")
            self.expect_kw("SELECT")
            return DeclareCursor(name, self._select(), hold)
        if self.accept_kw("FETCH"):
            count: Optional[int] = 1
            tok = self.peek()
            if self.accept_kw("ALL"):
                count = None
            elif self.accept_kw("FORWARD"):
                count = None if self.accept_kw("ALL") else int(self.literal())
            elif tok is not None and tok[0] == "number":
                count = int(self.literal())
            self.accept_kw("FROM") or self.accept_kw("IN")
            return FetchCursor(self.name(), count)
        if self.accept_kw("CLOSE"):
            return CloseCursor(self.name())
        raise ParseError(f"unsupported statement near {self.peek()!r}")

    def _alter_table(self) -> AlterTable:
        table = self._table_name()
        add: List[Tuple[str, str]] = []
        drop: List[str] = []
        while True:
            if self.accept_kw("ADD"):
                self.accept_kw("COLUMN")
                col = self.name()
                add.append((col, self._type_name()))
            elif self.accept_kw("DROP"):
                self.accept_kw("COLUMN")
                drop.append(self.name())
            else:
                raise ParseError(
                    f"expected ADD or DROP, got {self.peek()!r}")
            if not self.accept_op(","):
                break
        return AlterTable(table, add, drop)

    def parse_script(self) -> List[Statement]:
        out = []
        while True:
            while self.accept_op(";"):
                pass
            stmt = self.parse_one()
            if stmt is None:
                return out
            out.append(stmt)
            if self.peek() is not None:
                self.expect_op(";")

    # ----------------------------------------------------------- helpers
    _RESERVED = {"JOIN", "INNER", "LEFT", "OUTER", "ON", "WHERE", "GROUP",
                 "ORDER", "LIMIT", "OFFSET", "AND", "OR", "FROM", "AS",
                 "FETCH", "FOR", "UNION", "HAVING"}

    def _maybe_alias(self) -> Optional[str]:
        if self.accept_kw("AS"):
            return self.name()
        tok = self.peek()
        if (tok is not None and tok[0] == "name"
                and tok[1].upper() not in self._RESERVED):
            return self.name()
        return None

    def _col_ref(self) -> str:
        """A possibly table-qualified column reference: 'col' or 'a.col'."""
        first = self.name()
        if self.accept_op("."):
            return f"{first}.{self.name()}"
        return first

    def _at_semicolon(self) -> bool:
        tok = self.peek()
        return tok is not None and tok == ("op", ";")

    def _table_name(self) -> str:
        # accept a schema qualifier; 'public' is dropped (the default
        # search_path), catalog schemas stay qualified so their vtables
        # can never shadow a user table named e.g. 'tables'
        schema, name = self.qualified_name()
        if schema and schema.lower() in ("pg_catalog",
                                         "information_schema"):
            return f"{schema.lower()}.{name}"
        return name

    def _type_name(self) -> str:
        t = self.name().upper()
        if t == "DOUBLE":
            self.expect_kw("PRECISION")
            t = "FLOAT8"
        if t in ("VARCHAR", "CHAR") and self.accept_op("("):
            self.literal()
            self.expect_op(")")
        if t in ("NUMERIC", "DECIMAL") and self.accept_op("("):
            self.literal()               # precision (ignored: -> DOUBLE)
            if self.accept_op(","):
                self.literal()           # scale
            self.expect_op(")")
        if t in ("TIMESTAMP", "TIME"):
            # TIMESTAMP/TIME [(p)] [WITH|WITHOUT TIME ZONE]
            if self.accept_op("("):
                self.literal()           # precision (micros regardless)
                self.expect_op(")")
            if self.accept_kw("WITH") or self.accept_kw("WITHOUT"):
                self.expect_kw("TIME")
                self.expect_kw("ZONE")
        if t not in PG_TYPES:
            raise ParseError(f"unsupported type {t}")
        return PG_TYPES[t]

    def _create_table(self) -> CreateTable:
        if_not_exists = self.accept_kw("IF", "NOT", "EXISTS")
        name = self._table_name()
        self.expect_op("(")
        columns: List[Tuple[str, str]] = []
        pk: List[str] = []
        while True:
            if self.accept_kw("PRIMARY", "KEY"):
                self.expect_op("(")
                while True:
                    pk.append(self.name())
                    self.accept_kw("HASH") or self.accept_kw("ASC") \
                        or self.accept_kw("DESC")
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            else:
                col = self.name()
                columns.append((col, self._type_name()))
                if self.accept_kw("PRIMARY", "KEY"):
                    pk.append(col)
                self.accept_kw("NOT", "NULL")
            if not self.accept_op(","):
                break
        self.expect_op(")")
        num_tablets = 4
        if self.accept_kw("SPLIT", "INTO"):
            num_tablets = int(self.literal())
            self.expect_kw("TABLETS")
        if not pk:
            raise ParseError("CREATE TABLE requires a PRIMARY KEY")
        return CreateTable(name, columns, pk, num_tablets, if_not_exists)

    def _insert(self) -> Insert:
        name = self._table_name()
        columns = None
        if self.accept_op("("):
            columns = [self.name()]
            while self.accept_op(","):
                columns.append(self.name())
            self.expect_op(")")
        self.expect_kw("VALUES")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.literal()]
            while self.accept_op(","):
                row.append(self.literal())
            self.expect_op(")")
            rows.append(row)
            if not self.accept_op(","):
                break
        return Insert(name, columns, rows, on_conflict=self._on_conflict(),
                      returning=self._returning())

    def _on_conflict(self):
        """[ON CONFLICT [(cols)] DO NOTHING | DO UPDATE SET col =
        literal | EXCLUDED.col [, ...]] — the upsert clause."""
        if not self.accept_kw("ON", "CONFLICT"):
            return None
        target = None
        if self.accept_op("("):
            target = [self.name()]
            while self.accept_op(","):
                target.append(self.name())
            self.expect_op(")")
        self.expect_kw("DO")
        if self.accept_kw("NOTHING"):
            return ("nothing", target, [])
        self.expect_kw("UPDATE")
        self.expect_kw("SET")
        assigns = []
        while True:
            col = self.name()
            nxt2 = self.toks[self.pos + 1] \
                if self.pos + 1 < len(self.toks) else None
            if nxt2 is not None and nxt2[0] == "name" \
                    and nxt2[1].upper() == "EXCLUDED":
                self.expect_op("=")
                self.expect_kw("EXCLUDED")
                self.expect_op(".")
                assigns.append((col, ("__excluded__", self.name())))
            else:
                # literal or a row expression over the EXISTING row
                # (rides UPDATE's _assigned_value machinery)
                assigns.append((col, self._assigned_value()))
            if not self.accept_op(","):
                break
        return ("update", target, assigns)

    def _returning(self) -> Optional[List[str]]:
        if not self.accept_kw("RETURNING"):
            return None
        if self.accept_op("*"):
            return ["*"]
        out = [self._col_ref()]
        while self.accept_op(","):
            out.append(self._col_ref())
        return out

    _AGG_FUNCS = ("COUNT", "SUM", "AVG", "MIN", "MAX")

    def _select_item(self):
        """-> ("col", name) | ("agg", func, col_or_None) |
        ("func", name, args) for scalar builtins (yql/bfunc.py) |
        ("op", op, left, right) for arithmetic over any of these"""
        tok = self.peek()
        nxt = self._peek2()
        if tok is not None and tok[0] == "name" \
                and tok[1].upper() in self._AGG_FUNCS \
                and nxt == ("op", "("):
            return self._agg_call()
        return self._arith_expr()

    def _agg_call(self):
        """FUNC([DISTINCT] col | *) -> ("agg", func_name, col_or_None).
        DISTINCT is encoded by appending " DISTINCT" to the function name
        (consumers normalize with func.split()[0])."""
        func = self.name().upper()
        self.expect_op("(")
        if self.accept_op("*"):
            if func != "COUNT":
                raise ParseError(f"{func}(*) is not valid")
            col = None
        else:
            if self.accept_kw("DISTINCT"):
                func = func + " DISTINCT"
            col = self._col_ref()
        self.expect_op(")")
        return ("agg", func, col)

    # Arithmetic over select-list primaries (ref: PG a_expr — the subset
    # with + - * / % and standard precedence; no unary minus on columns).
    _ADD_OPS = ("+", "-")
    _MUL_OPS = ("*", "/", "%")

    def _arith_expr(self):
        left = self._arith_term()
        while True:
            tok = self.peek()
            if tok is not None and tok[0] == "op" \
                    and tok[1] in self._ADD_OPS:
                self.next()
                left = ("op", tok[1], left, self._arith_term())
            else:
                return left

    def _arith_term(self):
        left = self._arith_primary()
        while True:
            tok = self.peek()
            if tok is not None and tok[0] == "op" \
                    and tok[1] in self._MUL_OPS:
                # '*' here is multiplication: a primary always precedes it
                self.next()
                left = ("op", tok[1], left, self._arith_primary())
            else:
                return left

    def _arith_primary(self):
        tok = self.peek()
        nxt = self._peek2()
        if tok == ("op", "("):
            self.expect_op("(")
            e = self._arith_expr()
            self.expect_op(")")
            return e
        if tok is not None and tok[0] == "name" \
                and tok[1].upper() == "CASE":
            return self._case_expr()
        if tok is not None and tok[0] == "name" and nxt == ("op", "("):
            return self._scalar_func()
        if tok is not None and tok[0] == "name" \
                and tok[1].upper() not in ("TRUE", "FALSE", "NULL"):
            col = self._col_ref()
            if self.peek() in (("op", "->"), ("op", "->>")):
                return self._jsonb_suffix(col)
            return ("col", col)
        return ("lit", self.literal())

    def _jsonb_suffix(self, col: str):
        """col ->'k'->0[->>'leaf'] -> ("jsonb", col, path, as_text)
        (ref: PG jsonb -> / ->> operators, src/postgres jsonfuncs.c).
        Rides the base parser's path grammar (cql JsonOp)."""
        j = self._json_path(col)
        return ("jsonb", j.column, j.path, j.as_text)

    # CASE (ref: PG a_expr CaseExpr, src/postgres gram.y case_expr):
    # searched  CASE WHEN cond THEN expr ... [ELSE expr] END
    # simple    CASE expr WHEN val THEN expr ... [ELSE expr] END
    # -> ("case", [(cond, result_expr)...], else_expr_or_None) with cond
    # one of ("cmp", op, l, r) | ("isnull", expr, negated) |
    # ("and"|"or", [conds])
    def _case_expr(self):
        self.expect_kw("CASE")
        base = None
        if not (self.peek() is not None and self.peek()[0] == "name"
                and self.peek()[1].upper() == "WHEN"):
            base = self._arith_expr()
        whens = []
        while self.accept_kw("WHEN"):
            if base is not None:
                cond = ("cmp", "=", base, self._arith_expr())
            else:
                cond = self._case_cond()
            self.expect_kw("THEN")
            whens.append((cond, self._arith_expr()))
        if not whens:
            raise ParseError("CASE requires at least one WHEN")
        els = self._arith_expr() if self.accept_kw("ELSE") else None
        self.expect_kw("END")
        return ("case", whens, els)

    _CMP_OPS = ("=", "!=", "<>", "<", "<=", ">", ">=")

    def _case_cond(self):
        conds = [self._case_cond_and()]
        while self.accept_kw("OR"):
            conds.append(self._case_cond_and())
        return conds[0] if len(conds) == 1 else ("or", conds)

    def _case_cond_and(self):
        conds = [self._case_cond_one()]
        while self.accept_kw("AND"):
            conds.append(self._case_cond_one())
        return conds[0] if len(conds) == 1 else ("and", conds)

    def _case_cond_one(self):
        left = self._arith_expr()
        if self.accept_kw("IS"):
            neg = bool(self.accept_kw("NOT"))
            self.expect_kw("NULL")
            return ("isnull", left, neg)
        tok = self.next()
        if tok[0] != "op" or tok[1] not in self._CMP_OPS:
            raise ParseError(
                f"expected comparison in CASE WHEN, got {tok[1]!r}")
        op = tok[1]
        if op == "<" and self.accept_op(">"):
            op = "!="  # '<>' lexes as two tokens
        return ("cmp", op, left, self._arith_expr())

    def _scalar_func(self):
        fname = self.name()
        self.expect_op("(")
        args: List = []
        if not self.accept_op(")"):
            while True:
                tok = self.peek()
                nxt = self._peek2()
                if tok is not None and tok[0] == "name" \
                        and nxt == ("op", "("):
                    args.append(self._scalar_func())
                elif tok is not None and tok[0] == "name" \
                        and tok[1].upper() not in ("TRUE", "FALSE", "NULL"):
                    args.append(("col", self.name()))
                else:
                    args.append(("lit", self.literal()))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return ("func", fname, args)

    def _select_or_union(self):
        """One SELECT, or a UNION [ALL] chain (ref: PG set operations,
        src/postgres/.../analyze.c transformSetOperationStmt). ORDER BY /
        LIMIT parsed inside the LAST member bind to the whole union."""
        first = self._select()
        selects = [first]
        alls: List[bool] = []
        while self.accept_kw("UNION"):
            if selects[-1].order_by or selects[-1].limit is not None \
                    or selects[-1].offset:
                raise ParseError(
                    "ORDER BY/LIMIT/OFFSET must follow the last UNION "
                    "member")
            alls.append(bool(self.accept_kw("ALL")))
            self.expect_kw("SELECT")
            selects.append(self._select())
        if len(selects) == 1:
            return first
        last = selects[-1]
        order_by, limit, offset = last.order_by, last.limit, last.offset
        last.order_by, last.limit, last.offset = [], None, 0
        return UnionSelect(selects, alls, order_by, limit, offset)

    def _subselect(self) -> Select:
        """'(' SELECT ... ')' (no nested unions inside predicates)."""
        self.expect_kw("SELECT")
        sub = self._select()
        self.expect_op(")")
        return sub

    def _select(self) -> Select:
        distinct = bool(self.accept_kw("DISTINCT"))
        columns: Optional[List[str]] = None
        count_star = False
        aggregates: List[Tuple[str, Optional[str]]] = []
        scalar_items: List = []
        if self.accept_op("*"):
            pass
        else:
            items = [self._select_item()]
            while self.accept_op(","):
                items.append(self._select_item())
            aggs = [i for i in items if i[0] == "agg"]
            cols = [i[1] for i in items if i[0] == "col"]
            exprs = [i for i in items
                     if i[0] in ("func", "op", "lit", "case", "jsonb")]
            if aggs and exprs:
                raise ParseError(
                    "mixing aggregates and scalar expressions in one "
                    "select list is not supported")
            if aggs:
                aggregates = [(f, c) for _k, f, c in aggs]
                columns = cols or None   # group-by columns, if any
            elif exprs:
                scalar_items = items
                # base columns the evaluation needs (validated + fetched)
                def _refs(it):
                    if it[0] == "col":
                        return [it[1]]
                    if it[0] == "jsonb":
                        return [it[1]]
                    if it[0] == "func":
                        out = []
                        for a in it[2]:
                            out.extend(_refs(a) if a[0] != "lit" else [])
                        return out
                    if it[0] == "op":
                        return _refs(it[2]) + _refs(it[3])
                    if it[0] == "case":
                        out = []

                        def _cond_refs(c):
                            if c[0] == "cmp":
                                return _refs(c[2]) + _refs(c[3])
                            if c[0] == "isnull":
                                return _refs(c[1])
                            return [r for x in c[1] for r in _cond_refs(x)]
                        for cond, res in it[1]:
                            out.extend(_cond_refs(cond))
                            out.extend(_refs(res))
                        if it[2] is not None:
                            out.extend(_refs(it[2]))
                        return out
                    return []
                seen = []
                for it in items:
                    for r in _refs(it):
                        if r not in seen:
                            seen.append(r)
                columns = seen or None
            else:
                columns = cols
        if scalar_items and not (self.peek() is not None
                                 and self.peek()[0] == "name"
                                 and self.peek()[1].upper() == "FROM"):
            # FROM-less scalar SELECT (PG: SELECT nextval('s'), 1 + 2)
            return Select(table=None, columns=None,
                          scalar_items=scalar_items)
        self.expect_kw("FROM")
        name = self._table_name()
        alias = self._maybe_alias()
        joins: List[Join] = []
        while True:
            kind = None
            if self.accept_kw("JOIN") or self.accept_kw("INNER", "JOIN"):
                kind = "inner"
            elif self.accept_kw("LEFT", "OUTER", "JOIN") \
                    or self.accept_kw("LEFT", "JOIN"):
                kind = "left"
            if kind is None:
                break
            jt = self._table_name()
            jalias = self._maybe_alias()
            self.expect_kw("ON")
            lref = self._col_ref()
            self.expect_op("=")
            rref = self._col_ref()
            joins.append(Join(jt, jalias, kind, (lref, rref)))
        where, or_where = self._pg_where_full()
        group_by = None
        if self.accept_kw("GROUP", "BY"):
            cols_gb = [self._col_ref()]
            while self.accept_op(","):
                cols_gb.append(self._col_ref())
            # a single column stays a string (the historical shape every
            # consumer handles); multiple columns ride as a tuple
            group_by = cols_gb[0] if len(cols_gb) == 1 else tuple(cols_gb)
        having: List[Tuple[tuple, str, object]] = []
        if self.accept_kw("HAVING"):
            while True:
                item = self._having_item()
                op = self._comparison_op()
                having.append((item, op, self.literal()))
                if not self.accept_kw("AND"):
                    break
        order_by: List[Tuple[str, bool]] = []
        if self.accept_kw("ORDER", "BY"):
            while True:
                col = self._col_ref()
                desc = bool(self.accept_kw("DESC"))
                if not desc:
                    self.accept_kw("ASC")
                order_by.append((col, desc))
                if not self.accept_op(","):
                    break
        limit = None
        if self.accept_kw("LIMIT"):
            limit = self.literal()   # int literal or $n placeholder
            if not isinstance(limit, Param):
                limit = int(limit)
        offset = 0
        if self.accept_kw("OFFSET"):
            offset = self.literal()   # int literal or $n placeholder
            if not isinstance(offset, Param):
                offset = int(offset)
        # a lone COUNT(*) with no grouping is the classic count-star fast
        # path; COUNT(*) under GROUP BY must stay an aggregate per group
        if (aggregates == [("COUNT", None)] and columns is None
                and group_by is None and not having):
            count_star = True
            aggregates = []
        return Select(name, columns, where, limit, count_star,
                      alias=alias, joins=joins,
                      aggregates=aggregates, group_by=group_by,
                      order_by=order_by, scalar_items=scalar_items,
                      having=having, distinct=distinct,
                      or_where=or_where, offset=offset)

    def _having_item(self) -> tuple:
        """("agg", FUNC, col_or_None) | ("col", name)."""
        tok = self.peek()
        if tok is not None and tok[0] == "name" \
                and tok[1].upper() in self._AGG_FUNCS \
                and self._peek2() == ("op", "("):
            return self._agg_call()
        return ("col", self._col_ref())

    def _comparison_op(self) -> str:
        tok = self.next()
        if tok[0] != "op":
            raise ParseError(f"expected operator, got {tok[1]!r}")
        op = tok[1]
        if op == "<" and self.accept_op(">"):
            op = "!="  # <> tokenizes as two ops
        if op not in ("=", "!=", "<", "<=", ">", ">="):
            raise ParseError(f"unsupported operator {op!r}")
        return op

    def _one_predicate(self) -> Tuple[str, str, object]:
        # EXISTS / NOT EXISTS (SELECT ...)
        if self.accept_kw("EXISTS"):
            self.expect_op("(")
            return ("", "exists", self._subselect())
        if self.accept_kw("NOT", "EXISTS"):
            self.expect_op("(")
            return ("", "not exists", self._subselect())
        col = self._col_ref()
        if self.peek() in (("op", "->"), ("op", "->>")):
            # jsonb path predicate: the lhs becomes the pushdown form
            # ("jsonb", col, path, as_text) evaluated by
            # common/wire.row_matches on the tserver scan
            col = self._jsonb_suffix(col)
        if self.accept_kw("IS"):
            neg = bool(self.accept_kw("NOT"))
            self.expect_kw("NULL")
            return (col, "is not null" if neg else "is null", None)
        if self.accept_kw("LIKE"):
            return (col, "like", self.literal())
        if self.accept_kw("NOT", "LIKE"):
            return (col, "not like", self.literal())
        in_op = None
        if self.accept_kw("IN"):
            in_op = "in"
        elif self.accept_kw("NOT", "IN"):
            in_op = "not in"
        if in_op is not None:
            self.expect_op("(")
            tok = self.peek()
            if tok is not None and tok[0] == "name" \
                    and tok[1].upper() == "SELECT":
                return (col, in_op, self._subselect())
            vals = [self.literal()]
            while self.accept_op(","):
                vals.append(self.literal())
            self.expect_op(")")
            return (col, in_op, tuple(vals))
        op = self._comparison_op()
        tok = self.peek()
        if tok == ("op", "(") \
                and self._peek2() is not None \
                and self._peek2()[0] == "name" \
                and self._peek2()[1].upper() == "SELECT":
            self.expect_op("(")
            return (col, op, self._subselect())
        return (col, op, self.literal())

    def _bool_factor(self) -> List[List[Tuple[str, str, object]]]:
        """factor := predicate | '(' expr ')' — returns DNF branches.
        A '(' followed by SELECT is NOT grouping (scalar subqueries are
        consumed inside _one_predicate)."""
        tok = self.peek()
        nxt = self._peek2()
        if tok == ("op", "(") and not (
                nxt is not None and nxt[0] == "name"
                and nxt[1].upper() == "SELECT"):
            self.expect_op("(")
            branches = self._bool_expr()
            self.expect_op(")")
            return branches
        return self._predicate_branches()

    def _predicate_branches(self) -> List[List[Tuple[str, str, object]]]:
        """One predicate as DNF branches: most are a single triple;
        BETWEEN expands to a range conjunction, NOT BETWEEN to the
        complementary disjunction (PG desugars identically)."""
        tok = self.peek()
        if tok is not None and tok[0] == "name" \
                and tok[1].upper() not in ("EXISTS", "NOT"):
            save = self.pos
            col = self._col_ref()
            if self.accept_kw("BETWEEN"):
                lo = self.literal()
                self.expect_kw("AND")
                hi = self.literal()
                return [[(col, ">=", lo), (col, "<=", hi)]]
            if self.accept_kw("NOT", "BETWEEN"):
                lo = self.literal()
                self.expect_kw("AND")
                hi = self.literal()
                return [[(col, "<", lo)], [(col, ">", hi)]]
            self.pos = save
        return [[self._one_predicate()]]

    _MAX_DNF_BRANCHES = 64

    def _bool_term(self) -> List[List[Tuple[str, str, object]]]:
        """term := factor (AND factor)* — DNF product of the factors,
        capped: AND-ed OR-groups multiply, and an unbounded product would
        let one cheap query build 2^40 branch lists inside the parser."""
        branches = self._bool_factor()
        while self.accept_kw("AND"):
            rhs = self._bool_factor()
            if len(branches) * len(rhs) > self._MAX_DNF_BRANCHES:
                raise ParseError(
                    "WHERE clause is too complex (more than "
                    f"{self._MAX_DNF_BRANCHES} OR branches after "
                    "normalization)")
            branches = [lb + rb for lb in branches for rb in rhs]
        return branches

    def _bool_expr(self) -> List[List[Tuple[str, str, object]]]:
        """expr := term (OR term)* — DNF union of the terms."""
        branches = self._bool_term()
        while self.accept_kw("OR"):
            branches = branches + self._bool_term()
            if len(branches) > self._MAX_DNF_BRANCHES:
                raise ParseError(
                    "WHERE clause is too complex (more than "
                    f"{self._MAX_DNF_BRANCHES} OR branches after "
                    "normalization)")
        return branches

    def _pg_where_full(self):
        """-> (conjunction, or_branches): the WHERE boolean expression —
        AND/OR with PG precedence plus parenthesized grouping — is
        normalized to disjunctive normal form (ref: PG's planner reaches
        the same shape via BitmapOr paths). A plain conjunction returns
        ([triples], []); a disjunction returns ([], [branch, ...])."""
        if not self.accept_kw("WHERE"):
            return [], []
        branches = self._bool_expr()
        if len(branches) == 1:
            return branches[0], []
        return [], branches

    def _pg_where(self) -> List[Tuple[str, str, object]]:
        where, or_branches = self._pg_where_full()
        if or_branches:
            raise ParseError(
                "disjunctions (OR / NOT BETWEEN) are not supported in "
                "this statement")
        return where

    def _update(self) -> Update:
        name = self._table_name()
        self.expect_kw("SET")
        assignments = [(self.name(), self._assigned_value())]
        while self.accept_op(","):
            assignments.append((self.name(), self._assigned_value()))
        return Update(name, assignments, self._pg_where(),
                      self._returning())

    def _assigned_value(self):
        """RHS of SET col = ...: a plain literal (the blind-write fast
        path) or an expression over the row, tagged ("__expr__", node)
        for the executor's read-modify-write path."""
        self.expect_op("=")
        node = self._arith_expr()
        if node[0] == "lit":
            return node[1]
        return ("__expr__", node)

    def _delete(self) -> Delete:
        name = self._table_name()
        where = self._pg_where()
        return Delete(name, where, self._returning())


def _sub_expr_node(node, sub):
    """Substitute Params inside a row-expression tree (lit/col/func/op)."""
    if node[0] == "lit":
        return ("lit", sub(node[1]))
    if node[0] == "func":
        return ("func", node[1], [_sub_expr_node(a, sub) for a in node[2]])
    if node[0] == "op":
        return ("op", node[1], _sub_expr_node(node[2], sub),
                _sub_expr_node(node[3], sub))
    return node


def max_param_idx(obj) -> int:
    """Highest $n placeholder index reachable in a parsed statement tree
    (0 = no parameters). Walks dataclasses, sequences and dicts — used
    by SQL-level EXECUTE to validate the argument count like PG's
    'wrong number of parameters' check (commands/prepare.c)."""
    import dataclasses as _dc
    if isinstance(obj, Param):
        return obj.idx
    if _dc.is_dataclass(obj) and not isinstance(obj, type):
        return max((max_param_idx(getattr(obj, f.name))
                    for f in _dc.fields(obj)), default=0)
    if isinstance(obj, (list, tuple, set, frozenset)):
        return max((max_param_idx(x) for x in obj), default=0)
    if isinstance(obj, dict):
        return max((max_param_idx(v) for v in obj.values()), default=0)
    return 0


def bind_params(stmt: Statement, params: List[object]) -> Statement:
    """Substitute $n placeholders with values (1-based), returning a new
    statement — the Bind step of the extended query protocol."""
    from dataclasses import replace

    def sub(v):
        if isinstance(v, Param):
            if not 1 <= v.idx <= len(params):
                raise ParseError(f"no parameter ${v.idx}")
            return params[v.idx - 1]
        return v

    if isinstance(stmt, Insert):
        oc = stmt.on_conflict
        if oc is not None and oc[0] == "update":
            def sub_oc(v):
                if isinstance(v, tuple) and len(v) == 2:
                    if v[0] == "__excluded__":
                        return v
                    if v[0] == "__expr__":
                        return ("__expr__", _sub_expr_node(v[1], sub))
                return sub(v)
            oc = (oc[0], oc[1], [(c, sub_oc(v)) for c, v in oc[2]])
        return replace(stmt, rows=[[sub(v) for v in row]
                                   for row in stmt.rows],
                       on_conflict=oc)
    if isinstance(stmt, UnionSelect):
        ulimit = sub(stmt.limit)
        if ulimit is not None:
            ulimit = int(ulimit)
        return replace(stmt, selects=[bind_params(s, params)
                                      for s in stmt.selects],
                       limit=ulimit, offset=int(sub(stmt.offset) or 0))
    if isinstance(stmt, Select):
        limit = sub(stmt.limit)
        if limit is not None:
            limit = int(limit)

        def sub_item(it):
            if it[0] == "lit":
                return ("lit", sub(it[1]))
            if it[0] == "func":
                return ("func", it[1], [sub_item(a) for a in it[2]])
            if it[0] == "op":
                return ("op", it[1], sub_item(it[2]), sub_item(it[3]))
            return it

        sub_val = _make_sub_val(sub, params)
        offset = sub(stmt.offset)
        return replace(stmt, where=[(c, op, sub_val(v))
                                    for c, op, v in stmt.where],
                       or_where=[[(c, op, sub_val(v)) for c, op, v in br]
                                 for br in stmt.or_where],
                       limit=limit, offset=int(offset or 0),
                       scalar_items=[sub_item(i)
                                     for i in stmt.scalar_items],
                       having=[(i, op, sub(v))
                               for i, op, v in stmt.having])
    if isinstance(stmt, Update):
        sub_val = _make_sub_val(sub, params)

        def sub_assign(v):
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "__expr__":
                return ("__expr__", _sub_expr_node(v[1], sub))
            return sub(v)
        return replace(stmt,
                       assignments=[(c, sub_assign(v))
                                    for c, v in stmt.assignments],
                       where=[(c, op, sub_val(v))
                              for c, op, v in stmt.where])
    if isinstance(stmt, Delete):
        sub_val = _make_sub_val(sub, params)
        return replace(stmt, where=[(c, op, sub_val(v))
                                    for c, op, v in stmt.where])
    return stmt


def _make_sub_val(sub, params):
    """WHERE-value substituter shared by Select/Update/Delete: recurses
    into IN-list tuples and subquery Selects so $n placeholders bind
    everywhere a predicate value can hold one."""
    def sub_val(v):
        if isinstance(v, Select):
            return bind_params(v, params)  # subquery: recurse
        if isinstance(v, tuple):
            return tuple(sub(x) for x in v)  # IN list
        return sub(v)
    return sub_val


def collect_param_columns(stmt: Statement) -> List[Tuple[int, object]]:
    """(param index, column ref) for every $n placeholder — the schema
    lookup that types bind parameters (like the reference's parse
    analysis typing bind variables). The column ref is a name, a
    ("pos", i) positional target (INSERT without a column list), or
    "__limit__"."""
    out: List[Tuple[int, object]] = []

    def visit(col, v):
        if isinstance(v, Param):
            out.append((v.idx, col))

    if isinstance(stmt, Insert):
        cols = stmt.columns
        for row in stmt.rows:
            for j, v in enumerate(row):
                visit(cols[j] if cols and j < len(cols) else ("pos", j), v)
        if stmt.on_conflict is not None:
            def visit_oc_expr(node, col):
                if node[0] == "lit":
                    visit(col, node[1])
                elif node[0] == "func":
                    for a in node[2]:
                        visit_oc_expr(a, "__expr__")
                elif node[0] == "op":
                    visit_oc_expr(node[2], col)
                    visit_oc_expr(node[3], col)
            for c, v in stmt.on_conflict[2]:
                if isinstance(v, tuple) and len(v) == 2 \
                        and v[0] == "__expr__":
                    visit_oc_expr(v[1], c)
                elif not (isinstance(v, tuple) and len(v) == 2
                          and v[0] == "__excluded__"):
                    visit(c, v)
    elif isinstance(stmt, UnionSelect):
        for s in stmt.selects:
            out.extend(collect_param_columns(s))
        visit("__limit__", stmt.limit)
    elif isinstance(stmt, Select):
        for c, _op, v in stmt.where + [t for br in stmt.or_where
                                       for t in br]:
            if isinstance(v, Select):
                out.extend(collect_param_columns(v))
            elif isinstance(v, tuple):
                for x in v:
                    visit(c, x)
            else:
                visit(c, v)
        for item, _op, v in stmt.having:
            visit(item[2] if item[0] == "agg" and item[2] else "__having__",
                  v)
        visit("__limit__", stmt.limit)
        visit("__limit__", stmt.offset)
    elif isinstance(stmt, Update):
        def visit_expr(node, col):
            if node[0] == "lit":
                visit(col, node[1])
            elif node[0] == "func":
                # a builtin argument's type comes from the function
                # signature, not the assigned column — leave it untyped
                for a in node[2]:
                    visit_expr(a, "__expr__")
            elif node[0] == "op":
                visit_expr(node[2], col)
                visit_expr(node[3], col)
        for c, v in stmt.assignments:
            if isinstance(v, tuple) and len(v) == 2 and v[0] == "__expr__":
                visit_expr(v[1], c)
            else:
                visit(c, v)
        for c, _op, v in stmt.where:
            visit(c, v)
    elif isinstance(stmt, Delete):
        for c, _op, v in stmt.where:
            visit(c, v)
    return out


def parse_script(text: str) -> List[Statement]:
    return PgParser(text).parse_script()
