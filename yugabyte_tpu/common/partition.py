"""Hash/range partitioning of tables into tablets.

Capability parity with yb::PartitionSchema / Partition (ref:
src/yb/common/partition.h:73,185): tables shard by a 16-bit hash of the hashed
key columns (multi-tablet hash partitioning) and/or by range over the encoded
key. A Partition owns [start, end) of encoded-key space.

The hash function diverges from the reference (YB uses Jenkins-based
YBPartition::HashColumnCompoundValue): we use a splittable 64->16 bit mix that
is also trivially vectorizable in JAX for the TPU bloom/scan kernels.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

kMaxHashCode = 0xFFFF


def hash_column_compound_value(encoded_columns: bytes) -> int:
    """16-bit hash of the encoded hashed-column group. FNV-1a 64 folded to 16."""
    h = 0xCBF29CE484222325
    for b in encoded_columns:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    # xor-fold 64 -> 16
    h ^= h >> 32
    h ^= h >> 16
    return h & kMaxHashCode


@dataclass(frozen=True)
class Partition:
    """[partition_key_start, partition_key_end) over encoded partition keys."""

    start: bytes = b""
    end: bytes = b""  # b"" means +infinity

    def contains(self, partition_key: bytes) -> bool:
        if partition_key < self.start:
            return False
        return not self.end or partition_key < self.end

    def __repr__(self) -> str:
        return f"Partition[{self.start.hex()},{self.end.hex() or 'inf'})"


@dataclass
class PartitionSchema:
    """Describes how a table's rows map to partitions.

    hash_partitioning: partition key = 2-byte big-endian hash bucket.
    range partitioning: partition key = encoded doc key itself.
    """

    hash_partitioning: bool = True

    def partition_key(self, hash_code: Optional[int], encoded_key: bytes) -> bytes:
        if self.hash_partitioning:
            assert hash_code is not None
            return struct.pack(">H", hash_code)
        return encoded_key

    def create_partitions(self, num_tablets: int,
                          split_keys: Sequence[bytes] = ()) -> List[Partition]:
        if self.hash_partitioning:
            bounds = [struct.pack(">H", (i * (kMaxHashCode + 1)) // num_tablets)
                      for i in range(1, num_tablets)]
        else:
            bounds = sorted(split_keys)
        starts = [b""] + list(bounds)
        ends = list(bounds) + [b""]
        return [Partition(s, e) for s, e in zip(starts, ends)]


def doc_key_bounds(partition: Partition,
                   hash_partitioning: bool) -> "tuple[bytes, bytes | None]":
    """(lower_doc_key, upper_doc_key) clamping a tablet's scans to its
    partition. Hash partition keys are the 2-byte hash bucket, which appears
    in every encoded DocKey right after the kUInt16Hash tag byte, so the
    bound prefixes are directly comparable to encoded keys (ref: the
    reference derives the same bounds in Tablet::DocDbScanSpec)."""
    if not hash_partitioning:
        return partition.start, partition.end or None
    from yugabyte_tpu.docdb.value_type import ValueType
    tag = bytes([ValueType.kUInt16Hash])
    lower = tag + partition.start if partition.start else b""
    upper = tag + partition.end if partition.end else None
    return lower, upper


def partition_for_key(partitions: Sequence[Partition], partition_key: bytes) -> int:
    """Index of the partition containing partition_key (meta-cache lookup)."""
    lo, hi = 0, len(partitions) - 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if partitions[mid].start <= partition_key:
            lo = mid
        else:
            hi = mid - 1
    return lo
