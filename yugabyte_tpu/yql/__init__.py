"""Query layers: YCQL-subset SQL and Redis-compatible servers
(ref: src/yb/yql — cql/ and redis/ trees)."""
