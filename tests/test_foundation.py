"""Foundation tests: Status, flags, metrics, trace, HybridTime, partitioning.

Modeled on the reference's colocated unit tests (util/metrics-test.cc,
common/hybrid_time-test? etc.) per SURVEY.md section 4 tier 1.
"""

import pytest

from yugabyte_tpu.utils.status import Status, StatusError, Code
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.metrics import MetricRegistry
from yugabyte_tpu.utils.trace import Trace, TRACE
from yugabyte_tpu.common.hybrid_time import HybridTime, DocHybridTime, HybridClock
from yugabyte_tpu.common.partition import (
    PartitionSchema, partition_for_key, hash_column_compound_value, kMaxHashCode)


class TestStatus:
    def test_ok(self):
        s = Status.OK()
        assert s.ok
        s.raise_if_error()

    def test_error_raises(self):
        s = Status.NotFound("missing tablet")
        assert not s.ok
        assert s.code == Code.NOT_FOUND
        with pytest.raises(StatusError):
            s.raise_if_error()


class TestFlags:
    def test_define_get_set(self):
        flags.define_flag("test_flag_x", 42, "test", [flags.FlagTag.RUNTIME])
        assert flags.get_flag("test_flag_x") == 42
        flags.set_flag("test_flag_x", 7)
        assert flags.get_flag("test_flag_x") == 7
        flags.reset_flag("test_flag_x")
        assert flags.get_flag("test_flag_x") == 42

    def test_validator(self):
        flags.define_flag("test_flag_pos", 1, "", [], validator=lambda v: v > 0)
        with pytest.raises(ValueError):
            flags.set_flag("test_flag_pos", -5)


class TestMetrics:
    def test_counter_histogram_prometheus(self):
        reg = MetricRegistry()
        ent = reg.entity("tablet", "t1", {"table_name": "foo"})
        c = ent.counter("rows_inserted")
        c.increment(10)
        h = ent.histogram("write_latency_us")
        for v in [100, 200, 300, 1000]:
            h.increment(v)
        assert c.value() == 10
        assert h.count() == 4
        assert 250 < h.percentile(99) <= 1100
        prom = reg.to_prometheus()
        assert 'rows_inserted{metric_type="tablet",metric_id="t1",table_name="foo"} 10' in prom
        assert "write_latency_us_count" in prom


class TestTrace:
    def test_trace_collects(self):
        with Trace() as t:
            TRACE("step %d", 1)
            TRACE("step 2")
        dump = t.dump()
        assert "step 1" in dump and "step 2" in dump

    def test_no_trace_is_noop(self):
        TRACE("ignored")  # must not raise


class TestHybridTime:
    def test_components(self):
        ht = HybridTime.from_micros(123456789, 42)
        assert ht.physical_micros == 123456789
        assert ht.logical == 42

    def test_ordering(self):
        a = HybridTime.from_micros(100)
        b = HybridTime.from_micros(100, 1)
        c = HybridTime.from_micros(101)
        assert a < b < c

    def test_clock_monotonic(self):
        fake = [1000]
        clock = HybridClock(time_source=lambda: fake[0])
        t1 = clock.now()
        t2 = clock.now()  # same wall time -> logical bump
        assert t2 > t1
        fake[0] = 2000
        t3 = clock.now()
        assert t3 > t2 and t3.physical_micros == 2000

    def test_clock_update(self):
        clock = HybridClock(time_source=lambda: 1000)
        remote = HybridTime.from_micros(99999)
        clock.update(remote)
        assert clock.now() > remote


class TestDocHybridTime:
    def test_encode_decode_roundtrip(self):
        dht = DocHybridTime(HybridTime.from_micros(1234567, 89), 7)
        assert DocHybridTime.decode(dht.encoded()) == dht

    def test_descending_encoding(self):
        # Later hybrid times must encode to SMALLER byte strings (sort first).
        early = DocHybridTime(HybridTime.from_micros(100), 0)
        late = DocHybridTime(HybridTime.from_micros(200), 0)
        assert late.encoded() < early.encoded()
        same_ht_w0 = DocHybridTime(HybridTime.from_micros(100), 0)
        same_ht_w1 = DocHybridTime(HybridTime.from_micros(100), 1)
        assert same_ht_w1.encoded() < same_ht_w0.encoded()


class TestPartitioning:
    def test_hash_is_16bit_and_stable(self):
        h = hash_column_compound_value(b"hello")
        assert 0 <= h <= kMaxHashCode
        assert h == hash_column_compound_value(b"hello")
        assert h != hash_column_compound_value(b"hellp")

    def test_hash_partitions_cover_space(self):
        ps = PartitionSchema(hash_partitioning=True)
        parts = ps.create_partitions(16)
        assert len(parts) == 16
        assert parts[0].start == b""
        assert parts[-1].end == b""
        for key_hash in [0, 1, 4095, 65535]:
            pk = ps.partition_key(key_hash, b"")
            idx = partition_for_key(parts, pk)
            assert parts[idx].contains(pk)

    def test_range_partitions(self):
        ps = PartitionSchema(hash_partitioning=False)
        parts = ps.create_partitions(3, split_keys=[b"m", b"t"])
        assert partition_for_key(parts, b"a") == 0
        assert partition_for_key(parts, b"n") == 1
        assert partition_for_key(parts, b"z") == 2
