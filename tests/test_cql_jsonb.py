"""YCQL JSONB: jsonb columns + -> / ->> path operators.

Capability parity with the reference's jsonb datatype
(ref: src/yb/common/jsonb.h — sorted-key serialization;
src/yb/common/jsonb.cc ApplyJsonbOperators for -> / ->> semantics;
the ycql jsonb surface in src/yb/yql/cql/ql). Our storage form is
canonical compact JSON text with sorted object keys — the same
deterministic-comparison property the reference gets from its binary
format.
"""

import json

import pytest

from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.status import StatusError
from yugabyte_tpu.yql.cql.executor import QLProcessor


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path_factory.mktemp("jsonbcluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture()
def proc(cluster):
    p = QLProcessor(cluster.new_client())
    p.execute("CREATE KEYSPACE IF NOT EXISTS ks")
    p.execute("USE ks")
    p.execute("DROP TABLE IF EXISTS docs")
    p.execute("CREATE TABLE docs (id INT PRIMARY KEY, body JSONB, "
              "tag TEXT)")
    return p


def _rows(rs):
    return [list(r) for r in rs.rows]


def test_insert_select_roundtrip_canonicalizes(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, "
                 "'{\"b\": 2,  \"a\": {\"y\": [1, 2, 3], \"x\": null}}')")
    rs = proc.execute("SELECT body FROM docs WHERE id = 1")
    assert _rows(rs) == [['{"a":{"x":null,"y":[1,2,3]},"b":2}']]


def test_arrow_object_and_array_navigation(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, "
                 "'{\"a\": {\"b\": [10, {\"c\": true}]}}')")
    rs = proc.execute("SELECT body->'a'->'b'->1->'c' FROM docs "
                      "WHERE id = 1")
    assert _rows(rs) == [["true"]]
    rs = proc.execute("SELECT body->'a'->'b'->0 FROM docs WHERE id = 1")
    assert _rows(rs) == [["10"]]


def test_arrow_text_extraction(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, "
                 "'{\"name\": \"widget\", \"n\": 7, \"flag\": false}')")
    # ->> unquotes strings, stringifies scalars
    assert _rows(proc.execute(
        "SELECT body->>'name' FROM docs WHERE id = 1")) == [["widget"]]
    assert _rows(proc.execute(
        "SELECT body->>'n' FROM docs WHERE id = 1")) == [["7"]]
    assert _rows(proc.execute(
        "SELECT body->>'flag' FROM docs WHERE id = 1")) == [["false"]]
    # -> keeps json form (strings stay quoted)
    assert _rows(proc.execute(
        "SELECT body->'name' FROM docs WHERE id = 1")) == [['"widget"']]


def test_missing_path_yields_null(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, '{\"a\": 1}')")
    assert _rows(proc.execute(
        "SELECT body->'nope' FROM docs WHERE id = 1")) == [[None]]
    assert _rows(proc.execute(
        "SELECT body->'a'->'deeper' FROM docs WHERE id = 1")) == [[None]]
    assert _rows(proc.execute(
        "SELECT body->5 FROM docs WHERE id = 1")) == [[None]]


def test_where_filter_on_json_path(proc):
    for i, name in enumerate(["alpha", "beta", "gamma"]):
        proc.execute("INSERT INTO docs (id, body) VALUES (%d, "
                     "'{\"name\": \"%s\", \"rank\": %d}')"
                     % (i, name, i * 10))
    rs = proc.execute("SELECT id FROM docs WHERE body->>'name' = 'beta' "
                      "ALLOW FILTERING")
    assert _rows(rs) == [[1]]
    # numeric compare via ->> is textual (both sides text) — use a text
    # value for a stable assertion across rows
    rs = proc.execute("SELECT id FROM docs WHERE body->'rank' = '20' "
                      "ALLOW FILTERING")
    assert _rows(rs) == [[2]]


def test_invalid_json_rejected(proc):
    with pytest.raises(StatusError, match="invalid json"):
        proc.execute(
            "INSERT INTO docs (id, body) VALUES (1, '{bad json')")


def test_jsonb_key_column_rejected(proc):
    with pytest.raises(StatusError, match="cannot be a key"):
        proc.execute("CREATE TABLE bad (j JSONB PRIMARY KEY, v INT)")


def test_update_replaces_document(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, '{\"v\": 1}')")
    proc.execute("UPDATE docs SET body = '{\"v\": 2}' WHERE id = 1")
    assert _rows(proc.execute(
        "SELECT body->>'v' FROM docs WHERE id = 1")) == [["2"]]


def test_scalar_and_array_documents(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, '[1, 2, 3]')")
    proc.execute("INSERT INTO docs (id, body) VALUES (2, '\"just text\"')")
    proc.execute("INSERT INTO docs (id, body) VALUES (3, '42')")
    assert _rows(proc.execute(
        "SELECT body->2 FROM docs WHERE id = 1")) == [["3"]]
    assert _rows(proc.execute(
        "SELECT body FROM docs WHERE id = 2")) == [['"just text"']]
    # navigating into a scalar yields null
    assert _rows(proc.execute(
        "SELECT body->'x' FROM docs WHERE id = 3")) == [[None]]


def test_arrow_after_text_extraction_is_syntax_error(proc):
    with pytest.raises(StatusError, match="no further json"):
        proc.execute("SELECT body->>'a'->'b' FROM docs WHERE id = 1")


def test_null_jsonb_column(proc):
    proc.execute("INSERT INTO docs (id, tag) VALUES (1, 'no-body')")
    assert _rows(proc.execute(
        "SELECT body->'a', tag FROM docs WHERE id = 1")) \
        == [[None, "no-body"]]


def test_select_label_and_star(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, '{\"a\": 1}')")
    rs = proc.execute("SELECT body->'a', body->>'a' FROM docs "
                      "WHERE id = 1")
    assert rs.columns == ["body->'a'", "body->>'a'"]
    rs = proc.execute("SELECT * FROM docs WHERE id = 1")
    assert rs.columns == ["id", "body", "tag"]
    row = dict(zip(rs.columns, rs.rows[0]))
    assert json.loads(row["body"]) == {"a": 1}


def test_where_equality_canonicalizes_literal(proc):
    proc.execute("INSERT INTO docs (id, body) VALUES (1, "
                 "'{\"b\": 2, \"a\": 1}')")
    # different key order / spacing still matches the stored form
    rs = proc.execute("SELECT id FROM docs WHERE body = "
                      "'{\"a\": 1,   \"b\": 2}' ALLOW FILTERING")
    assert _rows(rs) == [[1]]
    # -> (json output) comparisons canonicalize the rhs too
    proc.execute("INSERT INTO docs (id, body) VALUES (2, "
                 "'{\"pos\": {\"x\": 3, \"y\": 9}}')")
    rs = proc.execute("SELECT id FROM docs WHERE body->'pos' = "
                      "'{\"y\": 9, \"x\": 3}' ALLOW FILTERING")
    assert _rows(rs) == [[2]]


def test_where_arrow_on_non_jsonb_column_rejected(proc):
    proc.execute("INSERT INTO docs (id, tag) VALUES (1, '{\"a\": 1}')")
    with pytest.raises(StatusError, match="not a jsonb column"):
        proc.execute("SELECT id FROM docs WHERE tag->>'a' = '1' "
                     "ALLOW FILTERING")


def test_nan_infinity_rejected(proc):
    for bad in ("NaN", "Infinity", "-Infinity", "[1, NaN]"):
        with pytest.raises(StatusError, match="invalid json"):
            proc.execute("INSERT INTO docs (id, body) VALUES (9, '%s')"
                         % bad)


def test_truncate(proc):
    for i in range(20):
        proc.execute("INSERT INTO docs (id, tag) VALUES (%d, 't%d')"
                     % (i, i))
    assert len(proc.execute("SELECT id FROM docs").rows) == 20
    proc.execute("TRUNCATE docs")
    assert proc.execute("SELECT id FROM docs").rows == []
    # table still usable after truncate
    proc.execute("INSERT INTO docs (id, tag) VALUES (1, 'back')")
    assert _rows(proc.execute("SELECT tag FROM docs WHERE id = 1")) \
        == [["back"]]


def test_truncate_indexed_table(proc):
    proc.execute("DROP TABLE IF EXISTS idocs")
    proc.execute("CREATE TABLE idocs (id INT PRIMARY KEY, tag TEXT)")
    proc.execute("CREATE INDEX itag ON idocs (tag)")
    for i in range(10):
        proc.execute("INSERT INTO idocs (id, tag) VALUES (%d, 'x%d')"
                     % (i, i % 3))
    proc.execute("TRUNCATE idocs")
    assert proc.execute("SELECT id FROM idocs").rows == []
    # the index must not resurrect rows
    assert proc.execute(
        "SELECT id FROM idocs WHERE tag = 'x1'").rows == []
