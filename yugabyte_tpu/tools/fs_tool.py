"""yb-fs-tool: dump the on-disk layout of a server data root.

Capability parity with the reference (ref: src/yb/tools/fs_tool.cc —
dump_fs_tree / list tablets / per-tablet data files with sizes). Walks a
tserver fs root (or a single tablet dir) and reports tablets, their
regular/intents SSTs (base + data file sizes, entry counts from props),
WAL segments, and superblock metadata — without opening the server.

Usage: python -m yugabyte_tpu.tools.fs_tool <fs_root_or_tablet_dir>
"""

from __future__ import annotations

import json
import os
import sys


def _sst_infos(db_dir: str):
    out = []
    if not os.path.isdir(db_dir):
        return out
    from yugabyte_tpu.storage.sst import SSTReader, data_file_name
    for name in sorted(os.listdir(db_dir)):
        if not name.endswith(".sst"):
            continue
        path = os.path.join(db_dir, name)
        info = {"file": name,
                "base_bytes": os.path.getsize(path)}
        data = data_file_name(path)
        if os.path.exists(data):
            info["data_bytes"] = os.path.getsize(data)
        try:
            r = SSTReader(path)
            info["entries"] = r.props.n_entries
            info["blocks"] = r.n_blocks
            fr = r.props.frontier
            info["op_id_max"] = list(fr.op_id_max)
            info["ht_max"] = fr.ht_max
            r.close()
        except Exception as e:  # noqa: BLE001 — corrupt files still listed
            info["error"] = repr(e)
        out.append(info)
    return out


def _wal_infos(wal_dir: str):
    out = []
    if not os.path.isdir(wal_dir):
        return out
    for name in sorted(os.listdir(wal_dir)):
        if name.startswith("wal-"):
            out.append({"segment": name,
                        "bytes": os.path.getsize(
                            os.path.join(wal_dir, name))})
    return out


def tablet_report(tablet_dir: str) -> dict:
    rep = {"tablet_dir": tablet_dir}
    sb = os.path.join(tablet_dir, "meta.json")
    if os.path.exists(sb):
        try:
            with open(sb) as f:
                meta = json.load(f)
            rep["superblock"] = {k: meta.get(k) for k in
                                 ("tablet_id", "table_id", "state",
                                  "schema_version", "peers")
                                 if k in meta}
        except (OSError, json.JSONDecodeError) as e:
            rep["superblock_error"] = repr(e)
    for sub in ("regular", "intents"):
        infos = _sst_infos(os.path.join(tablet_dir, sub))
        rep[sub] = {
            "n_sst": len(infos),
            "total_bytes": sum(i.get("base_bytes", 0)
                               + i.get("data_bytes", 0) for i in infos),
            "ssts": infos,
        }
    rep["wal"] = _wal_infos(os.path.join(tablet_dir, "wal"))
    return rep


def find_tablet_dirs(root: str):
    """Yield tablet directories under a fs root (identified by a
    superblock or regular/+wal/ subdirs) WITHOUT opening any data files
    — discovery for tools that do their own per-tablet work."""
    for dirpath, dirnames, filenames in os.walk(root):
        if "meta.json" in filenames or (
                "regular" in dirnames and "wal" in dirnames):
            yield dirpath
            dirnames[:] = []  # don't descend into the tablet itself


def fs_report(root: str) -> dict:
    tablets = [tablet_report(d) for d in find_tablet_dirs(root)]
    return {"root": root, "n_tablets": len(tablets), "tablets": tablets}


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: fs_tool <fs_root_or_tablet_dir>", file=sys.stderr)
        return 2
    print(json.dumps(fs_report(argv[0]), indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
