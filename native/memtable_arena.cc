// Native memtable arena: the write-path hot structure in C++ (ref:
// src/yb/rocksdb/db/memtable.cc — skiplist + concurrent arena; here an
// append-only arena + sort-on-demand index, the same amortized shape the
// Python MemTable used, at memcpy speed).
//
// Entries are stored as FULL internal keys (prefix + kHybridTime byte +
// 12-byte descending-encoded DocHybridTime) so ordering is a plain
// memcmp and export strips the fixed-width suffix. Duplicate internal
// keys keep the LATEST insert (Python-dict overwrite semantics).
//
// C ABI only (ctypes binding in storage/memtable.py); one writer or
// reader at a time — the Python wrapper holds its own lock.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr int kSuffix = 13;  // 0x23 separator + 12B encoded DocHybridTime

struct Entry {
  int64_t off;   // into keys arena (full internal key)
  int32_t len;   // internal key length (incl. suffix)
  int64_t voff;  // into vals arena
  int32_t vlen;
  int64_t seq;   // insertion sequence; latest wins on duplicate ikey
};

struct MT {
  std::vector<uint8_t> keys;   // internal-key arena
  std::vector<uint8_t> vals;   // value arena
  std::vector<Entry> ents;     // insertion order
  std::vector<int32_t> order;  // sorted+deduped index into ents
  bool sorted = true;          // order valid for current ents
  int64_t bytes = 0;           // approximate accounting (ikey+val lens)
  std::string err;
};

inline int cmp_ikey(const MT* m, const Entry& a, const Entry& b) {
  int32_t n = a.len < b.len ? a.len : b.len;
  int c = memcmp(m->keys.data() + a.off, m->keys.data() + b.off, (size_t)n);
  if (c) return c;
  return a.len < b.len ? -1 : (a.len > b.len ? 1 : 0);
}

}  // namespace

extern "C" {

void* mt_new() { return new MT(); }

void mt_free(void* h) { delete (MT*)h; }

// keys_blob/koffs: n internal-key PREFIXES (without suffix); suffixes:
// n * 12 bytes of encoded DocHybridTime. The arena stores
// prefix + 0x23 + suffix contiguously per entry.
int mt_add_batch(void* h, const uint8_t* keys_blob, const int64_t* koffs,
                 const uint8_t* suffixes, const uint8_t* vals_blob,
                 const int64_t* voffs, int64_t n) {
  MT* m = (MT*)h;
  int64_t kbytes = koffs[n] + n * (int64_t)kSuffix;
  int64_t vbytes = voffs[n];
  size_t k0 = m->keys.size(), v0 = m->vals.size();
  m->keys.resize(k0 + (size_t)kbytes);
  m->vals.resize(v0 + (size_t)vbytes);
  memcpy(m->vals.data() + v0, vals_blob, (size_t)vbytes);
  int64_t seq0 = (int64_t)m->ents.size();
  m->ents.reserve(m->ents.size() + (size_t)n);
  uint8_t* kp = m->keys.data() + k0;
  int64_t off = (int64_t)k0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t plen = (int32_t)(koffs[i + 1] - koffs[i]);
    memcpy(kp, keys_blob + koffs[i], (size_t)plen);
    kp[plen] = 0x23;  // ValueType::kHybridTime
    memcpy(kp + plen + 1, suffixes + i * 12, 12);
    int32_t ilen = plen + kSuffix;
    int32_t vlen = (int32_t)(voffs[i + 1] - voffs[i]);
    m->ents.push_back({off, ilen, (int64_t)v0 + voffs[i], vlen, seq0 + i});
    m->bytes += ilen + vlen;
    kp += ilen;
    off += ilen;
  }
  m->sorted = false;
  return 0;
}

static void ensure_sorted(MT* m) {
  if (m->sorted) return;
  std::vector<int32_t>& ord = m->order;
  ord.resize(m->ents.size());
  for (size_t i = 0; i < ord.size(); ++i) ord[i] = (int32_t)i;
  const MT* mc = m;
  std::sort(ord.begin(), ord.end(), [mc](int32_t x, int32_t y) {
    int c = cmp_ikey(mc, mc->ents[x], mc->ents[y]);
    if (c) return c < 0;
    // equal internal keys: latest insert first (survives the dedup)
    return mc->ents[x].seq > mc->ents[y].seq;
  });
  // dedup consecutive equal ikeys, keeping the first (= latest seq)
  size_t w = 0;
  for (size_t r = 0; r < ord.size(); ++r) {
    if (w && cmp_ikey(mc, mc->ents[ord[w - 1]], mc->ents[ord[r]]) == 0)
      continue;
    ord[w++] = ord[r];
  }
  ord.resize(w);
  m->sorted = true;
}

int64_t mt_n(void* h) {  // distinct internal keys (dict semantics)
  MT* m = (MT*)h;
  ensure_sorted(m);
  return (int64_t)m->order.size();
}

int64_t mt_bytes(void* h) { return ((MT*)h)->bytes; }

int64_t mt_raw_n(void* h) { return (int64_t)((MT*)h)->ents.size(); }

// First sorted position whose internal key >= seek. Returns index into
// the sorted order, or mt_n if none.
int64_t mt_lower_bound(void* h, const uint8_t* seek, int32_t seek_len) {
  MT* m = (MT*)h;
  ensure_sorted(m);
  int64_t lo = 0, hi = (int64_t)m->order.size();
  while (lo < hi) {
    int64_t mid = (lo + hi) >> 1;
    const Entry& e = m->ents[m->order[mid]];
    int32_t n = e.len < seek_len ? e.len : seek_len;
    int c = memcmp(m->keys.data() + e.off, seek, (size_t)n);
    if (c == 0) c = e.len < seek_len ? -1 : (e.len > seek_len ? 1 : 0);
    if (c < 0)
      lo = mid + 1;
    else
      hi = mid;
  }
  return lo;
}

// Sizes of the export range [start, end) over the sorted order.
// include_suffix: 1 = full internal keys (iter paths), 0 = prefixes only
// (the to_packed / flush-encoder layout).
void mt_range_sizes(void* h, int64_t start, int64_t end,
                    int32_t include_suffix, int64_t* kbytes,
                    int64_t* vbytes) {
  MT* m = (MT*)h;
  ensure_sorted(m);
  int64_t kb = 0, vb = 0;
  for (int64_t i = start; i < end; ++i) {
    const Entry& e = m->ents[m->order[i]];
    kb += include_suffix ? e.len : e.len - kSuffix;
    vb += e.vlen;
  }
  *kbytes = kb;
  *vbytes = vb;
}

// Export sorted entries [start, end): keys (full internal or prefix-only
// per include_suffix) + values, plus decoded (ht, wid) columns (from the
// descending-encoded suffix).
void mt_export_range(void* h, int64_t start, int64_t end,
                     int32_t include_suffix, uint8_t* keys_out,
                     int64_t* koffs_out, uint64_t* ht_out, uint32_t* wid_out,
                     uint8_t* vals_out, int64_t* voffs_out) {
  MT* m = (MT*)h;
  ensure_sorted(m);
  int64_t ko = 0, vo = 0;
  koffs_out[0] = 0;
  voffs_out[0] = 0;
  for (int64_t i = start; i < end; ++i) {
    const Entry& e = m->ents[m->order[i]];
    int32_t klen = include_suffix ? e.len : e.len - kSuffix;
    memcpy(keys_out + ko, m->keys.data() + e.off, (size_t)klen);
    memcpy(vals_out + vo, m->vals.data() + e.voff, (size_t)e.vlen);
    const uint8_t* sfx = m->keys.data() + e.off + e.len - 12;
    uint64_t ht_c = 0;
    uint32_t wid_c = 0;
    for (int b = 0; b < 8; ++b) ht_c = (ht_c << 8) | sfx[b];
    for (int b = 8; b < 12; ++b) wid_c = (wid_c << 8) | sfx[b];
    ht_out[i - start] = ~ht_c;
    wid_out[i - start] = ~wid_c;
    ko += klen;
    vo += e.vlen;
    koffs_out[i - start + 1] = ko;
    voffs_out[i - start + 1] = vo;
  }
}

}  // extern "C"
