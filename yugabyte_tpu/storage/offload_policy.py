"""Offload vocabulary + fault quarantine for device-vs-native routing.

Round 3 wired the device into every live compaction unconditionally; at
the then-measured rates that was an ~11x pessimization over the native
C++ path (VERDICT r3 weak #3).  Rounds 4-15 gated routing on a static
calibration file; PR 16 replaced that frozen snapshot with the
LIVE per-(kernel family, bucket) health state machine in
storage/bucket_health.py — routing decisions now come from
`BucketHealthBoard.use_device()/allow_device()`, fed by measured rates,
fault events and shadow mismatches on the running process.

What stays here is the shared vocabulary and the fault registry:

  - the (k_pad, m) bucket-key helpers every dispatch site and the
    kernel manifest agree on;
  - the declared compile surface loaded from the manifest;
  - `BucketQuarantine`, the timed native-only fault registry — now
    embedded inside the board as its QUARANTINED state's memory, with
    its legacy `offload_quarantine_*` counters preserved;
  - the routing-decision counters.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional, Tuple

from yugabyte_tpu.utils import flags

flags.define_flag("device_offload_mode", "auto",
                  "auto = measured bucket-health routing; device/native "
                  "= force")
flags.define_flag("device_fault_quarantine_s", 300.0,
                  "how long a shape bucket stays native-only after a "
                  "device fault in its kernel path (timed decay; the "
                  "next job after expiry re-proves the bucket)")


def _offload_counters():
    """Decision counters: WHICH way each compaction routed, and WHY —
    the visibility LUDA-style offload systems attribute their wins with
    (offloaded vs CPU-fallback, forced/cold/measured)."""
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    e = ROOT_REGISTRY.entity("server", "offload_policy")
    return {
        "device": e.counter("offload_decisions_device_total",
                            "compactions routed to the device kernel"),
        "native": e.counter("offload_decisions_native_total",
                            "compactions routed to the native CPU path"),
        "forced": e.counter("offload_decisions_forced_total",
                            "decisions forced by device_offload_mode"),
        "cold": e.counter(
            "offload_decisions_cold_total",
            "native routings taken because the bucket is COLD (compile "
            "cost not yet amortized; prewarm pays it)"),
        "measured": e.counter(
            "offload_decisions_measured_total",
            "decisions made from live bucket-health measurements"),
    }


# ---------------------------------------------------------------------------
# Shape-bucket quarantine: device-fault containment's memory. When the
# kernel path of a compaction fails (XLA compile error, HBM OOM, runtime
# dispatch fault) the job completes via the native fallback — and the
# failing SHAPE BUCKET is parked native-only for a decay window, so every
# subsequent job that would compile/launch the same poisoned executable
# routes straight to native instead of re-failing (the RESYSTANCE lesson
# applied to faults: observe where the device path breaks and steer work
# around it). The bucket key is the padded run layout (k_pad, m) — the
# dominant part of the fused program's compile key.

class BucketQuarantine:
    """Timed native-only quarantine of kernel shape buckets."""

    def __init__(self):
        from yugabyte_tpu.utils import lock_rank
        self._lock = lock_rank.tracked(threading.Lock(),
                                       "offload_policy.quarantine_lock")
        # bucket -> {"until": monotonic, "reason": str, "faults": int,
        #            "since": wall}  # guarded-by: _lock
        self._entries: dict = {}

    def quarantine(self, bucket: Tuple[int, ...], reason: str,
                   ttl_s: Optional[float] = None) -> None:
        surface = declared_surface_keys()
        if surface and tuple(bucket) not in surface:
            # a fault on a shape the manifest never declared: the
            # compile-surface lattice leaked before the device did
            from yugabyte_tpu.utils.trace import TRACE
            TRACE("offload_policy: quarantining bucket k_pad=%s m=%s "
                  "OUTSIDE the declared compile surface (%d keys) — "
                  "regenerate/review tools/analysis/kernel_manifest.json",
                  bucket[0], bucket[1], len(surface))
        ttl = ttl_s if ttl_s is not None else \
            flags.get_flag("device_fault_quarantine_s")
        with self._lock:
            e = self._entries.get(bucket)
            self._entries[bucket] = {
                "until": time.monotonic() + ttl,
                "reason": reason,
                "faults": (e["faults"] + 1) if e else 1,
                "since": time.time(),
            }
        _quarantine_counter("added").increment()

    def is_quarantined(self, bucket: Tuple[int, ...]) -> bool:
        """True while the bucket's window is open; expired entries decay
        (are dropped) on the first check past their deadline."""
        decayed, hit = self._check_window(bucket)
        if decayed:
            _quarantine_counter("decayed").increment()
        elif hit:
            _quarantine_counter("hits").increment()
        return hit

    def open_window(self, bucket: Tuple[int, ...]) -> bool:
        """is_quarantined WITHOUT the legacy hits counter — the health
        board attributes routing decisions itself (the decayed counter
        still fires; a decay is a registry event either way)."""
        decayed, hit = self._check_window(bucket)
        if decayed:
            _quarantine_counter("decayed").increment()
        return hit

    def _check_window(self, bucket) -> Tuple[bool, bool]:
        """(decayed, open). The clock is read INSIDE the lock: reading
        it outside let a concurrent quarantine() land between the stale
        `now` and the decay compare, deleting a window that had just
        been re-armed (the PR 16 timed-decay race)."""
        with self._lock:
            now = time.monotonic()
            e = self._entries.get(bucket)
            if e is None:
                return False, False
            if now >= e["until"]:
                del self._entries[bucket]   # timed decay: re-prove it
                return True, False
            return False, True

    def restore(self, bucket: Tuple[int, ...], reason: str, faults: int,
                remaining_s: float) -> None:
        """Re-open a window from persisted board state WITHOUT bumping
        the added-counter — a process restart is not a new fault."""
        with self._lock:
            self._entries[tuple(bucket)] = {
                "until": time.monotonic() + max(0.0, remaining_s),
                "reason": reason,
                "faults": max(1, int(faults)),
                "since": time.time(),
            }

    def snapshot(self) -> List[dict]:
        """Open quarantine windows for /compactionz (expired entries are
        pruned here too, so the page never shows a decayed bucket)."""
        now = time.monotonic()
        with self._lock:
            for b in [b for b, e in self._entries.items()
                      if now >= e["until"]]:
                del self._entries[b]
            return [{"bucket": list(b), "reason": e["reason"],
                     "faults": e["faults"],
                     "remaining_s": round(e["until"] - now, 1),
                     "since": e["since"]}
                    for b, e in sorted(self._entries.items())]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


def _quarantine_counter(what: str):
    from yugabyte_tpu.utils.metrics import ROOT_REGISTRY
    helps = {"added": "shape buckets parked native-only after a device "
                      "fault",
             "hits": "compactions routed native because their shape "
                     "bucket is quarantined",
             "decayed": "quarantine windows that expired (bucket "
                        "eligible for the device path again)"}
    return ROOT_REGISTRY.entity("server", "offload_policy").counter(
        f"offload_quarantine_{what}_total", helps[what])


# ---------------------------------------------------------------------------
# Declared compile surface: the committed kernel manifest
# (tools/analysis/kernel_manifest.json, regenerated by
# `python -m tools.analysis.kernel_manifest --write` and drift-gated in
# tier-1) enumerates every (k_pad, m) shape bucket the kernel families
# are declared reachable with. The policy layer uses it as the shape
# vocabulary: a quarantine (or a device-native launch) on a key OUTSIDE
# the surface is the earliest signal that the bucket lattice has sprung
# a leak — some code path is minting executables the prewarm/budget
# discipline never reviewed.

_surface_keys: Optional[frozenset] = None  # guarded-by: _surface_lock
_surface_counts: Optional[dict] = None     # guarded-by: _surface_lock
_surface_lock = threading.Lock()


def _manifest_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "tools", "analysis", "kernel_manifest.json")


def _load_surface_unlocked() -> None:
    global _surface_keys, _surface_counts
    keys = set()
    counts: dict = {}
    try:
        with open(_manifest_path()) as f:
            manifest = json.load(f)
        for name, rec in manifest.get("families", {}).items():
            counts[name] = int(rec.get("distinct_executables") or 0)
            for e in rec.get("entries", ()):
                qk = e.get("quarantine_key")
                if qk:
                    keys.add((int(qk[0]), int(qk[1])))
    except (OSError, ValueError):  # yblint: contained(absent/corrupt manifest means no declared surface — the off-surface telemetry simply stays quiet)
        pass
    _surface_keys = frozenset(keys)
    _surface_counts = counts


def declared_surface_keys() -> frozenset:
    """(k_pad, m) quarantine keys of every declared manifest bucket;
    empty when no manifest is committed (telemetry-only consumer)."""
    with _surface_lock:
        if _surface_keys is None:
            _load_surface_unlocked()
        return _surface_keys


def declared_surface_counts() -> dict:
    """family -> declared distinct-executable count from the manifest
    (feeds the kernel_compile_surface gauges)."""
    with _surface_lock:
        if _surface_counts is None:
            _load_surface_unlocked()
        return dict(_surface_counts)


def bucket_key(run_ns) -> Tuple[int, int]:
    """The quarantine key for a job with (packed) run lengths run_ns:
    (k_pad, m) of the run-major layout — computed the same way
    ops/run_merge.stage_runs_from_slabs lays the matrix out, WITHOUT
    staging anything, so the pre-dispatch check and the fault-time
    quarantine agree on the key."""
    from yugabyte_tpu.ops.run_merge import run_bucket
    live = [n for n in run_ns if n]
    if not live:
        return (0, 0)
    k = len(live)
    k_pad = 1 << max(0, (k - 1).bit_length()) if k > 1 else 1
    m = max(run_bucket(n) for n in live)
    return (k_pad, m)


def point_read_bucket_key(n_pad: int) -> Tuple[int, int]:
    """Quarantine key for the batched point-read kernels over a staged
    matrix padded to n_pad: the single-run layout (k_pad=1, m=n_pad) —
    the same vocabulary scan_fused declares, so a locate-kernel fault
    parks exactly the declared bucket (ops/point_read.py)."""
    return (1, n_pad)


def bucket_quarantine() -> BucketQuarantine:
    """Process-wide quarantine registry — the health board's embedded
    fault registry (storage/bucket_health.py), so legacy callers and
    the board share ONE memory of poisoned buckets. Its `clear()`
    resets the whole board (test/operator isolation)."""
    from yugabyte_tpu.storage.bucket_health import health_board
    return health_board().quarantine_registry()
