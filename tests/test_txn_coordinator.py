"""TransactionCoordinator unit tests with a local status tablet.

Regression coverage for ADVICE r1 #2: a status request must not be able to
read 'pending' inside commit()'s window between picking commit_ht and the
replicated write applying — that would tear a snapshot (two reads at the
same read_ht seeing different data). status() now serializes with commit()
on the per-txn mutex (ref: the reference floors commit time above
outstanding status-request times, transaction_coordinator.cc)."""

import threading
import time
import uuid

import pytest

from yugabyte_tpu.common.hybrid_time import HybridTime
from yugabyte_tpu.tablet.tablet import Tablet
from yugabyte_tpu.tserver.transaction_coordinator import (
    TXN_STATUS_SCHEMA, TransactionCoordinator)


class LocalPeer:
    """Minimal TabletPeer stand-in: a real (non-replicated) status tablet
    plus a write hook for injecting delays."""

    def __init__(self, path):
        self.tablet = Tablet("status-t", path, TXN_STATUS_SCHEMA)
        self.clock = self.tablet.clock
        self.write_hook = None

    def write(self, ops):
        if self.write_hook is not None:
            self.write_hook(ops)
        return self.tablet.write(ops)


@pytest.fixture
def peer(tmp_path):
    p = LocalPeer(str(tmp_path / "status"))
    yield p
    p.tablet.close()


def test_create_heartbeat_commit(peer):
    coord = TransactionCoordinator()
    txn = uuid.uuid4().bytes
    resp = coord.create(peer, txn)
    assert resp["read_ht"] > 0
    assert coord.heartbeat(peer, txn)
    assert coord.status(peer, txn)["status"] == "pending"
    commit = coord.commit(peer, txn, [])
    assert commit["commit_ht"] > resp["read_ht"]
    st = coord.status(peer, txn)
    assert st == {"status": "committed", "commit_ht": commit["commit_ht"]}


def test_status_cannot_interleave_with_commit(peer):
    """ADVICE r1 #2: status() arriving while commit() has picked commit_ht
    but not yet applied its replicated write must WAIT and answer
    'committed' — never 'pending' with a smaller commit_ht racing in."""
    coord = TransactionCoordinator()
    txn = uuid.uuid4().bytes
    coord.create(peer, txn)

    in_commit_write = threading.Event()
    release_commit = threading.Event()

    def hook(ops):
        if ops and ops[0].values.get("status") == "committed":
            in_commit_write.set()
            assert release_commit.wait(10)

    peer.write_hook = hook
    commit_result = {}
    ct = threading.Thread(
        target=lambda: commit_result.update(coord.commit(peer, txn, [])))
    ct.start()
    assert in_commit_write.wait(10)
    # commit_ht is chosen and the status-row write is in flight. A reader
    # at a snapshot >= commit_ht asks for status now.
    observing = peer.clock.now().value
    status_result = {}
    st = threading.Thread(
        target=lambda: status_result.update(
            coord.status(peer, txn, observing_read_ht=observing)))
    st.start()
    # status must block on the txn mutex, not answer early.
    time.sleep(0.15)
    assert not status_result, (
        f"status answered {status_result} inside the commit window")
    release_commit.set()
    ct.join(10)
    st.join(10)
    assert status_result["status"] == "committed"
    assert status_result["commit_ht"] == commit_result["commit_ht"]


def test_expired_pending_txn_lazily_aborted(peer):
    from yugabyte_tpu.utils import flags
    coord = TransactionCoordinator()
    txn = uuid.uuid4().bytes
    coord.create(peer, txn)
    old = flags.get_flag("transaction_timeout_ms")
    flags.set_flag("transaction_timeout_ms", 0)
    try:
        time.sleep(0.002)
        assert coord.status(peer, txn)["status"] == "aborted"
    finally:
        flags.set_flag("transaction_timeout_ms", old)
    # commit after lazy abort must fail
    from yugabyte_tpu.utils.status import StatusError
    with pytest.raises(StatusError):
        coord.commit(peer, txn, [])
