"""yblint (tools/analysis) test suite + tier-1 CI wiring.

Three layers:
- seeded-defect fixtures proving each pass FIRES (positive cases) and
  stays quiet on the idiomatic negatives;
- framework behavior: baseline round-trip, inline suppression, JSON
  output, pass selection;
- the CI gate: `python -m tools.analysis yugabyte_tpu/` must be clean
  against the committed baseline, and the runtime lock-order tracker
  (utils/lock_rank.py) must have observed no acquisition cycles by the
  time this module runs.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.analysis import core  # noqa: E402
from tools.analysis.passes import ALL_PASSES, passes_by_name  # noqa: E402
from tools.analysis.passes.blocking_reactor import (  # noqa: E402
    BlockingReactorPass)
from tools.analysis.passes.jit_trace_safety import (  # noqa: E402
    JitTraceSafetyPass)
from tools.analysis.passes.lock_discipline import (  # noqa: E402
    LockDisciplinePass)
from tools.analysis.passes.metric_names import MetricNamesPass  # noqa: E402
from tools.analysis.passes.swallowed_errors import (  # noqa: E402
    SwallowedErrorsPass)
from yugabyte_tpu.utils import lock_rank  # noqa: E402


def _lint(src, passes, relpath="fixture.py"):
    ctx = core.FileContext(relpath, relpath, textwrap.dedent(src))
    out = []
    for p in passes:
        out.extend(f for f in p.run(ctx)
                   if not core._is_suppressed(ctx, f))
    return out


def _codes(findings):
    return sorted(f.code for f in findings)


# ---------------------------------------------------------------------------
# jit trace-safety
# ---------------------------------------------------------------------------

class TestJitTraceSafety:
    PASS = [JitTraceSafetyPass()]

    def test_host_syncs_and_branches_fire(self):
        src = """
            import jax
            import numpy as np

            @jax.jit
            def f(x):
                if x > 0:
                    y = x.item()
                print(x)
                z = np.asarray(x)
                return float(x)
        """
        codes = _codes(_lint(src, self.PASS))
        assert codes.count("tracer-branch") == 1
        assert codes.count("host-sync") == 3   # .item(), np.asarray, float
        assert codes.count("print-tracer") == 1

    def test_static_args_and_metadata_are_negative(self):
        src = """
            import functools
            import jax

            @functools.partial(jax.jit, static_argnames=("k", "w"))
            def f(x, k, w):
                if k > 1 and w > 4:        # statics: fine
                    x = x * 2
                if x.shape[0] > 1:         # shape metadata: fine
                    x = x + 1
                n = int(w)                 # static int(): fine
                if x is None:              # identity check: fine
                    return None
                return x
        """
        assert _lint(src, self.PASS) == []

    def test_call_site_taint_reaches_helpers(self):
        src = """
            import functools
            import jax

            _STATICS = ("m",)

            _fused = functools.partial(jax.jit, static_argnames=_STATICS)(
                lambda x, m: x)

            @functools.partial(jax.jit, static_argnames=("m",))
            def root(x, m):
                return helper(x, m)

            def helper(v, m):
                while m > 1:               # static via call site: fine
                    m //= 2
                while v > 1:               # tracer via call site: flagged
                    v = v - 1
                return v
        """
        fs = _lint(src, self.PASS)
        assert _codes(fs) == ["tracer-branch"]
        assert fs[0].symbol == "helper"

    def test_module_constant_static_argnames_resolved(self):
        src = """
            import functools
            import jax

            _STATICS = ("k", "m")

            def impl(cols, k, m):
                if k > 1:                  # static (resolved via _STATICS)
                    cols = cols * 2
                return cols

            fused = functools.partial(jax.jit, static_argnames=_STATICS)(impl)
        """
        assert _lint(src, self.PASS) == []

    def test_unhashable_static_call_site(self):
        src = """
            import jax

            @jax.jit
            def plain(x):
                return x

            def g(x, k):
                return x

            jg = jax.jit(g, static_argnames=("k",))

            def caller(a):
                return jg(a, k=[1, 2])
        """
        fs = _lint(src, self.PASS)
        assert _codes(fs) == ["unhashable-static"]

    def test_waiver(self):
        src = """
            import jax

            @jax.jit
            def f(x):
                return x.item()  # yblint: disable=jit-trace-safety
        """
        assert _lint(src, self.PASS) == []


# ---------------------------------------------------------------------------
# lock discipline
# ---------------------------------------------------------------------------

class TestLockDiscipline:
    PASS = [LockDisciplinePass()]

    def test_unguarded_instance_access_fires(self):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []   # guarded-by: _lock

                def good(self):
                    with self._lock:
                        self._items.append(1)

                def bad(self):
                    self._items.append(2)
        """
        fs = _lint(src, self.PASS)
        assert len(fs) == 1 and fs[0].symbol == "C.bad"

    def test_condition_alias_and_unlocked_suffix(self):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._cv = threading.Condition(self._lock)
                    self._n = 0   # guarded-by: _cv

                def via_lock(self):
                    with self._lock:       # alias of _cv: fine
                        self._n += 1

                def _bump_unlocked(self):  # caller-holds convention
                    self._n += 1
        """
        assert _lint(src, self.PASS) == []

    def test_module_global(self):
        src = """
            import threading

            _reg = {}                # guarded-by: _reg_lock
            _reg_lock = threading.Lock()

            def good():
                with _reg_lock:
                    _reg["x"] = 1

            def bad():
                return _reg.get("x")

            def shadowed(_reg):
                return _reg          # a parameter, not the global: fine
        """
        fs = _lint(src, self.PASS)
        assert len(fs) == 1 and fs[0].symbol == "bad"

    def test_def_level_caller_holds_annotation(self):
        src = """
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._m = {}   # guarded-by: _lock

                def _peek(self):   # guarded-by: _lock
                    return self._m.get(1)
        """
        assert _lint(src, self.PASS) == []

    def test_multiline_assignment_annotation(self):
        src = """
            import threading
            from typing import Dict

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._m: Dict[str,
                                  int] = {}   # guarded-by: _lock

                def bad(self):
                    return self._m
        """
        fs = _lint(src, self.PASS)
        assert len(fs) == 1


# ---------------------------------------------------------------------------
# blocking-call-in-reactor
# ---------------------------------------------------------------------------

class TestBlockingReactor:
    PASS = [BlockingReactorPass()]

    def test_rpc_reactor_seeds_and_reachability(self):
        src = """
            import time

            class Conn:
                def _read_loop(self):
                    while True:
                        self._handle()

                def _handle(self):
                    time.sleep(0.1)
                    f = open("/tmp/x")
                    self.done_event.wait()
        """
        fs = _lint(src, self.PASS, relpath="yugabyte_tpu/rpc/conn.py")
        assert _codes(fs) == ["reactor-file-io", "reactor-sleep",
                              "unbounded-wait"]

    def test_marker_and_bounded_negatives(self):
        src = """
            import time

            class W:
                def loop(self):   # yblint: reactor
                    self.work_queue.get(timeout=1)   # bounded: fine
                    self.done_event.wait(0.5)        # bounded: fine

                def not_reactor(self):
                    time.sleep(1)                     # off-path: fine
        """
        assert _lint(src, self.PASS, relpath="anywhere.py") == []

    def test_unbounded_queue_get(self):
        src = """
            class W:
                def _read_loop(self):
                    item = self.work_queue.get()
        """
        fs = _lint(src, self.PASS, relpath="yugabyte_tpu/rpc/w.py")
        assert _codes(fs) == ["unbounded-get"]


# ---------------------------------------------------------------------------
# migrated passes (swallowed errors / metric names) keep their behavior
# ---------------------------------------------------------------------------

class TestMigratedPasses:
    def test_swallowed_errors(self):
        src = """
            def risky():
                try:
                    work()
                except Exception:
                    pass

            def routed():
                try:
                    work()
                except Exception as e:
                    TRACE("failed: %s", e)

            def waived():
                try:
                    work()
                except Exception:  # lint: swallow-ok
                    pass

            class D:
                def __del__(self):
                    try:
                        self.close()
                    except Exception:
                        pass
        """
        p = SwallowedErrorsPass()
        assert p.applies_to("yugabyte_tpu/storage/db.py")
        assert not p.applies_to("yugabyte_tpu/rpc/messenger.py")
        fs = _lint(src, [p])
        assert len(fs) == 1 and fs[0].symbol == "risky"

    def test_metric_names(self):
        src = """
            e.counter('CamelCase')
            e.counter('missing_suffix')
            e.histogram('latency')
            e.gauge('depth_ok_depth')
            e.counter('waived')  # lint: metric-name-ok
            e.counter(dynamic_name)
            e.counter('fine_total')
        """
        fs = _lint(src, [MetricNamesPass()])
        assert len(fs) == 3
        assert sorted(set(_codes(fs))) == ["missing-unit-suffix",
                                           "not-snake-case"]

    def test_legacy_shims_still_answer(self, tmp_path):
        """The standalone entry points survive as shims over the passes
        (tests/test_backoff.py + tests/test_observability.py call them)."""
        sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
        try:
            import lint_metric_names
            import lint_swallowed_errors
        finally:
            sys.path.pop(0)
        bad = tmp_path / "bad.py"
        bad.write_text("e.counter('Nope')\n"
                       "try:\n    x()\nexcept Exception:\n    pass\n")
        assert len(lint_metric_names.check_file(str(bad))) == 1
        assert len(lint_swallowed_errors.check_file(str(bad))) == 1


# ---------------------------------------------------------------------------
# framework: baseline round-trip, suppression, CLI
# ---------------------------------------------------------------------------

BAD_LOCK_SRC = textwrap.dedent("""
    import threading

    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []   # guarded-by: _lock

        def bad(self):
            self._items.append(2)
""")


class TestFramework:
    def test_baseline_round_trip(self, tmp_path):
        target = tmp_path / "mod.py"
        target.write_text(BAD_LOCK_SRC)
        bl_path = str(tmp_path / "baseline.txt")

        findings = core.analyze_paths(str(tmp_path), ["mod.py"],
                                      [LockDisciplinePass()])
        assert len(findings) == 1

        # accept into the baseline -> clean run
        bl = core.Baseline.load(bl_path)
        bl.save(bl_path, findings)
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 0 and len(res.known) == 1

        # a NEW defect still fails, the old one stays baselined
        target.write_text(BAD_LOCK_SRC
                          + "\n    def also_bad(self):\n"
                            "        return self._items\n")
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 1
        assert len(res.new) == 1 and len(res.known) == 1

        # fingerprints are line-number-free: shifting the file by a
        # comment block must not invalidate the baseline
        target.write_text("# pad\n# pad\n# pad\n" + BAD_LOCK_SRC)
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 0 and len(res.known) == 1

        # fixing the defect leaves a STALE entry, reported but not fatal
        target.write_text(BAD_LOCK_SRC.replace(
            "self._items.append(2)",
            "with self._lock:\n            self._items.append(2)"))
        res = core.run_analysis(str(tmp_path), ["mod.py"],
                                [LockDisciplinePass()], bl_path)
        assert res.exit_code == 0 and len(res.stale) == 1

    def test_inline_suppression(self, tmp_path):
        target = tmp_path / "mod.py"
        target.write_text(BAD_LOCK_SRC.replace(
            "self._items.append(2)",
            "self._items.append(2)  # yblint: disable=lock-discipline"))
        findings = core.analyze_paths(str(tmp_path), ["mod.py"],
                                      [LockDisciplinePass()])
        assert findings == []

    def test_cli_json_and_pass_selection(self, tmp_path):
        target = tmp_path / "mod.py"
        target.write_text(BAD_LOCK_SRC)
        env = dict(os.environ, PYTHONPATH=REPO_ROOT)
        proc = subprocess.run(
            [sys.executable, "-m", "tools.analysis", str(target),
             "--no-baseline", "--json", "--passes", "lock-discipline"],
            capture_output=True, text=True, cwd=REPO_ROOT, env=env)
        assert proc.returncode == 1, proc.stderr
        report = json.loads(proc.stdout)
        assert report["counts"]["new"] == 1
        assert report["new"][0]["pass"] == "lock-discipline"

    def test_unknown_pass_rejected(self):
        with pytest.raises(KeyError):
            passes_by_name(["nope"])

    def test_all_passes_have_unique_names(self):
        names = [p.name for p in ALL_PASSES]
        assert len(names) == len(set(names)) == 5


# ---------------------------------------------------------------------------
# runtime lock-order tracker
# ---------------------------------------------------------------------------

class TestLockRank:
    def test_cycle_detection_unit(self):
        lock_rank.reset()
        try:
            a = lock_rank.TrackedLock(threading.Lock(), "test.A")
            b = lock_rank.TrackedLock(threading.Lock(), "test.B")
            c = lock_rank.TrackedLock(threading.Lock(), "test.C")
            with a:
                with b:
                    pass
            with b:
                with c:
                    pass
            assert lock_rank.find_cycle() is None
            with c:
                with a:   # closes A -> B -> C -> A
                    pass
            cycle = lock_rank.find_cycle()
            assert cycle is not None
            assert lock_rank.violations(), "cycle must be latched"
            with pytest.raises(AssertionError):
                lock_rank.assert_no_cycles()
        finally:
            lock_rank.reset()

    def test_enabled_under_pytest_and_noop_probe(self):
        assert lock_rank.enabled()   # pytest is in sys.modules here
        raw = threading.Lock()
        t = lock_rank.tracked(raw, "test.probe")
        assert isinstance(t, lock_rank.TrackedLock)
        # non-blocking probe failures record nothing
        with t:
            held_before = list(lock_rank._held_stack())
            assert not t.acquire(blocking=False)
            assert lock_rank._held_stack() == held_before

    def test_condition_over_tracked_lock(self):
        inner = lock_rank.tracked(threading.Lock(), "test.cv_lock")
        cv = threading.Condition(inner)
        done = []

        def waiter():
            with cv:
                cv.wait(timeout=2.0)
                done.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.05)
        with cv:
            cv.notify()
        t.join(timeout=5)
        assert done == [1]


# ---------------------------------------------------------------------------
# CI gates (tier-1): repo is yblint-clean; no lock-order cycles observed
# ---------------------------------------------------------------------------

def test_repo_is_yblint_clean():
    """The tier-1 gate: the full analyzer over yugabyte_tpu/ must report
    no findings beyond the committed baseline (and the baseline itself
    must not rot: stale entries are tolerated here but reported by the
    CLI so they get pruned)."""
    res = core.run_analysis()
    assert not res.new, "\n".join(f.render() for f in res.new)


def test_repo_baseline_is_empty():
    """Acceptance: the final tree needs no suppressions — every entry
    added to the baseline must carry a per-line justification, and today
    there are none."""
    bl = core.Baseline.load(core.DEFAULT_BASELINE)
    unjustified = [fp for fp in bl.entries if fp not in bl.notes]
    assert not unjustified, (
        "baseline entries without a justification: "
        + "\n".join(unjustified))


def test_no_lock_order_cycles_observed():
    """Every MiniCluster/raft/WAL/device-cache lock acquired anywhere in
    this pytest process runs through utils/lock_rank.py; by the time this
    module executes, the recorded acquisition graph must be acyclic."""
    lock_rank.assert_no_cycles()
