"""yb-ts-cli: per-tablet-server operations CLI.

Capability parity with the reference (ref: src/yb/tools/yb-ts-cli.cc —
status, list_tablets, flush_tablet, compact_tablet, are_tablets_running,
dump_tablet against ONE tserver, no master involved).

Usage: python -m yugabyte_tpu.tools.ts_cli --server <host:port> <cmd> ...
"""

from __future__ import annotations

import argparse
import json
import sys

from yugabyte_tpu.rpc.messenger import Messenger
from yugabyte_tpu.utils.status import StatusError


def _p(obj) -> None:
    print(json.dumps(obj, indent=2, default=lambda b: b.hex()
                     if isinstance(b, bytes) else str(b)))


class TsCli:
    def __init__(self, server_addr: str):
        self.addr = server_addr
        self.m = Messenger("ts-cli")

    def call(self, mth, **kw):
        return self.m.call(self.addr, "tserver", mth, **kw)

    def status(self) -> None:
        _p(self.call("status"))

    def list_tablets(self) -> None:
        _p(self.call("list_tablets"))

    def are_tablets_running(self) -> int:
        """Exit 0 iff every hosted tablet reports RUNNING (the reference's
        readiness probe for rolling restarts)."""
        report = self.call("status")["tablets"]
        not_running = [t for t in report
                       if t.get("state", "RUNNING") != "RUNNING"]
        _p({"total": len(report), "not_running": not_running})
        return 1 if not_running else 0

    def flush_tablet(self, tablet_id: str) -> None:
        _p({"flushed": self.call("flush_tablet", tablet_id=tablet_id)})

    def flush_all_tablets(self) -> None:
        out = {}
        for tid in self.call("list_tablets"):
            out[tid] = self.call("flush_tablet", tablet_id=tid)
        _p(out)

    def compact_tablet(self, tablet_id: str) -> None:
        _p({"compacted": self.call("compact_tablet", tablet_id=tablet_id)})

    def compact_all_tablets(self) -> None:
        out = {}
        for tid in self.call("list_tablets"):
            out[tid] = self.call("compact_tablet", tablet_id=tid)
        _p(out)

    def dump_tablet(self, tablet_id: str) -> None:
        _p(self.call("dump_tablet", tablet_id=tablet_id,
                     read_ht=(1 << 62)))

    def delete_tablet(self, tablet_id: str) -> None:
        _p({"deleted": self.call("delete_tablet", tablet_id=tablet_id)})

    def close(self) -> None:
        self.m.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="yb-ts-cli")
    ap.add_argument("--server", required=True, help="tserver host:port")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    sub.add_parser("list_tablets")
    sub.add_parser("are_tablets_running")
    sub.add_parser("flush_all_tablets")
    sub.add_parser("compact_all_tablets")
    for name in ("flush_tablet", "compact_tablet", "dump_tablet",
                 "delete_tablet"):
        p = sub.add_parser(name)
        p.add_argument("tablet_id")
    args = ap.parse_args(argv)
    cli = TsCli(args.server)
    try:
        fn = getattr(cli, args.cmd)
        rc = fn(args.tablet_id) if hasattr(args, "tablet_id") else fn()
        return int(rc or 0)
    except StatusError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    finally:
        cli.close()


if __name__ == "__main__":
    sys.exit(main())
