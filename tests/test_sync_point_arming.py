"""sync_point arming semantics: in-process callbacks and the
cross-process "<point>@<hits>" multi-hit crash arming (the kill -9
simulator behind the external-cluster crash tests). The crash mode calls
os._exit(137), so it is exercised in a subprocess.
"""

import os
import subprocess
import sys

from yugabyte_tpu.utils import sync_point

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_in_process_arm_and_disarm():
    hits = []
    sync_point.arm("test.point", lambda: hits.append(1))
    try:
        sync_point.hit("test.point")
        sync_point.hit("other.point")
        sync_point.hit("test.point")
        assert len(hits) == 2
    finally:
        sync_point.disarm("test.point")
    sync_point.hit("test.point")
    assert len(hits) == 2


def _run_child(crash_spec: str, n_hits: int) -> subprocess.CompletedProcess:
    code = (
        "from yugabyte_tpu.utils import sync_point\n"
        f"for _ in range({n_hits}):\n"
        "    sync_point.hit('crash.me')\n"
        "print('SURVIVED')\n"
    )
    env = dict(os.environ, YBTPU_CRASH_POINT=crash_spec,
               PYTHONPATH=REPO_ROOT)
    return subprocess.run([sys.executable, "-u", "-c", code], env=env,
                          capture_output=True, text=True, timeout=60)


def test_crash_point_single_hit_kills_like_kill9():
    r = _run_child("crash.me", n_hits=1)
    assert r.returncode == 137
    assert "SURVIVED" not in r.stdout


def test_crash_point_multi_hit_arms_at_nth_hit():
    """"<point>@<hits>" dies exactly on the hits-th reach: below the
    threshold the process survives, at it the process exits 137."""
    r = _run_child("crash.me@3", n_hits=2)
    assert r.returncode == 0 and "SURVIVED" in r.stdout
    r = _run_child("crash.me@3", n_hits=3)
    assert r.returncode == 137
    assert "SURVIVED" not in r.stdout


def test_crash_point_rearm_resets_count():
    """arm_crash() resets the hit counter (node_runner re-arms AFTER
    startup so bootstrap-time hits don't count)."""
    code = (
        "from yugabyte_tpu.utils import sync_point\n"
        "sync_point.hit('crash.me')\n"
        "sync_point.arm_crash('crash.me@2')\n"  # reset mid-run
        "sync_point.hit('crash.me')\n"
        "print('ONE-AFTER-REARM')\n"
        "sync_point.hit('crash.me')\n"
        "print('NEVER')\n"
    )
    env = dict(os.environ, YBTPU_CRASH_POINT="crash.me@2",
               PYTHONPATH=REPO_ROOT)
    r = subprocess.run([sys.executable, "-u", "-c", code], env=env,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 137
    assert "ONE-AFTER-REARM" in r.stdout
    assert "NEVER" not in r.stdout
