"""Consensus traffic over the host RPC layer.

The reference sends AppendEntries/RequestVote through generated proxies to a
`ConsensusService` that routes by tablet id (ref: src/yb/consensus/
consensus_peers.cc `Peer::SendNextRequest`; tserver registers the service in
tserver/tablet_server.cc). Here:

- `ConsensusService` is the server half: one instance per Messenger, holding
  the local RaftConsensus instances keyed by peer address
  "<server_id>/<tablet_id>" (the same keying LocalTransport uses, so
  TabletPeer code is transport-agnostic).
- `RpcTransport` is the client half implementing the consensus transport
  seam (register/update_consensus/request_vote). It resolves the *server*
  half of a peer address to host:port via a resolver callable — the cluster
  config (master heartbeats) keeps that mapping fresh.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from yugabyte_tpu.consensus.raft import (
    AppendEntriesReq, AppendEntriesResp, ReplicateMsg, VoteReq, VoteResp)
from yugabyte_tpu.consensus.transport import PeerUnreachable
from yugabyte_tpu.rpc.messenger import (
    Messenger, RemoteError, RpcTimeout, ServiceUnavailable)

SERVICE_NAME = "consensus"


def _msg_to_wire(m: ReplicateMsg) -> list:
    return [m.term, m.index, m.op_type, m.ht_value, m.payload]


def _msg_from_wire(w: list) -> ReplicateMsg:
    return ReplicateMsg(w[0], w[1], w[2], w[3], w[4])


def append_req_to_wire(req: AppendEntriesReq) -> dict:
    w = {
        "term": req.term, "leader_id": req.leader_id,
        "preceding_term": req.preceding_term,
        "preceding_index": req.preceding_index,
        "entries": [_msg_to_wire(m) for m in req.entries],
        "committed_index": req.committed_index,
        "propagated_safe_time": req.propagated_safe_time,
        "lease_duration_s": req.lease_duration_s,
    }
    if req.trace_ctx is not None:
        w["trace_ctx"] = req.trace_ctx
    return w


def append_req_from_wire(w: dict) -> AppendEntriesReq:
    return AppendEntriesReq(
        term=w["term"], leader_id=w["leader_id"],
        preceding_term=w["preceding_term"],
        preceding_index=w["preceding_index"],
        entries=tuple(_msg_from_wire(m) for m in w["entries"]),
        committed_index=w["committed_index"],
        propagated_safe_time=w["propagated_safe_time"],
        lease_duration_s=w["lease_duration_s"],
        trace_ctx=w.get("trace_ctx"))  # absent from old peers: untraced


class ConsensusService:
    """Server-side dispatch to local RaftConsensus instances."""

    def __init__(self):
        self._peers: Dict[str, object] = {}

    def register(self, peer_id: str, consensus: object) -> None:
        self._peers[peer_id] = consensus

    def unregister(self, peer_id: str) -> None:
        self._peers.pop(peer_id, None)

    def _peer(self, peer_id: str):
        peer = self._peers.get(peer_id)
        if peer is None:
            from yugabyte_tpu.utils.status import Status, StatusError
            raise StatusError(Status.NotFound(
                f"no consensus instance for {peer_id!r} here"))
        return peer

    # -------------------------------------------------------- wire handlers
    def update_consensus(self, dst: str, req: dict) -> dict:
        resp = self._peer(dst).handle_update(append_req_from_wire(req))
        return {"responder_id": resp.responder_id, "term": resp.term,
                "success": resp.success,
                "last_received_index": resp.last_received_index}

    def request_vote(self, dst: str, req: dict) -> dict:
        resp = self._peer(dst).handle_vote_request(VoteReq(
            term=req["term"], candidate_id=req["candidate_id"],
            last_log_term=req["last_log_term"],
            last_log_index=req["last_log_index"],
            ignore_lease=req["ignore_lease"]))
        return {"responder_id": resp.responder_id, "term": resp.term,
                "granted": resp.granted}

    def multi_update_consensus(self, items: list) -> dict:
        """Batched cross-tablet heartbeats (ref multi_raft_batcher.cc):
        [(dst_peer, wire_req), ...] -> positional responses; per-item
        failures come back as {'err': ...} so one dead tablet cannot fail
        its whole batch."""
        out = []
        for dst, req in items:
            try:
                out.append(self.update_consensus(dst, req))
            except Exception as e:  # noqa: BLE001 — isolate per item
                out.append({"err": repr(e)})
        return {"resps": out}


class RpcTransport:  # yblint: disable=ybsan-coverage (stateless dispatch seam: every attr is set once in __init__ and read-only after; the .submit goes to MultiRaftBatcher, whose shared state carries its own guarded-by annotations)
    """Client-side consensus transport seam over the Messenger.

    resolver(peer_address) -> 'host:port' of the server hosting that peer,
    or None if unknown (treated as unreachable, like a failed DNS lookup in
    the reference's periodic proxy refresh)."""

    def __init__(self, messenger: Messenger,
                 resolver: Callable[[str], Optional[str]]):
        from yugabyte_tpu.consensus.multi_raft_batcher import (
            MultiRaftBatcher)
        self._messenger = messenger
        self._resolver = resolver
        self._service = ConsensusService()
        messenger.register_service(SERVICE_NAME, self._service)
        # cross-tablet heartbeat coalescing (one per server process)
        self.batcher = MultiRaftBatcher(self._send_batch)

    def _send_batch(self, addr: str, items):
        try:
            w = self._messenger.call(addr, SERVICE_NAME,
                                     "multi_update_consensus",
                                     items=[[d, r] for d, r in items])
        except (RpcTimeout, ServiceUnavailable, RemoteError) as e:
            raise PeerUnreachable(f"batch@{addr}: {e}") from e
        return w["resps"]

    def register(self, peer_id: str, consensus: object) -> None:
        self._service.register(peer_id, consensus)

    def unregister(self, peer_id: str) -> None:
        self._service.unregister(peer_id)

    def _call(self, dst: str, mth: str, req: dict) -> dict:
        addr = self._resolver(dst)
        if addr is None:
            raise PeerUnreachable(f"{dst}: no address known")
        try:
            return self._messenger.call(addr, SERVICE_NAME, mth,
                                        dst=dst, req=req)
        except (RpcTimeout, ServiceUnavailable, RemoteError) as e:
            raise PeerUnreachable(f"{dst}@{addr}: {e}") from e

    # ------------------------------------------------------------- dispatch
    def update_consensus(self, src: str, dst: str,
                         request: AppendEntriesReq) -> AppendEntriesResp:
        from yugabyte_tpu.utils import flags as _flags
        if (not request.entries
                and _flags.get_flag("multi_raft_batch_window_ms") > 0):
            # empty AppendEntries = heartbeat: coalesce across tablets
            # sharing this destination server (multi_raft_batcher.py);
            # data-bearing requests never wait in the batch window
            addr = self._resolver(dst)
            if addr is None:
                raise PeerUnreachable(f"{dst}: no address known")
            w = self.batcher.submit(addr, dst,
                                    append_req_to_wire(request))
        else:
            w = self._call(dst, "update_consensus",
                           append_req_to_wire(request))
        return AppendEntriesResp(
            responder_id=w["responder_id"], term=w["term"],
            success=w["success"],
            last_received_index=w["last_received_index"])

    def request_vote(self, src: str, dst: str, request: VoteReq) -> VoteResp:
        w = self._call(dst, "request_vote", {
            "term": request.term, "candidate_id": request.candidate_id,
            "last_log_term": request.last_log_term,
            "last_log_index": request.last_log_index,
            "ignore_lease": request.ignore_lease})
        return VoteResp(responder_id=w["responder_id"], term=w["term"],
                        granted=w["granted"])
