"""Distributed transactions over MiniCluster: cross-tablet atomicity,
snapshot isolation, conflicts, aborts, expiry (ref: client/
ql-transaction-test.cc over mini_cluster)."""

import time

import pytest

from yugabyte_tpu.client.transaction import (
    TransactionError, TransactionManager)
from yugabyte_tpu.common.schema import ColumnSchema, DataType, Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, WriteOpKind
from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags

SCHEMA = Schema(
    columns=[ColumnSchema("k", DataType.STRING),
             ColumnSchema("v", DataType.STRING),
             ColumnSchema("n", DataType.INT64)],
    num_hash_key_columns=1)


def dk(k: str) -> DocKey:
    return DocKey(hash_components=(k,))


def ins(k: str, v: str, n: int = 0) -> QLWriteOp:
    return QLWriteOp(WriteOpKind.INSERT, dk(k), {"v": v, "n": n})


def wait_for(cond, timeout=30, msg="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timeout: {msg}"
        time.sleep(0.05)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 3)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path_factory.mktemp("txncluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def env(cluster):
    client = cluster.new_client()
    client.create_namespace("bank")
    table = client.create_table("bank", "accounts", SCHEMA, num_tablets=4)
    cluster.wait_all_replicas_running(table.table_id)
    cluster.wait_for_table_leaders("bank", "accounts")  # no election race
    manager = TransactionManager(client)
    manager.status_table()  # force creation up front
    return cluster, client, table, manager


def test_cross_tablet_atomic_commit(env):
    cluster, client, table, manager = env
    # Writes spanning multiple tablets commit atomically.
    txn = manager.begin()
    for i in range(8):
        txn.write(table, [ins(f"acct{i}", "opened", 100)])
    # Invisible to outside readers pre-commit.
    assert client.read_row(table, dk("acct0")) is None
    txn.commit()
    for i in range(8):
        row = client.read_row(table, dk(f"acct{i}"))
        assert row is not None
        assert row.columns[SCHEMA.column_id("v")] == "opened"


def test_read_your_writes_and_snapshot(env):
    cluster, client, table, manager = env
    client.write(table, [ins("snap", "before", 1)])
    txn = manager.begin()
    txn.write(table, [ins("rytw", "mine", 7)])
    row = txn.read_row(table, dk("rytw"))
    assert row is not None and row.columns[SCHEMA.column_id("v")] == "mine"
    # Writes committed AFTER the txn began are outside its snapshot.
    client.write(table, [ins("snap", "after", 2)])
    row = txn.read_row(table, dk("snap"))
    assert row.columns[SCHEMA.column_id("v")] == "before"
    txn.commit()


def test_abort_discards_everything(env):
    cluster, client, table, manager = env
    txn = manager.begin()
    txn.write(table, [ins("ghost1", "x")])
    txn.write(table, [ins("ghost2", "y")])
    txn.abort()
    assert client.read_row(table, dk("ghost1")) is None
    assert client.read_row(table, dk("ghost2")) is None
    # Non-transactional writes to those keys work (intents cleaned/ignored).
    client.write(table, [ins("ghost1", "real")])
    assert client.read_row(
        table, dk("ghost1")).columns[SCHEMA.column_id("v")] == "real"


def test_write_write_conflict(env):
    cluster, client, table, manager = env
    t1 = manager.begin()
    t2 = manager.begin()
    t1.write(table, [ins("contested", "t1")])
    with pytest.raises(TransactionError):
        t2.write(table, [ins("contested", "t2")])
    t1.commit()
    t2.abort()
    row = client.read_row(table, dk("contested"))
    assert row.columns[SCHEMA.column_id("v")] == "t1"


def test_snapshot_write_conflict_after_commit(env):
    cluster, client, table, manager = env
    t1 = manager.begin()
    time.sleep(0.01)
    client.write(table, [ins("si", "newer")])  # commits after t1's snapshot
    with pytest.raises(TransactionError):
        t1.write(table, [ins("si", "stale")])
    t1.abort()


def test_commit_then_intents_applied(env):
    cluster, client, table, manager = env
    txn = manager.begin()
    txn.write(table, [ins("applied", "val", 3)])
    participant = list(txn._participants)[0]
    txn.commit()

    def intents_resolved():
        from yugabyte_tpu.docdb.intents import txn_intents
        for ts in cluster.tservers:
            try:
                peer = ts.tablet_manager.get_tablet(participant)
            except Exception:  # noqa: BLE001
                continue
            if txn_intents(peer.tablet.intents_db, txn.txn_id):
                return False
        return True

    wait_for(intents_resolved, msg="intent apply fanout")
    row = client.read_row(table, dk("applied"))
    assert row is not None and row.columns[SCHEMA.column_id("v")] == "val"


def test_expired_txn_aborts(env):
    cluster, client, table, manager = env
    flags.set_flag("transaction_timeout_ms", 300)
    try:
        txn = manager.begin()
        txn._hb_stop.set()  # silence heartbeats: txn will expire
        txn.write(table, [ins("expired", "never")])
        time.sleep(0.6)
        # Another writer hitting the stale intent forces status resolution,
        # which lazily aborts the expired txn and lets the write through.
        deadline = time.monotonic() + 20
        while True:
            try:
                client.write(table, [ins("expired", "winner")])
                break
            except Exception:  # noqa: BLE001 — conflict until expiry seen
                assert time.monotonic() < deadline
                time.sleep(0.2)
        row = client.read_row(table, dk("expired"))
        assert row.columns[SCHEMA.column_id("v")] == "winner"
        with pytest.raises(TransactionError):
            txn.commit()
    finally:
        flags.reset_flag("transaction_timeout_ms")


def test_participant_recorded_before_write(env):
    """ADVICE r1 #4: a write whose outcome is unknown (timeout) may have
    left intents on the tablet — commit/abort must still notify it, so the
    participant is recorded BEFORE the RPC goes out."""
    from yugabyte_tpu.utils.status import Status, StatusError
    cluster, client, table, manager = env
    txn = manager.begin()
    orig = client._tablet_call
    def failing(table_, tablet, mth, **kw):
        if mth == "write":
            raise StatusError(Status.TimedOut("injected outcome-unknown"))
        return orig(table_, tablet, mth, **kw)
    client._tablet_call = failing
    try:
        with pytest.raises(StatusError):
            txn.write(table, [ins("orphan-key", "x")])
    finally:
        client._tablet_call = orig
    assert len(txn._participants) == 1, (
        "tablet that may hold orphaned intents was not recorded")
    txn.abort()


def test_concurrent_bank_transfers_conserve_total(env):
    """The classic transactional invariant stress (ref: the reference's
    snapshot-isolation bank workloads over mini_cluster): N threads move
    random amounts between M accounts under snapshot isolation with
    conflict retries, racing flushes — the total balance is conserved at
    every read point and no account observes a torn transfer."""
    import random
    import threading

    cluster, client, table, manager = env
    n_accounts = 8
    initial = 100
    for a in range(n_accounts):
        client.write(table, [QLWriteOp(
            WriteOpKind.INSERT, dk(f"acct{a}"), {"n": initial})])

    stop = threading.Event()
    stats = {"committed": 0, "conflicts": 0}
    lock = threading.Lock()
    errors = []

    def transfer_loop(seed: int):
        rng = random.Random(seed)
        while not stop.is_set():
            src, dst = rng.sample(range(n_accounts), 2)
            amount = rng.randrange(1, 20)
            txn = manager.begin()
            try:
                rs = txn.read_row(table, dk(f"acct{src}"))
                rd = txn.read_row(table, dk(f"acct{dst}"))
                sbal = rs.columns[table.schema.column_id("n")]
                dbal = rd.columns[table.schema.column_id("n")]
                if sbal < amount:
                    txn.abort()
                    continue
                txn.write(table, [
                    QLWriteOp(WriteOpKind.UPDATE, dk(f"acct{src}"),
                              {"n": sbal - amount}),
                    QLWriteOp(WriteOpKind.UPDATE, dk(f"acct{dst}"),
                              {"n": dbal + amount})])
                txn.commit()
                with lock:
                    stats["committed"] += 1
            except TransactionError:
                with lock:
                    stats["conflicts"] += 1
                try:
                    txn.abort()
                except Exception:  # noqa: BLE001
                    pass
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
                try:
                    txn.abort()  # never leak intents that block peers
                except Exception:  # noqa: BLE001
                    pass
                return

    def audit_loop():
        cid = table.schema.column_id("n")
        while not stop.is_set():
            txn = manager.begin()
            try:
                total = 0
                for a in range(n_accounts):
                    row = txn.read_row(table, dk(f"acct{a}"))
                    bal = row.columns[cid]
                    if bal < 0:
                        errors.append(f"negative balance acct{a}: {bal}")
                    total += bal
                txn.abort()  # read-only
                if total != n_accounts * initial:
                    errors.append(f"total drifted: {total}")
                    return
            except TransactionError:
                try:
                    txn.abort()
                except Exception:  # noqa: BLE001
                    pass
            except Exception as e:  # noqa: BLE001 — the auditor dying
                # silently would leave the invariant unchecked mid-run
                errors.append(f"auditor died: {e!r}")
                try:
                    txn.abort()
                except Exception:  # noqa: BLE001
                    pass
                return

    def churn_loop():
        while not stop.is_set():
            for ts in cluster.tservers:
                for tid in list(ts.tablet_manager.tablet_ids()):
                    try:
                        ts.tablet_manager.get_tablet(tid).tablet.flush()
                    except Exception:  # noqa: BLE001 — tablet moving
                        pass
            time.sleep(0.5)

    threads = [threading.Thread(target=transfer_loop, args=(i,),
                                daemon=True) for i in range(4)]
    threads.append(threading.Thread(target=audit_loop, daemon=True))
    threads.append(threading.Thread(target=churn_loop, daemon=True))
    for t in threads:
        t.start()
    time.sleep(8)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors[:5]
    assert stats["committed"] >= 10, stats

    # final audit outside any races
    cid = table.schema.column_id("n")
    total = 0
    for a in range(n_accounts):
        row = client.read_row(table, dk(f"acct{a}"))
        total += row.to_dict(table.schema)["n"] \
            if hasattr(row, "to_dict") else row.columns[cid]
    assert total == n_accounts * initial, (total, stats)
