#!/usr/bin/env python
"""bench_compare: key-by-key diff of two bench round JSONs, with a
regression gate.

    python tools/bench_compare.py BENCH_SELF_r09.json BENCH_SELF_r10.json
    python tools/bench_compare.py old.json new.json --check
    python tools/bench_compare.py cpu.json tpu.json --force

Rounds are the flat JSON documents bench.py emits (BENCH_*.json /
MULTICHIP_*.json). The tool flattens nested blocks into dotted keys,
keeps numeric leaves, and prints a labeled table of every key present
in both rounds: old, new, delta, percent change, and a direction-aware
verdict. Keys present in only one round are listed separately (a
renamed metric silently dropping out of comparison is itself a bug).

Backend labels are honored: each round's identity comes from
`meta.backend` (the PR-17 round stamp) falling back to the legacy
top-level `platform` key. Two rounds with different backends are
DIFFERENT EXPERIMENTS — a CPU round "regressing" against a TPU round
is noise — so the tool refuses the comparison (exit 2) unless --force.

Direction is inferred from the key's unit suffix:

  higher-better : *_per_sec, *_ratio, *_hits, vs_* / *_vs_* (speedup
                  ratios), *_scaling_*
  lower-better  : *_ms, *_s, *_mismatches, *_failures, *_fallbacks,
                  *_retries, *_errors
  neutral       : everything else — reported, never gated

With --check, every gated key's regression beyond its tolerance
(tools/bench_tolerances.json: `default_pct` plus per-key overrides;
keys matching an `ignore` prefix are never gated) fails the run with
exit 1 — the check.sh wiring that turns a bench regression into a red
build instead of a quietly worse committed round.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# Sub-documents that are identity/provenance, not measurements.
_SKIP_SUBTREES = ("meta", "timeseries", "knobs")

_HIGHER_SUFFIXES = ("_per_sec", "_ratio", "_hits", "_ok")
_LOWER_SUFFIXES = ("_ms", "_s", "_mismatches", "_failures", "_fallbacks",
                   "_retries", "_errors", "_leaked_pins", "_leaked_leases")


def flatten(doc: dict, prefix: str = "") -> Dict[str, float]:
    """Dotted-key numeric leaves of a round document; identity
    subtrees and non-numeric leaves are skipped."""
    out: Dict[str, float] = {}
    for k, v in doc.items():
        if not prefix and k in _SKIP_SUBTREES:
            continue
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(flatten(v, prefix=f"{key}."))
        elif isinstance(v, bool):
            continue
        elif isinstance(v, (int, float)):
            out[key] = float(v)
    return out


def backend_of(doc: dict) -> str:
    meta = doc.get("meta")
    if isinstance(meta, dict) and meta.get("backend"):
        return str(meta["backend"])
    return str(doc.get("platform") or "unknown")


def direction(key: str) -> int:
    """+1 higher-better, -1 lower-better, 0 neutral (never gated)."""
    leaf = key.rsplit(".", 1)[-1]
    if leaf.startswith("vs_") or "_vs_" in leaf or "_scaling_" in leaf:
        return +1
    if leaf.endswith(_HIGHER_SUFFIXES):
        return +1
    if leaf.endswith(_LOWER_SUFFIXES):
        return -1
    return 0


def regression_pct(old: float, new: float, sign: int) -> float:
    """How much WORSE new is than old, in percent of old (0 when equal
    or improved). sign is direction()'s verdict."""
    if sign == 0 or old == 0:
        return 0.0
    worse = (old - new) if sign > 0 else (new - old)
    return max(0.0, 100.0 * worse / abs(old))


def load_tolerances(path: str) -> dict:
    try:
        with open(path) as f:
            tol = json.load(f)
    except OSError:
        return {"default_pct": 25.0, "keys": {}, "ignore": []}
    tol.setdefault("default_pct", 25.0)
    tol.setdefault("keys", {})
    tol.setdefault("ignore", [])
    return tol


def tolerance_for(key: str, tol: dict) -> Optional[float]:
    """The key's regression tolerance in percent, or None when the key
    is ignored (never gated)."""
    for pre in tol["ignore"]:
        if key.startswith(pre):
            return None
    if key in tol["keys"]:
        return float(tol["keys"][key])
    leaf = key.rsplit(".", 1)[-1]
    if leaf in tol["keys"]:
        return float(tol["keys"][leaf])
    return float(tol["default_pct"])


def compare(old: Dict[str, float], new: Dict[str, float], tol: dict
            ) -> Tuple[List[dict], List[str], List[str]]:
    rows = []
    for key in sorted(set(old) & set(new)):
        o, n = old[key], new[key]
        sign = direction(key)
        reg = regression_pct(o, n, sign)
        limit = tolerance_for(key, tol) if sign != 0 else None
        rows.append({
            "key": key, "old": o, "new": n, "delta": n - o,
            "pct": (100.0 * (n - o) / abs(o)) if o else 0.0,
            "dir": {1: "higher", -1: "lower", 0: "-"}[sign],
            "regression_pct": reg,
            "tolerance_pct": limit,
            "fails": limit is not None and reg > limit,
        })
    only_old = sorted(set(old) - set(new))
    only_new = sorted(set(new) - set(old))
    return rows, only_old, only_new


def print_table(rows: List[dict], label_a: str, label_b: str) -> None:
    w = max([len(r["key"]) for r in rows] + [12])
    print(f"{'key':<{w}}  {'old':>14}  {'new':>14}  {'change':>9}  "
          f"{'better':>7}  verdict")
    for r in rows:
        if r["fails"]:
            verdict = (f"REGRESSED ({r['regression_pct']:.1f}% > "
                       f"{r['tolerance_pct']:.0f}% tol)")
        elif r["dir"] == "-" or r["tolerance_pct"] is None:
            verdict = "info"
        elif r["regression_pct"] > 0:
            verdict = f"worse ({r['regression_pct']:.1f}% within tol)"
        else:
            verdict = "ok"
        print(f"{r['key']:<{w}}  {r['old']:>14.4g}  {r['new']:>14.4g}  "
              f"{r['pct']:>+8.1f}%  {r['dir']:>7}  {verdict}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_compare",
        description="diff two bench round JSONs key-by-key; --check "
                    "gates regressions against the committed tolerances")
    ap.add_argument("old")
    ap.add_argument("new")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when any gated key regresses beyond "
                         "its tolerance")
    ap.add_argument("--force", action="store_true",
                    help="compare across different backend labels "
                         "(CPU-vs-TPU rounds are different experiments; "
                         "refused by default)")
    ap.add_argument("--tolerances", default=None,
                    help="tolerance JSON (default: tools/"
                         "bench_tolerances.json next to this script)")
    args = ap.parse_args(argv)

    import os
    tol_path = args.tolerances or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_tolerances.json")

    with open(args.old) as f:
        doc_a = json.load(f)
    with open(args.new) as f:
        doc_b = json.load(f)

    ba, bb = backend_of(doc_a), backend_of(doc_b)
    print(f"old: {args.old}  [backend={ba}]")
    print(f"new: {args.new}  [backend={bb}]")
    if ba != bb:
        if not args.force:
            print(f"bench_compare: REFUSING {ba}-vs-{bb} comparison — "
                  f"different backends measure different experiments; "
                  f"pass --force to override", file=sys.stderr)
            return 2
        print(f"bench_compare: WARNING — comparing across backends "
              f"({ba} vs {bb}) under --force; regressions below are "
              f"backend deltas, not code regressions")

    rows, only_old, only_new = compare(
        flatten(doc_a), flatten(doc_b), load_tolerances(tol_path))
    if rows:
        print_table(rows, args.old, args.new)
    else:
        print("no common numeric keys")
    if only_old:
        print(f"\nonly in {args.old}: {', '.join(only_old)}")
    if only_new:
        print(f"\nonly in {args.new}: {', '.join(only_new)}")

    failures = [r for r in rows if r["fails"]]
    if failures:
        print(f"\n{len(failures)} regression(s) beyond tolerance:")
        for r in failures:
            print(f"  {r['key']}: {r['old']:.4g} -> {r['new']:.4g} "
                  f"({r['regression_pct']:.1f}% worse, tolerance "
                  f"{r['tolerance_pct']:.0f}%)")
    if args.check:
        if failures:
            return 1
        print("\nbench_compare: OK (no regression beyond tolerance)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
