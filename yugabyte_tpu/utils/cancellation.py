"""Cancellation tokens for long-running background work.

The compaction offload pipeline (storage/compaction.py) spans three
overlapped stages across threads and a device queue; DB shutdown and a
tablet-FAILED transition must be able to abort the in-flight job at the
next stage boundary — without corrupting the writer and while releasing
every HostStagingPool lease — instead of racing it to the filesystem.

`CancellationToken.check()` raises `OperationCancelled`, a StatusError
with Code.ABORTED, which callers treat as a CLEAN abort: no background
error is recorded, partial outputs are swept, and the job simply ends
(ref: rocksdb's ShutdownInProgress status threading through
CompactionJob).
"""

from __future__ import annotations

import threading
from typing import Optional

from yugabyte_tpu.utils.status import Code, Status, StatusError

__all__ = ["CancellationToken", "OperationCancelled"]


class OperationCancelled(StatusError):
    """The operation was aborted by shutdown / tablet failure — a clean
    abort, not an error to contain or report."""

    def __init__(self, msg: str):
        super().__init__(Status(Code.ABORTED, msg))


class CancellationToken:
    """One-way latch shared by a job's stages; thread-safe.

    cancel() is idempotent and carries a reason for the abort message.
    """

    def __init__(self, what: str = "operation"):
        self._what = what
        self._event = threading.Event()
        self._reason: Optional[str] = None  # written once before set()

    def cancel(self, reason: str = "shutdown") -> None:
        # reason is published BEFORE the event: a checker that observes
        # the set event always reads a complete reason
        if not self._event.is_set():
            self._reason = reason
            self._event.set()

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def check(self) -> None:
        """Raise OperationCancelled if cancel() was called."""
        if self._event.is_set():
            raise OperationCancelled(
                f"{self._what} cancelled: {self._reason}")
