"""KV slabs: the TPU-native columnar representation of sorted-run entries.

This is the central TPU-first design decision of the storage engine
(SURVEY.md section 7 stage 4): instead of the reference's delta-encoded,
byte-granular SST entries (ref: src/yb/rocksdb/table/block_builder.cc), a
batch of KV entries is a structure-of-arrays "slab":

  key_words : uint32[N, W]  big-endian words of the key prefix (no HT suffix),
                            zero-padded to W*4 bytes. Because DocDB key
                            encoding is order-preserving bytewise
                            (docdb/doc_key.py), lexicographic order over
                            (key_words, key_len) == memcmp order over keys.
  key_len   : int32[N]      true byte length of the key prefix
  doc_key_len: int32[N]     byte length of the embedded DocKey (root prefix)
  ht_hi/ht_lo: uint32[N]    DocHybridTime.ht split into high/low words
  write_id  : uint32[N]
  flags     : uint32[N]     bit0 tombstone, bit1 object-init, bit2 has-TTL
  ttl_ms    : int64[N]      TTL in ms (0 = none)
  value_idx : int32[N]      index into the out-of-band value array

Values stay out-of-band (host memory / HBM byte buffer) because merge + GC
only permute and drop entries — value bytes move once, at output-write time.

Sorting a slab by (key_words..., key_len, ht_hi_desc, ht_lo_desc,
write_id_desc) reproduces exactly the reference's internal key order:
user key ascending, hybrid time descending (ref:
src/yb/rocksdb/db/dbformat.h internal key ordering + descending HT suffix,
common/doc_hybrid_time.cc:50).
"""

from __future__ import annotations

import struct

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime
from yugabyte_tpu.docdb.value import decode_control_fields
from yugabyte_tpu.docdb.value_type import ValueType

FLAG_TOMBSTONE = 1
FLAG_OBJECT_INIT = 2
FLAG_HAS_TTL = 4
# Key addresses a document deeper than row+column (2+ subkey levels below
# the DocKey). The fused device kernel implements only depth-2 overwrite
# truncation; slabs containing deep entries are routed to the full
# overwrite-STACK semantic path (native C++ / host model, ref:
# docdb/docdb_compaction_filter.cc:104-123) by the compaction job and scan.
FLAG_DEEP = 8


class ValueArray:
    """Columnar value payloads: ONE contiguous byte buffer + row offsets.

    The slab counterpart of hot loop ③'s output path (ref:
    rocksdb/db/compaction_job.cc:958-1024 block building): gather/concat
    are pure numpy offset arithmetic, so permuting a million values for an
    SST write costs two vectorized indexing passes instead of a
    per-row Python loop. Duck-types as a sequence of bytes rows
    (va[i] -> bytes), which keeps point-read paths unchanged.
    """

    __slots__ = ("data", "offsets")

    def __init__(self, data: np.ndarray, offsets: np.ndarray):
        self.data = data        # uint8 [total_bytes]
        self.offsets = offsets  # int64 [n_rows + 1]

    # ------------------------------------------------------------- construct
    @staticmethod
    def from_list(values) -> "ValueArray":
        if isinstance(values, ValueArray):
            return values
        n = len(values)
        lens = np.fromiter((len(v) for v in values), dtype=np.int64, count=n)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        data = (np.frombuffer(b"".join(values), dtype=np.uint8)
                if n else np.zeros(0, dtype=np.uint8))
        return ValueArray(data, offsets)

    @staticmethod
    def from_blob(blob, offsets) -> "ValueArray":
        """Zero-copy adoption of an already-contiguous layout (block
        decode path: the on-disk format IS blob + offsets)."""
        return ValueArray(np.frombuffer(blob, dtype=np.uint8),
                          np.asarray(offsets, dtype=np.int64))

    @staticmethod
    def empty_rows(n: int) -> "ValueArray":
        return ValueArray(np.zeros(0, dtype=np.uint8),
                          np.zeros(n + 1, dtype=np.int64))

    # ------------------------------------------------------------ sequence
    def __len__(self) -> int:
        return len(self.offsets) - 1

    def __getitem__(self, i) -> bytes:
        i = int(i)
        if i < 0:
            i += len(self)
        return self.data[self.offsets[i]: self.offsets[i + 1]].tobytes()

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __eq__(self, other):
        if isinstance(other, (list, tuple)):
            other = ValueArray.from_list(list(other))
        if not isinstance(other, ValueArray):
            return NotImplemented
        return (np.array_equal(self.offsets, other.offsets)
                and np.array_equal(self.data[: self.nbytes],
                                   other.data[: other.nbytes]))

    @property
    def nbytes(self) -> int:
        return int(self.offsets[-1])

    def lengths(self) -> np.ndarray:
        return self.offsets[1:] - self.offsets[:-1]

    def blob(self) -> bytes:
        return self.data[: self.nbytes].tobytes()

    # ---------------------------------------------------------- vectorized
    def gather(self, idx: np.ndarray, replace_mask: Optional[np.ndarray] = None,
               replacement: bytes = b"") -> "ValueArray":
        """Rows at `idx`, with rows under `replace_mask` substituted by
        `replacement` (the compaction TTL-expiry -> tombstone rewrite)."""
        idx = np.asarray(idx, dtype=np.int64)
        starts = self.offsets[idx]
        lens = self.offsets[idx + 1] - starts
        if replace_mask is not None and replacement is not None \
                and replace_mask.any():
            rep = np.frombuffer(replacement, dtype=np.uint8)
            data_all = np.concatenate([self.data, rep])
            starts = np.where(replace_mask, len(self.data), starts)
            lens = np.where(replace_mask, len(rep), lens)
        else:
            data_all = self.data
        n = len(idx)
        out_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(lens, out=out_off[1:])
        if n and int(lens.min()) == int(lens.max()):
            # uniform stride (common: fixed-width rows, bench slabs): a 2-D
            # fancy index replaces the arange/repeat scatter — 2 passes
            stride = int(lens[0])
            if stride == 0:
                return ValueArray(np.zeros(0, dtype=np.uint8), out_off)
            pos2d = starts[:, None] + np.arange(stride, dtype=np.int64)[None, :]
            return ValueArray(data_all[pos2d].reshape(-1), out_off)
        total = int(out_off[-1])
        pos = (np.arange(total, dtype=np.int64)
               - np.repeat(out_off[:-1], lens)
               + np.repeat(starts, lens))
        return ValueArray(data_all[pos], out_off)

    def slice_rows(self, start: int, end: int) -> "ValueArray":
        """Zero-copy contiguous row range."""
        o = self.offsets[start: end + 1]
        base = o[0] if len(o) else 0
        return ValueArray(self.data[base: o[-1] if len(o) else 0], o - base)

    @staticmethod
    def concat(arrays: Sequence["ValueArray"]) -> "ValueArray":
        arrays = [ValueArray.from_list(a) for a in arrays]
        datas = [a.data[: a.nbytes] for a in arrays]
        n_total = sum(len(a) for a in arrays)
        offsets = np.zeros(n_total + 1, dtype=np.int64)
        pos = 0
        base = 0
        for a in arrays:
            n = len(a)
            offsets[pos + 1: pos + n + 1] = (a.offsets[1:] - a.offsets[0]) + base
            base += a.nbytes
            pos += n
        return ValueArray(
            np.concatenate(datas) if datas else np.zeros(0, dtype=np.uint8),
            offsets)


@dataclass
class KVSlab:
    key_words: np.ndarray   # uint32 [N, W]
    key_len: np.ndarray     # int32  [N]
    doc_key_len: np.ndarray  # int32 [N]
    ht_hi: np.ndarray       # uint32 [N]
    ht_lo: np.ndarray       # uint32 [N]
    write_id: np.ndarray    # uint32 [N]
    flags: np.ndarray       # uint32 [N]
    ttl_ms: np.ndarray      # int64  [N]
    value_idx: np.ndarray   # int32  [N]
    # out-of-band value payloads (indexed by value_idx): a ValueArray
    # (contiguous blob + offsets); plain lists of bytes are accepted at
    # construction seams and normalized by the vectorized paths
    values: "ValueArray"

    @property
    def n(self) -> int:
        return int(self.key_len.shape[0])

    @property
    def width_words(self) -> int:
        return int(self.key_words.shape[1])

    def key_bytes(self, i: int) -> bytes:
        return self.key_words[i].astype(">u4").tobytes()[: int(self.key_len[i])]

    def doc_ht(self, i: int) -> DocHybridTime:
        ht = (int(self.ht_hi[i]) << 32) | int(self.ht_lo[i])
        return DocHybridTime(HybridTime(ht), int(self.write_id[i]))


def _pad_keys_to_words(keys: Sequence[bytes], width_words: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized pack of variable-length key bytes into a zero-padded u32 word
    matrix. Avoids per-key Python in the inner loop (single-core host)."""
    n = len(keys)
    lens = np.fromiter((len(k) for k in keys), dtype=np.int64, count=n)
    w = width_words if width_words is not None else max(1, int(-(-int(lens.max(initial=1)) // 4)))
    stride = w * 4
    if lens.max(initial=0) > stride:
        raise ValueError(f"key longer than slab stride {stride}")
    out = np.zeros((n, stride), dtype=np.uint8)
    flat = np.frombuffer(b"".join(keys), dtype=np.uint8)
    starts = np.concatenate(([0], np.cumsum(lens)))[:-1]  # works for n == 0 too
    # target flat positions: row*stride + offset-within-key
    within = np.arange(lens.sum(), dtype=np.int64) - np.repeat(starts, lens)
    rows = np.repeat(np.arange(n, dtype=np.int64), lens)
    out.reshape(-1)[rows * stride + within] = flat
    words = out.reshape(n, w, 4)
    words = (words[:, :, 0].astype(np.uint32) << 24) | (words[:, :, 1].astype(np.uint32) << 16) \
        | (words[:, :, 2].astype(np.uint32) << 8) | words[:, :, 3].astype(np.uint32)
    return words, lens.astype(np.int32)


def pack_kvs(entries: Sequence[Tuple[bytes, int, bytes]],
             doc_key_lens: Optional[Sequence[int]] = None,
             width_words: Optional[int] = None) -> KVSlab:
    """Build a slab from (key_prefix_bytes, packed_doc_ht, value_bytes) triples.

    packed_doc_ht = (ht.value << 32) | write_id as a 96-bit concept; we pass
    (ht_value, write_id) packed as a single int for convenience:
    int = ht_value * 2^32 + write_id.
    """
    n = len(entries)
    keys = [e[0] for e in entries]
    key_words, key_len = _pad_keys_to_words(keys, width_words)
    ht_hi = np.empty(n, dtype=np.uint32)
    ht_lo = np.empty(n, dtype=np.uint32)
    write_id = np.empty(n, dtype=np.uint32)
    flags = np.zeros(n, dtype=np.uint32)
    ttl_ms = np.zeros(n, dtype=np.int64)
    value_idx = np.arange(n, dtype=np.int32)
    values: List[bytes] = []
    for i, (_, packed, val) in enumerate(entries):
        wid = packed & 0xFFFFFFFF
        ht = packed >> 32
        ht_hi[i] = ht >> 32
        ht_lo[i] = ht & 0xFFFFFFFF
        write_id[i] = wid
        mf, ttl, off = decode_control_fields(val)
        tag = val[off]
        if tag == ValueType.kTombstone:
            flags[i] |= FLAG_TOMBSTONE
        elif tag == ValueType.kObject:
            flags[i] |= FLAG_OBJECT_INIT
        if ttl is not None:
            flags[i] |= FLAG_HAS_TTL
            ttl_ms[i] = ttl
        values.append(val)
    if doc_key_lens is None:
        dkl = np.array([_doc_key_len(k) for k in keys], dtype=np.int32)
    else:
        dkl = np.asarray(doc_key_lens, dtype=np.int32)
    for i, k in enumerate(keys):
        if len(k) > dkl[i] and subkey_depth(k, int(dkl[i])) > 1:
            flags[i] |= FLAG_DEEP
    return KVSlab(key_words, key_len, dkl, ht_hi, ht_lo, write_id, flags,
                  ttl_ms, value_idx, ValueArray.from_list(values))


def subkey_depth(key_prefix: bytes, doc_key_len: int) -> int:
    """Number of subkey components below the DocKey (1 = row column,
    2+ = deep document: collections/jsonb paths)."""
    from yugabyte_tpu.docdb.doc_key import PrimitiveValue
    pos = doc_key_len
    depth = 0
    n = len(key_prefix)
    try:
        while pos < n:
            _, pos = PrimitiveValue.decode(key_prefix, pos)
            depth += 1
    except (ValueError, IndexError, struct.error):  # yblint: contained(undecodable subkey tail is classified as deep — a conservative routing answer, not a swallowed durability error)
        return depth + 1  # undecodable tail: treat as deep (conservative)
    return depth


def subkey_bounds(key_prefix: bytes, doc_key_len: int) -> List[int]:
    """Component end offsets: [doc_key_len, end_of_subkey_1, ...] — the
    reference's sub_key_ends_ (ref: SubDocKey::DecodeDocKeyAndSubKeyEnds)."""
    from yugabyte_tpu.docdb.doc_key import PrimitiveValue
    bounds = [doc_key_len]
    pos = doc_key_len
    n = len(key_prefix)
    while pos < n:
        _, pos = PrimitiveValue.decode(key_prefix, pos)
        bounds.append(pos)
    return bounds


def _doc_key_len(key_prefix: bytes) -> int:
    """Byte length of the DocKey portion (through the range-group kGroupEnd).

    Scans tag-structure: skips the hashed group's kGroupEnd if a hash prefix
    is present, then finds the range group's terminator. kGroupEnd bytes
    cannot appear inside components: every component encoding either escapes
    low bytes (strings escape only 0x00 — but '!' is 0x21; however string
    *content* can contain 0x21!). So we must parse, not scan.

    Keys that are NOT doc keys — intent reverse-index records and other
    system keys in the intents DB — count as one whole-key "document":
    they never share overwrite semantics with doc paths.
    """
    from yugabyte_tpu.docdb.doc_key import DocKey
    try:
        _, pos = DocKey.decode(key_prefix, 0)
    except (ValueError, IndexError, struct.error):  # yblint: contained(non-doc system keys are by definition undecodable — whole key is the document, no error to route)
        return len(key_prefix)
    return pos


def pack_doc_ht(dht: DocHybridTime) -> int:
    return (dht.ht.value << 32) | dht.write_id


def unpack_keys(slab: KVSlab) -> List[bytes]:
    """Materialize key byte strings from a slab (host-side, for SST writing)."""
    raw = slab.key_words.astype(">u4").tobytes()
    stride = slab.width_words * 4
    return [raw[i * stride: i * stride + int(slab.key_len[i])] for i in range(slab.n)]


def concat_slabs(slabs: Sequence[KVSlab]) -> KVSlab:
    """Concatenate runs into one slab (vectorized, including values)."""
    w = max(s.width_words for s in slabs)
    parts_words = []
    value_offsets = []
    off = 0
    for s in slabs:
        kw = s.key_words
        if s.width_words < w:
            kw = np.pad(kw, ((0, 0), (0, w - s.width_words)))
        parts_words.append(kw)
        value_offsets.append(off)
        off += len(s.values)
    return KVSlab(
        key_words=np.concatenate(parts_words, axis=0),
        key_len=np.concatenate([s.key_len for s in slabs]),
        doc_key_len=np.concatenate([s.doc_key_len for s in slabs]),
        ht_hi=np.concatenate([s.ht_hi for s in slabs]),
        ht_lo=np.concatenate([s.ht_lo for s in slabs]),
        write_id=np.concatenate([s.write_id for s in slabs]),
        flags=np.concatenate([s.flags for s in slabs]),
        ttl_ms=np.concatenate([s.ttl_ms for s in slabs]),
        value_idx=np.concatenate(
            [s.value_idx + o for s, o in zip(slabs, value_offsets)]).astype(np.int32),
        values=ValueArray.concat([s.values for s in slabs]),
    )
