"""blocking-in-reactor: no sleeps / blocking file I/O / unbounded waits
on RPC reactor threads or raft callback paths.

The messenger's accept/reader threads and the WAL-appender -> raft
durability callback chain are the system's reactors: one blocked reactor
stalls every call (or every replicate) multiplexed behind it. The
reference bans blocking work on reactor threads for the same reason
(rpc/reactor.h "fast path only"); handlers run on the service pool.

Reactor roots (per file):
- any function whose def line carries `# yblint: reactor`;
- in rpc/ modules: `_accept_loop`, `_serve_conn`, `_read_loop`;
- in consensus/ modules: `_on_local_durable` (runs on the WAL appender
  thread; see raft.py's durability-watermark comment).

Reachability: same-module functions called from a reactor root are
reactor-path too (call-graph BFS, bare-name resolution).

Flagged inside reactor-path code:
- `time.sleep(...)`                              -> reactor-sleep
- `open(...)` / `os.fsync` / `io.open`           -> reactor-file-io
- `<queue-ish>.get()` without timeout/block=False -> unbounded-get
- `<event/cond>.wait()` without a timeout         -> unbounded-wait
- `<thread>.join()` without a timeout             -> unbounded-join

Blocking on the reactor's own socket (`recv`/`accept`/`select`) is the
reactor's job and is not flagged. Waive deliberate cases with
`# yblint: disable=blocking-in-reactor`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.analysis.core import AnalysisPass, FileContext, Finding

PASS_NAME = "blocking-in-reactor"

_RPC_SEEDS = {"_accept_loop", "_serve_conn", "_read_loop"}
_CONSENSUS_SEEDS = {"_on_local_durable"}
_MARKER = "# yblint: reactor"
_QUEUEISH = ("queue", "_q")
_WAITABLE_HINTS = ("event", "cv", "cond", "done", "ready", "stop")


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _receiver_name(node: ast.AST) -> str:
    """Lowercased name of the object a method is called on ('' if not a
    simple name/attribute chain)."""
    if isinstance(node, ast.Attribute):
        base = node.value
        if isinstance(base, ast.Attribute):
            return base.attr.lower()
        if isinstance(base, ast.Name):
            return base.id.lower()
        if isinstance(base, ast.Subscript):
            # waiter["event"].wait() — use the subscript key if constant
            s = base.slice
            if isinstance(s, ast.Constant) and isinstance(s.value, str):
                return s.value.lower()
    return ""


def _has_timeout(call: ast.Call) -> bool:
    if any(kw.arg in ("timeout", "timeout_s", "timeout_ms")
           for kw in call.keywords):
        return True
    return bool(call.args)  # positional timeout (Event.wait(0.5)) / get(0)


class BlockingReactorPass(AnalysisPass):
    name = PASS_NAME

    def run(self, ctx: FileContext) -> List[Finding]:
        fns: Dict[str, ast.AST] = {}
        for node in ctx.nodes_of(ast.FunctionDef, ast.AsyncFunctionDef):
            fns.setdefault(node.name, node)
        roots = self._roots(ctx, fns)
        if not roots:
            return []
        reachable = self._reach(fns, roots)
        out: List[Finding] = []
        for name in sorted(reachable):
            out.extend(self._check(ctx, fns[name]))
        return out

    def _roots(self, ctx: FileContext,
               fns: Dict[str, ast.AST]) -> Set[str]:
        roots: Set[str] = set()
        seeds: Set[str] = set()
        if "/rpc/" in "/" + ctx.relpath:
            seeds |= _RPC_SEEDS
        if "/consensus/" in "/" + ctx.relpath:
            seeds |= _CONSENSUS_SEEDS
        for name, node in fns.items():
            if name in seeds:
                roots.add(name)
            elif _MARKER in ctx.line_text(node.lineno):
                roots.add(name)
        return roots

    def _reach(self, fns: Dict[str, ast.AST], roots: Set[str]) -> Set[str]:
        reachable = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for call in ast.walk(fns[cur]):
                if not isinstance(call, ast.Call):
                    continue
                callee: Optional[str] = None
                if isinstance(call.func, ast.Name):
                    callee = call.func.id
                elif (isinstance(call.func, ast.Attribute)
                      and isinstance(call.func.value, ast.Name)
                      and call.func.value.id == "self"):
                    callee = call.func.attr
                if callee in fns and callee not in reachable:
                    reachable.add(callee)
                    frontier.append(callee)
        return reachable

    def _check(self, ctx: FileContext, fn: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if fname == "time.sleep" or fname == "sleep":
                out.append(ctx.finding(
                    self.name, "reactor-sleep", node,
                    f"time.sleep on a reactor path ({fn.name}) stalls "
                    "every call multiplexed behind this thread"))
                continue
            if fname in ("open", "io.open", "os.fsync", "os.replace"):
                out.append(ctx.finding(
                    self.name, "reactor-file-io", node,
                    f"blocking file I/O ({fname}) on a reactor path "
                    f"({fn.name}) — hand it to a worker pool"))
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            recv = _receiver_name(node.func)
            if meth == "get" and any(h in recv for h in _QUEUEISH) \
                    and not _has_timeout(node) \
                    and not any(kw.arg == "block" for kw in node.keywords):
                out.append(ctx.finding(
                    self.name, "unbounded-get", node,
                    f"unbounded {recv}.get() on a reactor path "
                    f"({fn.name}) — pass a timeout"))
            elif meth == "wait" and not _has_timeout(node) \
                    and (any(h in recv for h in _WAITABLE_HINTS)
                         or recv in ("self",)):
                out.append(ctx.finding(
                    self.name, "unbounded-wait", node,
                    f"{recv}.wait() without a timeout on a reactor path "
                    f"({fn.name})"))
            elif meth == "join" and not _has_timeout(node) \
                    and "thread" in recv:
                out.append(ctx.finding(
                    self.name, "unbounded-join", node,
                    f"{recv}.join() without a timeout on a reactor path "
                    f"({fn.name})"))
        return out
