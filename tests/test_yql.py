"""Query-layer tests: YCQL parser/executor/server + Redis RESP server over
a MiniCluster (ref: cql_test_base.cc suites; redisserver-test.cc)."""

import socket
import time

import pytest

from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.yql.cql import parser as P
from yugabyte_tpu.yql.cql.executor import QLProcessor
from yugabyte_tpu.yql.cql.server import CQLServer
from yugabyte_tpu.yql.redis.server import RedisServer


# ---------------------------------------------------------------- parser
def test_parser_create_table():
    s = P.parse("CREATE TABLE ks.users (id TEXT, age BIGINT, name TEXT, "
                "PRIMARY KEY ((id), age)) WITH tablets = 8")
    assert s.keyspace == "ks" and s.name == "users"
    assert s.hash_keys == ["id"] and s.range_keys == ["age"]
    assert s.num_tablets == 8


def test_parser_inline_pk_and_literals():
    s = P.parse("CREATE TABLE t (k TEXT PRIMARY KEY, v BIGINT)")
    assert s.hash_keys == ["k"] and s.range_keys == []
    i = P.parse("INSERT INTO t (k, v) VALUES ('it''s', -42) USING TTL 5")
    assert i.values == ["it's", -42] and i.ttl_seconds == 5
    sel = P.parse("SELECT v FROM t WHERE k = ? AND v >= 3 LIMIT 10")
    assert sel.where[0][2] is P.MARKER and sel.limit == 10


def test_parser_transaction_block():
    t = P.parse("BEGIN TRANSACTION "
                "INSERT INTO t (k, v) VALUES ('a', 1); "
                "UPDATE t SET v = 2 WHERE k = 'b'; "
                "END TRANSACTION")
    assert len(t.statements) == 2


def test_parser_errors():
    with pytest.raises(P.ParseError):
        P.parse("CREATE TABLE t (v BIGINT)")  # no primary key
    with pytest.raises(P.ParseError):
        P.parse("SELEC * FROM t")


# ----------------------------------------------------------- integration
@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 3)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path_factory.mktemp("yqlcluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def ql(cluster):
    client = cluster.new_client()
    p = QLProcessor(client)
    p.execute("CREATE KEYSPACE store")
    p.execute("USE store")
    p.execute("CREATE TABLE items (cat TEXT, sku TEXT, price BIGINT, "
              "name TEXT, PRIMARY KEY ((cat), sku)) WITH tablets = 2")
    # READY-leader poll before the first write (the known RF3 create-
    # then-write election flake; PR-7 deflake pattern)
    cluster.wait_for_table_leaders("store", "items")
    return p


def test_cql_insert_select_point(ql):
    ql.execute("INSERT INTO items (cat, sku, price, name) "
               "VALUES ('fruit', 'a1', 150, 'apple')")
    ql.execute("INSERT INTO items (cat, sku, price, name) "
               "VALUES ('fruit', 'b2', 300, 'berry')")
    rs = ql.execute("SELECT name, price FROM items "
                    "WHERE cat = 'fruit' AND sku = 'a1'")
    assert rs.rows == [["apple", 150]]


def test_cql_bind_params(ql):
    ql.execute("INSERT INTO items (cat, sku, price, name) "
               "VALUES (?, ?, ?, ?)", ["veg", "c3", 80, "carrot"])
    rs = ql.execute("SELECT name FROM items WHERE cat = ? AND sku = ?",
                    ["veg", "c3"])
    assert rs.rows == [["carrot"]]


def test_cql_partition_and_filter_select(ql):
    rs = ql.execute("SELECT sku FROM items WHERE cat = 'fruit'")
    assert sorted(r[0] for r in rs.rows) == ["a1", "b2"]
    rs = ql.execute("SELECT name FROM items WHERE price > 100")
    assert sorted(r[0] for r in rs.rows) == ["apple", "berry"]


def test_cql_update_bind_order(ql):
    ql.execute("INSERT INTO items (cat, sku, price, name) "
               "VALUES ('bind', 'z9', 1, 'thing')")
    # Markers bind in statement-text order: SET first, then WHERE.
    ql.execute("UPDATE items SET price = ? WHERE cat = ? AND sku = ?",
               [777, "bind", "z9"])
    rs = ql.execute("SELECT price FROM items WHERE cat = 'bind' "
                    "AND sku = 'z9'")
    assert rs.rows == [[777]]


def test_cql_blob_literal(ql, cluster):
    ql.execute("CREATE TABLE blobs (k TEXT PRIMARY KEY, data BLOB)")
    cluster.wait_for_table_leaders("store", "blobs")
    ql.execute("INSERT INTO blobs (k, data) VALUES ('b', 0xDEADBEEF)")
    rs = ql.execute("SELECT data FROM blobs WHERE k = 'b'")
    assert rs.rows == [[bytes.fromhex("deadbeef")]]


def test_redis_hash_key_visibility(redis):
    redis.cmd("HSET", "hexists", "f", "v")
    assert redis.cmd("EXISTS", "hexists") == 1
    assert b"hexists" in redis.cmd("KEYS", "*")
    # Arity errors return a RESP error and must not kill the connection.
    with pytest.raises(RuntimeError, match="wrong number of arguments"):
        redis.cmd("GET")
    assert redis.cmd("PING") == "PONG"  # connection still alive


def test_cql_update_delete(ql):
    ql.execute("UPDATE items SET price = 200 "
               "WHERE cat = 'fruit' AND sku = 'a1'")
    rs = ql.execute("SELECT price FROM items "
                    "WHERE cat = 'fruit' AND sku = 'a1'")
    assert rs.rows == [[200]]
    ql.execute("DELETE FROM items WHERE cat = 'veg' AND sku = 'c3'")
    rs = ql.execute("SELECT * FROM items WHERE cat = 'veg' AND sku = 'c3'")
    assert rs.rows == []


def test_cql_transaction_block(ql):
    ql.execute("BEGIN TRANSACTION "
               "INSERT INTO items (cat, sku, price, name) "
               "VALUES ('txn', 't1', 1, 'one'); "
               "INSERT INTO items (cat, sku, price, name) "
               "VALUES ('txn', 't2', 2, 'two'); "
               "END TRANSACTION")
    rs = ql.execute("SELECT sku FROM items WHERE cat = 'txn'")
    assert sorted(r[0] for r in rs.rows) == ["t1", "t2"]


def test_cql_server_rpc(cluster):
    server = CQLServer(cluster.master_addrs())
    try:
        client_m = cluster.new_client()._messenger
        call = lambda mth, **kw: client_m.call(  # noqa: E731
            server.address, "cql", mth, **kw)
        call("execute", stmt="CREATE KEYSPACE IF NOT EXISTS wire")
        call("execute", stmt="USE wire", session="s1")
        call("execute", session="s1",
             stmt="CREATE TABLE kv (k TEXT PRIMARY KEY, v TEXT)")
        call("execute", session="s1",
             stmt="INSERT INTO kv (k, v) VALUES ('hello', 'world')")
        out = call("execute", session="s1",
                   stmt="SELECT v FROM kv WHERE k = 'hello'")
        assert out["rows"] == [["world"]]
    finally:
        server.shutdown()


# ---------------------------------------------------------------- redis
class RedisCli:
    """Minimal RESP client for tests."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port))
        self.f = self.sock.makefile("rb")

    def cmd(self, *args):
        parts = [b"*%d\r\n" % len(args)]
        for a in args:
            if isinstance(a, str):
                a = a.encode()
            parts.append(b"$%d\r\n%s\r\n" % (len(a), a))
        self.sock.sendall(b"".join(parts))
        return self._read()

    def _read(self):
        line = self.f.readline()[:-2]
        t, body = line[:1], line[1:]
        if t == b"+":
            return body.decode()
        if t == b"-":
            raise RuntimeError(body.decode())
        if t == b":":
            return int(body)
        if t == b"$":
            n = int(body)
            if n < 0:
                return None
            data = self.f.read(n + 2)[:-2]
            return data
        if t == b"*":
            n = int(body)
            if n < 0:
                return None
            return [self._read() for _ in range(n)]
        raise RuntimeError(f"bad RESP type {t!r}")

    def close(self):
        self.sock.close()


@pytest.fixture(scope="module")
def redis(cluster):
    server = RedisServer(cluster.new_client(), num_tablets=2)
    cli = RedisCli(server.host, server.port)
    yield cli
    cli.close()
    server.shutdown()


def test_redis_ping_echo(redis):
    assert redis.cmd("PING") == "PONG"
    assert redis.cmd("ECHO", "hey") == b"hey"


def test_redis_set_get_del(redis):
    assert redis.cmd("SET", "k1", "v1") == "OK"
    assert redis.cmd("GET", "k1") == b"v1"
    assert redis.cmd("GET", "nope") is None
    assert redis.cmd("EXISTS", "k1", "nope") == 1
    assert redis.cmd("DEL", "k1") == 1
    assert redis.cmd("GET", "k1") is None


def test_redis_mset_mget(redis):
    assert redis.cmd("MSET", "a", "1", "b", "2") == "OK"
    assert redis.cmd("MGET", "a", "b", "missing") == [b"1", b"2", None]


def test_redis_incr(redis):
    assert redis.cmd("INCR", "counter") == 1
    assert redis.cmd("INCRBY", "counter", "10") == 11
    assert redis.cmd("DECR", "counter") == 10


def test_redis_hashes(redis):
    assert redis.cmd("HSET", "user:1", "name", "ada", "age", "36") == 2
    assert redis.cmd("HGET", "user:1", "name") == b"ada"
    assert redis.cmd("HMGET", "user:1", "age", "ghost") == [b"36", None]
    all_kv = redis.cmd("HGETALL", "user:1")
    assert dict(zip(all_kv[::2], all_kv[1::2])) == \
        {b"name": b"ada", b"age": b"36"}
    assert redis.cmd("HLEN", "user:1") == 2
    assert redis.cmd("HDEL", "user:1", "age") == 1
    assert redis.cmd("HGET", "user:1", "age") is None


def test_redis_binary_safety(redis):
    blob = bytes(range(256))
    assert redis.cmd("SET", b"bin\x00key", blob) == "OK"
    assert redis.cmd("GET", b"bin\x00key") == blob


def test_redis_keys(redis):
    redis.cmd("FLUSHALL")
    redis.cmd("MSET", "x", "1", "y", "2")
    keys = redis.cmd("KEYS", "*")
    assert sorted(keys) == [b"x", b"y"]
    assert redis.cmd("DBSIZE") == 2


class TestScanChoices:
    """IN-list (discrete) and range-bound (hybrid) scan strategies
    (ref docdb/scan_choices.cc)."""

    @pytest.fixture(scope="class")
    def tql(self, cluster):
        from yugabyte_tpu.yql.cql.executor import QLProcessor
        proc = QLProcessor(cluster.new_client())
        proc.execute("CREATE KEYSPACE scks")
        proc.execute("USE scks")
        proc.execute("CREATE TABLE ts (h text, r bigint, v text, "
                     "PRIMARY KEY ((h), r))")
        for h in ("a", "b"):
            for r in range(10):
                proc.execute(f"INSERT INTO ts (h, r, v) "
                             f"VALUES ('{h}', {r}, '{h}{r}')")
        return proc

    def test_in_on_hash_key(self, tql):
        rs = tql.execute("SELECT h, r, v FROM ts WHERE h IN ('a', 'b') "
                         "AND r = 3")
        assert sorted(r[2] for r in rs.rows) == ["a3", "b3"]

    def test_in_on_range_key(self, tql):
        rs = tql.execute("SELECT v FROM ts WHERE h = 'a' AND r IN (1, 4, 8)")
        assert sorted(r[0] for r in rs.rows) == ["a1", "a4", "a8"]

    def test_in_on_value_column(self, tql):
        rs = tql.execute("SELECT v FROM ts WHERE h = 'a' "
                         "AND v IN ('a2', 'a5')")
        assert sorted(r[0] for r in rs.rows) == ["a2", "a5"]

    def test_range_bounds_on_clustering_column(self, tql):
        rs = tql.execute("SELECT r FROM ts WHERE h = 'a' AND r >= 3 "
                         "AND r < 7")
        assert sorted(r[0] for r in rs.rows) == [3, 4, 5, 6]
        rs = tql.execute("SELECT r FROM ts WHERE h = 'b' AND r > 8")
        assert [r[0] for r in rs.rows] == [9]

    def test_range_bounds_actually_prune(self, tql, cluster):
        """The scan request carries tightened byte bounds (not just a
        post-filter): verify via the doc-key window sent to the tserver."""
        from yugabyte_tpu.yql.cql import parser as P
        from yugabyte_tpu.docdb.doc_key import DocKey, PrimitiveValue
        proc = tql
        stmt = P.parse("SELECT r FROM ts WHERE h = 'a' AND r >= 3 AND r < 7")
        table = proc._table("scks", "ts")
        schema = table.schema
        where = [(c, op, v) for c, op, v in stmt.where]
        dk, residual = proc._doc_key_from_where(table, where)
        prefix = DocKey(hash_components=dk.hash_components,
                        range_components=dk.range_components).encode()[:-1]
        lo, hi = proc._range_scan_bounds(schema, dk, prefix, residual)
        buf3, buf7 = bytearray(), bytearray()
        PrimitiveValue.encode(3, buf3)
        PrimitiveValue.encode(7, buf7)
        assert lo == prefix + bytes(buf3)
        assert hi == prefix + bytes(buf7)
        assert lo > prefix and hi < prefix + b"\xff"

    def test_cross_type_bound_not_pushed(self, tql):
        """A float predicate on a bigint clustering column must not
        tighten byte bounds (different type tags would exclude all rows);
        the residual filter still applies it."""
        rs = tql.execute("SELECT r FROM ts WHERE h = 'a' AND r < 3.5")
        assert sorted(r[0] for r in rs.rows) == [0, 1, 2, 3]

    def test_in_with_markers(self, tql):
        rs = tql.execute("SELECT v FROM ts WHERE h = ? AND r IN (?, ?)",
                         ("a", 1, 4))
        assert sorted(r[0] for r in rs.rows) == ["a1", "a4"]
        rs = tql.execute("SELECT v FROM ts WHERE h = 'a' AND v IN (?)",
                         ("a2",))
        assert [r[0] for r in rs.rows] == ["a2"]

    def test_in_limit_respects_clustering_order(self, tql):
        rs = tql.execute("SELECT r FROM ts WHERE h = 'a' AND r IN (9, 1) "
                         "LIMIT 1")
        assert [r[0] for r in rs.rows] == [1]

    def test_in_duplicates_deduped(self, tql):
        rs = tql.execute("SELECT v FROM ts WHERE h = 'a' AND r IN (1, 1)")
        assert [r[0] for r in rs.rows] == ["a1"]

    def test_in_without_hash_key_single_scan(self, tql):
        rs = tql.execute("SELECT v FROM ts WHERE r IN (2, 5)")
        assert sorted(r[0] for r in rs.rows) == ["a2", "a5", "b2", "b5"]


class TestInListClusteringGuard:
    """ADVICE r3: `r2 IN (...)` with an earlier clustering column unbound
    must NOT take the per-option path — the per-option concatenation would
    order by (r2, r1) instead of clustering order, so LIMIT keeps the
    wrong rows.  It must fall back to one scan with IN as residual."""

    @pytest.fixture(scope="class")
    def tql2(self, cluster):
        from yugabyte_tpu.yql.cql.executor import QLProcessor
        proc = QLProcessor(cluster.new_client())
        proc.execute("CREATE KEYSPACE inks")
        proc.execute("USE inks")
        proc.execute("CREATE TABLE t2 (h text, r1 bigint, r2 bigint, "
                     "v text, PRIMARY KEY ((h), r1, r2))")
        for r1 in range(3):
            for r2 in range(3):
                proc.execute(f"INSERT INTO t2 (h, r1, r2, v) VALUES "
                             f"('a', {r1}, {r2}, 'v{r1}{r2}')")
        return proc

    def test_limit_respects_clustering_order(self, tql2):
        # clustering order: (r1, r2) = 00,01,02,10,11,12,20,21,22
        # rows with r2 IN (0, 2): 00,02,10,12,20,22 -> LIMIT 3 = 00,02,10
        rs = tql2.execute("SELECT r1, r2 FROM t2 WHERE h = 'a' "
                          "AND r2 IN (0, 2) LIMIT 3")
        assert [(r[0], r[1]) for r in rs.rows] == [(0, 0), (0, 2), (1, 0)]

    def test_bound_prefix_still_uses_options(self, tql2):
        rs = tql2.execute("SELECT r2 FROM t2 WHERE h = 'a' AND r1 = 1 "
                          "AND r2 IN (2, 0) LIMIT 1")
        assert [r[0] for r in rs.rows] == [0]


def test_cql_alter_table(cluster):
    from yugabyte_tpu.yql.cql.executor import QLProcessor
    ql = QLProcessor(cluster.new_client())
    ql.execute("CREATE KEYSPACE altks")
    ql.execute("USE altks")
    ql.execute("CREATE TABLE at (k text, v text, PRIMARY KEY ((k)))")
    cluster.wait_for_table_leaders("altks", "at")
    ql.execute("INSERT INTO at (k, v) VALUES ('a', '1')")
    ql.execute("ALTER TABLE at ADD extra int")
    ql.execute("INSERT INTO at (k, v, extra) VALUES ('b', '2', 42)")
    rs = ql.execute("SELECT k, v, extra FROM at")
    got = {tuple(r) for r in rs.rows}
    assert got == {("a", "1", None), ("b", "2", 42)}
    ql.execute("ALTER TABLE at DROP v")
    rs = ql.execute("SELECT k, extra FROM at")
    assert {tuple(r) for r in rs.rows} == {("a", None), ("b", 42)}
    # the dropped column's data is unreachable (CQL's permissive select
    # surfaces absent columns as nulls rather than erroring)
    rs = ql.execute("SELECT v FROM at")
    assert all(r == [None] for r in rs.rows)


class TestCqlOrderBy:
    def test_order_by_clustering(self, ql):
        ql.execute("CREATE TABLE series (dev TEXT, ts BIGINT, v BIGINT, "
                   "PRIMARY KEY ((dev), ts))")
        for i in range(5):
            ql.execute(f"INSERT INTO series (dev, ts, v) "
                       f"VALUES ('d1', {i}, {i * 10})")
        rs = ql.execute("SELECT ts FROM series WHERE dev = 'd1' "
                        "ORDER BY ts ASC")
        assert [r[0] for r in rs.rows] == [0, 1, 2, 3, 4]
        rs = ql.execute("SELECT ts FROM series WHERE dev = 'd1' "
                        "ORDER BY ts DESC")
        assert [r[0] for r in rs.rows] == [4, 3, 2, 1, 0]
        rs = ql.execute("SELECT ts FROM series WHERE dev = 'd1' "
                        "ORDER BY ts DESC LIMIT 2")
        assert [r[0] for r in rs.rows] == [4, 3]
        # range predicate composes with the reversed order
        rs = ql.execute("SELECT ts FROM series WHERE dev = 'd1' "
                        "AND ts >= 1 AND ts <= 3 ORDER BY ts DESC")
        assert [r[0] for r in rs.rows] == [3, 2, 1]

    def test_order_by_requires_partition_key(self, ql):
        from yugabyte_tpu.utils.status import StatusError
        import pytest as _pytest
        with _pytest.raises(StatusError, match="partition key"):
            ql.execute("SELECT ts FROM series ORDER BY ts DESC")
        # non-clustering column rejected even on a point lookup
        with _pytest.raises(StatusError, match="clustering"):
            ql.execute("SELECT v FROM series WHERE dev = 'd1' AND ts = 3 "
                       "ORDER BY v DESC")
        # IN on the clustering column with DESC takes the ordered path
        rs = ql.execute("SELECT ts FROM series WHERE dev = 'd1' "
                        "AND ts IN (1, 2, 3) ORDER BY ts DESC")
        assert [r[0] for r in rs.rows] == [3, 2, 1]


def test_redis_string_ops(redis):
    # APPEND / STRLEN / SETNX / GETSET / GETDEL
    assert redis.cmd("APPEND", "s1", "hello") == 5
    assert redis.cmd("APPEND", "s1", " world") == 11
    assert redis.cmd("STRLEN", "s1") == 11
    assert redis.cmd("STRLEN", "missing") == 0
    assert redis.cmd("SETNX", "s1", "x") == 0
    assert redis.cmd("SETNX", "s2", "first") == 1
    assert redis.cmd("GET", "s2") == b"first"
    assert redis.cmd("GETSET", "s2", "second") == b"first"
    assert redis.cmd("GETDEL", "s2") == b"second"
    assert redis.cmd("GET", "s2") is None


def test_redis_ranges(redis):
    redis.cmd("SET", "r1", "Hello World")
    assert redis.cmd("GETRANGE", "r1", "0", "4") == b"Hello"
    assert redis.cmd("GETRANGE", "r1", "-5", "-1") == b"World"
    assert redis.cmd("SETRANGE", "r1", "6", "Redis") == 11
    assert redis.cmd("GET", "r1") == b"Hello Redis"
    # SETRANGE past the end zero-pads
    assert redis.cmd("SETRANGE", "r2", "3", "x") == 4
    assert redis.cmd("GET", "r2") == b"\x00\x00\x00x"


def test_redis_type_rename_persist(redis):
    redis.cmd("SET", "t1", "v")
    redis.cmd("HSET", "t2", "f", "v")
    assert redis.cmd("TYPE", "t1") == "string"
    assert redis.cmd("TYPE", "t2") == "hash"
    assert redis.cmd("TYPE", "t3") == "none"
    assert redis.cmd("RENAME", "t1", "t1b") == "OK"
    assert redis.cmd("GET", "t1") is None
    assert redis.cmd("GET", "t1b") == b"v"
    assert redis.cmd("RENAME", "t2", "t2b") == "OK"
    assert redis.cmd("HGET", "t2b", "f") == b"v"
    assert redis.cmd("HGET", "t2", "f") is None
    with pytest.raises(RuntimeError):
        redis.cmd("RENAME", "ghost", "dst")
    redis.cmd("SET", "p1", "v", "EX", "100")
    assert redis.cmd("PERSIST", "p1") == 1
    assert redis.cmd("PERSIST", "ghost") == 0


def test_redis_hash_extras(redis):
    redis.cmd("HSET", "h9", "a", "1", "b", "two")
    assert redis.cmd("HEXISTS", "h9", "a") == 1
    assert redis.cmd("HEXISTS", "h9", "z") == 0
    assert sorted(redis.cmd("HKEYS", "h9")) == [b"a", b"b"]
    assert sorted(redis.cmd("HVALS", "h9")) == [b"1", b"two"]
    assert redis.cmd("HSTRLEN", "h9", "b") == 3
    assert redis.cmd("HINCRBY", "h9", "a", "41") == 42
    assert redis.cmd("HINCRBY", "h9", "cnt", "-5") == -5
    assert redis.cmd("HSETNX", "h9", "a", "99") == 0
    assert redis.cmd("HSETNX", "h9", "new", "n") == 1
    assert redis.cmd("HGET", "h9", "new") == b"n"


def test_redis_rename_semantics(redis):
    # self-rename is a successful no-op
    redis.cmd("SET", "rs", "val")
    assert redis.cmd("RENAME", "rs", "rs") == "OK"
    assert redis.cmd("GET", "rs") == b"val"
    # rename fully REPLACES an existing destination (no merge)
    redis.cmd("HSET", "rdst", "old", "1")
    redis.cmd("HSET", "rsrc", "new", "2")
    assert redis.cmd("RENAME", "rsrc", "rdst") == "OK"
    assert sorted(redis.cmd("HKEYS", "rdst")) == [b"new"]
    # string-over-hash rename clears the hash representation
    redis.cmd("HSET", "rh", "f", "v")
    redis.cmd("SET", "rstr", "sv")
    assert redis.cmd("RENAME", "rstr", "rh") == "OK"
    assert redis.cmd("TYPE", "rh") == "string"
    assert redis.cmd("HGET", "rh", "f") is None


def test_redis_setrange_empty_patch(redis):
    assert redis.cmd("SETRANGE", "srm", "3", "") == 0
    assert redis.cmd("EXISTS", "srm") == 0
    redis.cmd("SET", "srk", "abc")
    assert redis.cmd("SETRANGE", "srk", "10", "") == 3
    assert redis.cmd("GET", "srk") == b"abc"


def test_redis_range_clamping(redis):
    redis.cmd("SET", "gc", "abc")
    assert redis.cmd("GETRANGE", "gc", "0", "-5") == b"a"
    assert redis.cmd("GETRANGE", "gc", "2", "1") == b""
    assert redis.cmd("GETRANGE", "gc", "0", "99") == b"abc"
    with pytest.raises(RuntimeError):
        redis.cmd("SETRANGE", "gc", "-1", "x")
    assert redis.cmd("GET", "gc") == b"abc"  # untouched on error


def test_redis_rename_dual_representation(redis):
    redis.cmd("SET", "dual", "sv")
    redis.cmd("HSET", "dual", "f", "hv")
    assert redis.cmd("RENAME", "dual", "dualdst") == "OK"
    # BOTH representations moved; source fully gone
    assert redis.cmd("GET", "dualdst") == b"sv"
    assert redis.cmd("HGET", "dualdst", "f") == b"hv"
    assert redis.cmd("EXISTS", "dual") == 0


def test_cql_aggregates(ql, cluster):
    ql.execute("CREATE TABLE agg (k TEXT, r INT, price BIGINT, "
               "name TEXT, PRIMARY KEY ((k), r)) WITH tablets = 2")
    cluster.wait_for_table_leaders("store", "agg")
    for i in range(6):
        ql.execute("INSERT INTO agg (k, r, price, name) VALUES "
                   "('p', %d, %d, '%s')"
                   % (i, (i + 1) * 10, "n" if i % 2 else "m"))
    ql.execute("INSERT INTO agg (k, r) VALUES ('p', 99)")  # null price
    rs = ql.execute("SELECT COUNT(*) FROM agg WHERE k = 'p'")
    assert rs.columns == ["count(*)"] and rs.rows == [[7]]
    rs = ql.execute("SELECT COUNT(price), SUM(price), MIN(price), "
                    "MAX(price), AVG(price) FROM agg WHERE k = 'p'")
    assert rs.rows == [[6, 210, 10, 60, 35]]
    # AVG over ints is integer division (Cassandra semantics)
    assert isinstance(rs.rows[0][4], int)
    # filtered aggregate
    rs = ql.execute("SELECT COUNT(*) FROM agg WHERE k = 'p' "
                    "AND price > 30 ALLOW FILTERING")
    assert rs.rows == [[3]]
    # MIN over text works; SUM over text rejected
    rs = ql.execute("SELECT MIN(name) FROM agg WHERE k = 'p'")
    assert rs.rows == [["m"]]
    with pytest.raises(Exception, match="numeric"):
        ql.execute("SELECT SUM(name) FROM agg WHERE k = 'p'")
    with pytest.raises(Exception, match="mixed"):
        ql.execute("SELECT r, COUNT(*) FROM agg WHERE k = 'p'")
    # empty result set
    rs = ql.execute("SELECT COUNT(*), SUM(price), MIN(price) FROM agg "
                    "WHERE k = 'nope'")
    assert rs.rows == [[0, 0, None]]


def test_cql_count_limit_counts_all_rows(ql, cluster):
    """LIMIT on an aggregate applies to the one-row RESULT, not to the
    scan feeding it (ADVICE r5: `SELECT COUNT(*) ... LIMIT 1` truncated
    the scan to 1 row and returned count=1)."""
    ql.execute("CREATE TABLE cntl (k TEXT, r INT, PRIMARY KEY ((k), r))")
    cluster.wait_for_table_leaders("store", "cntl")
    for i in range(9):
        ql.execute("INSERT INTO cntl (k, r) VALUES ('p', %d)" % i)
    rs = ql.execute("SELECT COUNT(*) FROM cntl WHERE k = 'p' LIMIT 1")
    assert rs.rows == [[9]]
    rs = ql.execute("SELECT COUNT(*) FROM cntl WHERE k = 'p' LIMIT 3")
    assert rs.rows == [[9]]


def test_cql_aggregate_edges(ql, cluster):
    ql.execute("CREATE TABLE aggm (k TEXT PRIMARY KEY, m MAP<TEXT,INT>)")
    cluster.wait_for_table_leaders("store", "aggm")
    ql.execute("INSERT INTO aggm (k, m) VALUES ('a', {'x': 1})")
    ql.execute("INSERT INTO aggm (k, m) VALUES ('b', {'y': 2})")
    with pytest.raises(Exception, match="comparable"):
        ql.execute("SELECT MIN(m) FROM aggm")
    with pytest.raises(Exception, match="system"):
        ql.execute("SELECT COUNT(*) FROM system.peers")


def test_cql_sum_int32_widens(ql, cluster):
    ql.execute("CREATE TABLE s32 (k TEXT PRIMARY KEY, v INT)")
    cluster.wait_for_table_leaders("store", "s32")
    ql.execute("INSERT INTO s32 (k, v) VALUES ('a', 2000000000)")
    ql.execute("INSERT INTO s32 (k, v) VALUES ('b', 2000000000)")
    rs = ql.execute("SELECT SUM(v) FROM s32")
    assert rs.rows == [[4000000000]]
    from yugabyte_tpu.common.schema import DataType
    assert rs.types == [DataType.INT64]


def test_cql_select_distinct_partitions(ql):
    ql.execute("CREATE TABLE dparts (k TEXT, r INT, v INT, "
               "PRIMARY KEY ((k), r)) WITH tablets = 2")
    for k in ("a", "b", "c"):
        for r in range(3):
            ql.execute("INSERT INTO dparts (k, r, v) VALUES "
                       "('%s', %d, 1)" % (k, r))
    rs = ql.execute("SELECT DISTINCT k FROM dparts")
    assert sorted(r[0] for r in rs.rows) == ["a", "b", "c"]
    rs = ql.execute("SELECT DISTINCT k FROM dparts LIMIT 2")
    assert len(rs.rows) == 2
    with pytest.raises(Exception, match="partition key"):
        ql.execute("SELECT DISTINCT v FROM dparts")


def test_cql_distinct_edges(ql):
    ql.execute("CREATE TABLE IF NOT EXISTS dparts (k TEXT, r INT, v INT, "
               "PRIMARY KEY ((k), r)) WITH tablets = 2")
    for k in ("a", "b", "c"):
        ql.execute("INSERT INTO dparts (k, r, v) VALUES ('%s', 0, 1)" % k)
    with pytest.raises(Exception, match="DISTINCT \\*"):
        ql.execute("SELECT DISTINCT * FROM dparts")
    with pytest.raises(Exception, match="ORDER BY"):
        ql.execute("SELECT DISTINCT k FROM dparts ORDER BY k")
    # paging through the distinct set
    rs = ql.execute("SELECT DISTINCT k FROM dparts", page_size=2)
    assert len(rs.rows) == 2 and rs.paging_state is not None
    rs2 = ql.execute("SELECT DISTINCT k FROM dparts", page_size=2,
                     paging_state=rs.paging_state)
    assert len(rs2.rows) == 1 and rs2.paging_state is None
    all_keys = sorted(r[0] for r in rs.rows + rs2.rows)
    assert all_keys == ["a", "b", "c"]


def test_cql_token_function(ql, cluster):
    ql.execute("CREATE TABLE toks (k TEXT, r INT, v INT, "
               "PRIMARY KEY ((k), r)) WITH tablets = 2")
    # Deflake (the known once-per-full-run leadership-timing failure):
    # under full-suite load a fresh tablet's first election can outlast
    # the client retry budget, so poll actual leader state before the
    # first write instead of racing it.
    cluster.wait_for_table_leaders("store", "toks")
    for k in ("a", "b", "c", "d"):
        ql.execute("INSERT INTO toks (k, r, v) VALUES ('%s', 0, 1)" % k)
    rs = ql.execute("SELECT k, token(k) FROM toks")
    toks = {r[0]: r[1] for r in rs.rows}
    assert len(toks) == 4 and all(isinstance(t, int) for t in toks.values())
    # token-range scan: the Spark/bulk-reader split pattern — ranges
    # partition the keyspace without overlap
    mid = sorted(toks.values())[1]
    lo = ql.execute("SELECT k FROM toks WHERE token(k) <= %d "
                    "ALLOW FILTERING" % mid)
    hi = ql.execute("SELECT k FROM toks WHERE token(k) > %d "
                    "ALLOW FILTERING" % mid)
    got = sorted(r[0] for r in lo.rows + hi.rows)
    assert got == ["a", "b", "c", "d"]
    assert len(lo.rows) == 2 and len(hi.rows) == 2


def test_cql_token_wrong_columns_rejected(ql):
    ql.execute("CREATE TABLE tw (k TEXT, r INT, v INT, "
               "PRIMARY KEY ((k), r))")
    with pytest.raises(Exception, match="partition key"):
        ql.execute("SELECT token(v) FROM tw")
    with pytest.raises(Exception, match="partition key"):
        ql.execute("SELECT k FROM tw WHERE token(r) > 0 ALLOW FILTERING")
