"""Tablet: one shard = two LSM instances + MVCC + locks + write pipeline.

Capability parity with the reference (ref: src/yb/tablet/tablet.h:124;
regular_db_/intents_db_ pair :856-857; apply path tablet.cc:1116
ApplyRowOperations -> :1198 ApplyKeyValueRowOperations -> :1247 WriteToRocksDB
where the Raft index becomes the storage frontier; read handlers :1290+).

The write pipeline here is WriteQuery (ref: tablet/write_query.cc): acquire
doc-path locks -> (txn conflict resolution, stage 8) -> pick hybrid time and
register with MVCC -> submit through the consensus seam -> apply -> release.
Round-1 consensus seam is LocalConsensusContext (applies immediately,
monotonically numbering ops); RaftConsensus replaces it in stage 6 behind the
same `submit()` interface.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from yugabyte_tpu.common.hybrid_time import (
    DocHybridTime, HybridClock, HybridTime)
from yugabyte_tpu.consensus.raft import OperationOutcomeUnknown
from yugabyte_tpu.common.schema import Schema
from yugabyte_tpu.docdb.doc_key import DocKey
from yugabyte_tpu.docdb.doc_operations import QLWriteOp, prepare_and_assemble
from yugabyte_tpu.docdb.doc_rowwise_iterator import (
    DocRowwiseIterator, Row, VisibleEntryRowAssembler, read_row)
from yugabyte_tpu.docdb.lock_manager import SharedLockManager
from yugabyte_tpu.docdb.value_type import ValueType
from yugabyte_tpu.ops.slabs import _doc_key_len
from yugabyte_tpu.storage.db import DB, DBOptions
from yugabyte_tpu.tablet.mvcc import MvccManager
from yugabyte_tpu.utils import flags
from yugabyte_tpu.utils.metrics import Counter, Histogram, MetricRegistry
from yugabyte_tpu.utils.trace import TRACE

flags.define_flag(
    "timestamp_history_retention_interval_sec", 900,
    "how far back in time reads are repeatable; compaction keeps overwritten "
    "values younger than this (ref tablet_retention_policy.h:29)")
flags.define_flag("sst_files_soft_limit", 24,
                  "writes start delaying at this many live SST files "
                  "(ref sst_files_soft_limit)")
flags.define_flag("sst_files_hard_limit", 48,
                  "writes are rejected (retryably) at this many live SST "
                  "files (ref sst_files_hard_limit)")
flags.define_flag("write_backpressure_max_delay_ms", 100,
                  "max per-write delay as file pressure approaches the "
                  "hard limit (ref tablet_service.cc:1510 rejection score)")
flags.define_flag("scan_pushdown", True,
                  "compile simple predicates + aggregates into the fused "
                  "scan kernels (ROADMAP item 5); off = every query takes "
                  "the per-row host path (results are identical either "
                  "way — the device subset is exact by construction)")
flags.define_flag("scan_pushdown_min_rows", 4096,
                  "minimum approximate entry count before a query rides "
                  "the fused pushdown kernels: below it the per-row host "
                  "path wins (a device dispatch — and its first-time XLA "
                  "compile — must never stall a tiny scan inside an RPC "
                  "deadline); same size-class philosophy as the "
                  "compaction offload policy")


class TabletRetentionPolicy:
    """history_cutoff = now - retention interval (ref tablet_retention_policy.h).

    override_s: PITR snapshot schedules need MVCC history at least as deep
    as their snapshot interval — otherwise a compaction between the restore
    target time and the covering snapshot's barrier collapses the versions
    the restore must read, and import_snapshot silently reconstructs newer
    state.  The master computes the requirement from active schedules and
    ships it via heartbeat responses (ref: the snapshot coordinator feeding
    allowed history cutoff, master_snapshot_coordinator.cc /
    tablet_retention_policy.cc AllowedHistoryCutoff)."""

    def __init__(self, clock: HybridClock):
        self._clock = clock
        self.override_s: float = 0.0

    def set_override(self, seconds: float) -> None:
        self.override_s = float(seconds)

    def history_cutoff(self) -> int:
        retention_s = max(
            flags.get_flag("timestamp_history_retention_interval_sec"),
            self.override_s)
        now = self._clock.now()
        return max(0, HybridTime.from_micros(
            now.physical_micros - int(retention_s * 1_000_000)).value)


class TabletHasBeenSplit(Exception):
    """Writes to a split parent are rejected; the client re-routes to the
    children (ref tablet/operations/split_operation.h)."""

    def __init__(self, children):
        super().__init__(f"tablet split into {children}")
        self.children = children


class LocalConsensusContext:
    """Round-1 consensus seam: no replication, ops numbered monotonically.
    Same submit() surface RaftConsensus implements in stage 6."""

    def __init__(self, tablet: "Tablet"):
        self._tablet = tablet
        self._index = 0
        self._lock = threading.Lock()

    def submit(self, kv_pairs, ht: HybridTime, timeout_s: float = 10.0,
               target_intents: bool = False, request=None) -> Tuple[int, int]:
        with self._lock:
            self._index += 1
            op_id = (1, self._index)  # (term, index)
        if target_intents:
            self._tablet.apply_intent_batch(kv_pairs, ht, op_id)
        else:
            self._tablet.apply_write_batch(kv_pairs, ht, op_id)
        if request is not None:
            self._tablet.retryable.replicated(request[0], request[1],
                                              ht.value)
        return op_id


@dataclass
class TabletOptions:
    block_entries: Optional[int] = None  # None = sst_block_entries flag
    device: object = None
    mesh: object = None      # >1-device mesh for distributed compaction
    offload_policy: object = None   # measured device-vs-native router
    device_cache: object = None
    compaction_pool: object = None
    # tserver/compaction_pool.CompactionPool: the mesh-sharded multi-
    # tablet scheduler; device-routed compactions ride its batch slots
    mesh_pool: object = None
    # shared decoded-block cache (ref: db/table_cache.cc — one per server)
    block_cache: object = None
    auto_compact: bool = True
    memstore_size_bytes: Optional[int] = None
    # Doc-key-space clamp for split children, whose LSM initially holds the
    # whole parent key range (ref: post-split key-bounds filtering,
    # docdb/doc_db.h KeyBounds).
    lower_bound_key: bytes = b""
    upper_bound_key: Optional[bytes] = None


class Tablet:  # yblint: disable=ybsan-coverage (composition root: the .submit goes to the consensus seam, and all cross-thread mutable state lives in DB/RaftConsensus/ admission, each covered by its own guarded-by annotations)
    def __init__(self, tablet_id: str, data_dir: str, schema: Schema,
                 clock: Optional[HybridClock] = None,
                 options: Optional[TabletOptions] = None,
                 metrics: Optional[MetricRegistry] = None):
        self.tablet_id = tablet_id
        self.schema = schema
        self.clock = clock or HybridClock()
        self.opts = options or TabletOptions()
        self.retention_policy = TabletRetentionPolicy(self.clock)
        from yugabyte_tpu.tablet.retryable_requests import RetryableRequests
        self.retryable = RetryableRequests()
        db_opts = DBOptions(
            block_entries=self.opts.block_entries,
            device=self.opts.device,
            mesh=self.opts.mesh,
            offload_policy=self.opts.offload_policy,
            device_cache=self.opts.device_cache,
            compaction_pool=self.opts.compaction_pool,
            mesh_pool=self.opts.mesh_pool,
            block_cache=self.opts.block_cache,
            retention_policy=self.retention_policy.history_cutoff,
            memstore_size_bytes=self.opts.memstore_size_bytes,
            auto_compact=self.opts.auto_compact)
        # Two DB instances, exactly like the reference (tablet.h:856-857):
        # committed data in regular_db, provisional records in intents_db.
        self.regular_db = DB(os.path.join(data_dir, "regular"), db_opts)
        intents_opts = DBOptions(
            block_entries=self.opts.block_entries,
            device=self.opts.device,
            compaction_pool=self.opts.compaction_pool,
            block_cache=self.opts.block_cache,
            auto_compact=self.opts.auto_compact)
        self.intents_db = DB(os.path.join(data_dir, "intents"), intents_opts)
        # Flush-ordering invariant (ref: the reference flushes regular
        # before intents so intents cleanup never outlives the applied
        # rows): an intents flush first persists the regular DB, keeping
        # intents' flushed frontier <= regular's for txn-apply ops whose
        # effects span both DBs. Bootstrap replays from the min frontier,
        # so OP_UPDATE_TXN re-derivation always sees live intents.
        self.intents_db.pre_flush_hook = self._pre_intents_flush
        self.mvcc = MvccManager(self.clock)
        self.lock_manager = SharedLockManager()
        self.consensus = LocalConsensusContext(self)
        self.split_children = None  # (child0, child1) once split
        # status_resolver(status_tablet, txn_id) -> {"status", "commit_ht"}
        # — wired by the tserver to the transaction coordinator; None means
        # conservative resolution (pending).
        self.status_resolver = None
        # Write gate for splitting: the SPLIT op must be the last write-ish
        # entry the parent ever appends, so block_writes() drains in-flight
        # writes BEFORE the split appends (an acked write appended after the
        # SPLIT entry would apply to the parent after the children snapshot
        # it — silently lost when the parent retires).
        self._write_gate = threading.Condition()
        self._inflight_writes = 0
        self._writes_blocked = False
        metrics = metrics or MetricRegistry()
        entity = metrics.entity("tablet", tablet_id)
        self.metric_rows_inserted = entity.counter(
            "rows_inserted_total", "rows written via QL write ops")
        self.metric_write_latency = entity.histogram(
            "ql_write_latency_us", "end-to-end WriteQuery latency (us)")
        self.metric_reads = entity.counter("ql_reads_total",
                                           "row reads served")
        self.metric_write_rejections = entity.counter(
            "write_rejections_total",
            "writes rejected retryably by write-pressure backpressure "
            "(SST files / memstore tracker / WAL backlog)")
        # Unified write-pressure state machine (tablet/admission.py):
        # SST-file pressure is bound here; TabletPeer binds the WAL
        # appender backlog and TabletMemoryManager binds the server-wide
        # memstore MemTracker. Evaluated at every write entry point.
        from yugabyte_tpu.tablet.admission import WriteAdmission
        self.admission = WriteAdmission(
            tablet_id, lambda: self.regular_db.n_live_files,
            rejection_counter=self.metric_write_rejections)

    def _pre_intents_flush(self) -> None:
        """Intents pre-flush hook. The regular flush contains I/O errors
        by parking its DB (it returns None, it does not raise), so the
        ordering invariant must be re-checked explicitly: if the regular
        DB failed to persist, the intents flush MUST abort too — an
        intents frontier ahead of the regular DB replays OP_UPDATE_TXN as
        a no-op after restart and loses rows."""
        from yugabyte_tpu.utils.status import StatusError
        self.regular_db.flush()
        err = self.regular_db.background_error
        if err is not None:
            raise StatusError(err)

    # ------------------------------------------------------------------ write
    def write(self, ops: Sequence[QLWriteOp], timeout_s: float = 10.0,
              request=None) -> HybridTime:
        """The WriteQuery pipeline (ref write_query.cc:211-566). Returns the
        hybrid time at which the batch became visible.

        request: optional (client_id, request_id) for exactly-once dedup
        (ref consensus/retryable_requests.cc): a duplicate of an
        already-replicated request returns its original hybrid time without
        re-applying; a duplicate of an in-flight one is pushed back to the
        client retry loop until the first attempt's fate settles."""
        # dedup BEFORE backpressure: a retry of an already-replicated write
        # must return its stored result even under file pressure (else a
        # long stall could outlive the dedup record and double-apply)
        if request is not None:
            state, ht_value = self.retryable.check_or_track(*request)
            if state == "duplicate":
                return HybridTime(ht_value)
            if state == "in_flight":
                from yugabyte_tpu.utils.status import Status, StatusError
                raise StatusError(Status.ServiceUnavailable(
                    "duplicate request still in flight"))
        try:
            self._check_write_backpressure()
        except BaseException:
            if request is not None:
                self.retryable.failed(*request)
            raise
        with self._write_gate:
            if self._writes_blocked or self.split_children is not None:
                if request is not None:
                    self.retryable.failed(*request)
                raise TabletHasBeenSplit(self.split_children or ())
            self._inflight_writes += 1
        try:
            return self._write_locked(ops, timeout_s, request=request)
        except OperationOutcomeUnknown:
            raise  # fate watcher resolves the in-flight registration
        except BaseException:
            if request is not None:
                self.retryable.failed(*request)
            raise
        finally:
            with self._write_gate:
                self._inflight_writes -= 1
                self._write_gate.notify_all()

    def _check_write_backpressure(self) -> None:
        """Unified score-based write throttling (ref:
        tserver/tablet_service.cc:1510 write-rejection score +
        sst_files_soft/hard_limit, plus the reference's memstore
        soft-limit rejection): the admission state machine
        (tablet/admission.py) scores SST-file, memstore-tracker and
        WAL-backlog pressure — between soft and hard each write is
        delayed proportionally, giving flushes/compactions bandwidth to
        catch up; at a hard limit writes are rejected retryably with
        typed Overloaded throttle extras."""
        self.admission.admit()

    def block_writes(self) -> None:
        """Reject new writes and drain in-flight ones (split prelude)."""
        with self._write_gate:
            self._writes_blocked = True
            while self._inflight_writes:
                self._write_gate.wait()

    def unblock_writes(self) -> None:
        with self._write_gate:
            self._writes_blocked = False

    def _write_locked(self, ops: Sequence[QLWriteOp],
                      timeout_s: float, request=None) -> HybridTime:
        t0 = time.monotonic()
        lock_batch, kv_pairs = prepare_and_assemble(
            ops, self.schema, self.lock_manager, timeout_s=timeout_s)
        try:
            # Even single-shard writes must not stomp on live provisional
            # records (ref write_query.cc:429 conflict resolution for
            # non-transactional writes). Skipped entirely while the intents
            # DB is empty — the overwhelmingly common case.
            if self.intents_db.approx_entry_count():
                from yugabyte_tpu.docdb.conflict_resolution import (
                    resolve_write_conflicts)
                resolve_write_conflicts(self.intents_db, self.regular_db,
                                        lock_batch.entries, None,
                                        self.status_resolver)
            # Hybrid-time draw + registration is atomic inside MvccManager;
            # the apply itself runs concurrently across writers (each KV
            # carries its own DocHybridTime, so apply order is irrelevant)
            # and MvccManager drains completions in hybrid-time order.
            ht = self.mvcc.add_pending_now()
            try:
                self.consensus.submit(kv_pairs, ht, timeout_s=timeout_s,
                                      request=request)
            except OperationOutcomeUnknown:
                # Fate unknown: the consensus seam registered a fate watcher
                # that resolves the MVCC registration when the entry commits
                # or is overwritten. Aborting here would let safe time
                # advance past a write that may yet land.
                raise
            except BaseException:
                self.mvcc.aborted(ht)
                raise
            self.mvcc.replicated(ht)
        finally:
            lock_batch.release()
        self.metric_rows_inserted.increment(len(ops))
        self.metric_write_latency.increment((time.monotonic() - t0) * 1e6)
        # group-commit accounting: this batch rode ONE raft replicate /
        # WAL append / apply_write_batch regardless of its op count
        from yugabyte_tpu.utils.metrics import serve_path_metrics
        m = serve_path_metrics()
        m.counter("write_group_commit_total",
                  "write batches replicated as ONE raft entry").increment()
        m.histogram("write_batch_rows",
                    "rows per group-committed write batch").increment(
            len(ops))
        if len(ops) > 1:
            m.counter("write_batch_coalesced_ops_total",
                      "ops that rode a multi-op group commit").increment(
                len(ops))
        return ht

    def apply_external_batch(self, kvs: Sequence[Sequence],
                             default_ht_value: int,
                             timeout_s: float = 30.0) -> HybridTime:
        """xCluster consumer apply: raw DocDB (key, value, ht_override)
        triples from a source cluster, replicated through THIS tablet's
        Raft with the source hybrid times preserved as per-entry overrides
        (ref: twodc_output_client.cc external hybrid times). Bypasses the
        QL write pipeline: entries are already DocDB-encoded and the
        target is passive for replicated ranges."""
        self._check_write_backpressure()  # replication also yields to
        # compactions — an unthrottled source would grow target L0 forever
        self.clock.update(HybridTime(default_ht_value))
        triples = [(bytes(k), bytes(v),
                    int(o) if o else default_ht_value)
                   for k, v, o in kvs]
        # same gate as every other write path: an apply racing a split's
        # write drain would land in the retiring parent and never reach
        # the children
        with self._write_gate:
            if self._writes_blocked or self.split_children is not None:
                raise TabletHasBeenSplit(self.split_children or ())
            self._inflight_writes += 1
        try:
            ht = self.mvcc.add_pending_now()
            try:
                self.consensus.submit(triples, ht, timeout_s=timeout_s)
            except OperationOutcomeUnknown:
                raise
            except BaseException:
                self.mvcc.aborted(ht)
                raise
            self.mvcc.replicated(ht)
            return ht
        finally:
            with self._write_gate:
                self._inflight_writes -= 1
                self._write_gate.notify_all()

    def apply_write_batch(self, kv_pairs: Sequence[Tuple],
                          ht: HybridTime, op_id: Tuple[int, int]) -> None:
        """Apply an already-replicated batch to regular_db. Position within
        the batch becomes the DocHybridTime write_id (ref tablet.cc:1198).
        An item may carry a per-entry hybrid-time override as a third
        element (index backfill, ref tablet.cc:2088)."""
        items = []
        for write_id, it in enumerate(kv_pairs):
            ht_i = HybridTime(it[2]) if len(it) == 3 and it[2] else ht
            items.append((it[0], DocHybridTime(ht_i, write_id), it[1]))
        self.regular_db.write_batch(items, op_id=op_id)
        TRACE("tablet %s applied %d kvs at %s", self.tablet_id, len(items), ht)

    # ------------------------------------------------------- transactions
    def write_transactional(self, ops: Sequence[QLWriteOp], txn_meta,
                            timeout_s: float = 10.0,
                            write_id_base: int = 0) -> HybridTime:
        """Transactional write: conflict-check, then replicate provisional
        records into the intents DB (ref write_query.cc:464 +
        docdb.h PrepareTransactionWriteBatch). Data becomes visible only
        when the coordinator commits and intents apply."""
        from yugabyte_tpu.docdb.conflict_resolution import (
            resolve_write_conflicts)
        from yugabyte_tpu.docdb.intents import make_intent_batch
        self._check_write_backpressure()  # both write entry points throttle
        with self._write_gate:
            if self._writes_blocked or self.split_children is not None:
                raise TabletHasBeenSplit(self.split_children or ())
            self._inflight_writes += 1
        try:
            lock_batch, kv_pairs = prepare_and_assemble(
                ops, self.schema, self.lock_manager, timeout_s=timeout_s)
            # backfill-ht overrides apply only to regular (non-transactional)
            # writes; intents are always stamped at commit time
            kv_pairs = [(p[0], p[1]) for p in kv_pairs]
            from yugabyte_tpu.utils.status import Status, StatusError
            if write_id_base and len(kv_pairs) > (1 << 16):
                # each statement owns a 2^16 IntraTxnWriteId slot
                # (client/transaction.py); overflowing into the next
                # statement's slot would silently re-introduce the
                # same-commit-ht shadowing bug the slots prevent
                raise StatusError(Status.InvalidArgument(
                    f"transaction statement writes {len(kv_pairs)} "
                    f"sub-writes (max {1 << 16}); split the batch"))
            try:
                resolve_write_conflicts(self.intents_db, self.regular_db,
                                        lock_batch.entries, txn_meta,
                                        self.status_resolver)
                intent_items = make_intent_batch(txn_meta, kv_pairs,
                                                 lock_batch.entries,
                                                 write_id_base=write_id_base)
                ht = self.mvcc.add_pending_now()
                try:
                    self.consensus.submit(intent_items, ht,
                                          timeout_s=timeout_s,
                                          target_intents=True)
                except OperationOutcomeUnknown:
                    raise
                except BaseException:
                    self.mvcc.aborted(ht)
                    raise
                self.mvcc.replicated(ht)
                return ht
            finally:
                lock_batch.release()
        finally:
            with self._write_gate:
                self._inflight_writes -= 1
                self._write_gate.notify_all()

    def apply_intent_batch(self, kv_pairs: Sequence[Tuple[bytes, bytes]],
                           ht: HybridTime, op_id: Tuple[int, int]) -> None:
        """Replicated-apply of provisional records into intents_db."""
        items = [(key, DocHybridTime(ht, write_id), value)
                 for write_id, (key, value) in enumerate(kv_pairs)]
        self.intents_db.write_batch(items, op_id=op_id)

    def apply_txn_update(self, action: str, txn_id: bytes,
                         commit_ht_value: int, resolution_ht_value: int,
                         op_id: Tuple[int, int]) -> None:
        """Replicated-apply of a transaction resolution (ref
        tablet.cc:1670 ApplyIntents / :1735 RemoveIntents). `apply` moves
        committed intents into regular_db at the commit hybrid time;
        `cleanup` just tombstones them. Deterministic across replicas: all
        hybrid times come from the raft entry."""
        from yugabyte_tpu.docdb.intents import (
            decode_intent_key, decode_intent_value, reverse_index_prefix,
            txn_intents)
        from yugabyte_tpu.docdb.lock_manager import IntentType
        from yugabyte_tpu.docdb.value import Value
        intents = txn_intents(self.intents_db, txn_id)
        regular_items = []
        tombstones = []
        tomb = Value.tombstone().encode()
        seq = 0
        for intent_key, _dht, raw in intents:
            decoded = decode_intent_key(intent_key)
            if decoded is None:
                continue
            subdoc_key, itype = decoded
            if action == "apply" and itype == IntentType.kStrongWrite:
                _txn, _st, write_id, value_bytes = decode_intent_value(raw)
                regular_items.append(
                    (subdoc_key,
                     DocHybridTime(HybridTime(commit_ht_value), write_id),
                     value_bytes))
            tombstones.append(
                (intent_key,
                 DocHybridTime(HybridTime(resolution_ht_value), seq), tomb))
            seq += 1
        # Reverse-index records get tombstoned too.
        prefix = reverse_index_prefix(txn_id)
        seen = set()
        for ikey, raw in self.intents_db.iter_from(prefix):
            from yugabyte_tpu.docdb.doc_key import split_key_and_ht
            rkey, dht = split_key_and_ht(ikey)
            if dht is None or not rkey.startswith(prefix):
                break
            if rkey in seen:
                continue
            seen.add(rkey)
            tombstones.append(
                (rkey, DocHybridTime(HybridTime(resolution_ht_value), seq),
                 tomb))
            seq += 1
        from yugabyte_tpu.utils import sync_point
        sync_point.hit("tablet.apply_txn:before_regular_write")
        if regular_items:
            self.regular_db.write_batch(regular_items, op_id=op_id)
        sync_point.hit("tablet.apply_txn:between_dbs")
        if tombstones:
            self.intents_db.write_batch(tombstones, op_id=op_id)
        TRACE("tablet %s: txn %s %s — %d applied, %d intents resolved",
              self.tablet_id, txn_id.hex()[:8], action, len(regular_items),
              len(tombstones))

    # ------------------------------------------------------------------- read
    def read_time(self, read_ht: Optional[HybridTime] = None,
                  timeout_s: float = 10.0) -> HybridTime:
        """Pick/validate a read point: wait until SafeTime >= read_ht (ref:
        read_query.cc:521 ScopedReadOperation + mvcc.h:135)."""
        if read_ht is None:
            return self.mvcc.safe_time(timeout_s=timeout_s)
        self.mvcc.safe_time(min_allowed=read_ht, timeout_s=timeout_s)
        return read_ht

    def read_row(self, doc_key: DocKey, read_ht: Optional[HybridTime] = None,
                 projection=None, txn_id: Optional[bytes] = None
                 ) -> Optional[Row]:
        ht = self.read_time(read_ht)
        self.metric_reads.increment()
        encoded = doc_key.encode()
        stream = self._entry_stream(ht, encoded,
                                    encoded + bytes([ValueType.kMaxByte]),
                                    txn_id)
        return read_row(self.regular_db, self.schema, doc_key, ht,
                        projection=projection, entry_stream=stream)

    def multi_read(self, doc_keys, read_ht: Optional[HybridTime] = None,
                   projection=None, txn_id: Optional[bytes] = None):
        """Batched point-row reads: one result per doc key, aligned with
        the input (None = row absent). Semantically N read_row calls at
        one shared read point, but the SST probes of every FLAT row go
        through ONE DB.multi_get batch (the device point-read kernels,
        ops/point_read.py) instead of a per-row iterator walk.

        Fast-path preconditions — any row outside them resolves through
        the exact read_row path: no transaction context, an empty intent
        overlay, no live SST holding deep documents (regular_db
        has_deep_files), and no memtable entry of the row off the
        enumerated (liveness + schema value columns) key set. Rows whose
        only surviving data lives at non-schema column ids inside SSTs
        (dropped columns) are the one documented divergence — they need
        the full iterator to prove existence."""
        ht = self.read_time(read_ht)
        if txn_id is not None \
                or self.intents_db.approx_entry_count() != 0 \
                or self.regular_db.has_deep_files():
            return [self.read_row(dk, ht, projection, txn_id=txn_id)
                    for dk in doc_keys]
        from yugabyte_tpu.docdb.doc_operations import (column_key_suffix,
                                                       kLivenessColumnId)
        schema = self.schema
        cids = [kLivenessColumnId] + [schema.column_id(c.name)
                                      for c in schema.value_columns]
        suffixes = [column_key_suffix(cid) for cid in cids]
        cid_by_suffix = dict(zip(suffixes, cids))
        # projection names -> ids ONCE per batch (mirrors
        # VisibleEntryRowAssembler: unknown names never match)
        proj_ids = None
        if projection is not None:
            proj_ids = set()
            for cname in projection:
                try:
                    proj_ids.add(cname if isinstance(cname, int)
                                 else schema.column_id(cname))
                except KeyError:
                    pass
        keys: list = []
        dkls: list = []
        spans = []          # per doc key: (start, count) into keys
        row_keys_by = []
        fallback = set()    # row indexes that need the exact path
        encs = []
        for ri, dk in enumerate(doc_keys):
            self.metric_reads.increment()
            enc = dk.encode()
            encs.append(enc)
            upper = enc + bytes([ValueType.kMaxByte])
            enumerated = sorted([enc] + [enc + s for s in suffixes])
            enum_set = set(enumerated)
            # memtable probe: recent writes at non-enumerated subkeys
            # (deep documents, unknown cids) make this row non-flat
            from yugabyte_tpu.docdb.doc_key import split_key_and_ht
            for ikey, _v in self.regular_db.mem_entries_range(enc, upper):
                prefix, dht = split_key_and_ht(ikey)
                if dht is None or prefix not in enum_set:
                    fallback.add(ri)
                    break
            row_keys_by.append(enumerated)
            spans.append((len(keys), len(enumerated)))
            keys.extend(enumerated)
            dkls.extend([len(enc)] * len(enumerated))
        results = self.regular_db.multi_get(keys, ht, doc_key_lens=dkls)
        from yugabyte_tpu.utils import latency as _latency
        rows = []
        asm_s = fb_s = 0.0
        for ri, dk in enumerate(doc_keys):
            if ri in fallback:
                t0 = time.monotonic()
                rows.append(self.read_row(dk, ht, projection))
                fb_s += time.monotonic() - t0
                continue
            start, count = spans[ri]
            t0 = time.monotonic()
            rows.append(self._assemble_flat_row(
                dk, encs[ri], row_keys_by[ri],
                results[start: start + count], ht, proj_ids,
                cid_by_suffix))
            asm_s += time.monotonic() - t0
        _latency.record_stage(_latency.STAGE_ROW_ASSEMBLY, asm_s * 1e3)
        _latency.record_stage(_latency.STAGE_HOST_FALLBACK, fb_s * 1e3)
        return rows

    def _assemble_flat_row(self, doc_key, enc: bytes, row_keys,
                           row_results, ht: HybridTime, proj_ids,
                           cid_by_suffix):
        """RESOLVE + ASSEMBLE one flat row from exact-key probe results,
        mirroring DocRowwiseIterator._resolve_visible +
        VisibleEntryRowAssembler for depth <= 1: the newest visible
        version per path is already in hand (multi_get semantics); drop
        tombstones/expired values, apply the bare-DocKey overwrite
        point, then build the Row DIRECTLY — every probe key came from
        our own enumeration, so its column id is the suffix we appended
        (no SubDocKey re-decode per entry)."""
        from yugabyte_tpu.docdb.doc_operations import kLivenessColumnId
        from yugabyte_tpu.docdb.doc_rowwise_iterator import Row, _is_expired
        from yugabyte_tpu.docdb.value import Value as DocValue
        bare_dht = None
        for k, res in zip(row_keys, row_results):
            if res is not None and k == enc:
                bare_dht = res[0]
        columns = {}
        liveness = False
        max_ht = 0
        n_enc = len(enc)
        for k, res in zip(row_keys, row_results):
            if res is None:
                continue
            dht, raw = res
            value = DocValue.decode(raw)
            if (value.is_tombstone or _is_expired(value, dht, ht)
                    or (k != enc and bare_dht is not None
                        and dht < bare_dht)):
                continue
            ht_value = dht.ht.value
            if ht_value > max_ht:
                max_ht = ht_value
            if k == enc:
                liveness = True  # visible init marker
                continue
            cid = cid_by_suffix[k[n_enc:]]
            liveness = True  # any visible column proves the row exists
            if cid == kLivenessColumnId:
                continue
            if proj_ids is not None and cid not in proj_ids:
                continue
            columns[cid] = {} if value.is_object else value.primitive
        if not liveness:
            return None
        return Row(doc_key, columns, HybridTime(max_ht))

    def _entry_stream(self, ht: HybridTime, lower: bytes,
                      upper: Optional[bytes], txn_id: Optional[bytes]):
        """Intent-aware merged stream, or None for the plain fast path when
        no provisional records can exist (ref intent_aware_iterator.h)."""
        from yugabyte_tpu.docdb.intent_aware_iterator import (
            intent_overlay_entries, merged_entry_stream)
        if txn_id is None and self.intents_db.approx_entry_count() == 0:
            return None
        overlay = intent_overlay_entries(
            self.intents_db, ht, txn_id, self.status_resolver,
            lower=lower, upper=upper)
        if not overlay and txn_id is None:
            return None
        return merged_entry_stream(self.regular_db, overlay, lower=lower)

    def scan(self, read_ht: Optional[HybridTime] = None,
             lower_doc_key: bytes = b"", upper_doc_key: Optional[bytes] = None,
             projection=None, use_device: Optional[bool] = None,
             txn_id: Optional[bytes] = None):
        """Range scan. use_device: True forces the TPU scan kernel, False the
        CPU iterator, None auto-picks: device path only for FULL-table scans
        on a device-configured tablet — the kernel resolves the whole DB in
        one fused program (great for big scans), while bounded scans seek
        directly to their range on the CPU iterator (ref: the reference
        always walks DocRowwiseIterator; here ops/scan.py)."""
        ht = self.read_time(read_ht)
        # Clamp to this tablet's key bounds (split children share the
        # parent's LSM files until post-split compaction).
        if self.opts.lower_bound_key:
            lower_doc_key = max(lower_doc_key, self.opts.lower_bound_key)
        if self.opts.upper_bound_key is not None:
            upper_doc_key = (self.opts.upper_bound_key
                             if upper_doc_key is None
                             else min(upper_doc_key,
                                      self.opts.upper_bound_key))
        stream = self._entry_stream(ht, lower_doc_key, upper_doc_key,
                                    txn_id)
        if use_device is None:
            use_device = (self.opts.device is not None
                          and self.opts.device != "native"
                          and not lower_doc_key and upper_doc_key is None
                          and stream is None)
        if use_device and stream is None:
            entries = self.regular_db.scan_visible(
                ht.value, lower_doc_key or None, upper_doc_key)
            return VisibleEntryRowAssembler(entries, self.schema,
                                            projection=projection)
        return DocRowwiseIterator(self.regular_db, self.schema, ht,
                                  lower_doc_key=lower_doc_key,
                                  upper_doc_key=upper_doc_key,
                                  projection=projection,
                                  entry_stream=stream)

    # ------------------------------------------------------ query pushdown
    def _pushdown_gate(self, ht: HybridTime, lower: bytes,
                       upper: Optional[bytes],
                       txn_id: Optional[bytes]) -> Optional[str]:
        """Why THIS scan cannot ride the fused pushdown kernels, or None
        when it can (flag off, no device, or provisional records that
        need the intent-aware host merge)."""
        if not flags.get_flag("scan_pushdown"):
            return "disabled"
        if self.opts.device is None or self.opts.device == "native":
            return "device"
        if self.regular_db.approx_row_entries() \
                < flags.get_flag("scan_pushdown_min_rows"):
            return "small"
        if self._entry_stream(ht, lower, upper, txn_id) is not None:
            return "intents"
        return None

    def _clamp_scan_bounds(self, lower_doc_key: bytes,
                           upper_doc_key: Optional[bytes]):
        if self.opts.lower_bound_key:
            lower_doc_key = max(lower_doc_key, self.opts.lower_bound_key)
        if self.opts.upper_bound_key is not None:
            upper_doc_key = (self.opts.upper_bound_key
                             if upper_doc_key is None
                             else min(upper_doc_key,
                                      self.opts.upper_bound_key))
        return lower_doc_key, upper_doc_key

    def scan_pushdown(self, read_ht: Optional[HybridTime] = None,
                      lower_doc_key: bytes = b"",
                      upper_doc_key: Optional[bytes] = None,
                      projection=None, spec=None,
                      txn_id: Optional[bytes] = None):
        """Fused filtered scan (ROADMAP item 5): rows satisfying
        spec.predicates assembled from one device dispatch, or None when
        this scan must fall back to the host path (reason counted in
        scan_pushdown_fallback_*_total; results identical either way)."""
        from yugabyte_tpu.docdb.scan_spec import PushdownUnsupported
        from yugabyte_tpu.ops.scan import count_pushdown_fallback
        if spec is None or not spec.predicates:
            return None
        ht = self.read_time(read_ht)
        lower_doc_key, upper_doc_key = self._clamp_scan_bounds(
            lower_doc_key, upper_doc_key)
        reason = self._pushdown_gate(ht, lower_doc_key, upper_doc_key,
                                     txn_id)
        if reason is not None:
            count_pushdown_fallback(reason)
            return None
        try:
            entries = self.regular_db.scan_filtered(
                ht.value, spec, lower_doc_key or None, upper_doc_key)
        except PushdownUnsupported as e:  # yblint: contained(typed refusal, not an error: the caller serves the SAME query through the byte-identical host path; the reason is counted for the offload policy)
            count_pushdown_fallback(e.reason)
            return None
        return VisibleEntryRowAssembler(entries, self.schema,
                                        projection=projection)

    def scan_aggregate(self, read_ht: Optional[HybridTime] = None,
                       lower_doc_key: bytes = b"",
                       upper_doc_key: Optional[bytes] = None,
                       spec=None,
                       txn_id: Optional[bytes] = None) -> Optional[dict]:
        """Fused aggregating scan: the aggregate partial for this
        tablet's row range ({"rows", "cols"}), or None when the query
        must fall back to the row path (the caller re-aggregates rows
        host-side — byte/result-identical by construction)."""
        from yugabyte_tpu.docdb.scan_spec import PushdownUnsupported
        from yugabyte_tpu.ops.scan import count_pushdown_fallback
        if spec is None or not spec.aggregates:
            return None
        ht = self.read_time(read_ht)
        lower_doc_key, upper_doc_key = self._clamp_scan_bounds(
            lower_doc_key, upper_doc_key)
        reason = self._pushdown_gate(ht, lower_doc_key, upper_doc_key,
                                     txn_id)
        if reason is not None:
            count_pushdown_fallback(reason)
            return None
        try:
            return self.regular_db.scan_aggregate(
                ht.value, spec, lower_doc_key or None, upper_doc_key)
        except PushdownUnsupported as e:  # yblint: contained(typed refusal: caller re-aggregates rows host-side, result-identical; reason counted)
            count_pushdown_fallback(e.reason)
            return None

    # ------------------------------------------------------------ maintenance
    def write_subdocument(self, doc_key: DocKey, path, doc,
                          timeout_s: float = 10.0):
        """Replicated arbitrary-depth subdocument write (ref
        doc_write_batch.cc InsertSubDocument): a dict becomes an object
        init marker + leaves; the marker overwrites the older subtree."""
        from yugabyte_tpu.docdb.subdocument import subdocument_writes
        ht = self.clock.now()
        kvs = subdocument_writes(doc_key, tuple(path), doc)
        return self.consensus.submit(kvs, ht, timeout_s=timeout_s)

    def delete_subdocument(self, doc_key: DocKey, path,
                           timeout_s: float = 10.0):
        from yugabyte_tpu.docdb.subdocument import delete_subdocument
        ht = self.clock.now()
        return self.consensus.submit(delete_subdocument(doc_key,
                                                        tuple(path)),
                                     ht, timeout_s=timeout_s)

    def read_subdocument(self, doc_key: DocKey, path=(),
                         read_ht=None):
        """Visible subdocument at read_ht (nested dict / primitive /
        None), honoring the ancestor overwrite stack."""
        from yugabyte_tpu.docdb.subdocument import read_subdocument
        ht = self.read_time(read_ht)
        return read_subdocument(self.regular_db, doc_key, tuple(path), ht)

    def memstore_bytes(self) -> int:
        return (self.regular_db.memstore_bytes()
                + self.intents_db.memstore_bytes())

    def oldest_memstore_write_s(self):
        times = [self.regular_db.oldest_memstore_write_s(),
                 self.intents_db.oldest_memstore_write_s()]
        times = [t for t in times if t is not None]
        return min(times) if times else None

    def flush(self) -> None:
        self.regular_db.flush()
        self.intents_db.flush()

    def compact(self) -> None:
        self.regular_db.compact_all()

    def scrub(self, limiter=None, cancel=None) -> dict:
        """At-rest integrity scrub of both DBs (block CRCs + footer +
        index/bloom consistency, throttled; storage/integrity.py). A
        corrupt file parks its DB with a sticky Corruption error, which
        fails this tablet for rebuild-from-peer. Returns the merged
        report."""
        merged = {"files": 0, "blocks": 0, "entries": 0, "bytes": 0,
                  "corrupt": []}
        for db in (self.regular_db, self.intents_db):
            rep = db.scrub(limiter=limiter, cancel=cancel)
            for k in ("files", "blocks", "entries", "bytes"):
                merged[k] += rep[k]
            merged["corrupt"].extend(rep["corrupt"])
        return merged

    def checkpoint(self, out_dir: str) -> None:
        """Hard-link snapshot of both DBs (remote bootstrap / backup input)."""
        self.flush()
        self.regular_db.checkpoint(os.path.join(out_dir, "regular"))
        self.intents_db.checkpoint(os.path.join(out_dir, "intents"))

    # -------------------------------------------------------------- snapshots
    def snapshots_dir(self) -> str:
        return os.path.join(
            os.path.dirname(self.regular_db.db_dir), "snapshots")

    def create_snapshot(self, snapshot_id: str) -> str:
        """Raft-applied snapshot: every replica checkpoints the identical
        applied state under snapshots/<id> (ref tablet/
        snapshot_coordinator.h + ent tserver/backup_service.cc). Idempotent
        for replay."""
        sdir = os.path.join(self.snapshots_dir(), snapshot_id)
        if os.path.exists(sdir):
            return sdir
        tmp = sdir + ".tmp"
        import shutil as _sh
        _sh.rmtree(tmp, ignore_errors=True)
        self.flush()
        self.regular_db.checkpoint(os.path.join(tmp, "regular"))
        self.intents_db.checkpoint(os.path.join(tmp, "intents"))
        os.rename(tmp, sdir)
        TRACE("tablet %s: snapshot %s created", self.tablet_id, snapshot_id)
        return sdir

    def delete_snapshot(self, snapshot_id: str) -> None:
        import shutil as _sh
        _sh.rmtree(os.path.join(self.snapshots_dir(), snapshot_id),
                   ignore_errors=True)

    def list_snapshots(self) -> List[str]:
        d = self.snapshots_dir()
        if not os.path.isdir(d):
            return []
        return sorted(s for s in os.listdir(d) if not s.endswith(".tmp"))

    def split_partition_key(self, hash_partitioning: bool) -> Optional[bytes]:
        """Partition-key-space split point derived from the median doc key
        (hash partitioning: the 2-byte bucket right after the kUInt16Hash
        tag; range partitioning: the encoded doc key itself)."""
        median = self.split_key()
        if median is None:
            return None
        if hash_partitioning:
            return median[1:3] if len(median) >= 3 else None
        return median

    def split_key(self) -> Optional[bytes]:
        """Encoded middle DocKey for tablet splitting (ref tablet.cc:3427
        GetEncodedMiddleSplitKey): median doc key of the live data."""
        docs: List[bytes] = []
        last = None
        for ikey, _v in self.regular_db.iter_from(b""):
            from yugabyte_tpu.docdb.doc_key import split_key_and_ht
            prefix, _ = split_key_and_ht(ikey)
            doc = prefix[:_doc_key_len(prefix)]
            if doc != last:
                docs.append(doc)
                last = doc
        if len(docs) < 2:
            return None
        return docs[len(docs) // 2]

    def cancel_background_work(self, reason: str = "tablet failed") -> None:
        """Abort in-flight background compactions of both DBs at their
        next pipeline-stage boundary (tablet-FAILED / shutdown): a dying
        tablet must not keep a device-offload job running against
        storage that is about to be torn down or re-bootstrapped."""
        self.regular_db.cancel_background_work(reason)
        self.intents_db.cancel_background_work(reason)

    def close(self) -> None:
        self.regular_db.close()
        self.intents_db.close()
