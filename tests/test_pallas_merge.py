"""Differential tests for the pallas merge-path kernel (ops/pallas_merge.py).

The round-4 flagship: merge-path diagonal splits + per-tile VMEM bitonic
merges, run in interpret mode on the CPU backend here.  Every case must
produce BYTE-IDENTICAL decisions to the jnp merge network and the native
C++ baseline — three independent implementations of the same comparator.
"""

import numpy as np
import pytest

from tests.test_run_merge import _make_run
from yugabyte_tpu.ops import pallas_merge, run_merge
from yugabyte_tpu.ops.merge_gc import GCParams
from yugabyte_tpu.ops.slabs import concat_slabs
from yugabyte_tpu.storage.cpu_baseline import compact_cpu_baseline


@pytest.fixture(autouse=True)
def _force_pallas(monkeypatch):
    monkeypatch.setenv("YBTPU_MERGE_IMPL", "pallas")
    monkeypatch.setenv("YBTPU_PALLAS_TILE", "128")


def _three_way(runs, cutoff, is_major, retain_deletes=False, snapshot=False,
               baseline=True):
    params = GCParams(cutoff, is_major, retain_deletes)
    staged = run_merge.stage_runs_from_slabs(runs)
    assert pallas_merge.supported(staged), "pallas preconditions must hold"
    h = pallas_merge.launch_merge_gc_pallas(staged, params, snapshot=snapshot)
    perm_p, keep_p, mk_p = h.result()

    staged2 = run_merge.stage_runs_from_slabs(runs)
    # jnp network on an identical staging (bypass _pick_impl)
    from yugabyte_tpu.ops.run_merge import MergeGCHandle, _merge_gc_runs_fused
    import jax.numpy as jnp
    cutoff_phys = cutoff >> 12
    pos = jnp.arange(staged2.n_pad, dtype=jnp.int32)
    packed, perm, keep, mk = _merge_gc_runs_fused(
        staged2.cols_dev, jnp.asarray(staged2.cmp_rows), pos,
        jnp.uint32(cutoff >> 32), jnp.uint32(cutoff & 0xFFFFFFFF),
        jnp.uint32(cutoff_phys >> 20), jnp.uint32(cutoff_phys & 0xFFFFF),
        k_pad=staged2.k_pad, m=staged2.m, w=staged2.w, n_cmp=staged2.n_cmp,
        is_major=is_major, retain_deletes=retain_deletes, snapshot=snapshot)
    perm_n, keep_n, mk_n = MergeGCHandle(packed, staged2, perm, keep,
                                         mk).result()

    assert np.array_equal(perm_p, perm_n), "merge order diverges from network"
    assert np.array_equal(keep_p, keep_n)
    assert np.array_equal(mk_p, mk_n)

    if not snapshot and baseline:
        merged = concat_slabs(runs)
        offsets = np.concatenate(
            ([0], np.cumsum([r.n for r in runs]))).tolist()
        order_c, keep_c, mk_c = compact_cpu_baseline(
            merged, offsets, cutoff, is_major, retain_deletes)
        assert np.array_equal(perm_p[keep_p], order_c[keep_c])
        assert np.array_equal(perm_p[mk_p], order_c[mk_c])
    return perm_p, keep_p


@pytest.mark.parametrize("k,seed", [(2, 0), (3, 1), (4, 2), (5, 3), (8, 4)])
def test_differential_multi_run(k, seed):
    rng = np.random.default_rng(seed)
    runs = [_make_run(rng, int(rng.integers(50, 400)), key_space=60)
            for _ in range(k)]
    _three_way(runs, cutoff=(1 << 21) << 12, is_major=True)
    _three_way(runs, cutoff=(1 << 19) << 12, is_major=False)


def test_unequal_run_sizes():
    rng = np.random.default_rng(11)
    runs = [_make_run(rng, n, key_space=100) for n in (1000, 17, 3, 260)]
    _three_way(runs, cutoff=(1 << 20) << 12, is_major=True)


def test_ttl_and_retain_deletes():
    rng = np.random.default_rng(13)
    runs = [_make_run(rng, 200, key_space=30, ttl_frac=0.4, tomb_frac=0.3)
            for _ in range(3)]
    _three_way(runs, cutoff=(1 << 22) << 12, is_major=False)
    _three_way(runs, cutoff=(1 << 22) << 12, is_major=True,
               retain_deletes=True)


def test_snapshot_scan_mode():
    rng = np.random.default_rng(17)
    runs = [_make_run(rng, 150, key_space=25) for _ in range(4)]
    _three_way(runs, cutoff=(1 << 19) << 12, is_major=False, snapshot=True)


def test_heavy_duplicates_cross_run_ties():
    """Many exact (key, ht, wid) collisions across runs: the index tiebreak
    must order them identically in both implementations."""
    rng = np.random.default_rng(23)
    runs = [_make_run(rng, 300, key_space=5, ht_lo_bits=4)
            for _ in range(4)]
    # exact (key, ht, wid) duplicates cannot occur physically (DocHybridTime
    # is unique per write); the C++ baseline keeps such duplicates while the
    # device GC collapses them, so only the pallas==network equivalence (the
    # point of this test: deterministic index tiebreak) is asserted here.
    _three_way(runs, cutoff=(1 << 10) << 12, is_major=True, baseline=False)


def test_auto_selection_prefers_network_on_cpu(monkeypatch):
    monkeypatch.setenv("YBTPU_MERGE_IMPL", "auto")
    rng = np.random.default_rng(29)
    runs = [_make_run(rng, 100, key_space=20) for _ in range(2)]
    # pack_runs=False: greedy run-packing would fold these two tiny runs
    # into one slot (k_pad=1, a GC-only launch) — this test probes impl
    # selection over a REAL 2-slot merge layout
    staged = run_merge.stage_runs_from_slabs(runs, pack_runs=False)
    assert run_merge._pick_impl(staged) == "network"
    monkeypatch.setenv("YBTPU_MERGE_IMPL", "pallas")
    assert run_merge._pick_impl(staged) == "pallas"


def test_merge_and_gc_runs_routes_to_pallas():
    """The public entry must produce baseline-identical results when the
    env forces the pallas implementation."""
    rng = np.random.default_rng(31)
    runs = [_make_run(rng, int(rng.integers(80, 300)), key_space=40)
            for _ in range(4)]
    cutoff = (1 << 20) << 12
    params = GCParams(cutoff, True)
    perm, keep, mk = run_merge.merge_and_gc_runs(runs, params)
    merged = concat_slabs(runs)
    offsets = np.concatenate(([0], np.cumsum([r.n for r in runs]))).tolist()
    order_c, keep_c, mk_c = compact_cpu_baseline(
        merged, offsets, cutoff, True)
    assert np.array_equal(perm[keep], order_c[keep_c])
