"""Batched device point-read path (ROADMAP item 4, PR perf_opt).

DB.multi_get must be BYTE-IDENTICAL to N sequential DB.get calls — with
the SST layer resolved through the vectorized bloom/locate/gather kernels
(ops/point_read.py) over HBM-resident slab matrices, memtable probes
host-side, and every degradation path (no device, quarantined bucket,
mid-batch device fault, learned-index misprediction) falling back exactly:

  - hit + miss mixes, MVCC read_ht snapshots, tombstones, memtable
    overlay, multi-version keys;
  - bloom probe bit-identical to storage/bloom.py, false positives
    resolved by the exact locate;
  - the learned per-SST index is ADVISORY: forced mispredictions are
    detected by the search-invariant check and re-resolve exactly; a
    model-bearing SST stays readable by the pre-model reader path;
  - device-fault injection at dispatch/result falls back byte-identically
    with zero leaked pins and a quarantined shape bucket;
  - read-path Corruption containment preserved (retryable
    ServiceUnavailable, never a raw Corruption).

The tablet layer rides it: Tablet/TabletPeer/TabletService.multi_read and
client.multi_read return rows identical to per-key read_row.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))

from yugabyte_tpu.common.hybrid_time import DocHybridTime, HybridTime  # noqa: E402
from yugabyte_tpu.ops import device_faults  # noqa: E402
from yugabyte_tpu.storage import learned_index  # noqa: E402
from yugabyte_tpu.storage import offload_policy  # noqa: E402
from yugabyte_tpu.storage.db import DB, DBOptions  # noqa: E402
from yugabyte_tpu.storage.device_cache import DeviceSlabCache  # noqa: E402
from yugabyte_tpu.storage.sst import SSTReader  # noqa: E402
from yugabyte_tpu.utils import flags  # noqa: E402
from yugabyte_tpu.utils.env import corrupt_file_range  # noqa: E402
from yugabyte_tpu.utils.status import Code, StatusError  # noqa: E402


def _device():
    import jax
    return jax.devices()[0]


@pytest.fixture(autouse=True)
def _clean_state():
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()
    yield
    device_faults.disarm_all()
    offload_policy.bucket_quarantine().clear()


def _key(i: int) -> bytes:
    return b"Suser%08d\x00\x00!" % i


def _tomb() -> bytes:
    from yugabyte_tpu.docdb.value import Value
    return Value.tombstone().encode()


def _fill_db(tmp_path, n_keys=1200, n_ssts=3, device=True,
             mem_overlay=True):
    """Keys across n_ssts SSTs with 1-2 versions, some tombstones, and a
    memtable overlay — the shapes a serving tablet's regular DB holds."""
    opts = DBOptions(auto_compact=False)
    if device:
        dev = _device()
        opts = DBOptions(device=dev,
                         device_cache=DeviceSlabCache(device=dev),
                         auto_compact=False)
    db = DB(str(tmp_path / "db"), opts)
    val = b"value-" + b"x" * 26
    for f in range(n_ssts):
        items = []
        for i in range(f, n_keys, n_ssts):
            v = _tomb() if i % 17 == 0 and f == 1 else val + b"%d" % f
            items.append((_key(i),
                          DocHybridTime(
                              HybridTime.from_micros(1000 + i + 7 * f),
                              f), v))
        db.write_batch(items, op_id=(1, f + 1))
        db.flush()
    if mem_overlay:
        items = [(_key(i), DocHybridTime(HybridTime.from_micros(99_999),
                                         1), b"memval%d" % i)
                 for i in range(0, 120, 7)]
        db.write_batch(items, op_id=(1, n_ssts + 1))
    return db


def _query_keys(n_keys, rng, m=400):
    # hits, misses past the range, and misses interleaved in the range
    ids = list(rng.integers(0, n_keys + 200, size=m))
    return [_key(int(i)) for i in ids]


# ---------------------------------------------------------------- identity
class TestByteIdentity:
    def test_multi_get_equals_sequential_gets(self, tmp_path):
        db = _fill_db(tmp_path)
        rng = np.random.default_rng(7)
        keys = _query_keys(1200, rng)
        try:
            for read_ht in (None, HybridTime.from_micros(1400),
                            HybridTime.from_micros(50_000),
                            HybridTime.from_micros(100_000)):
                seq = [db.get(k, read_ht) for k in keys]
                assert db.multi_get(keys, read_ht) == seq, read_ht
            # the batched path actually ran (not a silent fallback)
            from yugabyte_tpu.ops.point_read import point_read_metrics
            assert point_read_metrics()["batches"].value() > 0
        finally:
            db.close()

    def test_multi_get_native_fallback_identical(self, tmp_path):
        db = _fill_db(tmp_path)
        rng = np.random.default_rng(8)
        keys = _query_keys(1200, rng)
        try:
            dev = db.multi_get(keys)
            flags.set_flag("point_read_batched", False)
            try:
                nat = db.multi_get(keys)
            finally:
                flags.set_flag("point_read_batched", True)
            assert dev == nat == [db.get(k) for k in keys]
        finally:
            db.close()

    def test_multi_get_no_device_db(self, tmp_path):
        """A deviceless DB serves multi_get through the native per-key
        path (storage/native_read.py) — identical results."""
        db = _fill_db(tmp_path, device=False)
        rng = np.random.default_rng(9)
        keys = _query_keys(1200, rng)
        try:
            assert db.multi_get(keys) == [db.get(k) for k in keys]
        finally:
            db.close()

    def test_multi_get_edge_shapes(self, tmp_path):
        db = _fill_db(tmp_path, n_keys=400, mem_overlay=False)
        try:
            assert db.multi_get([]) == []
            # a key longer than any SST's key stride can never match
            long_key = _key(1) + b"\x00" * 64
            assert db.multi_get([long_key]) == [None]
            # read point below every write: nothing visible
            early = HybridTime.from_micros(1)
            assert db.multi_get([_key(3)], early) == [db.get(_key(3),
                                                             early)]
            # duplicate keys in one batch
            keys = [_key(5), _key(5), _key(9999), _key(5)]
            assert db.multi_get(keys) == [db.get(k) for k in keys]
        finally:
            db.close()


# ------------------------------------------------------------------ bloom
class TestBloom:
    def test_bloom_rejected_misses(self, tmp_path):
        import jax.numpy as jnp
        from yugabyte_tpu.ops import point_read as pr
        from yugabyte_tpu.ops.slabs import _doc_key_len, _pad_keys_to_words
        db = _fill_db(tmp_path, mem_overlay=False)
        try:
            from yugabyte_tpu.ops.point_read import point_read_metrics
            skips0 = point_read_metrics()["bloom_skips"].value()
            miss = [_key(5000 + i) for i in range(128)]
            # expected dispatch skips: SSTs whose bloom rejects EVERY
            # key of the batch (false positives may let a few through —
            # the exact locate resolves those to misses)
            dkls = np.asarray([_doc_key_len(k) for k in miss],
                              dtype=np.int32)
            words, _ = _pad_keys_to_words(miss, width_words=4)
            h1, h2 = pr._fnv64_fused(jnp.asarray(words),
                                     jnp.asarray(dkls), w=4)
            expected_skips = sum(
                1 for r in db._readers.values()
                if not np.asarray(pr.probe_bloom(r, h1, h2)
                                  )[:len(miss)].any())
            assert db.multi_get(miss) == [None] * len(miss)
            assert point_read_metrics()["bloom_skips"].value() \
                == skips0 + expected_skips
        finally:
            db.close()

    def test_device_probe_matches_cpu_bloom(self, tmp_path):
        """The kernel probe is bit-identical to the CPU bloom — false
        positives included (they are resolved by the exact locate)."""
        import jax.numpy as jnp
        from yugabyte_tpu.ops import point_read as pr
        from yugabyte_tpu.ops.slabs import _doc_key_len, _pad_keys_to_words
        from yugabyte_tpu.storage.bloom import fnv64_masked
        db = _fill_db(tmp_path, n_keys=600, n_ssts=1, mem_overlay=False)
        try:
            r = next(iter(db._readers.values()))
            keys = [_key(i) for i in range(0, 2000, 3)]
            dkls = np.asarray([_doc_key_len(k) for k in keys],
                              dtype=np.int64)
            w = 4
            words, _ = _pad_keys_to_words(keys, width_words=w)
            h1, h2 = pr._fnv64_fused(jnp.asarray(words),
                                     jnp.asarray(dkls.astype(np.int32)),
                                     w=w)
            dev = pr.probe_bloom(r, h1, h2)
            u8 = np.zeros((len(keys), w * 4), np.uint8)
            for i, k in enumerate(keys):
                u8[i, :len(k)] = np.frombuffer(k, np.uint8)
            cpu = r.bloom.may_contain_batch(fnv64_masked(u8, dkls))
            assert np.array_equal(dev[:len(keys)], cpu)
        finally:
            db.close()


# ---------------------------------------------------------- learned index
class TestLearnedIndex:
    def test_models_persisted_at_flush(self, tmp_path):
        db = _fill_db(tmp_path, mem_overlay=False)
        try:
            models = [r.props.lindex for r in db._readers.values()]
            assert all(m is not None for m in models), models
            for m in models:
                assert m["v"] == learned_index.MODEL_VERSION
                assert m["max_err"] <= learned_index.LINDEX_MAX_ERR
                # all-integer persistence: JSON round-trips exactly
                assert json.loads(json.dumps(m)) == m
        finally:
            db.close()

    def test_forced_mispredict_falls_back_exact(self, tmp_path):
        """A model whose anchors are garbage and whose error bound is a
        lie must change NOTHING: the search-invariant check flags every
        misprediction and those keys re-resolve exactly."""
        db = _fill_db(tmp_path)
        rng = np.random.default_rng(11)
        keys = _query_keys(1200, rng)
        try:
            expect = [db.get(k) for k in keys]
            from yugabyte_tpu.ops.point_read import point_read_metrics
            fb0 = point_read_metrics()["learned_fallbacks"].value()
            for fid, r in list(db._readers.items()):
                m = r.props.lindex
                if m is None:
                    continue
                bad = dict(m)
                bad["a_hi"] = list(reversed(m["a_hi"]))
                bad["a_lo"] = list(reversed(m["a_lo"]))
                bad["max_err"] = 0
                learned_index.attach_learned_index(r.base_path, bad)
                # reload the reader so the poisoned model serves
                db._readers[fid] = SSTReader(r.base_path,
                                             db.opts.block_cache)
                r.close()
            assert db.multi_get(keys) == expect
            assert point_read_metrics()["learned_fallbacks"].value() > fb0
        finally:
            db.close()

    def test_model_disabled_results_unchanged(self, tmp_path):
        db = _fill_db(tmp_path)
        rng = np.random.default_rng(12)
        keys = _query_keys(1200, rng)
        try:
            with_model = db.multi_get(keys)
            flags.set_flag("point_read_learned_index", False)
            try:
                without = db.multi_get(keys)
            finally:
                flags.set_flag("point_read_learned_index", True)
            assert with_model == without == [db.get(k) for k in keys]
        finally:
            db.close()

    def test_model_bearing_sst_readable_by_pre_model_path(self, tmp_path):
        """Format compatibility both ways: the lindex field is an
        OPTIONAL props key — the pre-model reader path (python
        iter_from/get, props parse) serves a model-bearing SST
        unchanged, and props without the field parse to None."""
        db = _fill_db(tmp_path, n_keys=600, n_ssts=1, mem_overlay=False)
        try:
            r = next(iter(db._readers.values()))
            assert r.props.lindex is not None
            # pre-model read paths: python merged iterator + bloom route
            flags.set_flag("read_native", False)
            flags.set_flag("point_read_batched", False)
            try:
                assert db.get(_key(3)) is not None
                assert db.get(_key(9999)) is None
                n_iter = sum(1 for _ in db.iter_from(b""))
                assert n_iter == r.props.n_entries
            finally:
                flags.set_flag("read_native", True)
                flags.set_flag("point_read_batched", True)
            # a pre-model properties dict (no lindex key) parses clean
            from yugabyte_tpu.storage.sst import SSTProps
            d = r.props.to_json()
            d.pop("lindex")
            assert SSTProps.from_json(d).lindex is None
        finally:
            db.close()

    def test_stale_model_ignored(self, tmp_path):
        """A model whose n disagrees with the file (stale/foreign) is
        advisory data — model_operands refuses it, the exact seek
        serves."""
        db = _fill_db(tmp_path, n_keys=600, n_ssts=1, mem_overlay=False)
        try:
            r = next(iter(db._readers.values()))
            m = dict(r.props.lindex)
            assert learned_index.model_operands(m,
                                               r.props.n_entries) \
                is not None
            m["n"] = m["n"] + 1
            assert learned_index.model_operands(m,
                                               r.props.n_entries) is None
            assert learned_index.model_operands(None, 100) is None
            assert learned_index.model_operands({"v": 99}, 100) is None
        finally:
            db.close()

    def test_device_and_host_fits_agree(self, tmp_path):
        """The device fit (staged cols in HBM) and the numpy twin must
        produce the SAME model for the same sorted keys."""
        from yugabyte_tpu.ops import point_read as pr
        from yugabyte_tpu.ops.merge_gc import stage_slab
        from yugabyte_tpu.ops.slabs import pack_kvs
        entries = [(_key(i), ((1000 + i) << 12 << 32), b"v%d" % i)
                   for i in range(800)]
        slab = pack_kvs(entries)
        host = learned_index.fit_from_slab(slab)
        dev = pr.fit_learned_index_device(stage_slab(slab, _device()))
        assert host == dev
        assert host["p"] >= 1  # the shared "Suser000…" prefix is skipped


# ----------------------------------------------------- fault containment
class TestDeviceFaults:
    @pytest.mark.parametrize("site", ["dispatch", "result"])
    @pytest.mark.parametrize("kind", ["compile", "oom", "runtime"])
    def test_fault_falls_back_byte_identical(self, tmp_path, site, kind):
        db = _fill_db(tmp_path)
        rng = np.random.default_rng(13)
        keys = _query_keys(1200, rng)
        try:
            expect = [db.get(k) for k in keys]
            from yugabyte_tpu.ops.point_read import point_read_metrics
            fb0 = point_read_metrics()["device_fallbacks"].value()
            device_faults.arm(kind, site, 1)
            assert db.multi_get(keys) == expect
            assert point_read_metrics()["device_fallbacks"].value() \
                == fb0 + 1
            # zero leaked pins on the fault path
            assert db._pins == {}
            # the shape bucket is parked native-only...
            snap = offload_policy.bucket_quarantine().snapshot()
            assert snap, "no bucket quarantined after a point-read fault"
            assert all(b["bucket"][0] == 1 for b in snap)
            # ...so the next batch routes native pre-dispatch (no
            # re-fault even if a fault is still armed)
            device_faults.arm(kind, site, 1)
            assert db.multi_get(keys) == expect
            assert device_faults.armed_count() == 1  # never consumed
        finally:
            device_faults.disarm_all()
            db.close()

    def test_corruption_containment(self, tmp_path):
        """A corrupt data block under the batched read parks the DB and
        surfaces RETRYABLY — never a raw Corruption (the client must
        walk to a healthy replica while the master rebuilds this one)."""
        db = _fill_db(tmp_path, mem_overlay=False)
        try:
            data_files = sorted(
                p for p in (os.path.join(db.db_dir, f)
                            for f in os.listdir(db.db_dir))
                if p.endswith(".sblock.0"))
            corrupt_file_range(data_files[0], length=64, nbits=3)
            # drop caches so the corrupt bytes are actually re-read
            for fid in list(db._readers):
                db._device_cache.drop(fid)
            keys = [_key(i) for i in range(0, 1200, 2)]
            with pytest.raises(StatusError) as ei:
                db.multi_get(keys)
            assert ei.value.status.code == Code.SERVICE_UNAVAILABLE
            assert db.background_error is not None
            assert db.background_error.code == Code.CORRUPTION
            assert db._pins == {}
        finally:
            db.close()


# ----------------------------------------------------------- tablet layer
SCHEMA = None


def _schema():
    global SCHEMA
    if SCHEMA is None:
        from yugabyte_tpu.common.schema import (ColumnSchema, DataType,
                                                Schema)
        SCHEMA = Schema(columns=[ColumnSchema("k", DataType.STRING),
                                 ColumnSchema("v", DataType.STRING),
                                 ColumnSchema("n", DataType.INT64)],
                        num_hash_key_columns=1)
    return SCHEMA


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from yugabyte_tpu.integration.mini_cluster import (MiniCluster,
                                                       MiniClusterOptions)
    flags.set_flag("replication_factor", 1)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=1,
        fs_root=str(tmp_path_factory.mktemp("pr-minicluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def table(cluster):
    client = cluster.new_client()
    client.create_namespace("db")
    t = client.create_table("db", "kv", _schema(), num_tablets=2)
    cluster.wait_all_replicas_running(t.table_id)
    cluster.wait_for_table_leaders("db", "kv")
    return t


def _dk(k: str):
    from yugabyte_tpu.docdb.doc_key import DocKey
    return DocKey(hash_components=(k,))


class TestMultiReadRPC:
    def _load(self, cluster, table):
        from yugabyte_tpu.docdb.doc_operations import (QLWriteOp,
                                                       WriteOpKind)
        client = cluster.new_client()
        ops = []
        for i in range(60):
            ops.append(QLWriteOp(WriteOpKind.INSERT, _dk(f"row{i:03d}"),
                                 {"v": f"val{i}", "n": i}))
        for op in ops:
            client.write(table, [op])
        # updates (newer versions), column tombstone via update-to-None,
        # and row deletes
        for i in range(0, 60, 5):
            client.write(table, [QLWriteOp(WriteOpKind.UPDATE,
                                           _dk(f"row{i:03d}"),
                                           {"v": f"val{i}-v2"})])
        for i in range(0, 60, 11):
            client.write(table, [QLWriteOp(WriteOpKind.DELETE_ROW,
                                           _dk(f"row{i:03d}"), {})])
        return client

    def test_multi_read_matches_read_row(self, cluster, table):
        client = self._load(cluster, table)
        dks = [_dk(f"row{i:03d}") for i in range(70)]  # incl. absent
        batched = client.multi_read(table, dks)
        seq = [client.read_row(table, dk) for dk in dks]
        assert len(batched) == len(seq)
        for b, s, dk in zip(batched, seq, dks):
            if s is None:
                assert b is None, dk
            else:
                assert b is not None, dk
                assert b.to_dict(_schema()) == s.to_dict(_schema()), dk

    def test_multi_read_after_flush_and_projection(self, cluster, table):
        client = cluster.new_client()
        for ts in cluster.tservers:
            for peer in ts.tablet_manager.peers():
                t = getattr(peer, "tablet", None)
                if t is not None and t.regular_db is not None:
                    t.regular_db.flush()
        dks = [_dk(f"row{i:03d}") for i in range(0, 70, 3)]
        batched = client.multi_read(table, dks, projection=["v"])
        seq = [client.read_row(table, dk, projection=["v"])
               for dk in dks]
        for b, s in zip(batched, seq):
            assert (b is None) == (s is None)
            if b is not None:
                assert b.to_dict(_schema()) == s.to_dict(_schema())

    def test_multi_read_deep_rows_fall_back(self, cluster, table):
        """Rows holding deep documents route through the exact per-row
        path (the flat fast path refuses them) — answers still match."""
        client = cluster.new_client()
        peer = None
        for ts in cluster.tservers:
            for p in ts.tablet_manager.peers():
                if getattr(p, "tablet", None) is not None \
                        and p.raft.is_leader():
                    peer = p
                    break
            if peer is not None:
                break
        assert peer is not None
        schema = peer.tablet.schema
        cid = schema.column_id("v")
        dk = None
        # find a doc key this tablet owns
        for i in range(60):
            cand = _dk(f"row{i:03d}")
            enc = cand.encode()
            lo = peer.tablet.opts.lower_bound_key
            hi = peer.tablet.opts.upper_bound_key
            if (not lo or enc >= lo) and (hi is None or enc < hi):
                dk = cand
                break
        assert dk is not None
        peer.tablet.write_subdocument(dk, (("col", cid), "deepkey"),
                                      {"a": 1})
        rows = peer.multi_read([dk])
        direct = peer.read_row(dk)
        assert (rows[0] is None) == (direct is None)
        if direct is not None:
            assert rows[0].to_dict(schema) == direct.to_dict(schema)
