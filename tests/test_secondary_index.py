"""Secondary indexes: DDL, online backfill, transactional maintenance,
index-accelerated reads — through both query layers on a MiniCluster.

Mirrors the reference's index test strategy (ref:
src/yb/master/backfill_index.cc state machine;
tablet-side backfill tablet.cc:2088; YSQL-layer maintenance
pggate/pg_dml_write.cc): correctness under concurrent writers during
backfill is the load-bearing case.
"""

import threading
import time

import pytest

from yugabyte_tpu.integration.mini_cluster import (
    MiniCluster, MiniClusterOptions)
from yugabyte_tpu.utils import flags
from yugabyte_tpu.yql.cql.executor import QLProcessor
from yugabyte_tpu.client.transaction import TransactionManager
from yugabyte_tpu.yql.pgsql.executor import PgSession


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    flags.set_flag("replication_factor", 3)
    flags.set_flag("index_backfill_grace_ms", 300)
    flags.set_flag("table_cache_ttl_ms", 100)
    c = MiniCluster(MiniClusterOptions(
        num_masters=1, num_tservers=3,
        fs_root=str(tmp_path_factory.mktemp("idx-cluster")))).start()
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def cql(cluster):
    proc = QLProcessor(cluster.new_client())
    proc.execute("CREATE KEYSPACE IF NOT EXISTS idx_ks")
    proc.execute("USE idx_ks")
    return proc


def test_cql_index_lifecycle(cql, cluster):
    cql.execute("CREATE TABLE users (id INT PRIMARY KEY, city TEXT, "
                "age INT) WITH tablets = 2")
    # READY-leader deadline poll before the INSERT burst (leadership-
    # timing flake shape: CREATE via the query layer, immediate writes)
    cluster.wait_for_table_leaders("idx_ks", "users")
    for i in range(40):
        cql.execute(f"INSERT INTO users (id, city, age) "
                    f"VALUES ({i}, 'c{i % 4}', {20 + i})")
    cql.execute("CREATE INDEX users_city ON users (city)")
    rs = cql.execute("SELECT id FROM users WHERE city = 'c1'")
    assert sorted(r[0] for r in rs.rows) == [i for i in range(40)
                                             if i % 4 == 1]
    # residual filter on top of the index lookup
    rs = cql.execute("SELECT id FROM users WHERE city = 'c1' AND age > 40")
    assert sorted(r[0] for r in rs.rows) == [i for i in range(40)
                                             if i % 4 == 1 and 20 + i > 40]
    # UPDATE moves the entry
    cql.execute("UPDATE users SET city = 'moved' WHERE id = 1")
    assert 1 not in [r[0] for r in cql.execute(
        "SELECT id FROM users WHERE city = 'c1'").rows]
    assert [r[0] for r in cql.execute(
        "SELECT id FROM users WHERE city = 'moved'").rows] == [1]
    # DELETE removes it
    cql.execute("DELETE FROM users WHERE id = 5")
    assert 5 not in [r[0] for r in cql.execute(
        "SELECT id FROM users WHERE city = 'c1'").rows]
    # INSERT after index creation maintains it
    cql.execute("INSERT INTO users (id, city, age) VALUES (99, 'c1', 70)")
    assert 99 in [r[0] for r in cql.execute(
        "SELECT id FROM users WHERE city = 'c1'").rows]


def test_cql_index_backfill_under_concurrent_writes(cql, cluster):
    cql.execute("CREATE TABLE events (id INT PRIMARY KEY, kind TEXT) "
                "WITH tablets = 2")
    cluster.wait_for_table_leaders("idx_ks", "events")
    for i in range(60):
        cql.execute(f"INSERT INTO events (id, kind) VALUES ({i}, "
                    f"'k{i % 3}')")
    stop = threading.Event()
    written = []
    errors = []

    def writer():
        # a separate session, like a second app server; its table handles
        # pick up the new index within the cache TTL
        proc = QLProcessor(cluster.new_client())
        proc.execute("USE idx_ks")
        i = 1000
        while not stop.is_set():
            try:
                proc.execute(f"INSERT INTO events (id, kind) VALUES "
                             f"({i}, 'k{i % 3}')")
                written.append(i)
                i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    time.sleep(0.2)  # writer running before, during and after backfill
    cql.execute("CREATE INDEX events_kind ON events (kind)")
    time.sleep(0.3)
    stop.set()
    t.join(timeout=10)
    assert not errors, errors
    assert len(written) > 0
    # give the last maintenance writes a beat, then check EVERY row —
    # pre-existing and concurrently written — is discoverable via the index
    expect = {i for i in range(60)} | set(written)
    got = set()
    for k in range(3):
        rs = cql.execute(f"SELECT id FROM events WHERE kind = 'k{k}'")
        ids = [r[0] for r in rs.rows]
        assert all(i % 3 == k for i in ids)
        got |= set(ids)
    assert got == expect, (sorted(expect - got), sorted(got - expect))


def test_cql_index_inside_explicit_transaction(cql, cluster):
    cql.execute("CREATE TABLE accts (id INT PRIMARY KEY, owner TEXT) "
                "WITH tablets = 2")
    cluster.wait_for_table_leaders("idx_ks", "accts")
    cql.execute("CREATE INDEX accts_owner ON accts (owner)")
    cql.execute(
        "BEGIN TRANSACTION "
        "INSERT INTO accts (id, owner) VALUES (1, 'alice'); "
        "INSERT INTO accts (id, owner) VALUES (2, 'alice'); "
        "END TRANSACTION")
    rs = cql.execute("SELECT id FROM accts WHERE owner = 'alice'")
    assert sorted(r[0] for r in rs.rows) == [1, 2]


def _pg_session(cluster, db="idx_pg"):
    c = cluster.new_client()
    boot = PgSession(c, TransactionManager(c))
    try:
        boot.execute(f"CREATE DATABASE {db}")
    except Exception:  # noqa: BLE001 — already exists
        pass
    return PgSession(c, TransactionManager(c), database=db)


def test_pg_index_lifecycle(cluster):
    sess = _pg_session(cluster)
    sess.execute("CREATE TABLE items (id INT PRIMARY KEY, cat TEXT, "
                 "price INT)")
    cluster.wait_for_table_leaders("idx_pg", "items")
    for i in range(30):
        sess.execute(f"INSERT INTO items (id, cat, price) VALUES "
                     f"({i}, 'g{i % 3}', {i * 10})")
    sess.execute("CREATE INDEX items_cat ON items (cat)")
    (res,) = sess.execute("SELECT id FROM items WHERE cat = 'g2'")
    assert sorted(r[0] for r in res.rows) == [i for i in range(30)
                                              if i % 3 == 2]
    # multi-row UPDATE through the implicit statement transaction
    (res,) = sess.execute("UPDATE items SET cat = 'gx' WHERE cat = 'g2'")
    assert res.tag == "UPDATE 10"
    (res,) = sess.execute("SELECT id FROM items WHERE cat = 'g2'")
    assert res.rows == []
    (res,) = sess.execute("SELECT id FROM items WHERE cat = 'gx'")
    assert sorted(r[0] for r in res.rows) == [i for i in range(30)
                                              if i % 3 == 2]
    # DELETE maintains the index
    (res,) = sess.execute("DELETE FROM items WHERE cat = 'gx'")
    assert res.tag == "DELETE 10"
    (res,) = sess.execute("SELECT id FROM items WHERE cat = 'gx'")
    assert res.rows == []


def test_pg_multirow_update_statement_atomicity(cluster):
    """A concurrent writer between the statement's scan and its writes must
    not be clobbered (round-2 Weak #5: lost update)."""
    sess = _pg_session(cluster)
    sess.execute("CREATE TABLE counters (id INT PRIMARY KEY, v INT)")
    cluster.wait_for_table_leaders("idx_pg", "counters")
    for i in range(10):
        sess.execute(f"INSERT INTO counters (id, v) VALUES ({i}, 0)")

    barrier = threading.Barrier(2, timeout=20)
    results = []

    def bulk():
        s = _pg_session(cluster)
        barrier.wait()
        (r,) = s.execute("UPDATE counters SET v = 1 WHERE v = 0")
        results.append(("bulk", r.tag))

    def point():
        s = _pg_session(cluster)
        barrier.wait()
        (r,) = s.execute("UPDATE counters SET v = 7 WHERE id = 3")
        results.append(("point", r.tag))

    ts = [threading.Thread(target=bulk), threading.Thread(target=point)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    # whatever the interleaving, no write may be silently lost: every row
    # is 1, except row 3 which is 1 or 7 depending on commit order — but
    # NEVER 0 (both statements ran)
    (res,) = sess.execute("SELECT id, v FROM counters")
    vals = {r[0]: r[1] for r in res.rows}
    assert all(vals[i] == 1 for i in range(10) if i != 3), vals
    assert vals[3] in (1, 7), vals


def test_create_index_validations(cql):
    cql.execute("CREATE TABLE vtab (id INT PRIMARY KEY, a TEXT) "
                "WITH tablets = 1")
    with pytest.raises(Exception):
        cql.execute("CREATE INDEX bad ON vtab (id)")  # key column
    with pytest.raises(Exception):
        cql.execute("CREATE INDEX bad2 ON vtab (nope)")  # unknown column
    cql.execute("CREATE INDEX va ON vtab (a)")
    with pytest.raises(Exception):
        cql.execute("CREATE INDEX va ON vtab (a)")  # duplicate
    cql.execute("CREATE INDEX IF NOT EXISTS va ON vtab (a)")  # idempotent


class TestMultiColumnIndex:
    """CREATE INDEX ON t (a, b): the first column hash-partitions the
    index table, the rest are leading range components (ref:
    common/index.h IndexInfo hash+range columns; multi-column index
    creation in master catalog_manager.cc)."""

    @pytest.fixture(scope="class")
    def pg(self, cluster):
        return _pg_session(cluster, db="idx_mc")

    def test_multicol_create_backfill_lookup(self, pg, cluster):
        pg.execute("CREATE TABLE ev (id INT PRIMARY KEY, city TEXT, "
                   "kind TEXT, amt INT)")
        # READY-leader poll before the write burst (leadership-timing
        # flake shape: CREATE via the query layer, immediate writes)
        cluster.wait_for_table_leaders("idx_mc", "ev")
        pg.execute("INSERT INTO ev VALUES "
                   "(1,'rome','click',5), (2,'rome','view',6), "
                   "(3,'oslo','click',7), (4,'rome','click',8)")
        # backfill path: index created AFTER the data
        pg.execute("CREATE INDEX ck ON ev (city, kind)")
        rows = pg.execute("SELECT id FROM ev WHERE city = 'rome' "
                          "AND kind = 'click'")[-1].rows
        assert sorted(r[0] for r in rows) == [1, 4]
        # prefix use: equality on the hash column only
        rows = pg.execute("SELECT id FROM ev WHERE city = 'oslo'")[-1].rows
        assert [r[0] for r in rows] == [3]
        # residual filter on top of the index probe
        rows = pg.execute("SELECT id FROM ev WHERE city = 'rome' AND "
                          "kind = 'click' AND amt > 5")[-1].rows
        assert [r[0] for r in rows] == [4]

    def test_multicol_maintenance(self, pg, cluster):
        pg.execute("CREATE TABLE mv (id INT PRIMARY KEY, a TEXT, b TEXT)")
        pg.execute("CREATE INDEX ab ON mv (a, b)")
        # transactional index maintenance spans base + index tablets:
        # both need READY leaders before the first write
        cluster.wait_for_table_leaders("idx_mc", "mv")
        cluster.wait_for_table_leaders("idx_mc", "ab")
        pg.execute("INSERT INTO mv VALUES (1, 'x', 'y')")
        assert [r[0] for r in pg.execute(
            "SELECT id FROM mv WHERE a = 'x' AND b = 'y'")[-1].rows] == [1]
        # updating the SECOND column moves the entry
        pg.execute("UPDATE mv SET b = 'z' WHERE id = 1")
        assert pg.execute("SELECT id FROM mv WHERE a = 'x' AND b = 'y'"
                          )[-1].rows == []
        assert [r[0] for r in pg.execute(
            "SELECT id FROM mv WHERE a = 'x' AND b = 'z'")[-1].rows] == [1]
        # deleting the row removes the entry
        pg.execute("DELETE FROM mv WHERE id = 1")
        assert pg.execute("SELECT id FROM mv WHERE a = 'x' AND b = 'z'"
                          )[-1].rows == []

    def test_multicol_explain_shows_index(self, pg):
        pg.execute("CREATE TABLE xv (id INT PRIMARY KEY, p TEXT, q TEXT)")
        pg.execute("CREATE INDEX pq ON xv (p, q)")
        plan = "\n".join(
            r[0] for r in pg.execute(
                "EXPLAIN SELECT id FROM xv WHERE p = 'a' AND q = 'b'"
            )[-1].rows)
        assert "Index Scan using pq" in plan
        assert "(p = 'a') AND (q = 'b')" in plan

    def test_key_column_rejected(self, pg):
        pg.execute("CREATE TABLE kv2 (id INT PRIMARY KEY, v TEXT)")
        from yugabyte_tpu.yql.pgsql.executor import PgError
        with pytest.raises(PgError):
            pg.execute("CREATE INDEX bad ON kv2 (v, id)")


def test_projected_point_read_returns_values(cluster):
    """Regression: name-based projections through the RPC read path must
    translate to column ids at the tablet (a broken projection silently
    returned None for every projected column, so index maintenance never
    saw old values and left stale entries behind on update)."""
    from yugabyte_tpu.client.transaction import TransactionManager
    from yugabyte_tpu.docdb.doc_key import DocKey
    sess = _pg_session(cluster, db="proj_db")
    sess.execute("CREATE TABLE pr (id INT PRIMARY KEY, a TEXT, b TEXT)")
    # READY-leader poll before the write (leadership-timing flake shape)
    cluster.wait_for_table_leaders("proj_db", "pr")
    sess.execute("INSERT INTO pr VALUES (1, 'va', 'vb')")
    t = sess._table("pr")
    cl = cluster.new_client()
    row = cl.read_row(t, DocKey(hash_components=(1,)))
    assert row.to_dict(t.schema) == {"id": 1, "a": "va", "b": "vb"}
    txn = TransactionManager(cl).begin()
    try:
        prow = txn.read_row(t, DocKey(hash_components=(1,)),
                            projection=["b"])
        d = prow.to_dict(t.schema)
        assert d["b"] == "vb"
    finally:
        txn.abort()


def test_index_update_removes_stale_entry(cluster):
    """After UPDATE moves an indexed value, the OLD index entry must be
    gone (not merely filtered by the lookup re-check)."""
    sess = _pg_session(cluster, db="stale_db")
    sess.execute("CREATE TABLE st (id INT PRIMARY KEY, tag TEXT)")
    sess.execute("CREATE INDEX stag ON st (tag)")
    # READY-leader deadline polls before the writes (the known
    # leadership-timing flake: CREATE via the query layer, then
    # immediate transactional writes spanning base AND index tablets —
    # this test was the one-flake-per-run in the PR-12 baseline)
    cluster.wait_for_table_leaders("stale_db", "st")
    cluster.wait_for_table_leaders("stale_db", "stag")
    sess.execute("INSERT INTO st VALUES (1, 'old')")
    sess.execute("UPDATE st SET tag = 'new' WHERE id = 1")
    cl = cluster.new_client()
    it = cl.open_table("stale_db", "stag")
    entries = [r.doc_key.hash_components[0] for r in cl.scan(it)]
    assert entries == ["new"], entries
