"""Retry pacing: capped exponential backoff with decorrelated jitter.

Capability parity with the reference's retry waiters (ref:
src/yb/util/backoff_waiter.h BackoffWaiter; rpc/rpc.cc
RpcRetrier::DelayMillis adds jitter the same way): every retry loop in the
stack — client master lookup, tablet-call replica walks, the heartbeater's
master hunt, and the maintenance manager's background-error recovery —
draws its sleeps from here instead of hard-coding a fixed interval.

Two shapes:

- `Backoff`: an iterator of delays for one bounded retry *attempt*
  (deadline-aware; decorrelated jitter so a thundering herd of retriers
  de-synchronizes: delay_n = uniform(base, prev * 3), clamped to cap).
- `RetrySchedule`: open-ended pacing for a long-lived background retrier
  (the maintenance manager's flush-recovery op): `ready()` gates the next
  attempt, `record_failure()` doubles the spacing up to a cap,
  `reset()` re-arms after success.
"""

from __future__ import annotations

import random
import time
from typing import Optional

__all__ = ["Backoff", "RetrySchedule"]


class Backoff:
    """Decorrelated-jitter delay source for one retry loop.

    next_delay() never exceeds cap_s nor the remaining deadline;
    sleep() performs the wait and returns False once the deadline is
    exhausted (callers break their loop and surface the last error).
    """

    def __init__(self, base_s: float = 0.05, cap_s: float = 2.0,
                 deadline_s: Optional[float] = None, rng=None):
        self.base_s = base_s
        self.cap_s = cap_s
        self._prev = base_s
        self._deadline = (None if deadline_s is None
                          else time.monotonic() + deadline_s)
        self._rng = rng if rng is not None else random
        self.attempts = 0

    @property
    def expired(self) -> bool:
        return (self._deadline is not None
                and time.monotonic() >= self._deadline)

    def remaining_s(self) -> Optional[float]:
        """Seconds left until the deadline; None when unbounded. Callers
        clamp per-attempt RPC timeouts to this so one slow attempt
        cannot blow the whole op budget."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def next_delay(self) -> float:
        """Draw the next delay (decorrelated jitter), deadline-clamped."""
        self.attempts += 1
        d = min(self.cap_s, self._rng.uniform(self.base_s, self._prev * 3))
        self._prev = d
        if self._deadline is not None:
            d = min(d, max(0.0, self._deadline - time.monotonic()))
        return d

    def sleep(self) -> bool:
        """Sleep for the next delay; False when the deadline is spent
        (no sleep happens in that case)."""
        if self.expired:
            return False
        time.sleep(self.next_delay())
        return not self.expired


class RetrySchedule:
    """Open-ended capped-exponential pacing for a background retrier.

    Unlike Backoff (one bounded loop), this survives across scheduler
    polls: the maintenance manager asks ready() each round, performs the
    recovery attempt when it fires, and records the outcome.

    deadline_s bounds the WHOLE schedule to an overall per-op budget:
    record_failure clamps each delay to the remaining budget (never
    scheduling an attempt past the deadline), and once the budget is
    spent `expired` turns True / ready() turns False — the owner must
    surface DeadlineExceeded instead of retrying forever."""

    def __init__(self, initial_s: float = 0.5, max_s: float = 30.0,
                 deadline_s: Optional[float] = None, rng=None):
        self.initial_s = initial_s
        self.max_s = max_s
        self._deadline = (None if deadline_s is None
                          else time.monotonic() + deadline_s)
        self._rng = rng if rng is not None else random
        self.failures = 0
        self._next_attempt = 0.0  # monotonic time; 0 = immediately ready

    @property
    def expired(self) -> bool:
        return (self._deadline is not None
                and time.monotonic() >= self._deadline)

    def remaining_s(self) -> Optional[float]:
        """Seconds left in the overall budget; None when unbounded."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def ready(self) -> bool:
        if self.expired:
            return False  # budget spent: surface, don't retry
        return time.monotonic() >= self._next_attempt

    def record_failure(self) -> float:
        """Push the next attempt out by initial * 2^n (capped), with a
        +-25% jitter so many parked tablets don't retry in lockstep;
        clamped to the remaining per-op budget so the schedule never
        waits past its deadline. Returns the chosen delay."""
        delay = min(self.max_s, self.initial_s * (2 ** self.failures))
        delay *= self._rng.uniform(0.75, 1.25)
        rem = self.remaining_s()
        if rem is not None:
            delay = min(delay, rem)
        self.failures += 1
        self._next_attempt = time.monotonic() + delay
        return delay

    def reset(self) -> None:
        self.failures = 0
        self._next_attempt = 0.0
