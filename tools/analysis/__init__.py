"""yblint: the project's unified AST analysis framework.

One parse + one walk per file, shared by every registered pass; per-file
parallel execution; a committed baseline for justified suppressions; JSON
and human output. Run as `python -m tools.analysis` (see __main__.py) or
from CI via `run_analysis()` / the tier-1 test in tests/test_yblint.py.

Adding a pass: subclass tools.analysis.core.AnalysisPass, implement
`run(ctx)` returning Findings, and append an instance to
tools.analysis.passes.ALL_PASSES. See tools/analysis/passes/ for the four
shipped passes (jit trace-safety, lock discipline, blocking-call-in-
reactor, swallowed errors) plus metric naming.
"""

from tools.analysis.core import (AnalysisPass, Baseline, FileContext,
                                 Finding, analyze_paths, run_analysis)

__all__ = ["AnalysisPass", "Baseline", "FileContext", "Finding",
           "analyze_paths", "run_analysis"]
